"""Host-stage microbenchmarks: queue drain (flat + band-aware), pack,
commit gather/assume, node-state delta update + reuse check, and the
streaming subsystem's controller step + trace generation.

The end-to-end bench (bench.py) measures the pipeline; this tool
isolates the host stages PR 4/PR 5 vectorized so a regression in any
one of them is visible WITHOUT the noise of the full burst (informers,
solver, bind pool). Synthetic input, no scheduler stack, no device work.

Prints ONE JSON line:

  {"pods": N, "nodes": M,
   "queue_drain_ms":     bulk pop_batch of N queued pods,
   "queue_drain_perpod_ms": the same drain via per-pod pop() calls,
   "pack_ms":            pack_pod_batch over the N pods,
   "commit_gather_ms":   argsort split + native commit_gather,
   "commit_assume_ms":   node-grouped cache.assume_pods of the clones,
   "node_update_ms_churn{0,1pct,100pct}":
                         NodeTensorCache.update() at M nodes when 0% /
                         1% / 100% of rows changed since the last pack,
   "reuse_check_ms_churn{0,1pct,100pct}":
                         the dispatch generation handshake (epoch compare
                         + changed-row content check) at the same churn,
   "reuse_check_full_sweep_ms":
                         the RETIRED pre-PR-5 validation (full [N, R]
                         np.array_equal sweep), for scale,
   "member_add_ms" / "member_remove_ms" / "member_readd_ms":
                         NodeTensorCache.update() for K node adds /
                         removes / free-slot re-adds at M-node scale --
                         the PR-6 slot path, O(changed rows),
   "member_churn_rows":  K (5% of M, the rows each step touched),
   "member_full_repack_ms":
                         the RETIRED pre-PR-6 membership path (full
                         M-row repack), for scale,
   "preempt_pack_ms" / "preempt_wave_{xla,pallas}_ms":
                         the ISSUE-11 batched preemption wave at M
                         nodes: per-snapshot victim pack, then ONE
                         kernel round trip for a 256-pod failed group
                         (victim scan + reprieve + 6-rule pick +
                         nomination carry) on the Pallas tier vs the
                         jnp twin (pallas is None off-TPU),
   "mesh_delta_scatter_{empty,bucket}_ms" / "mesh_full_upload_ms" /
   "mesh_{delta,full}_link_bytes":
                         the PR-9 mesh serving-link comparison at 20k
                         nodes on an N-device node-axis mesh: the fixed
                         DELTA_ROW_BUCKET shard-local scatter a steady
                         sharded dispatch ships (empty = 0% churn,
                         bucket = up to 64 changed rows) vs the full
                         [N, R] upload the pre-delta mesh path paid
                         every batch (and that >bucket churn still
                         escalates to); link_bytes is the payload each
                         variant ships -- the quantity that costs on a
                         tunneled serving link (on a CPU host the
                         "link" is a memcpy: read the bytes ratio),
   "mesh_{pallas,xla}_solve_ms" / "mesh_xla_vs_pallas_x" /
   "mask_row_{sharded,replicated}_bytes":
                         the PR-10 mesh solver-tier comparison at 20k
                         nodes: one steady-state production dispatch on
                         the shard_map'd Pallas tier (per-shard fused
                         step, ONE scalar best-of-shards combine per
                         pod) vs the GSPMD XLA twin (per-step full
                         [N]-score gather), placements asserted
                         bit-identical; plus the [U, N] static-mask
                         link payload -- bool column shards per device
                         vs the replicated int32 rows the pre-PR-10
                         buffer shipped (<= 1/P by construction),
   "ingest_apply_{native,twin}_{10,100}k_ms" (+ _events_per_s) /
   "ingest_apply_decoded_reuse_{10,100}k_ms" /
   "ingest_stamp_{native,twin}_ms" /
   "pack_row_gather_ms" / "pack_perpod_retired_ms":
                         the ISSUE-12 ingest plane: watch-frame
                         decode+apply through the native C pass vs the
                         Python twin (and the decode-once memo reuse a
                         second informer set pays), the plain-pod
                         ingest stamp at 5k pods, and pack_pod_batch's
                         memo gather vs the RETIRED per-pod spec walk,
   "watch_fanout_{perevent,bulk}_{1,4}w_ms":
                         apiserver watch fan-out: 20k pod events
                         broadcast to 1 vs 4 concurrent watchers,
                         per-event vs batched delivery. With the
                         shared-log cursor design (PR 8) the 4-watcher
                         cost tracks the 1-watcher cost (broadcast is
                         O(events), watcher-count independent) and
                         batched delivery beats per-event ~4x,
   "trace_{on,off}_hot_ms" / "trace_overhead_pct" /
   "trace_{span,mark}_us":
                         the ISSUE-13 flight-recorder spine on a real
                         1k-pod closed-loop burst, recorder ON vs
                         compiled-out (interleaved arms, best-of-2
                         each; denominator = the pop+pack+solve+
                         download+commit stage-timer delta), plus the
                         raw per-span / per-mark op costs the tier-1
                         self-time guard multiplies out,
   "spec_{serial,pipelined}_ms" / "spec_overlap_x" / "spec_launches" /
   "spec_conflict_rewinds" / "spec_conflict_rewind_rate" /
   "carry_full_bytes_{i32,i16}" / "carry_delta_bytes_{i32,i16}" /
   "carry_link_ratio_x":
                         the ISSUE-18 pipelined speculative dispatch:
                         an identical seeded burst at 5k nodes through
                         the RETIRED serial solve->commit path vs the
                         double-buffered pipeline (committer overlapped
                         with the next speculative solve), the rewind
                         rate under a seeded bind-conflict sprinkle,
                         and the resident-carry link/HBM payload int32
                         vs packed int16 (full upload + steady delta
                         slot)}

Usage: python tools/bench_hotpath.py [bench_speculative]
       [--pods 10000] [--nodes 5000]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

import numpy as np  # noqa: E402


def _make_queue(pods):
    from kubernetes_tpu.framework.interface import PodInfo
    from kubernetes_tpu.plugins.queuesort import PrioritySort
    from kubernetes_tpu.queue.scheduling_queue import PriorityQueue

    sorter = PrioritySort()
    q = PriorityQueue(
        sorter.queue_sort_less, sort_key_func=sorter.queue_sort_key
    )
    q.add_many(pods)
    return q, PodInfo


def bench_queue_drain(pods, batch):
    """One bulk pop_batch over the full backlog vs the same drain
    through per-pod pop() calls (the pre-PR-4 shape)."""
    q, _ = _make_queue(pods)
    t0 = time.perf_counter()
    got = 0
    while got < len(pods):
        out = q.pop_batch(batch, timeout=0.0)
        if not out:
            break
        got += len(out)
    bulk_ms = (time.perf_counter() - t0) * 1000
    assert got == len(pods), f"bulk drain lost pods: {got}/{len(pods)}"

    q, _ = _make_queue(pods)
    t0 = time.perf_counter()
    got = 0
    while got < len(pods):
        if q.pop(timeout=0.0) is None:
            break
        got += 1
    perpod_ms = (time.perf_counter() - t0) * 1000
    assert got == len(pods), f"per-pod drain lost pods: {got}/{len(pods)}"
    return bulk_ms, perpod_ms


def bench_band_drain(pods, batch):
    """The band-aware drain vs the flat drain on the same backlog: the
    per-drained-pod band check + wait histogram must stay in the noise
    (pods carry mixed priorities, so both bands are exercised)."""
    q, _ = _make_queue(pods)
    q.band_threshold = 2  # priority(i % 3): ~1/3 of pods are high band
    t0 = time.perf_counter()
    got = 0
    while got < len(pods):
        out = q.pop_batch(batch, timeout=0.0)
        if not out:
            break
        got += len(out)
    band_ms = (time.perf_counter() - t0) * 1000
    assert got == len(pods), f"band drain lost pods: {got}/{len(pods)}"
    return band_ms


def bench_controller_step(n_steps=10000):
    """The SLO-adaptive controller's decision cost: it runs once per
    controller interval on the dispatcher thread, so a step must be
    microseconds. Synthetic signal walks depth up and down so both
    poles and the hysteresis band are visited."""
    from kubernetes_tpu.streaming.autobatch import AutoBatchController

    c = AutoBatchController(slo_p99_seconds=1.0, max_batch=4096)
    t0 = time.perf_counter()
    cycle = 0
    for i in range(n_steps):
        depth = (i * 37) % 9000
        cycle += 400
        c.step(depth, cycle, 0.25 * (i + 1), pop_wait_seconds=0.01 * i)
    total = time.perf_counter() - t0
    return total / n_steps * 1e6  # us per step


def bench_arrivals_gen(rate=10000.0, duration=10.0):
    """Trace generation cost for a 100k-arrival Poisson trace (runs
    once per bench step, off the clock -- recorded for scale)."""
    from kubernetes_tpu.streaming.arrivals import poisson_trace

    t0 = time.perf_counter()
    offsets = poisson_trace(rate, duration, seed=0)
    ms = (time.perf_counter() - t0) * 1000
    assert offsets.size > 0
    return ms, int(offsets.size)


def bench_pack(pods):
    from kubernetes_tpu.tensors import pack_pod_batch
    from kubernetes_tpu.tensors.node_tensor import ResourceDims

    dims = ResourceDims()
    # memoization is part of the measured steady state: first call warms
    # the per-pod memos exactly like the first burst batch does
    t0 = time.perf_counter()
    pack_pod_batch(pods, dims)
    return (time.perf_counter() - t0) * 1000


def bench_commit(pods, node_names):
    """The fused committer tail on synthetic assignments: stable argsort
    split, gather + clone (native when available), node-grouped bulk
    assume into a fresh cache."""
    from kubernetes_tpu.cache.cache import SchedulerCache
    from kubernetes_tpu.framework.interface import PodInfo
    from kubernetes_tpu.scheduler.batch import (
        _commit_gather_py,
        NO_NODE,
    )

    try:
        from kubernetes_tpu.native import commit_gather
    except Exception:  # noqa: BLE001
        commit_gather = None
    gather = commit_gather or _commit_gather_py

    infos = [PodInfo(p, float(i)) for i, p in enumerate(pods)]
    b = len(pods)
    rng = np.random.default_rng(0)
    assignments = rng.integers(0, len(node_names), size=b).astype(np.int64)
    assignments[:: max(1, b // 50)] = NO_NODE  # ~2% unplaced
    order = np.arange(b)

    t0 = time.perf_counter()
    grp = np.argsort(assignments, kind="stable")
    n_unplaced = int((assignments == NO_NODE).sum())
    placed = grp[n_unplaced:]
    order2 = order[placed].tolist()
    assign2 = assignments[placed].tolist()
    pis, clones, hosts = gather(infos, order2, assign2, node_names)
    gather_ms = (time.perf_counter() - t0) * 1000

    cache = SchedulerCache()
    t0 = time.perf_counter()
    errs = cache.assume_pods(clones)
    assume_ms = (time.perf_counter() - t0) * 1000
    assert not any(errs), "synthetic assume reported errors"
    assert len(pis) == b - n_unplaced
    return gather_ms, assume_ms


def bench_node_state(num_nodes):
    """The PR-5 node-state path: update() delta cost and the dispatch
    reuse check under 0% / 1% / 100% row churn, against a cluster the
    SchedulerCache change-tracks (the production shape)."""
    from kubernetes_tpu.cache.cache import SchedulerCache
    from kubernetes_tpu.cache.snapshot import Snapshot
    from kubernetes_tpu.tensors import NodeTensorCache
    from kubernetes_tpu.testing import make_node, make_pod

    cache = SchedulerCache()
    for i in range(num_nodes):
        cache.add_node(
            make_node(f"bn-{i}")
            .capacity(cpu="16", memory="32Gi", pods=110)
            .obj()
        )
    snap = Snapshot()
    cache.update_snapshot(snap)
    tc = NodeTensorCache()
    nt = tc.update(snap)  # cold full pack establishes the baseline

    out = {}
    seq = 0
    for churn, label in ((0.0, "0"), (0.01, "1pct"), (1.0, "100pct")):
        k = int(num_nodes * churn)
        for i in range(k):
            seq += 1
            cache.add_pod(
                make_pod(f"ch-{seq}").node(f"bn-{i}")
                .container(cpu="100m").obj()
            )
        cache.update_snapshot(snap)
        prev_epoch = nt.delta.epoch
        t0 = time.perf_counter()
        nt = tc.update(snap)
        out[f"node_update_ms_churn{label}"] = (
            time.perf_counter() - t0
        ) * 1000
        assert nt.delta.changed_rows.size == k, (
            f"delta reported {nt.delta.changed_rows.size} rows, "
            f"expected {k}"
        )
        # the dispatch handshake: shadow equals the expectation (pure
        # reuse), so this measures the steady-state validation cost
        shadow_req = nt.requested.copy()
        shadow_nzr = nt.non_zero_requested.copy()
        t0 = time.perf_counter()
        changed = tc.rows_changed_since(prev_epoch)
        if changed.size:
            ok = np.all(
                nt.requested[changed] == shadow_req[changed]
            ) and np.all(
                nt.non_zero_requested[changed] == shadow_nzr[changed]
            )
            assert ok
        out[f"reuse_check_ms_churn{label}"] = (
            time.perf_counter() - t0
        ) * 1000
    # the retired validation, for scale: one full-array sweep (the old
    # code ran one per shadow generation in the ring)
    shadow_req = nt.requested.copy()
    shadow_nzr = nt.non_zero_requested.copy()
    t0 = time.perf_counter()
    assert np.array_equal(nt.requested, shadow_req)
    assert np.array_equal(nt.non_zero_requested, shadow_nzr)
    out["reuse_check_full_sweep_ms"] = (time.perf_counter() - t0) * 1000
    return out


def bench_membership_churn(num_nodes, churn_fraction=0.05):
    """The PR-6 membership path: node add / remove / free-slot re-add
    as in-place slot scatters (O(changed rows)) vs the retired full
    repack (O(N rows)). Asserts what the churn guard test pins: zero
    layout bumps and zero full repacks for pure membership change."""
    from kubernetes_tpu.cache.cache import SchedulerCache
    from kubernetes_tpu.cache.snapshot import Snapshot
    from kubernetes_tpu.tensors import NodeTensorCache
    from kubernetes_tpu.api.types import Node, ObjectMeta
    from kubernetes_tpu.testing import make_node

    k = max(1, int(num_nodes * churn_fraction))
    cache = SchedulerCache()
    for i in range(num_nodes):
        cache.add_node(
            make_node(f"mc-{i}")
            .capacity(cpu="16", memory="32Gi", pods=110)
            .obj()
        )
    snap = Snapshot()
    cache.update_snapshot(snap)
    tc = NodeTensorCache()
    nt = tc.update(snap)  # cold full pack
    layout0 = tc.layout_epoch
    out = {"member_churn_rows": k}

    # K cold nodes join (autoscale scale-up): claim headroom slots
    for i in range(k):
        cache.add_node(
            make_node(f"mc-new-{i}")
            .capacity(cpu="16", memory="32Gi", pods=110)
            .obj()
        )
    cache.update_snapshot(snap)
    t0 = time.perf_counter()
    nt = tc.update(snap)
    out["member_add_ms"] = (time.perf_counter() - t0) * 1000
    assert nt.delta.membership_rows.size == k
    assert not nt.delta.full

    # the same K nodes reclaimed (spot storm): retire onto the free list
    for i in range(k):
        cache.remove_node(Node(metadata=ObjectMeta(name=f"mc-new-{i}")))
    cache.update_snapshot(snap)
    t0 = time.perf_counter()
    nt = tc.update(snap)
    out["member_remove_ms"] = (time.perf_counter() - t0) * 1000
    assert nt.delta.membership_rows.size == k

    # K replacements join (the flap closes): reclaim the freed slots
    for i in range(k):
        cache.add_node(
            make_node(f"mc-re-{i}")
            .capacity(cpu="16", memory="32Gi", pods=110)
            .obj()
        )
    cache.update_snapshot(snap)
    t0 = time.perf_counter()
    nt = tc.update(snap)
    out["member_readd_ms"] = (time.perf_counter() - t0) * 1000
    assert nt.delta.membership_rows.size == k

    # the acceptance shape: pure membership churn NEVER full-repacked
    assert tc.layout_epoch == layout0, "membership churn bumped layout"
    assert tc.full_repacks == 1, "membership churn full-repacked"
    assert tc.rows_added == 2 * k and tc.rows_retired == k

    # the retired path, for scale: what every membership change cost
    # before PR 6 (a from-scratch repack of every row)
    t0 = time.perf_counter()
    NodeTensorCache().update(snap)
    out["member_full_repack_ms"] = (time.perf_counter() - t0) * 1000
    return out


def bench_mesh_delta(num_nodes: int, mesh_devices: int):
    """The PR-9 mesh serving-link comparison: what a steady-state
    sharded dispatch ships (the fixed DELTA_ROW_BUCKET per-shard delta
    scatter, applied shard-locally onto the device-resident carry)
    vs what the pre-delta mesh path shipped every batch (a counted full
    [N, R] + [N, 2] node-state upload) at ``num_nodes`` scale.

    Both paths mirror the dispatch exactly: concatenate the variant's
    node-state pieces into the (replicated) upload buffer, ship it, and
    commit it to the node-sharded resident state inside one jit -- the
    delta variant scatters its DELTA_ROW_BUCKET slots shard-locally,
    the full variant reshards the uploaded [N, R]+[N, 2] to the node
    sharding (what the pre-delta mesh path, and >bucket churn today,
    pays every batch). Churn mapping at 20k nodes: 0% ships the EMPTY
    bucket, anything up to 64 rows ships the same fixed bucket, and
    both the 1% and 100% rungs of the node-state microbench exceed the
    bucket and escalate to exactly the measured full upload.
    ``*_link_bytes`` is the serving-link payload each variant ships --
    on the tunneled chip (~40-90ms/round trip + bandwidth) that is the
    quantity the delta path exists to cut; on a CPU host the "link" is
    a memcpy, so read the bytes ratio there, not wall-clock. Medians
    over repeats; both paths end device-committed."""
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from kubernetes_tpu.ops.assignment import shard_local_row_set
    from kubernetes_tpu.scheduler.batch import DELTA_ROW_BUCKET

    devs = jax.devices()
    n_dev = max(1, min(mesh_devices, len(devs)))
    mesh = Mesh(np.array(devs[:n_dev]), ("nodes",))
    node2d = NamedSharding(mesh, P("nodes", None))
    repl = NamedSharding(mesh, P())
    # bucket-pad like NodeTensorCache (128 rows), then to the mesh size
    n = 128 * ((num_nodes + 127) // 128)
    n = n_dev * ((n + n_dev - 1) // n_dev)
    r = 10  # fixed dims + a few scalar/encoding columns (bench shape)
    rng = np.random.default_rng(0)
    req_host = rng.integers(0, 1 << 20, size=(n, r), dtype=np.int32)
    nzr_host = rng.integers(0, 1 << 20, size=(n, 2), dtype=np.int32)
    req_dev = jax.device_put(req_host, node2d)
    nzr_dev = jax.device_put(nzr_host, node2d)
    jax.block_until_ready((req_dev, nzr_dev))
    k = DELTA_ROW_BUCKET

    @jax.jit
    def apply_delta(req, nzr, buf):
        didx = buf[:k]
        dreq = buf[k:k + k * r].reshape(k, r)
        dnzr = buf[k + k * r:].reshape(k, 2)
        return (
            shard_local_row_set(req, didx, dreq),
            shard_local_row_set(nzr, didx, dnzr),
        )

    @jax.jit
    def apply_full(buf):
        req = buf[:n * r].reshape(n, r)
        nzr = buf[n * r:].reshape(n, 2)
        return (
            jax.lax.with_sharding_constraint(req, node2d),
            jax.lax.with_sharding_constraint(nzr, node2d),
        )

    def run_delta(rows: int):
        didx = np.full(k, n, dtype=np.int32)
        if rows:
            didx[:rows] = rng.choice(n, size=rows, replace=False)
        dreq = np.zeros((k, r), dtype=np.int32)
        dnzr = np.zeros((k, 2), dtype=np.int32)

        def once():
            buf = np.concatenate(
                [didx.ravel(), dreq.ravel(), dnzr.ravel()]
            )
            out = apply_delta(
                req_dev, nzr_dev, jax.device_put(buf, repl)
            )
            jax.block_until_ready(out)
            return buf.nbytes

        nbytes = once()  # warm (compile)
        samples = []
        for _ in range(15):
            t0 = time.perf_counter()
            once()
            samples.append((time.perf_counter() - t0) * 1000)
        return sorted(samples)[len(samples) // 2], nbytes

    def run_full():
        def once():
            buf = np.concatenate([req_host.ravel(), nzr_host.ravel()])
            out = apply_full(jax.device_put(buf, repl))
            jax.block_until_ready(out)
            return buf.nbytes

        nbytes = once()  # warm (compile)
        samples = []
        for _ in range(15):
            t0 = time.perf_counter()
            once()
            samples.append((time.perf_counter() - t0) * 1000)
        return sorted(samples)[len(samples) // 2], nbytes

    empty_ms, delta_bytes = run_delta(0)
    bucket_ms, _ = run_delta(k)
    full_ms, full_bytes = run_full()
    return {
        "mesh_devices": n_dev,
        "mesh_nodes": n,
        "mesh_delta_rows_bucket": k,
        "mesh_delta_scatter_empty_ms": empty_ms,
        "mesh_delta_scatter_bucket_ms": bucket_ms,
        "mesh_full_upload_ms": full_ms,
        "mesh_delta_link_bytes": int(delta_bytes),
        "mesh_full_link_bytes": int(full_bytes),
        "mesh_full_vs_delta_ms_x": (
            round(full_ms / bucket_ms, 1) if bucket_ms > 0 else 0.0
        ),
        "mesh_full_vs_delta_bytes_x": (
            round(full_bytes / delta_bytes, 1) if delta_bytes else 0.0
        ),
    }


def bench_mesh_pallas(num_nodes: int, mesh_devices: int):
    """The PR-10 mesh solver-tier comparison: the shard_map'd Pallas
    tier (per-shard fused step + ONE best-of-shards scalar combine per
    pod) vs the GSPMD XLA twin (whose per-step argmax gathers the full
    [N] score row) on a steady-state solve at ``num_nodes`` scale, plus
    the static-mask link payload sharded-vs-replicated.

    Both tiers run the production path exactly: the same
    ``solve_packed`` steady layout (delta slots + replicated batch
    buffer) against the same device-resident sharded carry, one
    dispatch per sample, solve blocked to completion. Placements must
    be BIT-IDENTICAL between the tiers (the combine preserves the
    lowest-global-index tie-break), so the wall-clock delta is pure
    solver structure. On a CPU mesh the per-shard step runs the jnp
    twin of the fused kernel (the kernel itself is TPU-only), so the
    measured win here is the communication structure -- the scalar
    combine replacing the per-step full-score gather; the on-chip
    kernel win stacks on top of it.

    ``mask_row_*_bytes`` is the serving-link payload of the ``[U, N]``
    static-mask rows per dispatch: the replicated int32 rows the
    pre-PR-10 buffer shipped to EVERY device vs the bool columns each
    shard now uploads (``<= 1/P`` of the replicated payload by
    construction, measured from the actual device buffers)."""
    import jax
    from jax.sharding import Mesh

    from kubernetes_tpu.ops.assignment import (
        mesh_pallas_candidate,
        solve_packed,
    )
    from kubernetes_tpu.ops.host_masks import mask_rows_upload
    from kubernetes_tpu.scheduler.batch import (
        MASK_ROW_BUCKET,
        _delta_slot_pieces,
    )

    devs = jax.devices()
    n_dev = max(1, min(mesh_devices, len(devs)))
    mesh = Mesh(np.array(devs[:n_dev]), ("nodes",))
    n = 128 * ((num_nodes + 127) // 128)
    n = n_dev * ((n + n_dev - 1) // n_dev)
    r = 10
    b = 256
    u = MASK_ROW_BUCKET
    rng = np.random.default_rng(0)
    alloc = np.zeros((n, r), dtype=np.int32)
    alloc[:, 0] = rng.choice([4000, 8000, 16000], n)
    alloc[:, 1] = rng.choice([8, 16, 32], n) * 1024 * 1024
    alloc[:, 3] = 110
    requested = np.zeros_like(alloc)
    nzr = np.zeros((n, 2), dtype=np.int32)
    valid = np.ones(n, dtype=np.int32)
    pod_req = np.zeros((b, r), dtype=np.int32)
    pod_req[:, 0] = rng.choice([100, 250, 500, 1000], b)
    pod_req[:, 1] = rng.choice([128, 256, 512], b) * 1024
    pod_req[:, 3] = 1
    pod_nzr = pod_req[:, :2].copy()
    rows = rng.random((u, n)) > 0.1
    midx = rng.integers(0, u, b).astype(np.int32)
    active = np.ones(b, dtype=np.int32)

    base = [
        ("req", pod_req), ("nzr", pod_nzr), ("midx", midx),
        ("active", active), ("rows", mask_rows_upload(rows, mesh)),
    ]
    cold_tail = [
        ("alloc", alloc), ("valid", valid),
        ("req_state", requested), ("nzr_state", nzr),
    ]
    delta_slots = _delta_slot_pieces(n, r)
    eligible = mesh_pallas_candidate("greedy", n, mesh)

    def setup_tier(allow_pallas: bool):
        # cold upload establishes the resident sharded carry for the
        # tier, exactly like dispatch; every sample then rewinds
        # req/nzr to the SAME pre-batch carry so both tiers solve the
        # identical steady problem
        cold = solve_packed(
            base + cold_tail, None, None, None, None,
            allow_pallas=allow_pallas, mesh=mesh,
        )
        jax.block_until_ready(cold)
        _, _, _, alloc_d, valid_d = cold
        refresh = solve_packed(
            base + cold_tail[2:], alloc_d, valid_d, None, None,
            allow_pallas=allow_pallas, mesh=mesh,
        )
        jax.block_until_ready(refresh)

        def once():
            out = solve_packed(
                base + delta_slots, alloc_d, valid_d,
                refresh[1], refresh[2],
                allow_pallas=allow_pallas, mesh=mesh,
            )
            jax.block_until_ready(out)
            return out

        return once, np.asarray(once()[0])  # compile the steady layout

    xla_once, a_xla = setup_tier(False)
    tiers = {False: xla_once}
    if eligible:
        pallas_once, a_pallas = setup_tier(True)
        assert np.array_equal(a_pallas, a_xla), (
            "mesh pallas tier placements diverged from the XLA twin"
        )
        tiers[True] = pallas_once
    # INTERLEAVED sampling: on a contended host (the 2-core CI box runs
    # 2 virtual devices on 2 cores) sequential per-tier blocks absorb
    # machine drift as a between-tier bias; alternating samples put
    # both tiers under the same noise
    samples = {k: [] for k in tiers}
    for _ in range(11):
        for k, once in tiers.items():
            t0 = time.perf_counter()
            once()
            samples[k].append((time.perf_counter() - t0) * 1000)
    xla_ms = sorted(samples[False])[len(samples[False]) // 2]
    pallas_ms = (
        sorted(samples[True])[len(samples[True]) // 2] if eligible else 0.0
    )

    # mask-row link payload: what each variant actually ships per
    # dispatch. Replicated = the int32 rows inside the pre-PR-10
    # replicated buffer, paid once PER DEVICE; sharded = the bool
    # column shards, measured from the real device buffers.
    from jax.sharding import NamedSharding, PartitionSpec as P

    rows_dev = jax.device_put(
        mask_rows_upload(rows, mesh), NamedSharding(mesh, P(None, "nodes"))
    )
    jax.block_until_ready(rows_dev)
    sharded_bytes = sum(
        s.data.nbytes for s in rows_dev.addressable_shards
    )
    replicated_bytes = rows.astype(np.int32).nbytes * n_dev
    return {
        "mesh_pallas_devices": n_dev,
        "mesh_pallas_nodes": n,
        "mesh_pallas_batch": b,
        "mesh_pallas_eligible": bool(eligible),
        "mesh_pallas_solve_ms": pallas_ms,
        "mesh_xla_solve_ms": xla_ms,
        "mesh_xla_vs_pallas_x": (
            round(xla_ms / pallas_ms, 2) if pallas_ms > 0 else 0.0
        ),
        "mask_row_sharded_bytes": int(sharded_bytes),
        "mask_row_replicated_bytes": int(replicated_bytes),
        "mask_row_replicated_vs_sharded_x": (
            round(replicated_bytes / sharded_bytes, 1)
            if sharded_bytes else 0.0
        ),
    }


def bench_preemption_wave(num_nodes: int, wave: int = 256):
    """ISSUE-11 satellite: the batched preemption wave's device cost at
    scale -- the per-snapshot victim pack, then ONE kernel round trip
    for a whole failed-pod group (remove-all + reprieve simulation over
    every candidate node x victim, PLUS the in-kernel 6-rule
    lexicographic pick and the nomination carry) -- Pallas tier vs the
    bit-identical jnp twin. On non-TPU backends the pallas tier is
    ineligible (wave_pallas_eligible) and reported as None: interpret
    mode would time the emulator, not the kernel."""
    import numpy as np

    from kubernetes_tpu.cache.cache import SchedulerCache
    from kubernetes_tpu.cache.snapshot import Snapshot
    from kubernetes_tpu.ops.preemption import (
        pack_preemption_state,
        preempt_batch_device,
        wave_pallas_eligible,
    )
    from kubernetes_tpu.tensors import NodeTensorCache, pack_pod_batch
    from kubernetes_tpu.testing import make_node, make_pod

    cache = SchedulerCache()
    for i in range(num_nodes):
        cache.add_node(
            make_node(f"n{i}")
            .capacity(cpu="8", memory="32Gi", pods=16)
            .obj()
        )
    t0 = time.time() - 10_000
    # 4 victims/node at 1.8 cpu each: 800m free, so a 2-cpu preemptor
    # always needs one eviction per placement
    for i in range(num_nodes):
        for j in range(4):
            p = (
                make_pod(f"v-{i}-{j}").node(f"n{i}")
                .container(cpu="1800m", memory="4Gi")
                .priority(j % 3)
                .obj()
            )
            p.status.start_time = t0 + (i * 7 + j) % 9973
            cache.add_pod(p)
    snapshot = Snapshot()
    cache.update_snapshot(snapshot)
    nt = NodeTensorCache().update(snapshot)

    t = time.perf_counter()
    pack = pack_preemption_state(snapshot, nt, [])
    pack_ms = (time.perf_counter() - t) * 1000

    preemptors = [
        make_pod(f"hi-{k}").container(cpu="2", memory="4Gi")
        .priority(100).obj()
        for k in range(wave)
    ]
    batch = pack_pod_batch(preemptors, nt.dims)
    prio = np.full(wave, 100, dtype=np.int32)
    # a homogeneous wave shares one all-nodes candidate row (the
    # production path's dedup shape)
    rows = np.ones((1, len(pack.node_names)), dtype=bool)
    inverse = np.zeros(wave, dtype=np.int32)
    nom_req = np.zeros((0, nt.dims.num_dims), dtype=np.int32)
    nom_i = np.zeros(0, dtype=np.int32)

    def run(tier):
        chosen, _v, _viol, _nv = preempt_batch_device(
            pack, batch.requests, prio, None,
            nom_req, nom_i, nom_i,
            cand_dedup=(rows, inverse), tier=tier,
        )
        return chosen

    out = {
        "preempt_nodes": num_nodes,
        "preempt_wave_pods": wave,
        "preempt_wave_vmax": pack.v_max,
        "preempt_pack_ms": pack_ms,
    }
    chosen = run("xla")  # compile off the clock
    assert int((chosen >= 0).sum()) == wave, "wave should fully place"
    best = float("inf")
    for _ in range(3):
        t = time.perf_counter()
        run("xla")
        best = min(best, (time.perf_counter() - t) * 1000)
    out["preempt_wave_xla_ms"] = best
    if wave_pallas_eligible(pack, 0):
        run("pallas")
        best_p = float("inf")
        for _ in range(3):
            t = time.perf_counter()
            run("pallas")
            best_p = min(best_p, (time.perf_counter() - t) * 1000)
        out["preempt_wave_pallas_ms"] = best_p
    else:
        out["preempt_wave_pallas_ms"] = None
    return out


def bench_bisect(burst: int, num_nodes: int = 64):
    """ISSUE-14 satellite: blast-radius containment cost. One poison
    pod in a ``burst``-wide batch -- the bisection path (O(log B)
    sub-solves on the already-warm pad rungs; healthy pods commit at
    the device tier) vs the old full-ladder fail (the whole batch
    walks the per-pod sequential oracle). Sub-solves pad to the warmed
    max_batch rung, so the run must finish with ZERO mid-run
    recompiles -- asserted via the PR-13 jit-cache watchdog's own
    probe (jit_cache_sizes), not a heuristic."""
    import time as _time

    from kubernetes_tpu.apiserver.server import APIServer
    from kubernetes_tpu.client.client import Client
    from kubernetes_tpu.client.informer import InformerFactory
    from kubernetes_tpu.ops.assignment import jit_cache_sizes
    from kubernetes_tpu.robustness.circuit import RetryPolicy
    from kubernetes_tpu.robustness.containment import ContainmentConfig
    from kubernetes_tpu.robustness.faults import (
        FaultInjector,
        FaultProfile,
        POISON_ANNOTATION,
        install_injector,
    )
    from kubernetes_tpu.robustness.ladder import RobustnessConfig
    from kubernetes_tpu.scheduler.scheduler import new_scheduler
    from kubernetes_tpu.testing import make_node, make_pod
    from kubernetes_tpu.utils import metrics

    def run_arm(containment_enabled: bool):
        server = APIServer()
        client = Client(server)
        informers = InformerFactory(server)
        sched = new_scheduler(
            client, informers, batch=True, max_batch=burst,
            robustness_config=RobustnessConfig(
                solve_timeout_seconds=30.0,
                failure_threshold=burst,  # breakers out of the picture
                cooloff_seconds=0.1,
                retry=RetryPolicy(
                    max_attempts=1, backoff_seconds=0.0,
                    max_backoff_seconds=0.0,
                ),
            ),
            containment_config=ContainmentConfig(
                enabled=containment_enabled,
                max_strikes=1,  # isolate -> park immediately: the arm
                # measures the bisection search, not the hold schedule
            ),
        )
        sched.queue._initial_backoff = 0.05
        sched.queue._max_backoff = 0.1
        for i in range(num_nodes):
            client.create_node(
                make_node(f"n{i}")
                .capacity(cpu="64", memory="256Gi", pods=1100)
                .obj()
            )
        informers.start()
        informers.wait_for_cache_sync()
        sched.queue.run()
        sched.warmup()  # pad rungs compiled OFF the measured clock
        sizes_before = dict(jit_cache_sizes(None))
        install_injector(FaultInjector(FaultProfile(
            "bench-bisect", seed=0, points={}
        )))
        healthy = set()
        for i in range(burst):
            pw = make_pod(f"b-{i}").container(cpu="100m", memory="64Mi")
            if i == burst // 2:
                pw.annotation(POISON_ANNOTATION, "true")
            else:
                healthy.add(f"b-{i}")
            client.create_pod(pw.obj())
        t0 = _time.perf_counter()
        sched.start()
        deadline = _time.time() + 300
        while _time.time() < deadline:
            pods, _ = client.list_pods()
            if healthy <= {
                p.metadata.name for p in pods if p.spec.node_name
            }:
                break
            _time.sleep(0.005)
        elapsed_ms = (_time.perf_counter() - t0) * 1000.0
        sched.wait_for_inflight_binds()
        recompiles = sum(
            max(0, n - sizes_before.get(sig, 0))
            for sig, n in jit_cache_sizes(None).items()
        )
        out = (
            elapsed_ms,
            sched.bisections,
            float(metrics.bisect_subsolves.value()),
            recompiles,
        )
        install_injector(None)
        # the old-path arm leaves the poison pod cycling through the
        # sequential floor forever (the storm this bench quantifies):
        # delete it so teardown doesn't race a live retry
        try:
            client.delete_pod("default", f"b-{burst // 2}")
        except Exception:
            pass
        _time.sleep(0.1)
        sched.stop()
        informers.stop()
        return out

    sub0 = float(metrics.bisect_subsolves.value())
    bisect_ms, bisections, sub1, rec_b = run_arm(True)
    old_ms, _, _, rec_o = run_arm(False)
    assert rec_b == 0, (
        f"bisection arm recompiled {rec_b} signature(s) mid-run -- "
        f"sub-solves must reuse the warmed pad rungs"
    )
    return {
        f"bisect_b{burst}_ms": bisect_ms,
        f"bisect_b{burst}_subsolves": int(sub1 - sub0),
        f"bisect_b{burst}_bisections": bisections,
        f"bisect_b{burst}_recompiles": rec_b,
        f"bisect_b{burst}_oldpath_ms": old_ms,
        f"bisect_b{burst}_oldpath_recompiles": rec_o,
    }


def bench_tenant_columns(num_ns: int = 1000, num_pods: int = 5000):
    """ISSUE 15 hot-path costs of the multi-tenant fairness plane at
    1k namespaces / 5k pods: the quota ledger's charge+refund round
    trip (guaranteed_update check-and-increment per pod), the DRF
    tracker's incremental share update + dominant-share read, and the
    fair solve-order merge on a max_batch-sized multi-tenant batch
    (the per-dispatch cost the <5% single-tenant headline bounds)."""
    from kubernetes_tpu.api.types import ObjectMeta, ResourceQuota
    from kubernetes_tpu.apiserver.server import APIServer
    from kubernetes_tpu.client.client import Client
    from kubernetes_tpu.client.informer import InformerFactory
    from kubernetes_tpu.controllers.quota import QuotaController
    from kubernetes_tpu.scheduler.tenancy import (
        TenantShareTracker,
        fair_order,
    )
    from kubernetes_tpu.testing import make_pod

    server = APIServer()
    client = Client(server)
    informers = InformerFactory(server)
    qc = QuotaController(client, informers)
    for t in range(num_ns):
        client.create_resource_quota(ResourceQuota(
            metadata=ObjectMeta(name="quota", namespace=f"tenant-{t}"),
            hard={"pods": num_pods, "cpu": 1 << 30},
        ))
    pods = []
    for i in range(num_pods):
        p = make_pod(f"tq-{i}").container(cpu="250m", memory="512Mi").obj()
        p.metadata.namespace = f"tenant-{i % num_ns}"
        pods.append(p)
    client.create_pods_bulk(pods)
    informers.pump()  # the gate's liveness re-read needs the lister

    # charge every pod (one guaranteed_update per pod), then refund all
    t0 = time.perf_counter()
    for p in pods:
        qc.try_admit(p)
    charge_ms = (time.perf_counter() - t0) * 1000
    t0 = time.perf_counter()
    for p in pods:
        qc.refund(p, reason="requeue")
    refund_ms = (time.perf_counter() - t0) * 1000

    # DRF tracker: incremental usage update + per-namespace share reads
    tracker = TenantShareTracker()
    tracker.set_capacity(32000 * 5000, (64 << 30) // 1024 * 5000)
    t0 = time.perf_counter()
    tracker.note_bound(pods)
    note_ms = (time.perf_counter() - t0) * 1000
    t0 = time.perf_counter()
    shares = tracker.shares_for({p.metadata.namespace for p in pods})
    share_ms = (time.perf_counter() - t0) * 1000
    assert len(shares) == num_ns

    # fair solve-order merge on a 1024-pod multi-tenant batch (and the
    # single-tenant fast path next to it -- the steady-state cost)
    batch = pods[:1024]
    prio = np.asarray([p.spec.priority for p in batch], dtype=np.int32)
    base = np.arange(len(batch), dtype=np.int32)
    t0 = time.perf_counter()
    for _ in range(10):
        fair_order(base, batch, prio, tracker)
    fair_ms = (time.perf_counter() - t0) * 1000 / 10
    single = [make_pod(f"st-{i}").container(cpu="100m").obj()
              for i in range(1024)]
    sprio = np.zeros(1024, dtype=np.int32)
    t0 = time.perf_counter()
    for _ in range(50):
        fair_order(base, single, sprio, tracker)
    fair_single_ms = (time.perf_counter() - t0) * 1000 / 50
    return {
        "tenant_charge_ms": charge_ms,
        "tenant_charge_perpod_us": charge_ms * 1000 / num_pods,
        "tenant_refund_ms": refund_ms,
        "tenant_note_bound_ms": note_ms,
        "tenant_share_read_ms": share_ms,
        "tenant_fair_order_1024_ms": fair_ms,
        "tenant_fair_order_single_ns_ms": fair_single_ms,
    }


def bench_watch_fanout(events: int = 20000):
    """Apiserver watch fan-out under N consumers (the partitioned
    control plane runs one full informer set PER STACK): broadcast
    ``events`` pod creates with 1 vs 4 open watchers, per-event
    (create) vs batched (create_bulk) delivery, watchers draining
    concurrently. With the shared-log cursor design the broadcast cost
    is O(events) regardless of watcher count -- the 4-watcher runs
    should track the 1-watcher runs, and batched delivery should beat
    per-event on the producer side (one log extend + one wakeup per
    transaction)."""
    import threading

    from kubernetes_tpu.apiserver.server import APIServer
    from kubernetes_tpu.testing import make_pod

    out = {}
    for watchers in (1, 4):
        for batched in (False, True):
            server = APIServer()
            ws = [server.watch("Pod") for _ in range(watchers)]
            drained = [0] * watchers
            stop = threading.Event()

            def drain(i, w):
                while not stop.is_set() or drained[i] < events:
                    evs = w.next_batch(timeout=0.05)
                    drained[i] += len(evs)
                    if drained[i] >= events:
                        return

            threads = [
                threading.Thread(target=drain, args=(i, w), daemon=True)
                for i, w in enumerate(ws)
            ]
            for t in threads:
                t.start()
            pods = [
                make_pod(f"wf-{i}").container(cpu="1m", memory="1Mi").obj()
                for i in range(events)
            ]
            t0 = time.perf_counter()
            if batched:
                for i in range(0, events, 256):
                    server.create_bulk(pods[i:i + 256])
            else:
                for p in pods:
                    server.create(p)
            produce_ms = (time.perf_counter() - t0) * 1000
            stop.set()
            for t in threads:
                t.join(timeout=10)
            total_ms = (time.perf_counter() - t0) * 1000
            assert all(d >= events for d in drained), drained
            key = (
                f"watch_fanout_{'bulk' if batched else 'perevent'}"
                f"_{watchers}w"
            )
            out[key + "_produce_ms"] = produce_ms
            out[key + "_ms"] = total_ms
            for w in ws:
                w.stop()
    return out


def bench_heartbeat_fanout(events: int = 5000, host_counts=(50, 200)):
    """ISSUE-17 satellite: the per-host sharded event-log broadcast.
    A hollow fleet runs one pod watch PER HOST. On the plain broadcast
    log every host's cursor drains EVERY bind event and filters
    client-side (O(events * hosts) delivered frames); the routed watch
    keys each event by ``spec.nodeName`` and delivers it only to the
    one host it names (O(events) total, O(interested) per event). The
    routed drain should stay roughly FLAT as hosts grows while the
    plain drain scales linearly with it."""
    from kubernetes_tpu.apiserver.server import APIServer
    from kubernetes_tpu.testing import make_pod

    out = {}
    for hosts in host_counts:
        names = [f"h{i}" for i in range(hosts)]
        pods = [
            make_pod(f"hb-{i}").node(names[i % hosts])
            .container(cpu="1m", memory="1Mi").obj()
            for i in range(events)
        ]

        # plain broadcast: every host drains the full log and filters
        server = APIServer(watch_history_limit=events + 16)
        _, rv = server.list("Pod")
        for p in pods:
            server.create(p)
        ws = [server.watch("Pod", since_rv=rv) for _ in range(hosts)]
        mine = [set() for _ in range(hosts)]
        t0 = time.perf_counter()
        frames = 0
        for i, w in enumerate(ws):
            want = names[i]
            while True:
                evs = w.next_batch(timeout=0)
                if not evs:
                    break
                frames += len(evs)
                for ev in evs:
                    if ev.object.spec.node_name == want:
                        mine[i].add(ev.object.metadata.name)
        plain_ms = (time.perf_counter() - t0) * 1000
        assert frames == events * hosts, frames
        assert sum(len(m) for m in mine) == events
        for w in ws:
            w.stop()

        # routed: the server's one dict probe per event delivers each
        # frame only to the interested host
        server = APIServer(watch_history_limit=events + 16)
        _, rv = server.list("Pod")
        for p in pods:
            server.create(p)
        rws = [
            server.watch_routes("Pod", {n}, since_rv=rv) for n in names
        ]
        t0 = time.perf_counter()
        rframes = 0
        for w in rws:
            rframes += len(w.pending())
        routed_ms = (time.perf_counter() - t0) * 1000
        assert rframes == events, rframes

        out[f"hb_fanout_{hosts}h_plain_ms"] = plain_ms
        out[f"hb_fanout_{hosts}h_routed_ms"] = routed_ms
        out[f"hb_fanout_{hosts}h_plain_frames"] = frames
        out[f"hb_fanout_{hosts}h_routed_frames"] = rframes
    return out


def bench_ingest(pack_pods: int = 5000):
    """The ISSUE-12 ingest plane: watch-frame decode+apply events/s for
    the native C pass vs the Python twin at 10k/100k events (plus the
    decode-once memo reuse a second informer set pays), the plain-pod
    ingest stamp, and the pack-row gather vs the RETIRED per-pod pack
    walk at ``pack_pods`` pods."""
    from kubernetes_tpu import native
    from kubernetes_tpu.api.types import pod_resource_requests
    from kubernetes_tpu.apiserver.server import WatchEvent
    from kubernetes_tpu.cache.node_info import (
        non_zero_requests,
        pod_hot_info,
    )
    from kubernetes_tpu.client.informer import _apply_events_py
    from kubernetes_tpu.scheduler.admission import (
        ingest_stamp_cfg,
        plain_admission,
        stamp_plain_pods,
    )
    from kubernetes_tpu.tensors.node_tensor import (
        PODS,
        ResourceDims,
        _kib_ceil,
        pack_pod_batch,
    )
    from kubernetes_tpu.testing import make_pod

    out = {}
    have_native = native.hotpath is not None

    def mk_raw(n):
        pods = [
            make_pod(f"ing-{i}").container(cpu="100m", memory="128Mi").obj()
            for i in range(n // 2)
        ]
        raw = []
        rv = 0
        for p in pods:  # the create wave...
            rv += 1
            raw.append(("ADDED", p, rv))
        for p in pods:  # ...then its bind-echo wave
            rv += 1
            raw.append(("MODIFIED", p, rv))
        return raw[:n]

    import gc

    def best_of(k, fn):
        """min-of-k: this is a contended box, and a single capture mixes
        scheduler noise into a sub-100ms measurement"""
        best = float("inf")
        for _ in range(k):
            gc.collect()
            t0 = time.perf_counter()
            fn()
            best = min(best, time.perf_counter() - t0)
        return best * 1000

    for n in (10_000, 100_000):
        raw = mk_raw(n)
        for variant in ("native", "twin"):
            def run(variant=variant):
                evs = [WatchEvent(t, o, r) for t, o, r in raw]  # undecoded
                store: dict = {}
                if variant == "native" and have_native:
                    native.hotpath.ingest_apply(store, evs)
                else:
                    _apply_events_py(store, evs)

            ms = best_of(3, run)
            label = f"ingest_apply_{variant}_{n // 1000}k"
            out[label + "_ms"] = ms
            out[label + "_events_per_s"] = int(n / (ms / 1000)) if ms else 0
        # decode-once fan-out: one ingest_decode pass fills the shared
        # key records, then every LATER informer cursor draining the
        # same log (the twin here) skips the metadata walk entirely
        decoded_evs = [WatchEvent(t, o, r) for t, o, r in raw]
        if have_native:
            t0 = time.perf_counter()
            native.hotpath.ingest_decode(decoded_evs)
            out[f"ingest_decode_{n // 1000}k_ms"] = (
                time.perf_counter() - t0
            ) * 1000
        else:
            _apply_events_py({}, decoded_evs)  # twin fills the memos
        out[f"ingest_apply_decoded_reuse_{n // 1000}k_ms"] = best_of(
            3, lambda: _apply_events_py({}, decoded_evs)
        )

    # plain-pod ingest stamp (the per-pod classify cost at ingest)
    pods_n = [
        make_pod(f"st-{i}").container(cpu="250m", memory="512Mi").obj()
        for i in range(pack_pods)
    ]
    pods_t = [
        make_pod(f"su-{i}").container(cpu="250m", memory="512Mi").obj()
        for i in range(pack_pods)
    ]
    plain = plain_admission(object())
    cfg = ingest_stamp_cfg(plain)
    if have_native:
        assert not native.hotpath.ingest_stamp(pods_n[:64], cfg)
        out["ingest_stamp_native_ms"] = best_of(
            3, lambda: native.hotpath.ingest_stamp(pods_n, cfg)
        )
    out["ingest_stamp_twin_ms"] = best_of(
        3, lambda: stamp_plain_pods(pods_t, plain)
    )

    # pack-row gather over the stamped memos vs the RETIRED per-pod
    # spec walk (the pre-ISSUE-12 pack_pod_batch inner loop)
    dims = ResourceDims()
    pack_src = pods_n if have_native else pods_t
    pack_pod_batch(pack_src, dims)  # warm
    out["pack_row_gather_ms"] = best_of(
        3, lambda: pack_pod_batch(pack_src, dims)
    )

    def retired_perpod_pack(pods):
        b = len(pods)
        row_cache: dict = {}
        uniq = []
        idx = np.empty(b, dtype=np.int32)
        nzr = np.empty((b, 2), dtype=np.int32)
        prio = [0] * b
        for i, pod in enumerate(pods):
            req = pod_resource_requests(pod)
            pod_hot_info(pod)
            vc = pod.__dict__.get("_volcount_memo") or ()
            key = (tuple(req.items()), vc)
            u = row_cache.get(key)
            if u is None:
                row, _ = dims.encode_requests(req, grow=False)
                row[PODS] = 1
                u = len(uniq)
                uniq.append(row)
                row_cache[key] = u
            idx[i] = u
            cpu, mem = non_zero_requests(pod)
            nzr[i, 0] = cpu
            nzr[i, 1] = _kib_ceil(mem)
            prio[i] = pod.spec.priority
        return np.stack(uniq)[idx]

    retired_perpod_pack(pack_src)  # warm (memo-hit parity with above)
    out["pack_perpod_retired_ms"] = best_of(
        3, lambda: retired_perpod_pack(pack_src)
    )
    out["ingest_native_available"] = have_native
    return out


def bench_trace_overhead(num_pods: int = 1000, num_nodes: int = 200):
    """BatchSpan spine + flight recorder ON vs compiled-out
    (KTPU_FLIGHTRECORDER=0 semantics) on a real 1k-pod closed-loop
    burst: ONE warmed scheduler stack, arms interleaved OFF/ON/OFF/ON
    so box drift doesn't read as recorder bias. The denominator is the
    hot-path wall-clock the ISSUE bounds -- the pop+pack+solve+
    download+commit stage-timer delta, not the end-to-end burst (which
    is dominated by apiserver/bind threads the recorder never touches).

    Also measures the recorder's raw op costs (one full span lifecycle
    with a 256-pod link list + 5 stage stamps, and one mark), which the
    tier-1 guard (tests/test_flightrecorder.py) multiplies by the op
    counts of a real burst for a deterministic <1% self-time bound.
    """
    from kubernetes_tpu.apiserver.server import APIServer
    from kubernetes_tpu.client.client import Client
    from kubernetes_tpu.client.informer import InformerFactory
    from kubernetes_tpu.scheduler.scheduler import new_scheduler
    from kubernetes_tpu.testing import make_node, make_pod
    from kubernetes_tpu.utils import flightrecorder

    HOT = ("pop_batch", "pack", "device_solve", "download", "commit")

    server = APIServer()
    client = Client(server)
    informers = InformerFactory(server)
    sched = new_scheduler(client, informers, batch=True, max_batch=256)
    for i in range(num_nodes):
        client.create_node(
            make_node(f"to-node-{i}")
            .capacity(cpu="64", memory="256Gi", pods=2000)
            .obj()
        )
    informers.start()
    informers.wait_for_cache_sync()
    sched.queue.run()
    sched.warmup()
    sched.start()

    def one_burst(tag: str) -> float:
        names = [f"to-{tag}-{i}" for i in range(num_pods)]
        before = dict(sched.stage_seconds)
        t_deadline = time.time() + 120
        for n in names:
            client.create_pod(
                make_pod(n).container(cpu="10m", memory="16Mi").obj()
            )
        outstanding = set(names)
        while outstanding and time.time() < t_deadline:
            pods_now, _ = client.list_pods()
            outstanding -= {
                p.metadata.name for p in pods_now if p.spec.node_name
            }
            if outstanding:
                time.sleep(0.02)
        assert not outstanding, f"burst {tag} did not bind"
        sched.wait_for_inflight_binds()
        after = sched.stage_seconds
        hot = sum(after.get(k, 0.0) - before.get(k, 0.0) for k in HOT)
        # return the cluster to baseline: a burst's bound pods must not
        # make the NEXT arm's stack heavier (the arms would otherwise
        # read cluster fill as recorder overhead)
        for ns, name in [("default", n) for n in names]:
            client.delete_pod(ns, name)
        deadline = time.time() + 30
        while time.time() < deadline:
            pods_now, _ = client.list_pods()
            if not pods_now:
                break
            time.sleep(0.02)
        return hot

    saved = flightrecorder.ENABLED
    on_runs, off_runs = [], []
    spans_before = flightrecorder.RECORDER._next_id
    try:
        one_burst("warm")  # discarded: first burst pays residual warmup
        spans_before = flightrecorder.RECORDER._next_id
        for i, arm in enumerate(("off", "on") * 3):
            flightrecorder.ENABLED = arm == "on"
            hot = one_burst(f"{arm}{i}")
            (on_runs if arm == "on" else off_runs).append(hot)
    finally:
        flightrecorder.ENABLED = saved
        sched.stop()
        informers.stop()

    on_ms = sorted(on_runs)[len(on_runs) // 2] * 1000
    off_ms = sorted(off_runs)[len(off_runs) // 2] * 1000
    spans_per_burst = max(
        1, (flightrecorder.RECORDER._next_id - spans_before) // 3
    )

    # raw op costs on a private recorder (ring appends + tuple lists);
    # min-of-3 loops -- the right estimator for a fixed op cost under
    # scheduler-noise interference
    rec = flightrecorder.FlightRecorder()
    pod_links = [(f"uid-{i}", 0.001, 1) for i in range(256)]
    n_ops = 2000
    span_us = min(
        _time_span_ops(rec, pod_links, HOT, n_ops) for _ in range(3)
    )
    mark_us = min(_time_mark_ops(rec, n_ops * 5) for _ in range(3))

    # deterministic self-time bound: the ops a 1k-pod burst actually
    # performs, costed at the measured per-op rate. The wall-clock A/B
    # above is reported for honesty but on a busy 2-core box its noise
    # floor (+-20-30%) is far above a <1% effect; the self-time share
    # is the number the tier-1 guard asserts on.
    self_ms = (spans_per_burst * span_us + 50 * mark_us) / 1000.0
    return {
        "trace_on_hot_ms": round(on_ms, 1),
        "trace_off_hot_ms": round(off_ms, 1),
        "trace_overhead_wallclock_pct": round(
            (on_ms - off_ms) / off_ms * 100.0, 2
        ) if off_ms > 0 else 0.0,
        "trace_spans_per_burst": spans_per_burst,
        "trace_span_us": round(span_us, 2),
        "trace_mark_us": round(mark_us, 3),
        "trace_selftime_ms": round(self_ms, 3),
        "trace_overhead_selftime_pct": round(
            self_ms / off_ms * 100.0, 3
        ) if off_ms > 0 else 0.0,
    }


def _time_span_ops(rec, pod_links, stages, n_ops: int) -> float:
    """us per full span lifecycle: the 256-entry pod-link list build
    (the per-pod tuple comprehension _dispatch_solve pays), begin (ring
    append), 5 stage stamps, finish."""
    uids = [u for u, _, _ in pod_links]
    t0 = time.perf_counter()
    for _ in range(n_ops):
        links = [(u, 0.001, 1) for u in uids]
        span = rec.begin_batch(256, pods=links)
        for st in stages:
            span.stage(st, 0.001)
        span.finish(tier="xla")
    return (time.perf_counter() - t0) / n_ops * 1e6


def _time_mark_ops(rec, n_ops: int) -> float:
    t0 = time.perf_counter()
    for _ in range(n_ops):
        rec.mark("fallback", tier="xla", reason="bench")
    return (time.perf_counter() - t0) / n_ops * 1e6


def bench_speculative(num_nodes: int = 5000, num_pods: int = 2000):
    """ISSUE-18 satellite: steady-state overlap microbench. Three full-
    stack arms over identical seeded bursts at ``num_nodes`` nodes:

    - serial: the RETIRED pre-pipeline path (every batch drains
      solve -> download -> commit before the next solve launches);
    - pipelined: the production path (committer thread overlapped with
      the next batch's speculative solve against the shadow-expected
      carry);
    - conflict sprinkle: the pipelined path under seeded BIND_CONFLICT
      faults -- reports how many speculative links the divergences
      rewound (the cheap row-patch re-solve, not a drain).

    Plus the carry-compression link/HBM payload at this node scale:
    the int32 resident carry vs the packed-int16 'h' piece, for the
    cold full upload and the steady DELTA_ROW_BUCKET slot."""
    import random as _random
    import time as _time

    from kubernetes_tpu.apiserver.server import APIServer
    from kubernetes_tpu.client.client import Client
    from kubernetes_tpu.client.informer import InformerFactory
    from kubernetes_tpu.robustness.faults import (
        FaultInjector,
        FaultPoint,
        FaultProfile,
        PointConfig,
        install_injector,
    )
    from kubernetes_tpu.scheduler.batch import DELTA_ROW_BUCKET
    from kubernetes_tpu.scheduler.scheduler import new_scheduler
    from kubernetes_tpu.testing import make_node, make_pod

    def run_arm(serial: bool, conflicts: bool):
        server = APIServer()
        client = Client(server)
        informers = InformerFactory(server)
        sched = new_scheduler(
            client, informers, batch=True, max_batch=256,
        )
        if serial:
            # the retired serial pipeline: same solver, no committer
            # thread, no speculation
            sched._solve_pipelined = sched._solve_and_commit
        for i in range(num_nodes):
            client.create_node(
                make_node(f"sp{i}")
                .capacity(cpu="64", memory="256Gi", pods=500)
                .obj()
            )
        informers.start()
        informers.wait_for_cache_sync()
        sched.queue.run()
        sched.warmup()  # compiles off the measured clock
        if conflicts:
            install_injector(FaultInjector(FaultProfile(
                "bench-spec-conflicts", seed=0,
                points={
                    FaultPoint.BIND_CONFLICT: PointConfig(
                        rate=0.02, max_fires=8
                    ),
                },
            )))
        rng = _random.Random(18)
        pods = [
            make_pod(f"sb-{i}")
            .creation_timestamp(float(i))
            .container(
                cpu=f"{rng.choice([100, 200, 250])}m",
                memory=f"{rng.choice([128, 256])}Mi",
            )
            .obj()
            for i in range(num_pods)
        ]
        sched.start()
        t0 = _time.perf_counter()
        for lo in range(0, num_pods, 256):
            client.create_pods_bulk(pods[lo:lo + 256])
        deadline = _time.time() + 300
        while _time.time() < deadline:
            ps, _ = client.list_pods()
            if sum(1 for p in ps if p.spec.node_name) >= num_pods:
                break
            _time.sleep(0.005)
        elapsed_ms = (_time.perf_counter() - t0) * 1000.0
        sched.wait_for_inflight_binds()
        launches = sched.speculative_launches
        rewinds = sched.speculative_rewinds
        install_injector(None)
        sched.stop()
        informers.stop()
        return elapsed_ms, launches, rewinds

    serial_ms, _, _ = run_arm(serial=True, conflicts=False)
    pipe_ms, launches, _ = run_arm(serial=False, conflicts=False)
    _, c_launches, c_rewinds = run_arm(serial=False, conflicts=True)

    # carry payloads: what the serving link ships (and HBM holds) per
    # variant. int16 packs two values per int32 word ('h' piece), so
    # the byte count is exactly half at even sizes
    from kubernetes_tpu.tensors.node_tensor import ResourceDims

    r = ResourceDims().num_dims
    full_i32 = num_nodes * (r + 2) * 4
    full_i16 = num_nodes * (r + 2) * 2
    delta_i32 = DELTA_ROW_BUCKET * (r + 2) * 4
    delta_i16 = DELTA_ROW_BUCKET * (r + 2) * 2
    return {
        "spec_serial_ms": serial_ms,
        "spec_pipelined_ms": pipe_ms,
        "spec_overlap_x": serial_ms / pipe_ms if pipe_ms else 0.0,
        "spec_launches": int(launches),
        "spec_conflict_launches": int(c_launches),
        "spec_conflict_rewinds": int(c_rewinds),
        "spec_conflict_rewind_rate": (
            c_rewinds / c_launches if c_launches else 0.0
        ),
        "carry_full_bytes_i32": full_i32,
        "carry_full_bytes_i16": full_i16,
        "carry_delta_bytes_i32": delta_i32,
        "carry_delta_bytes_i16": delta_i16,
        "carry_link_ratio_x": full_i32 / full_i16,
    }


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "which", nargs="?", default=None,
        choices=(None, "bench_speculative"),
        help="run ONLY the named bench and print its record "
             "(default: the full microbench suite)",
    )
    ap.add_argument("--pods", type=int, default=10000)
    ap.add_argument("--nodes", type=int, default=5000)
    ap.add_argument(
        "--batch", type=int, default=4096,
        help="pop_batch size for the queue drain (bench.py default)",
    )
    ap.add_argument(
        "--mesh-devices", type=int, default=0,
        help="node-axis mesh size for the mesh delta microbench. "
             "Default 0 = use the devices the process already has "
             "(mesh of 1 on a plain CPU box). An EXPLICIT N > 1 on a "
             "CPU box force-splits the host platform into N virtual "
             "devices -- which changes the jax backend under EVERY "
             "microbench in this process, so the historical series "
             "for the single-device numbers only compares against "
             "runs with the same flag",
    )
    ap.add_argument(
        "--mesh-nodes", type=int, default=20000,
        help="node count for the mesh delta microbench",
    )
    args = ap.parse_args()

    # must land before the first jax import below (the kubernetes_tpu
    # imports inside the bench functions pull jax in); opt-in only --
    # see the --mesh-devices help text
    if args.mesh_devices > 1 and (
        "xla_force_host_platform_device_count"
        not in os.environ.get("XLA_FLAGS", "")
    ):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.mesh_devices}"
        ).strip()

    if args.which == "bench_speculative":
        spec = bench_speculative(args.nodes)
        record = {"metric": "bench_speculative", "nodes": args.nodes}
        record.update({
            k: (v if isinstance(v, int) else round(v, 3))
            for k, v in spec.items()
        })
        print(json.dumps(record))
        return

    from kubernetes_tpu.testing import make_pod

    pods = [
        make_pod(f"hp-{i}")
        .container(cpu="250m", memory="512Mi")
        .priority(i % 3)
        .obj()
        for i in range(args.pods)
    ]
    node_names = [f"node-{i}" for i in range(args.nodes)]

    drain_ms, drain_perpod_ms = bench_queue_drain(pods, args.batch)
    band_drain_ms = bench_band_drain(pods, args.batch)
    controller_step_us = bench_controller_step()
    arrivals_gen_ms, arrivals_n = bench_arrivals_gen()
    pack_ms = bench_pack(pods)
    gather_ms, assume_ms = bench_commit(pods, node_names)
    node_state = bench_node_state(args.nodes)
    member = bench_membership_churn(args.nodes)
    mesh_delta = bench_mesh_delta(args.mesh_nodes, args.mesh_devices)
    mesh_pallas = bench_mesh_pallas(args.mesh_nodes, args.mesh_devices)
    preempt = bench_preemption_wave(args.nodes)
    fanout = bench_watch_fanout()
    hb_fanout = bench_heartbeat_fanout()
    tenant = bench_tenant_columns()
    ingest = bench_ingest()
    trace_overhead = bench_trace_overhead()
    bisect = {}
    for b in (256, 1024):
        bisect.update(bench_bisect(b))

    record = {
        "metric": "hotpath_microbench",
        "pods": args.pods,
        "nodes": args.nodes,
        "queue_drain_ms": round(drain_ms, 2),
        "queue_drain_perpod_ms": round(drain_perpod_ms, 2),
        # streaming subsystem (PR 7): band-aware drain vs flat drain,
        # controller decision cost, trace generation for scale
        "queue_drain_band_ms": round(band_drain_ms, 2),
        "controller_step_us": round(controller_step_us, 3),
        "arrivals_gen_ms": round(arrivals_gen_ms, 2),
        "arrivals_gen_count": arrivals_n,
        "pack_ms": round(pack_ms, 2),
        "commit_gather_ms": round(gather_ms, 2),
        "commit_assume_ms": round(assume_ms, 2),
    }
    record.update({k: round(v, 3) for k, v in node_state.items()})
    record.update(
        {
            k: (v if isinstance(v, int) else round(v, 3))
            for k, v in member.items()
        }
    )
    record.update(
        {
            k: (v if isinstance(v, int) else round(v, 3))
            for k, v in mesh_delta.items()
        }
    )
    record.update(
        {
            k: (v if isinstance(v, (int, bool)) else round(v, 3))
            for k, v in mesh_pallas.items()
        }
    )
    record.update(
        {
            k: (
                v if v is None or isinstance(v, int) else round(v, 3)
            )
            for k, v in preempt.items()
        }
    )
    record.update({k: round(v, 2) for k, v in fanout.items()})
    record.update(
        {
            k: (v if isinstance(v, int) else round(v, 2))
            for k, v in hb_fanout.items()
        }
    )
    record.update({k: round(v, 3) for k, v in tenant.items()})
    record.update(
        {
            k: (v if isinstance(v, (int, bool)) else round(v, 3))
            for k, v in ingest.items()
        }
    )
    record.update(trace_overhead)
    record.update(
        {
            k: (v if isinstance(v, int) else round(v, 2))
            for k, v in bisect.items()
        }
    )
    record.update(
        {
            k: (v if isinstance(v, int) else round(v, 3))
            for k, v in bench_speculative(args.nodes).items()
        }
    )
    print(json.dumps(record))


if __name__ == "__main__":
    main()
