"""Hollow kubelet (layer 7): bindings are acknowledged, pods report
Running, and nodes heartbeat Lease + Ready condition.

Reference: pkg/kubemark/hollow_kubelet.go:64 + kubelet.go:885.
"""

import time

from kubernetes_tpu.api.types import POD_RUNNING
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client.client import Client
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.kubelet import HollowKubelet, HollowNodePool
from kubernetes_tpu.kubelet.hollow import LEASE_NAMESPACE
from kubernetes_tpu.scheduler.scheduler import new_scheduler
from kubernetes_tpu.testing import make_node, make_pod


def test_bound_pod_acked_running():
    server = APIServer()
    client = Client(server)
    client.create_node(make_node("n").capacity(cpu="4", memory="8Gi").obj())
    client.create_pod(make_pod("p").node("n").container(cpu="1").obj())
    kubelet = HollowKubelet(client, "n")
    assert kubelet.sync_once() == 1
    pod = client.get_pod("default", "p")
    assert pod.status.phase == POD_RUNNING
    assert pod.status.start_time is not None
    # idempotent
    assert kubelet.sync_once() == 0


def test_heartbeat_lease_and_ready_condition():
    server = APIServer()
    client = Client(server)
    client.create_node(make_node("n").capacity(cpu="4", memory="8Gi").obj())
    kubelet = HollowKubelet(client, "n")
    kubelet.heartbeat_once()
    lease = server.get("Lease", LEASE_NAMESPACE, "n")
    first_renew = lease.renew_time
    assert lease.holder_identity == "n"
    node = client.get_node("n")
    assert any(
        c.type == "Ready" and c.status == "True"
        for c in node.status.conditions
    )
    time.sleep(0.01)
    kubelet.heartbeat_once()
    lease = server.get("Lease", LEASE_NAMESPACE, "n")
    assert lease.renew_time > first_renew


def test_pool_end_to_end_with_scheduler():
    """Full control loop: create -> schedule -> bind -> hollow kubelet
    observes -> Running (SURVEY section 1 control flow)."""
    server = APIServer()
    client = Client(server)
    informers = InformerFactory(server)
    sched = new_scheduler(client, informers, batch=True, max_batch=16)
    names = [f"n{i}" for i in range(4)]
    for n in names:
        client.create_node(
            make_node(n).capacity(cpu="4", memory="8Gi").obj()
        )
    pool = HollowNodePool(client, names, heartbeat_interval=0.2)
    informers.start()
    informers.wait_for_cache_sync()
    sched.queue.run()
    pool.start()
    for i in range(12):
        client.create_pod(
            make_pod(f"p{i}").container(cpu="500m", memory="256Mi").obj()
        )
    sched.start()
    deadline = time.time() + 30
    running = 0
    while time.time() < deadline:
        pods, _ = client.list_pods()
        running = sum(1 for p in pods if p.status.phase == POD_RUNNING)
        if running == 12:
            break
        time.sleep(0.05)
    sched.stop()
    pool.stop()
    informers.stop()
    assert running == 12
    assert pool.pods_started >= 12
    # every node heartbeated a lease
    leases, _ = server.list("Lease")
    assert {le.metadata.name for le in leases} >= set(names)
