"""Device preemption (stage 7): randomized differential tests of the
vectorized victim search (ops/preemption.py) against the host oracle
(scheduler/preemption.py select_victims_on_node +
pick_one_node_for_preemption), plus the batch path's per-node reason
codes and an end-to-end preemption run through the BatchScheduler.

Reference: generic_scheduler.go:850 selectNodesForPreemption,
:940 selectVictimsOnNode, :721 pickOneNodeForPreemption,
:884 filterPodsWithPDBViolation, :1033 nodesWherePreemptionMightHelp.
"""

import random
import time

import pytest

from kubernetes_tpu.api.types import LabelSelector, PodDisruptionBudget
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.cache.cache import SchedulerCache
from kubernetes_tpu.cache.snapshot import Snapshot
from kubernetes_tpu.client.client import Client
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.framework.interface import CycleState, FitError, StatusCode
from kubernetes_tpu.framework.runtime import Framework
from kubernetes_tpu.plugins import new_in_tree_registry
from kubernetes_tpu.scheduler.generic import GenericScheduler
from kubernetes_tpu.scheduler.preemption import (
    Preemptor,
    Victims,
    pick_one_node_for_preemption,
)
from kubernetes_tpu.scheduler.provider import default_plugins
from kubernetes_tpu.scheduler.scheduler import new_scheduler
from kubernetes_tpu.testing import make_node, make_pod


def _env(pods, nodes):
    cache = SchedulerCache()
    for n in nodes:
        cache.add_node(n)
    for p in pods:
        cache.add_pod(p)
    snapshot = Snapshot()
    cache.update_snapshot(snapshot)
    algorithm = GenericScheduler(cache, snapshot)
    fw = Framework(
        new_in_tree_registry(),
        default_plugins(),
        snapshot_provider=lambda: snapshot,
    )
    return algorithm, fw


def _fail(algorithm, fw, pod):
    state = CycleState()
    with pytest.raises(FitError) as exc:
        algorithm.schedule(fw, state, pod)
    return state, exc.value


def _random_cluster(rng, with_pdbs):
    nodes = []
    for i in range(16):
        w = make_node(f"n{i}").capacity(
            cpu=str(rng.choice([2, 4, 8])), memory="16Gi", pods=32
        )
        if rng.random() < 0.2:
            w.label("disk", "ssd")
        if rng.random() < 0.15:
            w.taint("dedicated", "infra")
        nodes.append(w.obj())
    pods = []
    t0 = time.time() - 10_000
    # near-fill every node so the preemptor always needs victims
    for i, n in enumerate(nodes):
        cap_milli = n.status.allocatable["cpu"]
        p = (
            make_pod(f"fill{i}")
            .node(n.metadata.name)
            .container(cpu=f"{cap_milli - 1000}m", memory="8Gi")
            .labels(app=rng.choice(["a", "b", "c"]))
            .priority(rng.choice([0, 5]))
            .obj()
        )
        p.status.start_time = t0 + rng.randrange(10_000)
        pods.append(p)
    for j in range(40):
        node = f"n{rng.randrange(16)}"
        p = (
            make_pod(f"p{j}")
            .node(node)
            .container(
                cpu=f"{rng.choice([250, 500, 1000, 2000])}m",
                memory=f"{rng.choice([128, 512, 1024])}Mi",
            )
            .labels(app=rng.choice(["a", "b", "c"]))
            .priority(rng.choice([0, 0, 5, 10, 50]))
            .obj()
        )
        p.status.start_time = t0 + rng.randrange(10_000)
        pods.append(p)
    pdbs = []
    if with_pdbs:
        for app, budget in (("a", 1), ("b", 0)):
            pdbs.append(
                PodDisruptionBudget(
                    selector=LabelSelector(match_labels={"app": app}),
                )
            )
            pdbs[-1].status.disruptions_allowed = budget
            pdbs[-1].metadata.name = f"pdb-{app}"
            pdbs[-1].metadata.namespace = "default"
    return nodes, pods, pdbs


def _host_answer(preemptor, prof, state, pod, fit_err, pdbs):
    """The oracle: per-node select_victims + 6-rule pick."""
    potential = preemptor.nodes_where_preemption_might_help(fit_err)
    nodes_to_victims = {}
    for ni in potential:
        victims, num_violating, fits = preemptor.select_victims_on_node(
            prof, state, pod, ni, pdbs
        )
        if fits:
            nodes_to_victims[ni.node_name] = Victims(victims, num_violating)
    node = pick_one_node_for_preemption(nodes_to_victims)
    if node is None:
        return "", set()
    return node, {p.metadata.name for p in nodes_to_victims[node].pods}


@pytest.mark.parametrize("seed", range(6))
@pytest.mark.parametrize("with_pdbs", [False, True])
def test_device_matches_host_oracle(seed, with_pdbs):
    rng = random.Random(seed)
    nodes, pods, pdbs = _random_cluster(rng, with_pdbs)
    algorithm, fw = _env(pods, nodes)
    preemptor = Preemptor(algorithm, None, None)

    # a preemptor big enough to need victims somewhere
    preemptor_pod = (
        make_pod("preemptor")
        .container(cpu="2", memory="4Gi")
        .priority(100)
        .obj()
    )
    if rng.random() < 0.5:
        preemptor_pod.spec.node_selector["disk"] = "ssd"
    state, fit_err = _fail(algorithm, fw, preemptor_pod)

    assert preemptor.device_eligible(fw, preemptor_pod)
    dev, tier = preemptor._find_preemption_device(
        preemptor_pod,
        preemptor.nodes_where_preemption_might_help(fit_err),
        pdbs,
    )
    assert dev is not None and tier in ("pallas", "xla")
    dev_node, dev_victims, _ = dev
    host_node, host_victims = _host_answer(
        preemptor, fw, state, preemptor_pod, fit_err, pdbs
    )
    assert dev_node == host_node
    assert {p.metadata.name for p in dev_victims} == host_victims


def test_pdb_budget_ordering_matches_oracle():
    """Victims protected by an exhausted PDB go violating-first through
    reprieve, matching filterPodsWithPDBViolation + the reprieve order."""
    rng = random.Random(99)
    nodes, pods, pdbs = _random_cluster(rng, True)
    # park every pod on one node so PDB budgets really contend
    for p in pods[:20]:
        p.spec.node_name = "n0"
    nodes[0].status.allocatable["cpu"] = 64000
    nodes[0].status.capacity["cpu"] = 64000
    nodes[0].status.allocatable["memory"] = 128 * 1024**3
    algorithm, fw = _env(pods, nodes)
    preemptor = Preemptor(algorithm, None, None)
    preemptor_pod = (
        make_pod("preemptor").container(cpu="60", memory="100Gi")
        .priority(100).obj()
    )
    state, fit_err = _fail(algorithm, fw, preemptor_pod)
    dev, _tier = preemptor._find_preemption_device(
        preemptor_pod,
        preemptor.nodes_where_preemption_might_help(fit_err),
        pdbs,
    )
    host_node, host_victims = _host_answer(
        preemptor, fw, state, preemptor_pod, fit_err, pdbs
    )
    assert dev is not None
    assert dev[0] == host_node
    assert {p.metadata.name for p in dev[1]} == host_victims


def test_batch_path_emits_static_mask_reason_codes():
    """A device-solved NO_NODE pod's FitError carries
    UnschedulableAndUnresolvable for statically masked nodes
    (generic_scheduler.go:1033 pruning input)."""
    server = APIServer()
    client = Client(server)
    informers = InformerFactory(server)
    sched = new_scheduler(client, informers, batch=True, max_batch=16)
    client.create_node(
        make_node("match").capacity(cpu="1", memory="1Gi").label("disk", "ssd").obj()
    )
    client.create_node(
        make_node("nomatch").capacity(cpu="8", memory="16Gi").obj()
    )
    informers.start()
    informers.wait_for_cache_sync()
    sched.queue.run()

    captured = []
    orig = sched.handle_fit_error

    def capture(prof, state, pi, fit_err, cycle):
        captured.append(fit_err)
        return orig(prof, state, pi, fit_err, cycle)

    sched.handle_fit_error = capture
    orig_pb = sched.preemptor.preempt_batch

    def capture_pb(prof, items):
        captured.extend(fe for _, fe in items)
        return orig_pb(prof, items)

    sched.preemptor.preempt_batch = capture_pb
    # fits only on the labeled node by selector, but is too big for it
    client.create_pod(
        make_pod("p").container(cpu="4").node_selector(disk="ssd").obj()
    )
    deadline = time.time() + 10
    while not captured and time.time() < deadline:
        sched.schedule_batch(timeout=0.2)
    sched.stop()
    informers.stop()
    assert captured, "pod never hit the fit-error path"
    statuses = captured[0].filtered_nodes_statuses
    assert (
        statuses["nomatch"].code == StatusCode.UNSCHEDULABLE_AND_UNRESOLVABLE
    )
    assert "match" not in statuses  # resource misfit: preemption may help


def test_batch_preemption_end_to_end_device():
    """Full-cluster preemption through the BatchScheduler: high-priority
    burst evicts low-priority pods via the DEVICE victim search and
    eventually binds."""
    server = APIServer()
    client = Client(server)
    informers = InformerFactory(server)
    sched = new_scheduler(client, informers, batch=True, max_batch=16)
    for i in range(4):
        client.create_node(
            make_node(f"n{i}").capacity(cpu="4", memory="8Gi", pods=10).obj()
        )
    informers.start()
    informers.wait_for_cache_sync()
    sched.queue.run()
    # fill the cluster completely with low-priority pods
    for i in range(8):
        client.create_pod(
            make_pod(f"low{i}").container(cpu="2", memory="2Gi")
            .priority(0).obj()
        )
    t = sched.start()
    deadline = time.time() + 30
    while time.time() < deadline:
        pods, _ = client.list_pods()
        if sum(1 for p in pods if p.spec.node_name) >= 8:
            break
        time.sleep(0.05)
    # high-priority pod must preempt
    client.create_pod(
        make_pod("high").container(cpu="3", memory="3Gi").priority(100).obj()
    )
    deadline = time.time() + 30
    bound = False
    while time.time() < deadline:
        try:
            p = client.get_pod("default", "high")
        except KeyError:
            break
        if p.spec.node_name:
            bound = True
            break
        time.sleep(0.05)
    sched.stop()
    informers.stop()
    assert bound, "high-priority pod never bound after preemption"
    assert sched.preemptor.device_preemptions >= 1
    assert sched.preemptor.host_preemptions == 0


def test_host_port_preemptor_takes_host_oracle():
    """A host-port pod whose only remedy is evicting the current port
    holder must preempt via the HOST oracle: the device victim search's
    candidate mask bakes existing port conflicts in and cannot model
    ports freed by eviction (the reference re-runs NodePorts with
    victims removed, generic_scheduler.go:940)."""
    server = APIServer()
    client = Client(server)
    informers = InformerFactory(server)
    sched = new_scheduler(client, informers, batch=True, max_batch=16)
    client.create_node(
        make_node("only").capacity(cpu="8", memory="16Gi", pods=10).obj()
    )
    informers.start()
    informers.wait_for_cache_sync()
    sched.queue.run()
    client.create_pod(
        make_pod("holder").priority(0)
        .container(cpu="100m", memory="64Mi", host_port=8080).obj()
    )
    sched.start()
    deadline = time.time() + 30
    while time.time() < deadline:
        try:
            if client.get_pod("default", "holder").spec.node_name:
                break
        except KeyError:
            pass
        time.sleep(0.05)
    sched.wait_for_inflight_binds()
    client.create_pod(
        make_pod("vip").priority(1000)
        .container(cpu="100m", memory="64Mi", host_port=8080).obj()
    )
    deadline = time.time() + 30
    ok = False
    while time.time() < deadline:
        try:
            vip = client.get_pod("default", "vip")
        except KeyError:
            time.sleep(0.05)
            continue
        try:
            client.get_pod("default", "holder")
            holder_gone = False
        except KeyError:
            holder_gone = True
        if vip.spec.node_name == "only" and holder_gone:
            ok = True
            break
        time.sleep(0.05)
    sched.stop()
    informers.stop()
    assert ok, "vip never preempted the host-port holder"
    assert sched.preemptor.host_preemptions >= 1
