"""Differential parity: the native commit spine vs the Python fallback.

native/_hotpath.c assume_clones + bind_assumed_bulk are the C forms of
Pod.assumed_clone (api/types.py) and the bind_bulk transaction loop
(apiserver/server.py _bind_locked); these tests run the same inputs
through both implementations and require identical outcomes (slots,
error types, store state, watch events, sharing structure).
"""

from __future__ import annotations

import pytest

from kubernetes_tpu.api.types import Binding
from kubernetes_tpu.apiserver import server as server_mod
from kubernetes_tpu.apiserver.server import APIServer, Conflict, NotFound
from kubernetes_tpu.testing import make_pod

native = pytest.importorskip("kubernetes_tpu.native")
if native.hotpath is None:  # pragma: no cover - build failure environment
    pytest.skip("native module unavailable", allow_module_level=True)


def _mk_pods(n, prefix="p"):
    return [
        make_pod(f"{prefix}-{i}").container(cpu="100m", memory="128Mi").obj()
        for i in range(n)
    ]


# -- assume_clones vs Pod.assumed_clone ------------------------------------


def test_assume_clones_matches_assumed_clone_structure():
    pods = _mk_pods(4)
    hosts = [f"node-{i}" for i in range(4)]
    clones = native.assume_clones(pods, hosts)
    for pod, host, clone in zip(pods, hosts, clones):
        ref = pod.assumed_clone()
        ref.spec.node_name = host
        # same mutation result
        assert clone.spec.node_name == host
        assert pod.spec.node_name == ""  # original untouched
        # same sharing structure: fresh pod + fresh spec, shared rest
        assert clone is not pod and clone.spec is not pod.spec
        assert clone.metadata is pod.metadata
        assert clone.status is pod.status
        assert clone.spec.containers is pod.spec.containers
        assert ref.metadata is pod.metadata  # fallback agrees
        assert clone.kind == "Pod"


def test_assume_clones_inherits_memos():
    from kubernetes_tpu.cache.node_info import pod_hot_info

    pods = _mk_pods(2, "m")
    memo = pod_hot_info(pods[0])
    clones = native.assume_clones(pods, ["n1", "n2"])
    assert clones[0].__dict__.get("_hot_memo") == memo
    assert "_hot_memo" not in clones[1].__dict__


# -- bind_assumed_bulk: native vs fallback ---------------------------------


def _run_bind_scenario(use_native):
    """One mixed scenario through either implementation; returns
    (errors, store_pods, events) for comparison."""
    server = APIServer()
    pods = _mk_pods(6, "b")
    server.create_bulk(pods)
    # slot 2: already bound to another node; slot 5: bound to the SAME
    # node (idempotent re-bind succeeds, _bind_locked semantics)
    server.bind(
        Binding(
            pod_namespace="default", pod_name="b-2",
            pod_uid=pods[2].metadata.uid, target_node="elsewhere",
        )
    )
    server.bind(
        Binding(
            pod_namespace="default", pod_name="b-5",
            pod_uid=pods[5].metadata.uid, target_node="node-5",
        )
    )
    watch = server.watch("Pod", since_rv=server.current_rv())

    assumed = native.assume_clones(
        [server.get("Pod", "default", f"b-{i}") for i in range(6)],
        [f"node-{i}" for i in range(6)],
    )
    # slot 1: uid mismatch; slot 3: missing pod; slot 4: empty target
    assumed[1].metadata = pods[1].metadata.__class__(
        name="b-1", namespace="default", uid="wrong-uid"
    )
    gone = make_pod("gone").container(cpu="1m", memory="1Mi").obj()
    assumed[3] = native.assume_clones([gone], ["node-3"])[0]
    assumed[4].spec.node_name = ""

    if use_native:
        errors = server.bind_assumed_bulk(assumed)
    else:
        orig = server_mod._bind_assumed_bulk
        server_mod._bind_assumed_bulk = None
        try:
            errors = server.bind_assumed_bulk(assumed)
        finally:
            server_mod._bind_assumed_bulk = orig
    store = {
        name: server.get("Pod", "default", name).spec.node_name
        for name in [f"b-{i}" for i in range(6)]
    }
    events = watch.pending()
    return errors, store, events


def test_bind_assumed_bulk_native_matches_fallback():
    n_err, n_store, n_events = _run_bind_scenario(use_native=True)
    f_err, f_store, f_events = _run_bind_scenario(use_native=False)

    # slot 1 uid mismatch, slot 2 rebind-to-other-node, slot 3 missing,
    # slot 4 empty target; slots 0 and 5 bind
    assert [i for i, _ in n_err] == [i for i, _ in f_err] == [1, 2, 3, 4]
    for (ni, ne), (fi, fe) in zip(n_err, f_err):
        assert type(ne) is type(fe), (ne, fe)
    assert isinstance(n_err[0][1], Conflict)
    assert isinstance(n_err[1][1], Conflict)
    assert isinstance(n_err[2][1], NotFound)
    assert isinstance(n_err[3][1], ValueError)

    assert n_store == f_store
    assert n_store["b-0"] == "node-0"
    assert n_store["b-2"] == "elsewhere"  # conflict slot untouched
    assert n_store["b-4"] == ""  # empty-target slot untouched
    assert n_store["b-5"] == "node-5"  # idempotent re-bind

    # same event stream shape: MODIFIED for each success, rv ascending;
    # the slot-5 same-node re-bind is idempotent success WITHOUT a
    # duplicate event (no write, no rv bump) -- only slot 0 emits
    assert len(n_events) == len(f_events) == 1
    assert all(ev.type == "MODIFIED" for ev in n_events)
    rvs = [ev.resource_version for ev in n_events]
    assert rvs == sorted(rvs)
    assert [ev.object.metadata.name for ev in n_events] == [
        ev.object.metadata.name for ev in f_events
    ]


def test_bind_assumed_bulk_cow_and_memo_semantics():
    server = APIServer()
    pods = _mk_pods(2, "c")
    server.create_bulk(pods)
    stored_before = server.get("Pod", "default", "c-0")
    stored_before.__dict__["_sig_memo"] = ("stale",)
    assumed = native.assume_clones(pods, ["n-0", "n-1"])
    assert server.bind_assumed_bulk(assumed) == []
    stored_after = server.get("Pod", "default", "c-0")
    # fresh pod object with fresh metadata (new rv) + fresh spec
    assert stored_after is not stored_before
    assert stored_after.metadata is not stored_before.metadata
    assert stored_after.spec is not stored_before.spec
    assert (
        stored_after.metadata.resource_version
        > stored_before.metadata.resource_version
    )
    # status may be shared (read-only contract); the sig memo computed
    # against the unbound spec must not ride along
    assert "_sig_memo" not in stored_after.__dict__
    # the old stored object is untouched (informer (old, new) contract)
    assert stored_before.spec.node_name == ""


def test_bind_assumed_bulk_rv_matches_store_counter():
    server = APIServer()
    pods = _mk_pods(3, "r")
    server.create_bulk(pods)
    assumed = native.assume_clones(pods, ["x", "y", "z"])
    assert server.bind_assumed_bulk(assumed) == []
    assert (
        server.get("Pod", "default", "r-2").metadata.resource_version
        == server.current_rv()
    )
    # a follow-up write continues the monotonic sequence
    more = _mk_pods(1, "rr")
    server.create_bulk(more)
    assert more[0].metadata.resource_version == server.current_rv()


# -- commit_gather vs the Python fallback ----------------------------------


def _gather_inputs(n, nodes, seed=0):
    import random

    from kubernetes_tpu.framework.interface import PodInfo

    rng = random.Random(seed)
    infos = [
        PodInfo(p, float(i)) for i, p in enumerate(_mk_pods(n, "g"))
    ]
    names = [f"node-{i}" for i in range(nodes)]
    order = list(range(n))
    rng.shuffle(order)
    assigns = [rng.randrange(nodes) for _ in range(n)]
    return infos, order, assigns, names


def test_commit_gather_matches_python_fallback():
    from kubernetes_tpu.scheduler.batch import _commit_gather_py

    infos, order, assigns, names = _gather_inputs(32, 7, seed=3)
    n_pis, n_clones, n_hosts = native.commit_gather(
        infos, order, assigns, names
    )
    p_pis, p_clones, p_hosts = _commit_gather_py(
        infos, order, assigns, names
    )
    assert n_hosts == p_hosts
    assert [pi.pod.metadata.name for pi in n_pis] == [
        pi.pod.metadata.name for pi in p_pis
    ]
    for nc, pc, host in zip(n_clones, p_clones, n_hosts):
        assert nc.spec.node_name == host == pc.spec.node_name
        assert nc.metadata is pc.metadata  # both share the original's
        # fresh pod + fresh spec, everything else shared (the
        # assumed_clone sharing contract)
        assert nc.spec.containers is pc.spec.containers
        assert nc.status is pc.status


def test_commit_gather_leaves_originals_untouched():
    infos, order, assigns, names = _gather_inputs(8, 3, seed=5)
    native.commit_gather(infos, order, assigns, names)
    for pi in infos:
        assert pi.pod.spec.node_name == ""


def test_commit_gather_rejects_out_of_range():
    infos, order, assigns, names = _gather_inputs(4, 2, seed=1)
    with pytest.raises(IndexError):
        native.commit_gather(infos, [0, 1, 99, 3], assigns, names)
    with pytest.raises(IndexError):
        native.commit_gather(infos, order, [0, 1, 0, 99], names)
    with pytest.raises(ValueError):
        native.commit_gather(infos, order[:2], assigns, names)
