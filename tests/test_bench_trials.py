"""Unit tests for bench.py's trial protocol helpers (the noise-robust
median headline; see the bench module docstring)."""

import importlib.util
import os

_BENCH_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "bench.py"
)
_spec = importlib.util.spec_from_file_location("bench_module", _BENCH_PATH)
bench = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(bench)


def _trial(n, pps, p99):
    return {
        "trial": n, "pods_per_sec": pps, "p99_pod_to_bind_ms": p99,
    }


def test_median_odd_count():
    trials = [_trial(1, 100.0, 50), _trial(2, 300.0, 20), _trial(3, 200.0, 30)]
    assert bench.pick_median_trial(trials)["trial"] == 3


def test_median_even_count_picks_conservative_middle():
    trials = [
        _trial(1, 100.0, 50), _trial(2, 400.0, 10),
        _trial(3, 200.0, 30), _trial(4, 300.0, 20),
    ]
    # lower middle of the throughput ranking: 200 pods/s
    assert bench.pick_median_trial(trials)["trial"] == 3


def test_median_single_trial():
    trials = [_trial(1, 123.0, 45)]
    assert bench.pick_median_trial(trials) is trials[0]


def test_noisy_outlier_cannot_move_headline():
    """The satellite's point: one noisy capture (slow trial, huge p99)
    must not become the recorded number."""
    trials = [
        _trial(1, 24000.0, 400.0),
        _trial(2, 5000.0, 900.0),  # driver hiccup
        _trial(3, 24500.0, 390.0),
    ]
    med = bench.pick_median_trial(trials)
    assert med["trial"] == 1
    assert med["p99_pod_to_bind_ms"] < 500


def test_trials_flag_defaults():
    import argparse

    ap = argparse.ArgumentParser()
    # mirror of bench.main's registration: default 3 measured trials
    ap.add_argument("--trials", type=int, default=3)
    assert ap.parse_args([]).trials == 3
