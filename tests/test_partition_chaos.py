"""Partition-chaos e2es (PR-8 acceptance): multiple ACTIVE partitioned
scheduler stacks over one apiserver, under stack kills, fence races, and
mid-bind crashes. The bar is the PR-2 bar generalized: every pod bound,
ZERO double-binds per pod INCARNATION asserted against the full
uid-keyed watch history, and the conflict ledger balanced -- every typed
bind conflict is either absorbed-and-requeued or satisfied elsewhere,
never silently dropped."""

import random
import time

import pytest

from kubernetes_tpu.api.types import Lease
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.config.types import (
    KubeSchedulerConfiguration,
    PartitionConfiguration,
)
from kubernetes_tpu.robustness.faults import (
    FaultInjector,
    FaultPoint,
    FaultProfile,
    PointConfig,
    install_injector,
)
from kubernetes_tpu.scheduler.app import SchedulerApp
from kubernetes_tpu.scheduler.partition import partition_of_name
from kubernetes_tpu.testing import make_node, make_pod


@pytest.fixture(autouse=True)
def _clean_injector():
    yield
    install_injector(None)


def _cfg(num_partitions=2, lease=0.6, retry=0.06):
    return KubeSchedulerConfiguration(
        partition=PartitionConfiguration(
            enabled=True,
            num_partitions=num_partitions,
            lease_duration_seconds=lease,
            retry_period_seconds=retry,
        )
    )


def _wait(predicate, timeout, step=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(step)
    return predicate()


def _bound_count(client):
    pods, _ = client.list_pods()
    return sum(1 for p in pods if p.spec.node_name)


def _incarnation_transitions(server):
    """uid-keyed unbound->bound transition counts replayed from the
    FULL watch history: the ground-truth double-bind assertion (a
    deleted+recreated pod is a new incarnation and may bind again)."""
    w = server.watch("Pod", since_rv=0)
    node, transitions = {}, {}
    for ev in w.pending():
        pod = ev.object
        uid = pod.metadata.uid
        cur = pod.spec.node_name or ""
        if ev.type == "DELETED":
            node.pop(uid, None)
            continue
        prev = node.get(uid, "")
        if not prev and cur:
            transitions[uid] = transitions.get(uid, 0) + 1
        node[uid] = cur
    w.stop()
    return transitions


def _assert_ledger_balanced(*scheds):
    """The conflict ledger invariant: every absorbed typed conflict
    landed in exactly one disposition bucket (requeued or satisfied
    elsewhere) -- no silent conflict loss."""
    for sched in scheds:
        assert sched.bind_conflicts_absorbed == (
            sched.conflict_requeues + sched.conflict_stale_binds
        ), (
            sched.bind_conflicts_absorbed,
            sched.conflict_requeues,
            sched.conflict_stale_binds,
        )


def test_mid_burst_stack_kill_neighbors_adopt_and_bind_all():
    """The headline chaos path: two stacks split four partitions; one
    stack's renews die right as a burst lands. The survivor must detect
    the lapsed leases via the map, adopt the orphaned node ranges AND
    the dead stack's in-flight pods, and drain everything -- zero
    double-binds, takeover metered, ledger balanced (the deposed
    stack's in-flight commits fence into absorbed conflicts)."""
    server = APIServer()
    app1 = SchedulerApp(config=_cfg(num_partitions=4), server=server)
    client = app1.client
    for i in range(24):
        client.create_node(
            make_node(f"n{i}").capacity(
                cpu="32", memory="64Gi", pods=110
            ).obj()
        )
    app1.start()
    app2 = SchedulerApp(config=_cfg(num_partitions=4), server=server)
    app2.start()
    assert _wait(
        lambda: len(app1.coordinator.held) == 2
        and len(app2.coordinator.held) == 2,
        10,
    ), "partitions never split 2/2"

    n = 800
    # kill app1's renews FIRST, then land the burst: roughly half the
    # pods' home partitions are orphaned mid-flight
    app1.coordinator.fault_injector = FaultInjector(FaultProfile(
        "stack-kill", seed=0,
        points={FaultPoint.LEASE_RENEW_FAIL: PointConfig(rate=1.0)},
    ))
    for i in range(0, n, 200):
        client.create_pods_bulk([
            make_pod(f"p{j}").container(cpu="100m", memory="128Mi").obj()
            for j in range(i, min(n, i + 200))
        ])

    assert _wait(lambda: _bound_count(client) == n, 120), (
        f"only {_bound_count(client)}/{n} bound after the stack kill"
    )
    assert _wait(lambda: len(app2.coordinator.held) == 4, 30), (
        "survivor never adopted the orphaned partitions"
    )
    assert app2.coordinator.takeovers >= 1
    assert not app1.coordinator.held, "deposed stack still claims ranges"

    app1.sched.wait_for_inflight_binds()
    app2.sched.wait_for_inflight_binds()
    transitions = _incarnation_transitions(server)
    assert len(transitions) == n
    assert all(v == 1 for v in transitions.values()), {
        k: v for k, v in transitions.items() if v != 1
    }
    _assert_ledger_balanced(app1.sched, app2.sched)
    app2.stop()
    app1.stop()


def test_fence_conflicts_absorbed_requeued_and_ledger_balances():
    """Deterministic fence race (the tier-1 conflict-ledger guard): the
    stack BELIEVES it holds both partitions, but partition 1's lease
    was seized by an intruder. Every commit onto a partition-1 node
    must fence into a typed absorbed conflict and requeue -- never
    bind, never drop. Restoring the lease lets the requeued pods bind,
    and the ledger balances exactly."""
    server = APIServer()
    app = SchedulerApp(config=_cfg(num_partitions=2), server=server)
    client = app.client
    part1_nodes = [
        f"n{i}" for i in range(40) if partition_of_name(f"n{i}", 2) == 1
    ][:8]
    part0_nodes = [
        f"n{i}" for i in range(40) if partition_of_name(f"n{i}", 2) == 0
    ][:8]
    for name in part0_nodes + part1_nodes:
        client.create_node(
            make_node(name).capacity(cpu="32", memory="64Gi", pods=110)
            .label("part", str(partition_of_name(name, 2))).obj()
        )
    app.start()
    assert _wait(lambda: sorted(app.coordinator.held) == [0, 1], 10)
    # pause the coordination loop so it cannot notice the seizure and
    # "helpfully" drop partition 1 locally -- this test needs the
    # stale-ownership window held open
    app.coordinator._stop.set()
    app.coordinator._wake.set()
    time.sleep(0.2)

    def seize(obj: Lease) -> None:
        obj.holder_identity = "intruder"
        obj.renew_time = time.monotonic()
        obj.lease_duration_seconds = 30.0

    server.guaranteed_update(
        "Lease", "kube-system", "ksp-partition-1", seize
    )

    n = 24
    for i in range(n):
        client.create_pod(
            make_pod(f"f{i}").container(cpu="100m", memory="128Mi")
            .node_selector(part="1").obj()
        )
    sched = app.sched
    assert _wait(lambda: sched.bind_conflicts_absorbed >= n, 30), (
        f"only {sched.bind_conflicts_absorbed} conflicts absorbed"
    )
    assert _bound_count(client) == 0, "a fenced commit bound anyway"
    _assert_ledger_balanced(sched)
    assert sched.conflict_requeues >= n

    def restore(obj: Lease) -> None:
        obj.holder_identity = app.identity
        obj.renew_time = time.monotonic()
        obj.lease_duration_seconds = 30.0

    server.guaranteed_update(
        "Lease", "kube-system", "ksp-partition-1", restore
    )
    assert _wait(lambda: _bound_count(client) == n, 60), (
        f"only {_bound_count(client)}/{n} bound after the lease returned"
    )
    app.sched.wait_for_inflight_binds()
    transitions = _incarnation_transitions(server)
    assert all(v == 1 for v in transitions.values())
    _assert_ledger_balanced(sched)
    app.stop()


def test_randomized_two_partition_differential_spill_never_drops():
    """Randomized two-partition differential: a mixed population --
    free pods plus pods nodeSelector-PINNED to a random partition's
    nodes (so pods homed to the wrong stack MUST spill) -- under a
    seeded bind-conflict transaction burst. Every pod binds exactly
    once, pinned pods land in their pinned partition, spills happened,
    and the ledger balances: no typed conflict and no spill is ever
    dropped."""
    rng = random.Random(7)
    server = APIServer()
    app1 = SchedulerApp(config=_cfg(num_partitions=2), server=server)
    client = app1.client
    for i in range(16):
        client.create_node(
            make_node(f"n{i}").capacity(cpu="32", memory="64Gi", pods=110)
            .label("part", str(partition_of_name(f"n{i}", 2))).obj()
        )
    app1.start()
    app2 = SchedulerApp(config=_cfg(num_partitions=2), server=server)
    app2.start()
    assert _wait(
        lambda: len(app1.coordinator.held) == 1
        and len(app2.coordinator.held) == 1,
        10,
    )
    install_injector(FaultInjector(FaultProfile(
        "conflict-burst", seed=3,
        points={FaultPoint.BIND_CONFLICT: PointConfig(rate=1.0, max_fires=2)},
    )))

    n = 300
    pinned = {}
    pods = []
    for i in range(n):
        w = make_pod(f"r{i}").container(cpu="100m", memory="128Mi")
        if rng.random() < 0.4:
            part = rng.choice(("0", "1"))
            w.node_selector(part=part)
            pinned[f"r{i}"] = part
        pods.append(w.obj())
    for i in range(0, n, 100):
        client.create_pods_bulk(pods[i:i + 100])

    assert _wait(lambda: _bound_count(client) == n, 120), (
        f"only {_bound_count(client)}/{n} bound"
    )
    app1.sched.wait_for_inflight_binds()
    app2.sched.wait_for_inflight_binds()
    live, _ = client.list_pods()
    for p in live:
        want = pinned.get(p.metadata.name)
        if want is not None:
            got = str(partition_of_name(p.spec.node_name, 2))
            assert got == want, (p.metadata.name, p.spec.node_name)
    transitions = _incarnation_transitions(server)
    assert len(transitions) == n
    assert all(v == 1 for v in transitions.values())
    # roughly half the pinned pods hash to the wrong home stack: spill
    # is the only path that binds them in their pinned partition
    assert app1.sched.pods_spilled + app2.sched.pods_spilled > 0
    _assert_ledger_balanced(app1.sched, app2.sched)
    app2.stop()
    app1.stop()


def test_mid_bind_crash_adoption_rebinds_exactly_once():
    """A stack dies BETWEEN assume and bind (the injected crash leaves
    pods assumed-but-unbound with no cleanup -- still pending at the
    apiserver). Its partition leases lapse unreleased; the survivor
    adopts the orphaned ranges, requeues the stranded in-flight pods,
    and re-binds each EXACTLY once against the full watch history."""
    server = APIServer()
    app1 = SchedulerApp(config=_cfg(num_partitions=2), server=server)
    client = app1.client
    for i in range(16):
        client.create_node(
            make_node(f"n{i}").capacity(
                cpu="32", memory="64Gi", pods=110
            ).obj()
        )
    app1.start()
    app2 = SchedulerApp(config=_cfg(num_partitions=2), server=server)
    app2.start()
    assert _wait(
        lambda: len(app1.coordinator.held) == 1
        and len(app2.coordinator.held) == 1,
        10,
    )
    # the FIRST bulk commit anywhere crashes its stack mid-bind
    install_injector(FaultInjector(FaultProfile(
        "midbind-crash", seed=0,
        points={FaultPoint.CRASH_BETWEEN_ASSUME_AND_BIND: PointConfig(
            rate=1.0, max_fires=1
        )},
    )))
    n = 200
    for i in range(0, n, 100):
        client.create_pods_bulk([
            make_pod(f"c{j}").container(cpu="100m", memory="128Mi").obj()
            for j in range(i, min(n, i + 100))
        ])
    assert _wait(
        lambda: app1.sched.crashed or app2.sched.crashed, 60
    ), "no stack hit the mid-bind crash"
    crashed, survivor = (
        (app1, app2) if app1.sched.crashed else (app2, app1)
    )
    assert _wait(lambda: _bound_count(client) == n, 120), (
        f"only {_bound_count(client)}/{n} bound after the mid-bind crash"
    )
    assert _wait(lambda: len(survivor.coordinator.held) == 2, 30), (
        "survivor never adopted the crashed stack's partition"
    )
    assert survivor.coordinator.takeovers >= 1
    survivor.sched.wait_for_inflight_binds()
    transitions = _incarnation_transitions(server)
    assert len(transitions) == n
    assert all(v == 1 for v in transitions.values()), {
        k: v for k, v in transitions.items() if v != 1
    }
    _assert_ledger_balanced(app1.sched, app2.sched)
    survivor.stop()
    crashed.stop()
