"""Device-side topology spread: pack + scan parity with the sequential
PodTopologySpread plugin, including within-batch count replay."""

import time

import numpy as np
import jax.numpy as jnp
import pytest

from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.cache.snapshot import new_snapshot
from kubernetes_tpu.client.client import Client
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.ops.assignment import greedy_assign_spread
from kubernetes_tpu.ops.topology import pack_spread_batch
from kubernetes_tpu.scheduler.scheduler import new_scheduler
from kubernetes_tpu.tensors import NodeTensorCache, pack_pod_batch
from kubernetes_tpu.testing import make_node, make_pod


def _zone_cluster():
    nodes = [
        make_node("n1a").labels(zone="z1").capacity(cpu="16", memory="32Gi").obj(),
        make_node("n1b").labels(zone="z1").capacity(cpu="16", memory="32Gi").obj(),
        make_node("n2a").labels(zone="z2").capacity(cpu="16", memory="32Gi").obj(),
        make_node("n2b").labels(zone="z2").capacity(cpu="16", memory="32Gi").obj(),
    ]
    return nodes


def _spread_pod(name, ts):
    return (
        make_pod(name).labels(app="web").creation_timestamp(ts)
        .container(cpu="500m", memory="512Mi")
        .spread_constraint(1, "zone", match_labels={"app": "web"})
        .obj()
    )


class TestPackSpreadBatch:
    def test_initial_counts_and_groups(self):
        nodes = _zone_cluster()
        existing = [
            make_pod("e1").node("n1a").labels(app="web").obj(),
            make_pod("e2").node("n1b").labels(app="web").obj(),
            make_pod("e3").node("n2a").labels(app="other").obj(),
        ]
        snap = new_snapshot(existing, nodes)
        nt = NodeTensorCache().update(snap)
        pods = [_spread_pod("p0", 0.0), _spread_pod("p1", 1.0)]
        sp = pack_spread_batch(pods, snap, nt)
        assert sp is not None
        # one group: (default, zone, app=web); z1 has 2 matches, z2 has 0
        counts = sorted(
            sp.group_counts[0][sp.value_valid[0]].tolist()
        )
        assert counts == [0, 2]
        assert sp.pod_groups[0, 0] == sp.pod_groups[1, 0] == 0
        assert sp.pod_self[:, 0].all()
        assert sp.pod_match[:, 0].all()

    def test_node_selector_combo_scopes_the_group(self):
        nodes = _zone_cluster()
        for i, nd in enumerate(nodes):
            nd.metadata.labels["pool"] = "x" if i % 2 == 0 else "y"
        snap = new_snapshot([], nodes)
        nt = NodeTensorCache().update(snap)
        pod = (
            make_pod("p").labels(app="web")
            .spread_constraint(1, "zone", match_labels={"app": "web"})
            .node_selector(pool="x")
            .obj()
        )
        sp = pack_spread_batch([pod], snap, nt)
        assert sp is not None
        g = int(sp.pod_groups[0, 0])
        # out-of-scope (pool=y) nodes carry -1 in the group's value row
        for j, nd in enumerate(nodes):
            v = int(sp.node_value[g, nt.row(nd.metadata.name)])
            if nd.metadata.labels["pool"] == "x":
                assert v >= 0
            else:
                assert v == -1


class TestSpreadScan:
    def test_within_batch_spread_maxskew_1(self):
        """8 pods in ONE batch must land 4/4 across zones -- only possible
        if the scan replays counts between steps."""
        nodes = _zone_cluster()
        snap = new_snapshot([], nodes)
        nt = NodeTensorCache().update(snap)
        pods = [_spread_pod(f"p{i}", float(i)) for i in range(8)]
        batch = pack_pod_batch(pods, nt.dims)
        order = batch.order
        sp = pack_spread_batch([pods[int(i)] for i in order], snap, nt)
        b = batch.size
        static = np.ones((b, nt.capacity), dtype=bool)
        assignments, _, _, counts = greedy_assign_spread(
            jnp.asarray(nt.allocatable),
            jnp.asarray(nt.requested),
            jnp.asarray(nt.non_zero_requested),
            jnp.asarray(nt.valid),
            jnp.asarray(batch.requests[order]),
            jnp.asarray(batch.non_zero_requests[order]),
            jnp.asarray(static),
            jnp.asarray(np.ones(b, dtype=bool)),
            jnp.asarray(sp.group_counts),
            jnp.asarray(sp.value_valid),
            jnp.asarray(sp.node_value),
            jnp.asarray(sp.pod_groups),
            jnp.asarray(sp.pod_max_skew),
            jnp.asarray(sp.pod_self),
            jnp.asarray(sp.pod_match),
        )
        assignments = np.asarray(assignments)
        assert (assignments >= 0).all()
        zone_of = {0: "z1", 1: "z1", 2: "z2", 3: "z2"}
        by_zone = {"z1": 0, "z2": 0}
        for a in assignments:
            by_zone[zone_of[int(a)]] += 1
        assert by_zone == {"z1": 4, "z2": 4}
        final_counts = np.asarray(counts)[0]
        assert sorted(final_counts[np.asarray(sp.value_valid)[0]].tolist()) \
            == [4, 4]

    def test_skew_blocks_overloaded_zone(self):
        """Existing imbalance: z1 has 3 matching pods, z2 has 0; a new
        maxSkew=1 pod must land in z2."""
        nodes = _zone_cluster()
        existing = [
            make_pod(f"e{i}").node("n1a").labels(app="web").obj()
            for i in range(3)
        ]
        snap = new_snapshot(existing, nodes)
        nt = NodeTensorCache().update(snap)
        pods = [_spread_pod("p", 0.0)]
        batch = pack_pod_batch(pods, nt.dims)
        sp = pack_spread_batch(pods, snap, nt)
        assignments, _, _, _ = greedy_assign_spread(
            jnp.asarray(nt.allocatable),
            jnp.asarray(nt.requested),
            jnp.asarray(nt.non_zero_requested),
            jnp.asarray(nt.valid),
            jnp.asarray(batch.requests),
            jnp.asarray(batch.non_zero_requests),
            jnp.asarray(np.ones((1, nt.capacity), dtype=bool)),
            jnp.asarray(np.ones(1, dtype=bool)),
            jnp.asarray(sp.group_counts),
            jnp.asarray(sp.value_valid),
            jnp.asarray(sp.node_value),
            jnp.asarray(sp.pod_groups),
            jnp.asarray(sp.pod_max_skew),
            jnp.asarray(sp.pod_self),
            jnp.asarray(sp.pod_match),
        )
        choice = int(np.asarray(assignments)[0])
        assert nt.names[choice] in ("n2a", "n2b")


class TestEndToEndDeviceSpread:
    def test_batch_scheduler_spreads_on_device(self):
        server = APIServer()
        client = Client(server)
        informers = InformerFactory(server)
        sched = new_scheduler(client, informers, batch=True, max_batch=64)
        for n in _zone_cluster():
            client.create_node(n)
        informers.start()
        informers.wait_for_cache_sync()
        for i in range(12):
            client.create_pod(_spread_pod(f"w{i}", float(i)))
        sched.start()
        deadline = time.time() + 20
        while time.time() < deadline:
            pods, _ = client.list_pods()
            if all(p.spec.node_name for p in pods):
                break
            time.sleep(0.05)
        sched.wait_for_inflight_binds()
        pods, _ = client.list_pods()
        zone_of = {"n1a": "z1", "n1b": "z1", "n2a": "z2", "n2b": "z2"}
        by_zone = {"z1": 0, "z2": 0}
        for p in pods:
            assert p.spec.node_name, p.name
            by_zone[zone_of[p.spec.node_name]] += 1
        assert by_zone == {"z1": 6, "z2": 6}
        assert sched.pods_fallback == 0  # all solved on device
        assert sched.pods_solved_on_device >= 12
        sched.stop()
        informers.stop()


class TestMultiKeyEligibility:
    """ADVICE round-1 (medium): reference pair counting excludes nodes
    missing ANY of a pod's constraint topology keys; shared group counts
    can't express that, so such batches fall back to the host path."""

    def _pod(self):
        return (
            make_pod("mk").labels(app="web")
            .container(cpu="100m", memory="128Mi")
            .spread_constraint(1, "zone", match_labels={"app": "web"})
            .spread_constraint(1, "rack", match_labels={"app": "web"})
            .obj()
        )

    def test_incomplete_key_coverage_falls_back(self):
        nodes = [
            make_node("a").labels(zone="z1", rack="r1").obj(),
            make_node("b").labels(zone="z2").obj(),  # lacks rack
        ]
        snap = new_snapshot([], nodes)
        nt = NodeTensorCache().update(snap)
        assert pack_spread_batch([self._pod()], snap, nt) is None

    def test_complete_key_coverage_packs(self):
        nodes = [
            make_node("a").labels(zone="z1", rack="r1").obj(),
            make_node("b").labels(zone="z2", rack="r2").obj(),
        ]
        snap = new_snapshot([], nodes)
        nt = NodeTensorCache().update(snap)
        assert pack_spread_batch([self._pod()], snap, nt) is not None

    def test_single_key_incomplete_coverage_still_packs(self):
        # one distinct key: missing-key nodes are simply ineligible for
        # that key's pairs, which per-group counting already models
        nodes = [
            make_node("a").labels(zone="z1").obj(),
            make_node("b").obj(),
        ]
        pod = (
            make_pod("sk").labels(app="web")
            .container(cpu="100m", memory="128Mi")
            .spread_constraint(1, "zone", match_labels={"app": "web"})
            .obj()
        )
        snap = new_snapshot([], nodes)
        nt = NodeTensorCache().update(snap)
        sp = pack_spread_batch([pod], snap, nt)
        assert sp is not None
        assert sp.node_value[0, 1] == -1  # keyless node ineligible
