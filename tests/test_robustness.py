"""Unit tests for the robustness subsystem (kubernetes_tpu/robustness/):
fault injector determinism, circuit-breaker state machine, watchdog,
retry policy, host-greedy tier parity, informer relist, and the config
surface."""

import threading
import time

import numpy as np
import pytest

from kubernetes_tpu.robustness.circuit import (
    CLOSED,
    HALF_OPEN,
    OPEN,
    CircuitBreaker,
    RetryPolicy,
    SolveTimeout,
    Watchdog,
)
from kubernetes_tpu.robustness.faults import (
    FaultInjected,
    FaultInjector,
    FaultPoint,
    FaultProfile,
    PointConfig,
    builtin_profiles,
    get_injector,
    install_injector,
    load_profile,
)
from kubernetes_tpu.robustness.ladder import (
    LadderExhausted,
    RobustnessConfig,
    SolverLadder,
    TIER_HOST_GREEDY,
    TIER_XLA,
    host_greedy_assign,
)


@pytest.fixture(autouse=True)
def _clean_injector():
    yield
    install_injector(None)


class TestFaultInjector:
    def test_deterministic_per_seed(self):
        prof = FaultProfile(
            "t", seed=7,
            points={FaultPoint.DEVICE_SOLVE: PointConfig(rate=0.5)},
        )
        a = [
            FaultInjector(prof).should_fire(FaultPoint.DEVICE_SOLVE)
            for _ in range(1)
        ]
        seq1 = [
            x for inj in [FaultInjector(prof)]
            for x in [
                inj.should_fire(FaultPoint.DEVICE_SOLVE) for _ in range(50)
            ]
        ]
        seq2 = [
            x for inj in [FaultInjector(prof)]
            for x in [
                inj.should_fire(FaultPoint.DEVICE_SOLVE) for _ in range(50)
            ]
        ]
        assert seq1 == seq2
        assert any(seq1) and not all(seq1)

    def test_max_fires_bounds_the_burst(self):
        prof = FaultProfile(
            "t", seed=0,
            points={
                FaultPoint.DEVICE_SOLVE: PointConfig(rate=1.0, max_fires=3)
            },
        )
        inj = FaultInjector(prof)
        fired = sum(
            inj.should_fire(FaultPoint.DEVICE_SOLVE) for _ in range(10)
        )
        assert fired == 3
        assert inj.fired_count(FaultPoint.DEVICE_SOLVE) == 3

    def test_raise_maybe(self):
        prof = FaultProfile(
            "t", points={FaultPoint.BIND_CONFLICT: PointConfig(rate=1.0)}
        )
        with pytest.raises(FaultInjected):
            FaultInjector(prof).raise_maybe(FaultPoint.BIND_CONFLICT)

    def test_unconfigured_point_never_fires(self):
        inj = FaultInjector(FaultProfile("t"))
        assert not any(
            inj.should_fire(FaultPoint.DEVICE_SOLVE) for _ in range(100)
        )

    def test_corrupt_assignments_flags_out_of_range(self):
        prof = FaultProfile(
            "t", points={FaultPoint.SOLVE_GARBAGE: PointConfig(rate=1.0)}
        )
        a = np.arange(6, dtype=np.int32)
        out = FaultInjector(prof).corrupt_assignments_maybe(
            FaultPoint.SOLVE_GARBAGE, a
        )
        assert (out != a).any()
        assert (out >= 6).any() or (out < -1).any()

    def test_global_install(self):
        assert get_injector() is None
        inj = FaultInjector(FaultProfile("t"))
        install_injector(inj)
        assert get_injector() is inj
        install_injector(None)
        assert get_injector() is None

    def test_builtin_profiles_load(self):
        for name in builtin_profiles():
            p = load_profile(name, seed=3)
            assert p.seed == 3
        with pytest.raises(KeyError):
            load_profile("no-such-profile")


class TestCircuitBreaker:
    def test_full_cycle(self):
        now = [0.0]
        br = CircuitBreaker(
            "xla", failure_threshold=2, cooloff_seconds=5.0,
            probe_batches=1, clock=lambda: now[0],
        )
        assert br.state == CLOSED and br.allow()
        br.record_failure()
        assert br.state == CLOSED
        br.record_failure()
        assert br.state == OPEN and not br.allow()
        now[0] = 5.1
        assert br.state == HALF_OPEN
        assert br.allow()  # the probe
        assert not br.allow()  # only probe_batches probes admitted
        br.record_success()
        assert br.state == CLOSED and br.allow()

    def test_failed_probe_reopens(self):
        now = [0.0]
        br = CircuitBreaker(
            "xla", failure_threshold=1, cooloff_seconds=1.0,
            clock=lambda: now[0],
        )
        br.record_failure()
        assert br.state == OPEN
        now[0] = 1.5
        assert br.allow()
        br.record_failure()
        assert br.state == OPEN and not br.allow()

    def test_success_resets_consecutive_count(self):
        br = CircuitBreaker("xla", failure_threshold=2)
        br.record_failure()
        br.record_success()
        br.record_failure()
        assert br.state == CLOSED

    def test_force_open(self):
        br = CircuitBreaker("xla", failure_threshold=99)
        br.force_open()
        assert br.state == OPEN


class TestWatchdog:
    def test_fast_call_passes_through(self):
        assert Watchdog().call(lambda: 42, timeout=5.0) == 42

    def test_timeout_raises(self):
        wd = Watchdog()
        t0 = time.monotonic()
        with pytest.raises(SolveTimeout):
            wd.call(lambda: time.sleep(2.0), timeout=0.1, tier="xla")
        assert time.monotonic() - t0 < 1.0

    def test_exception_relayed(self):
        with pytest.raises(ValueError):
            Watchdog().call(
                lambda: (_ for _ in ()).throw(ValueError("boom")),
                timeout=5.0,
            )

    def test_no_timeout_runs_on_caller_thread(self):
        tid = []
        Watchdog().call(
            lambda: tid.append(threading.get_ident()), timeout=0
        )
        assert tid == [threading.get_ident()]


class TestRetryPolicy:
    def test_exponential_backoff_capped(self):
        p = RetryPolicy(
            max_attempts=5, backoff_seconds=0.1, backoff_multiplier=2.0,
            max_backoff_seconds=0.3,
        )
        assert p.backoff_for_attempt(1) == pytest.approx(0.1)
        assert p.backoff_for_attempt(2) == pytest.approx(0.2)
        assert p.backoff_for_attempt(3) == pytest.approx(0.3)
        assert p.backoff_for_attempt(9) == pytest.approx(0.3)


class TestSolverLadder:
    def _ladder(self, **kw):
        kw.setdefault("solve_timeout_seconds", 2.0)
        kw.setdefault("cooloff_seconds", 0.2)
        kw.setdefault("failure_threshold", 1)
        kw.setdefault("retry", RetryPolicy(max_attempts=1))
        kw.setdefault("sleep", lambda s: None)
        return SolverLadder(RobustnessConfig(**kw))

    def test_first_tier_wins(self):
        lad = self._ladder()
        tier, out = lad.run([(TIER_XLA, lambda: "ok")])
        assert (tier, out) == (TIER_XLA, "ok")
        assert lad.solves_by_tier[TIER_XLA] == 1

    def test_steps_down_on_error(self):
        lad = self._ladder()

        def boom():
            raise RuntimeError("device down")

        tier, out = lad.run(
            [(TIER_XLA, boom), (TIER_HOST_GREEDY, lambda: "host")]
        )
        assert (tier, out) == (TIER_HOST_GREEDY, "host")
        assert lad.breakers[TIER_XLA].state == OPEN

    def test_open_breaker_skips_tier(self):
        lad = self._ladder()
        lad.breakers[TIER_XLA].force_open()
        calls = []

        def never():
            calls.append(1)
            return "x"

        tier, _ = lad.run(
            [(TIER_XLA, never), (TIER_HOST_GREEDY, lambda: "host")]
        )
        assert tier == TIER_HOST_GREEDY and not calls

    def test_exhaustion_raises(self):
        lad = self._ladder()

        def boom():
            raise RuntimeError("down")

        with pytest.raises(LadderExhausted):
            lad.run([(TIER_XLA, boom)])

    def test_retry_in_place_before_stepping_down(self):
        lad = self._ladder(retry=RetryPolicy(max_attempts=3))
        attempts = []

        def flaky():
            attempts.append(1)
            if len(attempts) < 3:
                raise RuntimeError("transient")
            return "ok"

        tier, out = lad.run([(TIER_XLA, flaky)])
        assert out == "ok" and len(attempts) == 3
        assert lad.breakers[TIER_XLA].state == CLOSED

    def test_timeout_force_opens_and_steps_down(self):
        lad = self._ladder(solve_timeout_seconds=0.1)
        tier, out = lad.run(
            [
                (TIER_XLA, lambda: time.sleep(1.0) or "late"),
                (TIER_HOST_GREEDY, lambda: "host"),
            ]
        )
        assert (tier, out) == (TIER_HOST_GREEDY, "host")
        assert lad.breakers[TIER_XLA].state == OPEN

    def test_breaker_closes_after_cooloff_probe(self):
        lad = self._ladder(cooloff_seconds=0.05)

        def boom():
            raise RuntimeError("down")

        lad.run([(TIER_XLA, boom), (TIER_HOST_GREEDY, lambda: "h")])
        assert lad.breakers[TIER_XLA].state == OPEN
        time.sleep(0.1)
        tier, _ = lad.run(
            [(TIER_XLA, lambda: "back"), (TIER_HOST_GREEDY, lambda: "h")]
        )
        assert tier == TIER_XLA
        assert lad.breakers[TIER_XLA].state == CLOSED


class TestHostGreedyParity:
    def test_matches_device_solver(self):
        """The host tier must replay the same placements as the
        unconstrained device scan (same fit, same scores, same
        lowest-index tie-break)."""
        import jax.numpy as jnp

        from kubernetes_tpu.ops.assignment import (
            GreedyConfig,
            greedy_assign_compact,
        )

        rng = np.random.default_rng(0)
        n, r, b_sz = 16, 5, 24
        allocatable = np.zeros((n, r), dtype=np.int32)
        allocatable[:, 0] = rng.integers(4000, 16000, n)  # mCPU
        allocatable[:, 1] = rng.integers(1 << 20, 1 << 22, n)  # KiB
        allocatable[:, 3] = 110  # pods
        requested = np.zeros_like(allocatable)
        nzr = np.zeros((n, 2), dtype=np.int32)
        valid = np.ones(n, dtype=bool)
        pod_req = np.zeros((b_sz, r), dtype=np.int32)
        pod_req[:, 0] = rng.integers(100, 2000, b_sz)
        pod_req[:, 1] = rng.integers(1 << 14, 1 << 17, b_sz)
        pod_req[:, 3] = 1
        pod_nzr = pod_req[:, :2].copy()
        mask_rows = np.ones((2, n), dtype=bool)
        mask_rows[1, : n // 2] = False
        mask_index = rng.integers(0, 2, b_sz).astype(np.int32)
        active = np.ones(b_sz, dtype=bool)
        active[-2:] = False

        cfg = GreedyConfig()
        dev_a, dev_req, dev_nzr = greedy_assign_compact(
            jnp.asarray(allocatable), jnp.asarray(requested),
            jnp.asarray(nzr), jnp.asarray(valid), jnp.asarray(pod_req),
            jnp.asarray(pod_nzr), jnp.asarray(mask_rows),
            jnp.asarray(mask_index), jnp.asarray(active), config=cfg,
        )
        host_a, host_req, host_nzr = host_greedy_assign(
            allocatable, requested, nzr, valid, pod_req, pod_nzr,
            mask_rows, mask_index, active, config=cfg,
        )
        np.testing.assert_array_equal(np.asarray(dev_a), host_a)
        np.testing.assert_array_equal(np.asarray(dev_req), host_req)
        np.testing.assert_array_equal(np.asarray(dev_nzr), host_nzr)


class TestInformerRelist:
    def test_relist_reconverges_after_drop(self):
        from kubernetes_tpu.apiserver.server import APIServer
        from kubernetes_tpu.client.client import Client
        from kubernetes_tpu.client.informer import InformerFactory
        from kubernetes_tpu.testing import make_pod
        from kubernetes_tpu.utils import metrics

        server = APIServer()
        client = Client(server)
        informers = InformerFactory(server)
        inf = informers.pods()
        client.create_pod(make_pod("a").container(cpu="1").obj())
        inf.pump()
        assert len(inf.list()) == 1
        # fire a guaranteed watch drop: events created while the stream
        # is down must still converge via the relist diff
        client.create_pod(make_pod("b").container(cpu="1").obj())
        client.delete_pod("default", "a")
        before = metrics.watch_relists.value(kind="Pod")
        install_injector(FaultInjector(FaultProfile(
            "t", points={FaultPoint.WATCH_DROP: PointConfig(rate=1.0)},
        )))
        inf.pump()  # drop fires -> relist
        install_injector(None)
        assert metrics.watch_relists.value(kind="Pod") == before + 1
        names = {p.metadata.name for p in inf.list()}
        assert names == {"b"}
        # handlers saw the synthetic diff: one more pump stays converged
        inf.pump()
        assert {p.metadata.name for p in inf.list()} == {"b"}


class TestConfigSurface:
    def test_loader_parses_robustness_and_faults(self):
        from kubernetes_tpu.config.loader import load_config_from_dict

        cfg = load_config_from_dict({
            "robustness": {
                "solveTimeout": "30s",
                "failureThreshold": 5,
                "cooloff": "2s",
                "probeBatches": 2,
                "retryMaxAttempts": 4,
                "retryBackoff": "10ms",
            },
            "faultInjection": {
                "enabled": True,
                "profile": "chaos-default",
                "seed": 42,
                "points": {
                    "device_solve": {"rate": 0.5, "maxFires": 7},
                    "device_solve_hang": {
                        "rate": 0.1, "hangSeconds": "1500ms",
                    },
                },
            },
        })
        rb = cfg.robustness
        assert rb.solve_timeout_seconds == 30.0
        assert rb.failure_threshold == 5
        assert rb.cooloff_seconds == 2.0
        assert rb.probe_batches == 2
        assert rb.retry_max_attempts == 4
        assert rb.retry_backoff_seconds == pytest.approx(0.01)
        fi = cfg.fault_injection
        assert fi.enabled and fi.profile == "chaos-default"
        assert fi.seed == 42
        assert fi.points["device_solve"].rate == 0.5
        assert fi.points["device_solve"].max_fires == 7
        assert fi.points["device_solve_hang"].hang_seconds == 1.5
        # round-trips into the runtime objects
        rc = RobustnessConfig.from_configuration(rb)
        assert rc.retry.max_attempts == 4
        from kubernetes_tpu.robustness.faults import (
            injector_from_configuration,
        )

        inj = injector_from_configuration(fi)
        assert inj is not None
        assert inj.profile.points["device_solve"].rate == 0.5
        # profile points not overridden are kept
        assert FaultPoint.BIND_CONFLICT in inj.profile.points

    def test_validation_rejects_bad_knobs(self):
        from kubernetes_tpu.config.loader import load_config_from_dict
        from kubernetes_tpu.config.validation import validate_config

        cfg = load_config_from_dict({
            "robustness": {"failureThreshold": 0},
            "faultInjection": {
                "enabled": True,
                "profile": "not-a-profile",
                "points": {"bogus_point": {"rate": 2.0}},
            },
        })
        errors = validate_config(cfg)
        assert any("failureThreshold" in e for e in errors)
        assert any("not-a-profile" in e for e in errors)
        assert any("bogus_point" in e for e in errors)
        assert any("rate" in e for e in errors)

    def test_disabled_injection_returns_none(self):
        from kubernetes_tpu.config.loader import load_config_from_dict
        from kubernetes_tpu.robustness.faults import (
            injector_from_configuration,
        )

        cfg = load_config_from_dict({})
        assert injector_from_configuration(cfg.fault_injection) is None
