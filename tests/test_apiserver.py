import pytest

from kubernetes_tpu.api.types import Binding
from kubernetes_tpu.apiserver import APIServer, Conflict, NotFound
from kubernetes_tpu.apiserver.server import ADDED, DELETED, MODIFIED
from kubernetes_tpu.client import Client
from kubernetes_tpu.testing import make_node, make_pod


def test_create_get_list_rv():
    api = APIServer()
    p = api.create(make_pod("p1").obj())
    assert p.metadata.resource_version == 1
    n = api.create(make_node("n1").obj())
    assert n.metadata.resource_version == 2
    pods, rv = api.list("Pod")
    assert len(pods) == 1 and rv == 2


def test_create_duplicate_conflict():
    api = APIServer()
    api.create(make_pod("p1").obj())
    with pytest.raises(Conflict):
        api.create(make_pod("p1").obj())


def test_update_optimistic_concurrency():
    api = APIServer()
    p = api.create(make_pod("p1").obj())
    rv = p.metadata.resource_version
    p2 = make_pod("p1").labels(v="2").obj()
    api.update(p2, expect_rv=rv)
    stale = make_pod("p1").labels(v="3").obj()
    with pytest.raises(Conflict):
        api.update(stale, expect_rv=rv)


def test_watch_streams_events_in_order():
    api = APIServer()
    w = api.watch("Pod")
    api.create(make_pod("p1").obj())
    api.guaranteed_update("Pod", "default", "p1", lambda p: None)
    api.delete("Pod", "default", "p1")
    types = [ev.type for ev in w.pending()]
    assert types == [ADDED, MODIFIED, DELETED]


def test_watch_since_rv_replays_history():
    api = APIServer()
    api.create(make_pod("p1").obj())
    _, rv = api.list("Pod")
    api.create(make_pod("p2").obj())
    w = api.watch("Pod", since_rv=rv)
    evs = w.pending()
    assert [e.object.metadata.name for e in evs] == ["p2"]


def test_binding_subresource():
    api = APIServer()
    client = Client(api)
    pod = client.create_pod(make_pod("p1").obj())
    client.create_node(make_node("n1").obj())
    bound = client.bind(
        Binding(pod_namespace="default", pod_name="p1", target_node="n1")
    )
    assert bound.spec.node_name == "n1"
    # re-bind to a different node is a conflict
    with pytest.raises(Conflict):
        client.bind(Binding(pod_namespace="default", pod_name="p1", target_node="n2"))
    # bind of a missing pod is NotFound
    with pytest.raises(NotFound):
        client.bind(Binding(pod_namespace="default", pod_name="nope", target_node="n1"))


def test_binding_uid_mismatch():
    api = APIServer()
    client = Client(api)
    client.create_pod(make_pod("p1").uid("uid-A").obj())
    with pytest.raises(Conflict):
        client.bind(
            Binding(
                pod_namespace="default",
                pod_name="p1",
                pod_uid="uid-B",
                target_node="n1",
            )
        )


def test_update_pod_status():
    api = APIServer()
    client = Client(api)
    client.create_pod(make_pod("p1").obj())

    def nominate(p):
        p.status.nominated_node_name = "n5"

    updated = client.update_pod_status("default", "p1", nominate)
    assert updated.status.nominated_node_name == "n5"


def test_update_invalidates_scheduler_memos():
    """Memoized per-pod scheduler state (_sig_memo/_hot_memo/_req_memo)
    must not survive a guaranteed_update: the mutate may change exactly
    the fields the memos were derived from (the code-review r4 repro: a
    toleration added post-parking kept the pod masked off tainted nodes
    forever)."""
    from kubernetes_tpu.api.types import Toleration, pod_resource_requests
    from kubernetes_tpu.ops.host_masks import _constraint_signature

    api = APIServer()
    client = Client(api)
    pod = make_pod("p1").container(cpu="100m", memory="64Mi").obj()
    client.create_pod(pod)
    # prime every memo the scheduler hot path writes
    pod_resource_requests(pod)
    sig_before = _constraint_signature(pod)
    assert sig_before[3] == ()  # no tolerations

    def add_toleration(p):
        p.spec.tolerations = [
            Toleration(key="dedicated", operator="Exists")
        ]

    updated = api.guaranteed_update("Pod", "default", "p1", add_toleration)
    sig_after = _constraint_signature(updated)
    assert sig_after[3] != (), "signature memo leaked through the update"
    req = pod_resource_requests(updated)
    assert req  # recomputed, not a stale shared memo


def test_bind_invalidates_signature_memo():
    """_constraint_signature includes spec.node_name; the binding path
    must drop the memo (resource memos may legitimately survive -- bind
    only writes node_name)."""
    from kubernetes_tpu.ops.host_masks import _constraint_signature

    api = APIServer()
    client = Client(api)
    pod = make_pod("p2").obj()
    client.create_pod(pod)
    assert _constraint_signature(pod)[0] == ""
    client.bind(
        Binding(
            pod_namespace="default", pod_name="p2",
            pod_uid=pod.metadata.uid, target_node="n1",
        )
    )
    bound = api.get("Pod", "default", "p2")
    assert _constraint_signature(bound)[0] == "n1"
