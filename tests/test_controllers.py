"""Disruption controller + storage/service queue wakeups.

Reference: pkg/controller/disruption/disruption.go (DisruptionsAllowed
reconcile) and pkg/scheduler/eventhandlers.go:415-460 (PV/PVC/Service/
StorageClass/CSINode informer handlers -> queue moves).
"""

import time

from kubernetes_tpu.api.types import (
    LabelSelector,
    ObjectMeta,
    PersistentVolume,
    PodDisruptionBudget,
)
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client.client import Client
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.controllers import DisruptionController
from kubernetes_tpu.scheduler.scheduler import new_scheduler
from kubernetes_tpu.testing import make_node, make_pod


def _pdb(name, match, min_available=None, max_unavailable=None):
    pdb = PodDisruptionBudget(
        selector=LabelSelector(match_labels=match),
        min_available=min_available,
        max_unavailable=max_unavailable,
    )
    pdb.metadata.name = name
    pdb.metadata.namespace = "default"
    return pdb


class TestDisruptionController:
    def _env(self):
        server = APIServer()
        client = Client(server)
        informers = InformerFactory(server)
        ctrl = DisruptionController(client, informers)
        return server, client, informers, ctrl

    def test_min_available(self):
        server, client, informers, ctrl = self._env()
        client.create_pdb(_pdb("a", {"app": "web"}, min_available=2))
        for i in range(3):
            client.create_pod(
                make_pod(f"p{i}").labels(app="web").node("n1").obj()
            )
        informers.pods().pump()
        informers.pdbs().pump()
        ctrl.sync_all()
        pdbs, _ = client.list_pdbs()
        assert pdbs[0].status.disruptions_allowed == 1  # 3 healthy - 2

    def test_max_unavailable(self):
        server, client, informers, ctrl = self._env()
        client.create_pdb(_pdb("a", {"app": "db"}, max_unavailable=1))
        for i in range(4):
            client.create_pod(
                make_pod(f"p{i}").labels(app="db").node("n1").obj()
            )
        informers.pods().pump()
        informers.pdbs().pump()
        ctrl.sync_all()
        pdbs, _ = client.list_pdbs()
        # expected 4, desired 3, healthy 4 -> 1 disruption allowed
        assert pdbs[0].status.disruptions_allowed == 1

    def test_unbound_pods_not_healthy(self):
        server, client, informers, ctrl = self._env()
        client.create_pdb(_pdb("a", {"app": "web"}, min_available=1))
        client.create_pod(make_pod("bound").labels(app="web").node("n").obj())
        client.create_pod(make_pod("pending").labels(app="web").obj())
        informers.pods().pump()
        informers.pdbs().pump()
        ctrl.sync_all()
        pdbs, _ = client.list_pdbs()
        assert pdbs[0].status.disruptions_allowed == 0  # 1 healthy - 1

    def test_event_driven_loop(self):
        server, client, informers, ctrl = self._env()
        client.create_pdb(_pdb("a", {"app": "web"}, min_available=1))
        informers.start()
        informers.wait_for_cache_sync()
        ctrl.start()
        for i in range(3):
            client.create_pod(
                make_pod(f"p{i}").labels(app="web").node("n1").obj()
            )
        deadline = time.time() + 10
        while time.time() < deadline:
            pdbs, _ = client.list_pdbs()
            if pdbs[0].status.disruptions_allowed == 2:
                break
            time.sleep(0.02)
        ctrl.stop()
        informers.stop()
        assert pdbs[0].status.disruptions_allowed == 2


class TestPdbPreemptionEndToEnd:
    def test_preemption_respects_controller_maintained_budget(self):
        """PDB-aware preemption works WITHOUT test-injected status: the
        controller computes DisruptionsAllowed and the preemptor prefers
        non-violating victims (generic_scheduler.go:885-887)."""
        server = APIServer()
        client = Client(server)
        informers = InformerFactory(server)
        sched = new_scheduler(client, informers, batch=True, max_batch=16)
        ctrl = DisruptionController(client, informers)
        # two nodes, each full with one low-priority pod; the protected
        # one (PDB budget 0) must be reprieved, the other evicted
        for n in ("n0", "n1"):
            client.create_node(
                make_node(n).capacity(cpu="2", memory="4Gi").obj()
            )
        client.create_pdb(_pdb("guard", {"app": "protected"}, min_available=1))
        informers.start()
        informers.wait_for_cache_sync()
        sched.queue.run()
        client.create_pod(
            make_pod("prot").labels(app="protected").container(cpu="2")
            .priority(0).obj()
        )
        client.create_pod(
            make_pod("loose").labels(app="loose").container(cpu="2")
            .priority(0).obj()
        )
        t = sched.start()
        ctrl.start()
        deadline = time.time() + 15
        while time.time() < deadline:
            pods, _ = client.list_pods()
            if sum(1 for p in pods if p.spec.node_name) >= 2:
                break
            time.sleep(0.02)
        # budget settles at 0 (1 healthy - 1 minAvailable)
        deadline = time.time() + 10
        while time.time() < deadline:
            pdbs, _ = client.list_pdbs()
            if pdbs[0].status.disruptions_allowed == 0:
                break
            time.sleep(0.02)
        client.create_pod(
            make_pod("high").container(cpu="2").priority(100).obj()
        )
        deadline = time.time() + 15
        bound_node = ""
        while time.time() < deadline:
            try:
                p = client.get_pod("default", "high")
            except KeyError:
                break
            if p.spec.node_name:
                bound_node = p.spec.node_name
                break
            time.sleep(0.02)
        sched.stop()
        ctrl.stop()
        informers.stop()
        assert bound_node, "high-priority pod never bound"
        # the protected pod survived; the loose one was evicted
        pods, _ = client.list_pods()
        names = {p.metadata.name for p in pods}
        assert "prot" in names
        assert "loose" not in names


class TestStorageWakeups:
    def test_pv_add_wakes_parked_pod(self):
        """A pod parked on a missing PVC moves out of unschedulableQ
        when a PV lands (eventhandlers.go:415 PvAdd)."""
        server = APIServer()
        client = Client(server)
        informers = InformerFactory(server)
        sched = new_scheduler(client, informers, batch=True, max_batch=16)
        client.create_node(make_node("n").capacity(cpu="4", memory="8Gi").obj())
        informers.start()
        informers.wait_for_cache_sync()
        sched.queue.run()
        client.create_pod(
            make_pod("p").container(cpu="1").pvc("missing-claim").obj()
        )
        deadline = time.time() + 10
        while time.time() < deadline:
            sched.schedule_batch(timeout=0.2)
            if sched.queue.num_pending()["unschedulable"] == 1:
                break
        assert sched.queue.num_pending()["unschedulable"] == 1
        pv = PersistentVolume(metadata=ObjectMeta(name="pv0", namespace=""))
        server.create(pv)
        deadline = time.time() + 10
        while time.time() < deadline:
            counts = sched.queue.num_pending()
            if counts["unschedulable"] == 0:
                break
            time.sleep(0.02)
        sched.stop()
        informers.stop()
        assert counts["unschedulable"] == 0
        assert counts["active"] + counts["backoff"] == 1
