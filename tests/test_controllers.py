"""Disruption controller + storage/service queue wakeups.

Reference: pkg/controller/disruption/disruption.go (DisruptionsAllowed
reconcile) and pkg/scheduler/eventhandlers.go:415-460 (PV/PVC/Service/
StorageClass/CSINode informer handlers -> queue moves).
"""

import time

from kubernetes_tpu.api.types import (
    LabelSelector,
    ObjectMeta,
    PersistentVolume,
    PodDisruptionBudget,
)
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client.client import Client
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.controllers import DisruptionController
from kubernetes_tpu.scheduler.scheduler import new_scheduler
from kubernetes_tpu.testing import make_node, make_pod


def _pdb(name, match, min_available=None, max_unavailable=None):
    pdb = PodDisruptionBudget(
        selector=LabelSelector(match_labels=match),
        min_available=min_available,
        max_unavailable=max_unavailable,
    )
    pdb.metadata.name = name
    pdb.metadata.namespace = "default"
    return pdb


class TestDisruptionController:
    def _env(self):
        server = APIServer()
        client = Client(server)
        informers = InformerFactory(server)
        ctrl = DisruptionController(client, informers)
        return server, client, informers, ctrl

    def test_min_available(self):
        server, client, informers, ctrl = self._env()
        client.create_pdb(_pdb("a", {"app": "web"}, min_available=2))
        for i in range(3):
            client.create_pod(
                make_pod(f"p{i}").labels(app="web").node("n1").obj()
            )
        informers.pods().pump()
        informers.pdbs().pump()
        ctrl.sync_all()
        pdbs, _ = client.list_pdbs()
        assert pdbs[0].status.disruptions_allowed == 1  # 3 healthy - 2

    def test_max_unavailable(self):
        server, client, informers, ctrl = self._env()
        client.create_pdb(_pdb("a", {"app": "db"}, max_unavailable=1))
        for i in range(4):
            client.create_pod(
                make_pod(f"p{i}").labels(app="db").node("n1").obj()
            )
        informers.pods().pump()
        informers.pdbs().pump()
        ctrl.sync_all()
        pdbs, _ = client.list_pdbs()
        # expected 4, desired 3, healthy 4 -> 1 disruption allowed
        assert pdbs[0].status.disruptions_allowed == 1

    def test_unbound_pods_not_healthy(self):
        server, client, informers, ctrl = self._env()
        client.create_pdb(_pdb("a", {"app": "web"}, min_available=1))
        client.create_pod(make_pod("bound").labels(app="web").node("n").obj())
        client.create_pod(make_pod("pending").labels(app="web").obj())
        informers.pods().pump()
        informers.pdbs().pump()
        ctrl.sync_all()
        pdbs, _ = client.list_pdbs()
        assert pdbs[0].status.disruptions_allowed == 0  # 1 healthy - 1

    def test_event_driven_loop(self):
        server, client, informers, ctrl = self._env()
        client.create_pdb(_pdb("a", {"app": "web"}, min_available=1))
        informers.start()
        informers.wait_for_cache_sync()
        ctrl.start()
        for i in range(3):
            client.create_pod(
                make_pod(f"p{i}").labels(app="web").node("n1").obj()
            )
        deadline = time.time() + 10
        while time.time() < deadline:
            pdbs, _ = client.list_pdbs()
            if pdbs[0].status.disruptions_allowed == 2:
                break
            time.sleep(0.02)
        ctrl.stop()
        informers.stop()
        assert pdbs[0].status.disruptions_allowed == 2


class TestPdbPreemptionEndToEnd:
    def test_preemption_respects_controller_maintained_budget(self):
        """PDB-aware preemption works WITHOUT test-injected status: the
        controller computes DisruptionsAllowed and the preemptor prefers
        non-violating victims (generic_scheduler.go:885-887)."""
        server = APIServer()
        client = Client(server)
        informers = InformerFactory(server)
        sched = new_scheduler(client, informers, batch=True, max_batch=16)
        ctrl = DisruptionController(client, informers)
        # two nodes, each full with one low-priority pod; the protected
        # one (PDB budget 0) must be reprieved, the other evicted
        for n in ("n0", "n1"):
            client.create_node(
                make_node(n).capacity(cpu="2", memory="4Gi").obj()
            )
        client.create_pdb(_pdb("guard", {"app": "protected"}, min_available=1))
        informers.start()
        informers.wait_for_cache_sync()
        sched.queue.run()
        client.create_pod(
            make_pod("prot").labels(app="protected").container(cpu="2")
            .priority(0).obj()
        )
        client.create_pod(
            make_pod("loose").labels(app="loose").container(cpu="2")
            .priority(0).obj()
        )
        t = sched.start()
        ctrl.start()
        deadline = time.time() + 15
        while time.time() < deadline:
            pods, _ = client.list_pods()
            if sum(1 for p in pods if p.spec.node_name) >= 2:
                break
            time.sleep(0.02)
        # budget settles at 0 (1 healthy - 1 minAvailable)
        deadline = time.time() + 10
        while time.time() < deadline:
            pdbs, _ = client.list_pdbs()
            if pdbs[0].status.disruptions_allowed == 0:
                break
            time.sleep(0.02)
        client.create_pod(
            make_pod("high").container(cpu="2").priority(100).obj()
        )
        deadline = time.time() + 15
        bound_node = ""
        while time.time() < deadline:
            try:
                p = client.get_pod("default", "high")
            except KeyError:
                break
            if p.spec.node_name:
                bound_node = p.spec.node_name
                break
            time.sleep(0.02)
        sched.stop()
        ctrl.stop()
        informers.stop()
        assert bound_node, "high-priority pod never bound"
        # the protected pod survived; the loose one was evicted
        pods, _ = client.list_pods()
        names = {p.metadata.name for p in pods}
        assert "prot" in names
        assert "loose" not in names


class TestStorageWakeups:
    def test_pv_add_wakes_parked_pod(self):
        """A pod parked on a missing PVC moves out of unschedulableQ
        when a PV lands (eventhandlers.go:415 PvAdd)."""
        server = APIServer()
        client = Client(server)
        informers = InformerFactory(server)
        sched = new_scheduler(client, informers, batch=True, max_batch=16)
        client.create_node(make_node("n").capacity(cpu="4", memory="8Gi").obj())
        informers.start()
        informers.wait_for_cache_sync()
        sched.queue.run()
        client.create_pod(
            make_pod("p").container(cpu="1").pvc("missing-claim").obj()
        )
        deadline = time.time() + 10
        while time.time() < deadline:
            sched.schedule_batch(timeout=0.2)
            if sched.queue.num_pending()["unschedulable"] == 1:
                break
        assert sched.queue.num_pending()["unschedulable"] == 1
        pv = PersistentVolume(metadata=ObjectMeta(name="pv0", namespace=""))
        server.create(pv)
        deadline = time.time() + 10
        while time.time() < deadline:
            counts = sched.queue.num_pending()
            if counts["unschedulable"] == 0:
                break
            time.sleep(0.02)
        sched.stop()
        informers.stop()
        assert counts["unschedulable"] == 0
        assert counts["active"] + counts["backoff"] == 1


class TestCanDisrupt:
    """The shared voluntary-disruption gate (PR 6): drains AND taint
    evictions spend the same PDB budget through can_disrupt, which
    check-and-decrements via guaranteed_update (eviction.go:141)."""

    def _env(self):
        server = APIServer()
        client = Client(server)
        informers = InformerFactory(server)
        ctrl = DisruptionController(client, informers)
        return server, client, informers, ctrl

    def test_no_matching_pdb_always_allows(self):
        server, client, informers, ctrl = self._env()
        pod = make_pod("free").labels(app="x").node("n").obj()
        client.create_pod(pod)
        informers.pods().pump()
        assert ctrl.can_disrupt(pod)

    def test_grant_consumes_budget_then_denies(self):
        server, client, informers, ctrl = self._env()
        client.create_pdb(_pdb("g", {"app": "web"}, min_available=2))
        pods = []
        for i in range(3):
            p = make_pod(f"p{i}").labels(app="web").node("n").obj()
            client.create_pod(p)
            pods.append(p)
        informers.pods().pump()
        informers.pdbs().pump()
        ctrl.sync_all()  # 3 healthy - 2 minAvailable = 1 allowed
        from kubernetes_tpu.utils import metrics

        blocked0 = metrics.evictions_blocked_by_pdb.value()
        assert ctrl.can_disrupt(pods[0])  # spends the single unit
        assert not ctrl.can_disrupt(pods[1])  # budget exhausted
        pdbs, _ = client.list_pdbs()
        assert pdbs[0].status.disruptions_allowed == 0
        assert metrics.evictions_blocked_by_pdb.value() == blocked0 + 1

    def test_budget_reopens_after_evictee_terminates(self):
        server, client, informers, ctrl = self._env()
        client.create_pdb(_pdb("g", {"app": "web"}, min_available=1))
        pods = []
        for i in range(2):
            p = make_pod(f"p{i}").labels(app="web").node("n").obj()
            client.create_pod(p)
            pods.append(p)
        informers.pods().pump()
        informers.pdbs().pump()
        ctrl.sync_all()
        assert ctrl.can_disrupt(pods[0])
        assert not ctrl.can_disrupt(pods[1])
        # the evictee actually terminates; the reconcile loop recomputes
        client.delete_pod("default", "p0")
        # a replacement binds elsewhere, restoring healthy count
        client.create_pod(
            make_pod("p0r").labels(app="web").node("m").obj()
        )
        informers.pods().pump()
        ctrl.sync_all()
        assert ctrl.can_disrupt(pods[1])

    def test_deny_refunds_sibling_pdbs(self):
        """A pod under TWO PDBs where only one has budget: the deny
        must refund the unit already taken from the granting sibling,
        or a blocked pod would starve unrelated disruptions."""
        server, client, informers, ctrl = self._env()
        client.create_pdb(_pdb("rich", {"app": "web"}, max_unavailable=2))
        client.create_pdb(_pdb("poor", {"tier": "gold"}, min_available=2))
        p = (
            make_pod("both").labels(app="web", tier="gold").node("n").obj()
        )
        client.create_pod(p)
        client.create_pod(
            make_pod("web2").labels(app="web").node("n").obj()
        )
        client.create_pod(
            make_pod("gold2").labels(tier="gold").node("n").obj()
        )
        informers.pods().pump()
        informers.pdbs().pump()
        ctrl.sync_all()  # rich: allowed=2; poor: 2 healthy - 2 = 0
        assert not ctrl.can_disrupt(p)
        pdbs = {pdb.metadata.name: pdb for pdb in client.list_pdbs()[0]}
        assert pdbs["rich"].status.disruptions_allowed == 2  # refunded
        assert pdbs["poor"].status.disruptions_allowed == 0
