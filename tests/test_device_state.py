"""Device-resident node-state handshake units (PR 5).

The dispatcher no longer validates device-state reuse with full [N, R]
``np.array_equal`` sweeps: ``NodeTensorCache.update`` returns a
``TensorDelta`` (changed rows + monotonic epochs) and
``BatchScheduler._negotiate_device_state`` reconciles O(changed rows)
against the committer-mirrored expectation. These tests drive the
handshake directly: the ahead-by-K committer-lag case, divergence
scatter-fix, ring-overflow degradation, and the order-insensitive row
remap.
"""

import time

import numpy as np
import pytest

from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.cache.cache import SchedulerCache
from kubernetes_tpu.cache.snapshot import Snapshot
from kubernetes_tpu.client.client import Client
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.scheduler.batch import _SHADOW_RING_CAP
from kubernetes_tpu.scheduler.scheduler import new_scheduler
from kubernetes_tpu.tensors import NodeTensorCache
from kubernetes_tpu.testing import make_node, make_pod


@pytest.fixture
def sched_stack():
    server = APIServer()
    client = Client(server)
    informers = InformerFactory(server)
    sched = new_scheduler(client, informers, batch=True, max_batch=16)
    yield sched
    sched.stop()
    informers.stop()


def _cluster(n):
    cache = SchedulerCache()
    for i in range(n):
        cache.add_node(
            make_node(f"hs-{i}").capacity(cpu="8", memory="16Gi").obj()
        )
    snap = Snapshot()
    cache.update_snapshot(snap)
    return cache, snap


def _negotiate(sched, nt, **kw):
    kw.setdefault("overlaid", False)
    kw.setdefault("allow_scatter", True)
    kw.setdefault("pending_exists", False)
    return sched._negotiate_device_state(
        nt, nt.requested, nt.non_zero_requested, **kw
    )


def _prime(sched, nt):
    """First dispatch: full upload route; fake the device refs the solve
    would have produced (content is irrelevant to the handshake)."""
    neg = _negotiate(sched, nt)
    assert neg == {
        "static_ok": False,
        "carry_ok": False,
        "didx": neg["didx"],
        "sidx": neg["sidx"],
        "member": 0,
    }
    ds = sched._dev
    ds.alloc_dev = object()
    ds.valid_dev = object()
    ds.req_dev = object()
    ds.nzr_dev = object()
    return neg


def _mirror(sched, rows, req_rows, nzr_rows):
    """What _complete_solve does when a batch commits: scatter-add the
    placements into the running shadow and remember the per-row delta."""
    ds = sched._dev
    with sched._shadow_lock:
        np.add.at(ds.req_shadow, rows, req_rows)
        np.add.at(ds.nzr_shadow, rows, nzr_rows)
        ds.pending_deltas.append((rows, req_rows, nzr_rows))


def _pod_rows(nt, k):
    r = nt.dims.num_dims
    req_rows = np.zeros((1, r), dtype=np.int32)
    req_rows[0, 0] = 500  # 500m cpu
    req_rows[0, 3] = 1  # pod count
    nzr_rows = np.asarray([[500, 128]], dtype=np.int32)
    return (
        np.asarray([k], dtype=np.int64),
        req_rows,
        nzr_rows,
    )


class TestHandshake:
    def test_steady_state_pure_reuse(self, sched_stack):
        sched = sched_stack
        cache, snap = _cluster(5)
        nt = sched.tensor_cache.update(snap)
        _prime(sched, nt)
        assert sched.state_uploads == 1
        nt = sched.tensor_cache.update(snap)
        neg = _negotiate(sched, nt)
        assert neg["carry_ok"] and neg["static_ok"]
        assert neg["didx"].size == 0 and neg["sidx"].size == 0
        assert sched.state_reuses == 1
        assert sched.delta_rows_uploaded == 0

    def test_own_commit_explained_by_mirror(self, sched_stack):
        """A batch commits (mirror + cache assume): the repacked row is
        explained by the expectation -- reuse, nothing uploaded."""
        sched = sched_stack
        cache, snap = _cluster(5)
        nt = sched.tensor_cache.update(snap)
        _prime(sched, nt)
        rows, req_rows, nzr_rows = _pod_rows(nt, 2)
        _mirror(sched, rows, req_rows, nzr_rows)
        pod = make_pod("own").node("hs-2").container(cpu="500m").obj()
        # match the mirror's arithmetic: nzr defaults differ, so pin them
        pod.__dict__["_nzr_memo"] = (500, 128 * 1024)
        cache.add_pod(pod)
        cache.update_snapshot(snap)
        nt = sched.tensor_cache.update(snap)
        assert nt.delta.changed_rows.tolist() == [2]
        neg = _negotiate(sched, nt)
        assert neg["carry_ok"]
        assert neg["didx"].size == 0
        assert sched.state_uploads == 1
        assert len(sched._dev.pending_deltas) == 0  # confirmed

    def test_ahead_by_k_committer_lag(self, sched_stack):
        """Regression for the ahead-by-K carry case: K batches mirrored
        but none visible in the host pack yet -- the carry must still
        validate (the host trails the shadow by exactly the ring)."""
        sched = sched_stack
        cache, snap = _cluster(5)
        nt = sched.tensor_cache.update(snap)
        _prime(sched, nt)
        k = _SHADOW_RING_CAP - 1
        for i in range(k):
            _mirror(sched, *_pod_rows(nt, i % 5))
        nt = sched.tensor_cache.update(snap)  # host saw NOTHING yet
        neg = _negotiate(sched, nt, pending_exists=True)
        assert neg is not None and neg["carry_ok"]
        # nothing confirmed: the ring still holds all K deltas
        assert len(sched._dev.pending_deltas) == k
        assert sched.state_uploads == 1

    def test_ring_overflow_degrades_to_counted_upload(self, sched_stack):
        """More unobserved mirrors than the ring holds: the oldest delta
        is dropped, so the handshake can no longer explain the lag and
        must resolve with a counted full upload -- never silently."""
        sched = sched_stack
        cache, snap = _cluster(5)
        nt = sched.tensor_cache.update(snap)
        _prime(sched, nt)
        for i in range(_SHADOW_RING_CAP + 2):
            _mirror(sched, *_pod_rows(nt, i % 5))
        assert len(sched._dev.pending_deltas) == _SHADOW_RING_CAP
        # host now shows NONE of them; commits land in the cache so the
        # rows repack with host-side content the shadow can't explain
        for i in range(5):
            pod = (
                make_pod(f"lag-{i}").node(f"hs-{i}")
                .container(cpu="250m").obj()
            )
            cache.add_pod(pod)
        cache.update_snapshot(snap)
        nt = sched.tensor_cache.update(snap)
        neg = _negotiate(sched, nt)
        assert not neg["carry_ok"]
        assert sched.state_uploads == 2
        assert sched.carry_divergences >= 1

    def test_external_divergence_scatter_fixed(self, sched_stack):
        """An external change (pod removed behind the scheduler's back)
        with nothing in flight: the changed rows ride a scatter patch,
        not a full upload."""
        sched = sched_stack
        cache, snap = _cluster(5)
        pod = make_pod("ext").node("hs-3").container(cpu="1").obj()
        cache.add_pod(pod)
        cache.update_snapshot(snap)
        nt = sched.tensor_cache.update(snap)
        _prime(sched, nt)
        cache.remove_pod(pod)  # external: never mirrored
        cache.update_snapshot(snap)
        nt = sched.tensor_cache.update(snap)
        neg = _negotiate(sched, nt)
        assert neg["carry_ok"]
        assert neg["didx"].tolist() == [3]
        assert sched.carry_divergences == 1
        assert sched.delta_rows_uploaded == 1
        assert sched.state_uploads == 1  # no second full upload
        # shadow reconciled to host truth
        assert np.array_equal(
            sched._dev.req_shadow[3], nt.requested[3]
        )

    def test_divergence_with_inflight_batches_drains(self, sched_stack):
        """Divergence while batches are in flight cannot be patched in
        place (the carry is ahead of the host): the caller must drain."""
        sched = sched_stack
        cache, snap = _cluster(5)
        pod = make_pod("ext2").node("hs-1").container(cpu="1").obj()
        cache.add_pod(pod)
        cache.update_snapshot(snap)
        nt = sched.tensor_cache.update(snap)
        _prime(sched, nt)
        cache.remove_pod(pod)
        cache.update_snapshot(snap)
        nt = sched.tensor_cache.update(snap)
        assert _negotiate(sched, nt, pending_exists=True) is None

    def test_allocatable_change_rides_scatter(self, sched_stack):
        """A node's capacity update (same membership) patches the
        resident allocatable by row instead of re-uploading it."""
        sched = sched_stack
        cache, snap = _cluster(5)
        nt = sched.tensor_cache.update(snap)
        _prime(sched, nt)
        cache.add_node(
            make_node("hs-4").capacity(cpu="32", memory="64Gi").obj()
        )
        cache.update_snapshot(snap)
        nt = sched.tensor_cache.update(snap)
        neg = _negotiate(sched, nt)
        assert neg["carry_ok"] and neg["static_ok"]
        assert neg["sidx"].tolist() == [4]
        assert sched.delta_rows_uploaded == 1
        assert np.array_equal(
            sched._dev.alloc_shadow[4], nt.allocatable[4]
        )

    def test_node_add_rides_membership_scatter(self, sched_stack):
        """Tentpole (PR 6): a node joining claims a headroom slot in
        place -- the carry stays warm, the new row rides the alloc+valid
        scatter, and NOTHING [N, R]-sized re-uploads."""
        sched = sched_stack
        cache, snap = _cluster(5)
        nt = sched.tensor_cache.update(snap)
        _prime(sched, nt)
        cache.add_node(
            make_node("hs-new").capacity(cpu="8", memory="16Gi").obj()
        )
        cache.update_snapshot(snap)
        nt = sched.tensor_cache.update(snap)
        assert not nt.delta.full
        new_row = nt.row("hs-new")
        assert nt.delta.membership_rows.tolist() == [new_row]
        neg = _negotiate(sched, nt)
        assert neg["static_ok"] and neg["carry_ok"]
        assert neg["sidx"].tolist() == [new_row]
        assert neg["member"] == 1
        assert sched.state_uploads == 1  # still only the cold upload
        assert sched.state_reuses == 1
        assert sched.membership_row_patches == 1
        assert sched.carry_divergences == 0
        # the shadow adopted the new slot's host truth
        assert np.array_equal(
            sched._dev.req_shadow[new_row], nt.requested[new_row]
        )

    def test_node_remove_rides_membership_scatter(self, sched_stack):
        """A node retiring frees its slot in place: its row rides the
        scatter (alloc zeroed, valid dropped, requested reset) with the
        carry warm -- an expected reset, never a divergence."""
        sched = sched_stack
        cache, snap = _cluster(5)
        pod = make_pod("on3").node("hs-3").container(cpu="1").obj()
        cache.add_pod(pod)
        cache.update_snapshot(snap)
        nt = sched.tensor_cache.update(snap)
        row3 = nt.row("hs-3")
        _prime(sched, nt)
        from kubernetes_tpu.api.types import Node, ObjectMeta

        cache.remove_pod(pod)
        cache.remove_node(Node(metadata=ObjectMeta(name="hs-3")))
        cache.update_snapshot(snap)
        nt = sched.tensor_cache.update(snap)
        assert not nt.delta.full
        assert nt.delta.membership_rows.tolist() == [row3]
        assert nt.names[row3] == ""
        assert not nt.valid[row3]
        neg = _negotiate(sched, nt)
        assert neg["static_ok"] and neg["carry_ok"]
        assert neg["sidx"].tolist() == [row3]
        # the slot carried requested content on device: the didx scatter
        # must reset it (free slots are infeasible like padding)
        assert neg["didx"].tolist() == [row3]
        assert sched.state_uploads == 1
        assert sched.carry_divergences == 0
        assert sched.membership_row_patches == 1
        assert (sched._dev.req_shadow[row3] == 0).all()

    def test_membership_with_inflight_batches_drains(self, sched_stack):
        """Membership churn while batches are in flight cannot be
        adopted under them: the dispatcher must drain first."""
        sched = sched_stack
        cache, snap = _cluster(5)
        nt = sched.tensor_cache.update(snap)
        _prime(sched, nt)
        cache.add_node(
            make_node("hs-new").capacity(cpu="8", memory="16Gi").obj()
        )
        cache.update_snapshot(snap)
        nt = sched.tensor_cache.update(snap)
        assert _negotiate(sched, nt, pending_exists=True) is None

    def test_headroom_exhaustion_full_repacks_once(self, sched_stack):
        """Adds past the pre-allocated slot headroom force ONE counted
        full repack (fresh headroom), after which churn scatters
        again."""
        sched = sched_stack
        cache, snap = _cluster(5)
        nt = sched.tensor_cache.update(snap)
        cap = nt.capacity
        _prime(sched, nt)
        tc = sched.tensor_cache
        for i in range(cap - 5 + 1):  # one past the allocated capacity
            cache.add_node(
                make_node(f"hs-x{i}")
                .capacity(cpu="8", memory="16Gi")
                .obj()
            )
        cache.update_snapshot(snap)
        nt = sched.tensor_cache.update(snap)
        assert nt.delta.full
        assert tc.full_repacks == 2
        assert nt.capacity > cap
        neg = _negotiate(sched, nt)
        assert not neg["static_ok"] and not neg["carry_ok"]
        assert sched.state_uploads == 2

    def test_mesh_mode_full_upload_fallback(self, sched_stack):
        """allow_scatter=False (the multichip path): any change resolves
        as a counted full upload, never a scatter."""
        sched = sched_stack
        cache, snap = _cluster(5)
        pod = make_pod("m").node("hs-0").container(cpu="1").obj()
        cache.add_pod(pod)
        cache.update_snapshot(snap)
        nt = sched.tensor_cache.update(snap)
        _prime(sched, nt)
        cache.remove_pod(pod)
        cache.update_snapshot(snap)
        nt = sched.tensor_cache.update(snap)
        neg = _negotiate(sched, nt, allow_scatter=False)
        assert not neg["carry_ok"]
        assert neg["didx"].size == 0 and neg["sidx"].size == 0
        assert sched.state_uploads == 2
        assert sched.carry_divergences == 1


class TestTensorDeltaMembership:
    def test_pure_reorder_is_a_noop(self):
        """A pure node-ordering change moves NOTHING: slots stay in
        place, zero rows repack, the layout epoch stands (device buffers
        remain valid row-for-row)."""
        cache, snap = _cluster(6)
        tc = NodeTensorCache()
        nt1 = tc.update(snap)
        assert tc.full_repacks == 1
        repacked = tc.rows_repacked
        content = {
            name: nt1.allocatable[nt1.row(name)].copy()
            for name in nt1.names
        }
        # rebuild the snapshot map in a rotated order (same node set)
        names = list(snap.node_info_map)
        rotated = names[2:] + names[:2]
        snap.node_info_map = {n: snap.node_info_map[n] for n in rotated}
        snap.refresh_lists()
        nt2 = tc.update(snap)
        assert tc.full_repacks == 1  # NOT a membership change
        assert tc.reorders == 1
        assert tc.rows_repacked == repacked  # zero rows repacked
        assert nt2.names == nt1.names  # slots do not move
        assert nt2.delta.layout_epoch == nt1.delta.layout_epoch
        assert nt2.delta.changed_rows.size == 0
        for name in rotated:
            assert np.array_equal(
                nt2.allocatable[nt2.row(name)], content[name]
            ), name
        # the packers' position->row map follows the new snapshot order
        infos = snap.list_node_infos()
        rows = nt2.rows_for(infos)
        for j, ni in enumerate(infos):
            assert int(rows[j]) == nt2.row(ni.node_name)

    def test_reorder_plus_changed_row_repacks_only_that_row(self):
        cache, snap = _cluster(6)
        tc = NodeTensorCache()
        tc.update(snap)
        repacked = tc.rows_repacked
        pod = make_pod("rr").node("hs-5").container(cpu="2").obj()
        cache.add_pod(pod)
        cache.update_snapshot(snap)
        names = list(snap.node_info_map)
        snap.node_info_map = {
            n: snap.node_info_map[n] for n in reversed(names)
        }
        snap.refresh_lists()
        nt = tc.update(snap)
        assert tc.full_repacks == 1
        assert tc.reorders == 1
        assert tc.rows_repacked == repacked + 1
        assert nt.requested[nt.row("hs-5"), 0] == 2000

    def test_add_claims_slot_remove_frees_it(self):
        """Incremental membership: an add claims a headroom slot, a
        remove retires it onto the free list, and the NEXT add reclaims
        the lowest free slot -- zero full repacks, zero layout bumps."""
        cache, snap = _cluster(3)
        tc = NodeTensorCache()
        nt0 = tc.update(snap)
        layout0 = nt0.delta.layout_epoch
        from kubernetes_tpu.api.types import Node, ObjectMeta

        cache.add_node(make_node("hs-x").capacity(cpu="1").obj())
        cache.update_snapshot(snap)
        nt = tc.update(snap)
        assert tc.full_repacks == 1
        assert not nt.delta.full
        assert nt.row("hs-x") == 3  # first headroom slot
        cache.remove_node(Node(metadata=ObjectMeta(name="hs-1")))
        cache.update_snapshot(snap)
        nt = tc.update(snap)
        assert tc.full_repacks == 1
        assert tc.rows_retired == 1
        assert nt.names[1] == ""
        assert not nt.valid[1]
        assert (nt.allocatable[1] == 0).all()
        cache.add_node(make_node("hs-y").capacity(cpu="2").obj())
        cache.update_snapshot(snap)
        nt = tc.update(snap)
        assert nt.row("hs-y") == 1  # reclaimed the freed slot
        assert nt.valid[1]
        assert nt.delta.layout_epoch == layout0
        assert tc.full_repacks == 1


class TestTensorDeltaEpochs:
    def test_changed_rows_and_epoch_monotonic(self):
        cache, snap = _cluster(4)
        tc = NodeTensorCache()
        nt1 = tc.update(snap)
        assert nt1.delta.full
        assert nt1.delta.changed_rows.tolist() == [0, 1, 2, 3]
        pod = make_pod("e").node("hs-1").container(cpu="1").obj()
        cache.add_pod(pod)
        cache.update_snapshot(snap)
        nt2 = tc.update(snap)
        assert nt2.delta.epoch > nt1.delta.epoch
        assert nt2.delta.layout_epoch == nt1.delta.layout_epoch
        assert nt2.delta.changed_rows.tolist() == [1]
        assert tc.rows_changed_since(nt1.delta.epoch).tolist() == [1]
        assert tc.rows_changed_since(nt2.delta.epoch).size == 0

    def test_sibling_consumers_do_not_steal_change_notes(self):
        """Regression: the preemptor's sibling cache and the prewarm
        thread's fresh cache update() against the SAME shared snapshot
        as the scheduler's tensor cache -- a one-shot note consume
        would let one consumer steal another's changed rows (silently
        stale packs). Reads are cursor-based now: every consumer sees
        every change."""
        cache, snap = _cluster(4)
        tc1, tc2 = NodeTensorCache(), NodeTensorCache()
        tc1.update(snap)
        tc2.update(snap)
        pod = make_pod("sib").node("hs-2").container(cpu="1").obj()
        cache.add_pod(pod)
        cache.update_snapshot(snap)
        # the OTHER consumer reads first...
        nt2 = tc2.update(snap)
        assert nt2.delta.changed_rows.tolist() == [2]
        # ...and tc1 still sees the change (and packs the row)
        nt1 = tc1.update(snap)
        assert nt1.delta.changed_rows.tolist() == [2]
        assert nt1.requested[2, 0] == 1000
        assert nt2.requested[2, 0] == 1000

    def test_foreign_snapshot_full_walk_same_result(self):
        """A snapshot the cache has no baseline for still packs
        correctly (tests/tools construct fresh snapshots)."""
        from kubernetes_tpu.cache.snapshot import new_snapshot

        node = make_node("f").capacity(cpu="4", memory="8Gi").obj()
        pod = make_pod("fp").node("f").container(cpu="1").obj()
        tc = NodeTensorCache()
        nt = tc.update(new_snapshot([pod], [node]))
        assert nt.requested[nt.row("f"), 0] == 1000
        nt = tc.update(new_snapshot([pod], [node]))
        assert nt.requested[nt.row("f"), 0] == 1000


class TestRandomizedMembershipChurn:
    """PR-6 satellite: interleaved node add/remove/reorder + external
    pod churn (the bind-failure shape: content changes the scheduler
    never mirrored) must keep (a) the slot-packed tensor equal to a
    fresh full pack of the same cluster, per name, (b) the handshake's
    shadow equal to host truth after every negotiation, and (c) the
    layout epoch UNCHANGED -- pure membership churn never full-repacks
    while adds stay inside the slot headroom."""

    def test_differential_vs_fresh_pack(self, sched_stack):
        import random

        rng = random.Random(20260803)
        sched = sched_stack
        cache = SchedulerCache()
        from kubernetes_tpu.api.types import Node, ObjectMeta

        nodes = {}
        pods_by_node = {}
        seq = [0]

        def new_node():
            name = f"rc-{seq[0]}"
            seq[0] += 1
            node = (
                make_node(name)
                .capacity(cpu="16", memory="32Gi", pods=64)
                .obj()
            )
            nodes[name] = node
            pods_by_node[name] = []
            cache.add_node(node)

        for _ in range(12):
            new_node()
        snap = Snapshot()
        cache.update_snapshot(snap)
        tc = sched.tensor_cache
        nt = tc.update(snap)
        capacity0 = nt.capacity
        layout0 = tc.layout_epoch
        _prime(sched, nt)

        def fresh_pack():
            from kubernetes_tpu.cache.snapshot import new_snapshot

            live_pods = [
                p for ps in pods_by_node.values() for p in ps
            ]
            return NodeTensorCache().update(
                new_snapshot(live_pods, list(nodes.values()))
            )

        uploads0 = sched.state_uploads
        for step in range(80):
            op = rng.choice(
                ["add", "remove", "reorder", "pod_add", "pod_del"]
            )
            if op == "add" and len(nodes) < capacity0 - 2:
                new_node()
            elif op == "remove" and len(nodes) > 3:
                name = rng.choice(sorted(nodes))
                for p in pods_by_node.pop(name):
                    cache.remove_pod(p)
                del nodes[name]
                cache.remove_node(
                    Node(metadata=ObjectMeta(name=name))
                )
            elif op == "reorder":
                names = list(snap.node_info_map)
                rng.shuffle(names)
                snap.node_info_map = {
                    n: snap.node_info_map[n] for n in names
                }
                snap.refresh_lists()
            elif op == "pod_add":
                name = rng.choice(sorted(nodes))
                p = (
                    make_pod(f"rp-{step}")
                    .node(name)
                    .container(cpu="250m", memory="256Mi")
                    .obj()
                )
                pods_by_node[name].append(p)
                cache.add_pod(p)
            else:  # pod_del: external removal the mirror never saw
                cands = [n for n in sorted(nodes) if pods_by_node[n]]
                if not cands:
                    continue
                name = rng.choice(cands)
                p = pods_by_node[name].pop()
                cache.remove_pod(p)
            cache.update_snapshot(snap)
            nt = tc.update(snap)

            # -- handshake: carry must stay warm (scatters only) --------
            neg = _negotiate(sched, nt)
            assert neg is not None, f"step {step}: drain demanded"
            assert neg["carry_ok"], f"step {step}: carry dropped"
            s = len(nt.names)
            assert np.array_equal(
                sched._dev.req_shadow[:s], nt.requested[:s]
            ), f"step {step}: shadow != host"

            # -- tensor content: equal to a fresh full pack per name ----
            fresh = fresh_pack()
            assert sorted(n for n in nt.names if n) == sorted(
                fresh.names
            )
            for name in nodes:
                i, k = nt.row(name), fresh.row(name)
                assert np.array_equal(
                    nt.requested[i], fresh.requested[k]
                ), f"step {step}: {name} requested"
                assert np.array_equal(
                    nt.allocatable[i], fresh.allocatable[k]
                ), f"step {step}: {name} allocatable"
                assert np.array_equal(
                    nt.non_zero_requested[i],
                    fresh.non_zero_requested[k],
                ), f"step {step}: {name} nzr"
                assert nt.valid[i]
            # free slots stay infeasible like padding
            for i, name in enumerate(nt.names):
                if not name:
                    assert not nt.valid[i]
                    assert (nt.allocatable[i] == 0).all()
                    assert (nt.requested[i] == 0).all()

        # the whole churn run rode scatters: zero layout bumps, zero
        # extra full uploads
        assert tc.layout_epoch == layout0
        assert tc.full_repacks == 1
        assert sched.state_uploads == uploads0


class TestApplyAssignmentDelta:
    def test_no_node_slots_drop_instead_of_wrapping(self):
        """Regression: JAX wraps negative indices even with
        ``mode="drop"`` -- NO_NODE (-1) slots must not scatter their
        pod rows onto the LAST node row of the resident state."""
        import jax.numpy as jnp

        from kubernetes_tpu.ops.assignment import (
            NO_NODE,
            apply_assignment_delta,
        )

        req = jnp.zeros((4, 3), dtype=jnp.int32)
        nzr = jnp.zeros((4, 2), dtype=jnp.int32)
        assigns = np.asarray([NO_NODE, 2, NO_NODE, 7], dtype=np.int32)
        pod_req = np.full((4, 3), 5, dtype=np.int32)
        pod_nzr = np.full((4, 2), 7, dtype=np.int32)
        req2, nzr2 = apply_assignment_delta(
            req, nzr, assigns, pod_req, pod_nzr
        )
        req2, nzr2 = np.asarray(req2), np.asarray(nzr2)
        assert req2[2].tolist() == [5, 5, 5]  # the one placed pod
        assert nzr2[2].tolist() == [7, 7]
        # NO_NODE and past-the-end slots leave every other row alone
        for i in (0, 1, 3):
            assert req2[i].tolist() == [0, 0, 0], f"row {i} corrupted"
            assert nzr2[i].tolist() == [0, 0], f"row {i} corrupted"


class TestHostTierAllocBookkeeping:
    def test_host_tier_after_layout_change_drops_stale_alloc(
        self, monkeypatch
    ):
        """Regression: the handshake books a full static upload
        (layout moved), but the ladder lands on the HOST tier so no
        jitted solve runs and the alloc/valid pieces never reach the
        device -- the stale device refs must drop, or the next dispatch
        would solve against the previous layout's allocatable."""
        from kubernetes_tpu.robustness.ladder import TIER_HOST_GREEDY

        server = APIServer()
        client = Client(server)
        informers = InformerFactory(server)
        sched = new_scheduler(client, informers, batch=True, max_batch=8)
        for i in range(3):
            client.create_node(
                make_node(f"ht-{i}")
                .capacity(cpu="8", memory="16Gi")
                .obj()
            )
        informers.start()
        informers.wait_for_cache_sync()
        sched.queue.run()
        try:
            # dispatch 1 on the device tier: resident alloc established
            client.create_pod(
                make_pod("ht-p0").container(cpu="100m").obj()
            )
            deadline = time.time() + 10
            while time.time() < deadline:
                if sched.schedule_batch(timeout=0.2):
                    break
            sched.wait_for_inflight_binds(timeout=30)
            assert sched._dev.alloc_dev is not None
            assert sched.state_uploads == 1

            # layout change: a node joins (full static upload booked)
            client.create_node(
                make_node("ht-new")
                .capacity(cpu="8", memory="16Gi")
                .obj()
            )
            deadline = time.time() + 10
            while time.time() < deadline:
                if "ht-new" in sched.cache._nodes:
                    break
                time.sleep(0.02)

            # ...but the device tiers are down: the HOST tier solves
            orig_run = sched.ladder.run

            def host_only(attempts, label="batch"):
                for tier, thunk in attempts:
                    if tier == TIER_HOST_GREEDY:
                        return tier, thunk()
                return orig_run(attempts, label=label)

            monkeypatch.setattr(sched.ladder, "run", host_only)
            client.create_pod(
                make_pod("ht-p1").container(cpu="100m").obj()
            )
            deadline = time.time() + 10
            while time.time() < deadline:
                if sched.schedule_batch(timeout=0.2):
                    break
            sched.wait_for_inflight_binds(timeout=30)
            assert sched._dev.alloc_dev is None, (
                "stale device alloc survived a host-tier solve that "
                "never uploaded the new layout"
            )
            assert sched._dev.valid_dev is None

            # device tier back: the next dispatch re-uploads in full
            # and places correctly against the 4-node layout
            monkeypatch.setattr(sched.ladder, "run", orig_run)
            uploads = sched.state_uploads
            client.create_pod(
                make_pod("ht-p2").container(cpu="100m").obj()
            )
            deadline = time.time() + 10
            while time.time() < deadline:
                if sched.schedule_batch(timeout=0.2):
                    break
            sched.wait_for_inflight_binds(timeout=30)
            assert sched.state_uploads == uploads + 1
            bound = [
                p for p in client.list_pods()[0] if p.spec.node_name
            ]
            assert len(bound) == 3
        finally:
            sched.stop()
            informers.stop()
