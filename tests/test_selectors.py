from kubernetes_tpu.api.selectors import (
    labels_match_selector,
    match_node_selector_term,
    node_matches_node_selector,
)
from kubernetes_tpu.api.types import (
    LabelSelector,
    LabelSelectorRequirement,
    NodeSelector,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    Taint,
    Toleration,
)


def test_nil_selector_matches_nothing():
    assert not labels_match_selector({"a": "b"}, None)


def test_empty_selector_matches_everything():
    assert labels_match_selector({"a": "b"}, LabelSelector())
    assert labels_match_selector({}, LabelSelector())


def test_match_labels():
    sel = LabelSelector(match_labels={"app": "web"})
    assert labels_match_selector({"app": "web", "x": "y"}, sel)
    assert not labels_match_selector({"app": "db"}, sel)


def test_match_expressions():
    sel = LabelSelector(
        match_expressions=[
            LabelSelectorRequirement(key="tier", operator="In", values=["a", "b"]),
            LabelSelectorRequirement(key="gone", operator="DoesNotExist"),
        ]
    )
    assert labels_match_selector({"tier": "a"}, sel)
    assert not labels_match_selector({"tier": "c"}, sel)
    assert not labels_match_selector({"tier": "a", "gone": "1"}, sel)


def test_node_selector_terms_or():
    sel = NodeSelector(
        node_selector_terms=[
            NodeSelectorTerm(
                match_expressions=[
                    NodeSelectorRequirement(key="zone", operator="In", values=["z1"])
                ]
            ),
            NodeSelectorTerm(
                match_expressions=[
                    NodeSelectorRequirement(key="zone", operator="In", values=["z2"])
                ]
            ),
        ]
    )
    assert node_matches_node_selector({"zone": "z2"}, sel)
    assert not node_matches_node_selector({"zone": "z3"}, sel)


def test_empty_term_matches_nothing():
    assert not match_node_selector_term({"a": "b"}, NodeSelectorTerm())


def test_gt_lt_operators():
    term = NodeSelectorTerm(
        match_expressions=[
            NodeSelectorRequirement(key="cores", operator="Gt", values=["8"])
        ]
    )
    assert match_node_selector_term({"cores": "16"}, term)
    assert not match_node_selector_term({"cores": "4"}, term)
    assert not match_node_selector_term({"cores": "abc"}, term)
    assert not match_node_selector_term({}, term)


def test_match_fields():
    term = NodeSelectorTerm(
        match_fields=[
            NodeSelectorRequirement(
                key="metadata.name", operator="In", values=["node-1"]
            )
        ]
    )
    assert match_node_selector_term({}, term, node_fields={"metadata.name": "node-1"})
    assert not match_node_selector_term({}, term, node_fields={"metadata.name": "x"})


def test_toleration_matching():
    taint = Taint(key="gpu", value="true", effect="NoSchedule")
    assert Toleration(key="gpu", operator="Equal", value="true").tolerates(taint)
    assert Toleration(key="gpu", operator="Exists").tolerates(taint)
    assert Toleration(key="", operator="Exists").tolerates(taint)  # match-all
    assert not Toleration(key="gpu", operator="Equal", value="false").tolerates(taint)
    assert not Toleration(
        key="gpu", operator="Exists", effect="NoExecute"
    ).tolerates(taint)
