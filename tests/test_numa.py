"""Single-NUMA-aligned extended resources (plugins/numa.py): the
device-manager hint semantics (manager.go:103 GetTopologyHints) lifted
to scheduling time. BASELINE config #4."""

import time

from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.cache.node_info import NodeInfo
from kubernetes_tpu.client.client import Client
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.framework.interface import CycleState
from kubernetes_tpu.plugins.numa import (
    ALIGNED_ANNOTATION,
    ASSIGNED_ANNOTATION,
    GROUPS_LABEL,
    NodeResourcesNumaAligned,
    group_free,
)
from kubernetes_tpu.scheduler.scheduler import new_scheduler
from kubernetes_tpu.testing import make_node, make_pod


def _gpu_pod(name, gpus, aligned=True):
    w = make_pod(name).container(
        cpu="100m", memory="128Mi", **{"nvidia_com__gpu": gpus}
    )
    if aligned:
        w.pod.metadata.annotations[ALIGNED_ANNOTATION] = "nvidia.com/gpu"
    return w.obj()


def _gpu_node(name, groups="4_4"):
    nw = make_node(name).capacity(
        cpu="32", memory="64Gi", pods=20, **{"nvidia_com__gpu": 8}
    )
    nw.label(GROUPS_LABEL, groups)
    return nw.obj()


class TestFilterAndReserve:
    def test_filter_rejects_fragmented_groups(self):
        plugin = NodeResourcesNumaAligned()
        ni = NodeInfo(_gpu_node("n"))
        # two pods holding 3 GPUs in each group: 1+1 free, no group fits 2
        for g in (0, 1):
            p = _gpu_pod(f"held{g}", 3)
            p.metadata.annotations[ASSIGNED_ANNOTATION] = str(g)
            ni.add_pod(p)
        assert group_free(ni, "nvidia.com/gpu") == [1, 1]
        st = plugin.filter(CycleState(), _gpu_pod("w", 2), ni)
        assert st is not None and not st.is_success()
        # an unaligned 2-GPU pod is untouched by the plugin
        assert plugin.filter(CycleState(), _gpu_pod("w2", 2, aligned=False), ni) is None

    def test_filter_rejects_unlabeled_node(self):
        plugin = NodeResourcesNumaAligned()
        node = _gpu_node("n")
        del node.metadata.labels[GROUPS_LABEL]
        st = plugin.filter(CycleState(), _gpu_pod("w", 2), NodeInfo(node))
        assert st is not None and not st.is_success()


class TestE2EAlignment:
    def test_group_capacity_never_exceeded(self):
        server = APIServer()
        client = Client(server)
        informers = InformerFactory(server)
        sched = new_scheduler(client, informers, batch=True, max_batch=64)
        for i in range(6):
            client.create_node(_gpu_node(f"n{i}"))
        informers.start()
        informers.wait_for_cache_sync()
        sched.queue.run()
        # 24 aligned 2-GPU pods exactly fill 6 nodes x 2 groups x 4 GPUs
        for i in range(24):
            client.create_pod(_gpu_pod(f"g{i}", 2))
        sched.start()
        deadline = time.time() + 60
        while time.time() < deadline:
            pods, _ = client.list_pods()
            if sum(1 for p in pods if p.spec.node_name) >= 24:
                break
            time.sleep(0.05)
        sched.wait_for_inflight_binds()
        pods, _ = client.list_pods()
        bound = [p for p in pods if p.spec.node_name]
        assert len(bound) == 24
        # invariant: per (node, group) GPU usage <= 4
        usage = {}
        for p in bound:
            g = p.metadata.annotations[ASSIGNED_ANNOTATION]
            key = (p.spec.node_name, g)
            usage[key] = usage.get(key, 0) + 2
        assert all(v <= 4 for v in usage.values()), usage
        sched.stop()
        informers.stop()

    def test_misaligned_excess_pod_stays_pending(self):
        server = APIServer()
        client = Client(server)
        informers = InformerFactory(server)
        sched = new_scheduler(client, informers, batch=True, max_batch=64)
        client.create_node(_gpu_node("only", groups="3_5"))
        informers.start()
        informers.wait_for_cache_sync()
        sched.queue.run()
        # 5-aligned fits only group 1; a second 4-GPU pod can't align
        client.create_pod(_gpu_pod("big", 5))
        sched.start()
        deadline = time.time() + 30
        while time.time() < deadline:
            pods, _ = client.list_pods()
            if any(p.spec.node_name for p in pods):
                break
            time.sleep(0.05)
        client.create_pod(_gpu_pod("second", 4))
        deadline = time.time() + 15
        cond = False
        while time.time() < deadline:
            try:
                p2 = client.get_pod("default", "second")
            except KeyError:
                break
            if p2.spec.node_name:
                raise AssertionError("4-GPU pod cannot align on 3_5 node")
            if any(
                c.type == "PodScheduled" and c.status == "False"
                for c in p2.status.conditions
            ):
                cond = True
                break
            time.sleep(0.05)
        assert cond
        sched.stop()
        informers.stop()


class TestFragmentationDiscriminates:
    def test_fragmented_node_rejected_despite_total_capacity(self):
        """The alignment-discriminating shape: total free devices would
        fit the pod, but no single group does -- only the NUMA filter
        can reject this (plain resource fit would pass)."""
        server = APIServer()
        client = Client(server)
        informers = InformerFactory(server)
        sched = new_scheduler(client, informers, batch=True, max_batch=64)
        client.create_node(_gpu_node("frag", groups="4_4"))
        # a second node with one whole free group: the aligned pod must
        # land HERE, not on the fragmented node
        client.create_node(_gpu_node("roomy", groups="4_4"))
        informers.start()
        informers.wait_for_cache_sync()
        sched.queue.run()
        # fragment node "frag": 3 GPUs held in EACH group (2 free total,
        # 1+1 split); fill one roomy group too
        holders = []
        for node, g, gpus in (
            ("frag", 0, 3), ("frag", 1, 3), ("roomy", 0, 4),
        ):
            p = _gpu_pod(f"h-{node}-{g}", gpus)
            p.spec.node_name = node
            p.metadata.annotations[ASSIGNED_ANNOTATION] = str(g)
            holders.append(p)
            client.create_pod(p)
        sched.start()
        client.create_pod(_gpu_pod("want2", 2))
        deadline = time.time() + 30
        placed = None
        while time.time() < deadline:
            try:
                w = client.get_pod("default", "want2")
            except KeyError:
                break
            if w.spec.node_name:
                placed = w
                break
            time.sleep(0.05)
        assert placed is not None and placed.spec.node_name == "roomy", (
            placed.spec.node_name if placed else "never bound"
        )
        assert placed.metadata.annotations[ASSIGNED_ANNOTATION] == "1"
        sched.stop()
        informers.stop()
