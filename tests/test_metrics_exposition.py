"""Exposition-format guards (ISSUE 13 satellites): label-value
escaping, labeled callback gauges, and the end-to-end /metrics scrape
lint -- every line parses, HELP/TYPE precede samples, no duplicate
series, histogram _count equals the +Inf bucket. Catches the two
metrics.py fixes regressing, with the real HTTP handler in the loop.
"""

import json
import re
import time
import urllib.request

import pytest

from kubernetes_tpu.scheduler.app import SchedulerApp
from kubernetes_tpu.testing import make_node, make_pod
from kubernetes_tpu.utils import metrics


class TestLabelEscaping:
    def test_quote_backslash_newline_escape(self):
        c = metrics.Counter("esc_total", "help", ("point",))
        c.inc(point='node "a"\\zone\nline2')
        line = [ln for ln in c.collect() if not ln.startswith("#")][0]
        assert line == (
            'esc_total{point="node \\"a\\"\\\\zone\\nline2"} 1.0'
        )
        # the escaped form survives a strict sample-line parse
        assert _SAMPLE_RE.match(line), line

    def test_plain_values_unchanged(self):
        c = metrics.Counter("esc2_total", "help", ("tier",))
        c.inc(tier="pallas")
        line = [ln for ln in c.collect() if not ln.startswith("#")][0]
        assert line == 'esc2_total{tier="pallas"} 1.0'

    def test_histogram_labels_escape_too(self):
        h = metrics.Histogram(
            "esc_seconds", "help", ("name",), buckets=(1.0,)
        )
        h.observe(0.5, name='x"y')
        for ln in h.collect():
            if ln.startswith("#"):
                continue
            assert _SAMPLE_RE.match(ln), ln


class TestCallbackGauges:
    def test_constructor_fn_with_labels_rejected(self):
        with pytest.raises(ValueError):
            metrics.Gauge("bad_gauge", "help", ("q",), fn=lambda: 1.0)

    def test_per_label_callbacks_collect(self):
        g = metrics.Gauge("cb_gauge", "help", ("q",))
        g.register_callback(lambda: 0.25, q="0.5")
        g.register_callback(lambda: 0.75, q="0.99")
        lines = [ln for ln in g.collect() if not ln.startswith("#")]
        assert 'cb_gauge{q="0.5"} 0.25' in lines
        assert 'cb_gauge{q="0.99"} 0.75' in lines
        assert g.value(q="0.5") == 0.25
        # a set() under the same labels does not shadow the callback
        g.set(99.0, q="0.5")
        assert g.value(q="0.5") == 0.25
        assert len(
            [ln for ln in g.collect() if 'q="0.5"' in ln]
        ) == 1

    def test_unlabeled_callback_still_works(self):
        g = metrics.Gauge("plain_cb", "help", fn=lambda: 7.0)
        assert g.value() == 7.0
        assert "plain_cb 7.0" in g.collect()


# one Prometheus text-format sample line: name{labels} value
_SAMPLE_RE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*'
    r'(\{[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\\n]|\\\\|\\"|\\n)*"'
    r'(,[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\\n]|\\\\|\\"|\\n)*")*\})? '
    r'-?[0-9.e+\-]+(\.[0-9]+)?$'
)


def _lint_exposition(body: str):
    """The scrape lint: every line parses, HELP/TYPE precede their
    family's samples, no duplicate series, histogram _count == +Inf
    bucket. Returns (families_seen, problems)."""
    problems = []
    seen_series = set()
    headered = set()  # families with HELP+TYPE already emitted
    help_seen = set()
    type_of = {}
    inf_buckets = {}
    counts = {}
    for ln in body.splitlines():
        if not ln:
            continue
        if ln.startswith("# HELP "):
            help_seen.add(ln.split()[2])
            continue
        if ln.startswith("# TYPE "):
            parts = ln.split()
            fam = parts[2]
            type_of[fam] = parts[3]
            if fam not in help_seen:
                problems.append(f"TYPE before HELP: {fam}")
            headered.add(fam)
            continue
        if ln.startswith("#"):
            problems.append(f"unknown comment line: {ln!r}")
            continue
        if not _SAMPLE_RE.match(ln):
            problems.append(f"unparseable sample: {ln!r}")
            continue
        series = ln.rsplit(" ", 1)[0]
        name = series.split("{", 1)[0]
        fam = re.sub(r"_(bucket|sum|count)$", "", name)
        if fam not in headered and name not in headered:
            problems.append(f"sample before HELP/TYPE: {ln!r}")
        if series in seen_series:
            problems.append(f"duplicate series: {series!r}")
        seen_series.add(series)
        value = float(ln.rsplit(" ", 1)[1])
        if name.endswith("_bucket") and 'le="+Inf"' in series:
            key = re.sub(r',?le="\+Inf"', "", series).replace(
                "_bucket", ""
            ).replace("{}", "")
            inf_buckets[key] = value
        elif name.endswith("_count") and type_of.get(fam) == "histogram":
            counts[series.replace("_count", "")] = value
    for key, n in counts.items():
        if key not in inf_buckets:
            problems.append(f"histogram without +Inf bucket: {key!r}")
        elif inf_buckets[key] != n:
            problems.append(
                f"histogram {key!r}: _count {n} != +Inf bucket "
                f"{inf_buckets[key]}"
            )
    return headered, problems


class TestMetricsEndpointE2E:
    def test_scrape_lints_clean_during_burst(self):
        """Scrape the real SchedulerApp HTTP handler after a small
        burst (histograms, labeled counters, callback gauges, and the
        fault-point label with a quoted value all live) and lint the
        payload."""
        app = SchedulerApp()
        host, port = app.start_serving()
        client = app.client
        for i in range(8):
            client.create_node(
                make_node(f"n{i}").capacity(cpu="16", memory="32Gi")
                .obj()
            )
        app.start()
        names = [f"m-{i}" for i in range(60)]
        for n in names:
            client.create_pod(make_pod(n).container(cpu="100m").obj())
        deadline = time.time() + 30
        while time.time() < deadline:
            pods, _ = client.list_pods()
            if all(p.spec.node_name for p in pods):
                break
            time.sleep(0.05)
        app.sched.wait_for_inflight_binds()
        # a label value with quote/backslash/newline must survive the
        # scrape (the _fmt_labels escaping fix, end-to-end)
        metrics.faults_injected.inc(point='evil "point"\\with\nnewline')

        base = f"http://{host}:{port}"
        body = urllib.request.urlopen(base + "/metrics").read().decode()
        families, problems = _lint_exposition(body)
        assert not problems, problems[:10]
        # the new series are live
        assert "scheduler_tpu_state_uploads_total" in body
        assert "scheduler_pod_to_bind_quantile_seconds" in body
        assert 'q="0.99"' in body
        # blast-radius containment families (ISSUE 14): registered in
        # the default registry so dashboards can alert on a quarantine
        # or audit mismatch the moment the first one books
        assert "scheduler_tpu_bisections_total" in body
        assert "scheduler_tpu_bisect_subsolves_total" in body
        assert "scheduler_ladder_exhausted_crashloops_total" in body
        assert "scheduler_quarantine_pods_total" in body
        assert "scheduler_quarantine_parked" in body
        assert "scheduler_quarantine_releases_total" in body
        assert "scheduler_tpu_carry_audit_sweeps_total" in body
        assert "scheduler_tpu_carry_audit_mismatches_total" in body
        assert "scheduler_tpu_device_lost_total" in body
        assert "scheduler_tpu_device_rebuild_ms" in body
        # multi-tenant fairness families (ISSUE 15): the quota ledger
        # counters and the DRF dominant-share gauge ride the default
        # registry so a starving tenant or a leaking ledger alerts from
        # the first scrape
        assert "scheduler_quota_admissions_total" in body
        assert "scheduler_quota_refunds_total" in body
        assert "scheduler_quota_parked" in body
        assert "scheduler_quota_releases_total" in body
        assert "scheduler_tenant_dominant_share" in body
        # hollow-node / closed-bind-loop families (ISSUE 17): ack path,
        # heartbeat plane, and the zombie-recovery arc are all
        # registered in the default registry so a silent kubelet shows
        # up on a dashboard before the rebind sweep fires
        assert "scheduler_hollow_acks_total" in body
        assert "scheduler_hollow_heartbeats_total" in body
        assert "scheduler_bind_acks_total" in body
        assert "scheduler_bind_ack_latency_seconds" in body
        assert "scheduler_bind_ack_timeouts_total" in body
        assert "scheduler_rebinds_total" in body
        assert "scheduler_bind_ack_pending" in body
        assert "scheduler_bind_ack_suspect_nodes_tainted_total" in body
        assert "scheduler_node_heartbeat_lapses_total" in body
        assert "scheduler_taint_evictions_total" in body
        # pipelined speculative dispatch + carry compression (ISSUE 18):
        # the rewind ledger and the compression engage/disengage state
        # must be scrapeable even at zero samples (HELP/TYPE emit
        # unconditionally) so dashboards can alert on rewind storms
        assert "scheduler_speculative_launches_total" in body
        assert "scheduler_speculative_rewinds_total" in body
        assert "scheduler_tpu_carry_compressed" in body
        assert "scheduler_tpu_carry_compress_bytes_saved_total" in body
        assert "scheduler_tpu_carry_compress_disengages_total" in body
        # and the quantile gauge carries a real estimate post-burst
        p99 = metrics.pod_to_bind_quantile.value(q="0.99")
        assert p99 > 0.0

        # the flight-recorder debug endpoint next door: valid JSON with
        # the burst's spans
        fr = urllib.request.urlopen(
            base + "/debug/flightrecorder"
        ).read().decode()
        doc = json.loads(fr)
        assert isinstance(doc["spans"], list)
        assert isinstance(doc["marks"], list)
        assert any(
            s["tier"] in ("pallas", "xla", "host_greedy")
            for s in doc["spans"]
        )
        app.stop()
