"""Speculative-chain tier-1 guard (ISSUE 18).

The pipelined dispatcher launches batch N+1's solve against the
post-N EXPECTED carry (the committer's shadow) while batch N is still
committing. This suite pins the whole contract:

- a steady 1k-pod burst with in-flight speculation places every pod
  IDENTICALLY to the sequential oracle (batch=False scheduler) with
  ``carry_divergences == 0`` -- the expectation was never wrong;
- under a one-bind-conflict chaos profile, all pods still bind, the
  rewind ledger (``speculative_rewinds``) stays bounded, and the
  uid-keyed watch-history replay proves exactly-once binds per
  incarnation (zero double-binds);
- the int16 carry-compression differential: a cluster sized inside the
  lossless range gate places bit-identically with
  KTPU_CARRY_COMPRESS=1 and =0, and matches the oracle.
"""

import random
import time

import numpy as np

from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client.client import Client
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.robustness.faults import (
    FaultInjector,
    FaultPoint,
    FaultProfile,
    PointConfig,
    install_injector,
)
from kubernetes_tpu.scheduler.scheduler import new_scheduler
from kubernetes_tpu.testing import make_node, make_pod
from kubernetes_tpu.utils import metrics

import pytest


@pytest.fixture(autouse=True)
def _clean_injector():
    yield
    install_injector(None)


class _KeepFirstRng:
    def randrange(self, n):
        return 1 if n > 1 else 0

    def randint(self, a, b):
        return b


def _pods(num, seed, cpu_choices=(100, 200, 250), mem_choices=(128, 256)):
    rng = random.Random(seed)
    out = []
    for i in range(num):
        out.append(
            make_pod(f"s{i}")
            .creation_timestamp(float(i))
            .container(
                cpu=f"{rng.choice(cpu_choices)}m",
                memory=f"{rng.choice(mem_choices)}Mi",
            )
            .obj()
        )
    return out


def _run(
    pods,
    *,
    batch,
    nodes=16,
    node_cpu="64",
    node_mem="256Gi",
    max_batch=128,
    chunk=128,
    timeout=120.0,
    slow_commit=0.0,
):
    server = APIServer()
    client = Client(server)
    informers = InformerFactory(server)
    sched = new_scheduler(
        client, informers, batch=batch, max_batch=max_batch,
        rng=_KeepFirstRng(),
    )
    if batch and slow_commit:
        # hold each commit on the committer thread long enough that the
        # dispatcher provably gets ahead: the next solves launch against
        # the shadow expectation while batches are still committing.
        # Purely a scheduling-pressure knob -- the commit itself is
        # untouched, so correctness must hold with REAL speculation.
        orig_complete = sched._complete_solve

        def _held(p, _orig=orig_complete):
            time.sleep(slow_commit)
            _orig(p)

        sched._complete_solve = _held
    for i in range(nodes):
        client.create_node(
            make_node(f"g{i}")
            .capacity(cpu=node_cpu, memory=node_mem, pods=200)
            .obj()
        )
    informers.start()
    informers.wait_for_cache_sync()
    sched.queue.run()
    sched.start()
    # chunked creates so several batches are in flight concurrently
    # (one bulk create of everything would drain as one giant batch)
    for lo in range(0, len(pods), chunk):
        client.create_pods_bulk(pods[lo:lo + chunk])
    deadline = time.time() + timeout
    while time.time() < deadline:
        ps, _ = client.list_pods()
        if sum(1 for p in ps if p.spec.node_name) >= len(pods):
            break
        time.sleep(0.05)
    sched.wait_for_inflight_binds()
    placements = {
        p.metadata.name: p.spec.node_name
        for p in client.list_pods()[0]
    }
    sched.stop()
    informers.stop()
    return placements, sched, server


def test_speculative_burst_matches_sequential_oracle():
    """1k pods, max_batch small enough that the burst spans many
    batches with in-flight speculation: every pod places exactly where
    the sequential oracle puts it, and the speculative expectation was
    never wrong (zero carry divergences, zero drains)."""
    want, _o, _ = _run(_pods(1000, seed=42), batch=False)
    assert all(want.values()), "oracle failed to place a fitting pod"

    got, sched, _ = _run(
        _pods(1000, seed=42), batch=True, max_batch=128,
        slow_commit=0.03,
    )
    assert got == want
    assert sched.pods_fallback == 0
    assert sched.pods_solved_on_device == 1000
    assert sched.carry_divergences == 0, (
        "speculative shadow expectation diverged on a conflict-free run"
    )
    # the pipeline actually pipelined: overlapping launches were counted
    assert sched.speculative_launches > 0, (
        "no solve ever launched with a batch still committing -- the "
        "burst ran serially"
    )
    assert sched.speculative_rewinds == 0


def test_one_bind_conflict_bounded_rewinds_exactly_once_binds():
    """One injected bind conflict mid-burst: every pod still binds, the
    rewind ledger stays bounded (the divergence re-solves ONE batch, it
    does not cascade), and the uid-keyed watch-history replay shows
    exactly-once binds per incarnation -- no double-bind ever reaches
    the apiserver."""
    install_injector(FaultInjector(FaultProfile(
        "spec-one-conflict", seed=0,
        points={
            FaultPoint.BIND_CONFLICT: PointConfig(rate=1.0, max_fires=1),
        },
    )))
    fired_before = metrics.faults_injected.value(
        point=FaultPoint.BIND_CONFLICT
    )
    pods = _pods(600, seed=7)
    placements, sched, server = _run(
        pods, batch=True, max_batch=64, slow_commit=0.03,
    )

    assert all(placements.values()), (
        f"unbound after conflict: "
        f"{[k for k, v in placements.items() if not v][:5]}"
    )
    assert metrics.faults_injected.value(
        point=FaultPoint.BIND_CONFLICT
    ) > fired_before, "the conflict never fired"
    # bounded: a single conflict rewinds at most the in-flight window,
    # not the whole burst
    assert sched.speculative_rewinds <= sched.max_inflight + 2, (
        f"rewind cascade: {sched.speculative_rewinds} rewinds from one "
        f"injected conflict"
    )

    # uid-keyed watch-history replay: per incarnation, the node_name is
    # written exactly once and never rewritten to a different node
    bind_count = {}
    for ev in server._history["Pod"]:
        uid = ev.object.metadata.uid
        node = ev.object.spec.node_name
        if not node:
            continue
        prev = bind_count.get(uid)
        if prev is None:
            bind_count[uid] = (node, 1)
        elif prev[0] != node:
            raise AssertionError(
                f"uid {uid} double-bound: {prev[0]} -> {node}"
            )
    assert len(bind_count) == len(pods)


class TestCarryCompressionDifferential:
    """Randomized placement-parity differential for the int16 resident
    carry: a cluster whose per-node KiB/milliCPU totals sit inside the
    lossless range gate must place bit-identically with the compressed
    carry, the int32 carry (KTPU_CARRY_COMPRESS=0), and the sequential
    oracle."""

    def _small_unit_pods(self, num, seed):
        # 1Mi = 1024 KiB per pod: 24 pods saturate a 24Mi node at
        # exactly the 24576 ceiling, so the gate stays engaged for the
        # whole run and compression is lossless by construction
        rng = random.Random(seed)
        out = []
        for i in range(num):
            out.append(
                make_pod(f"c{i}")
                .creation_timestamp(float(i))
                .container(
                    cpu=f"{rng.choice([50, 100, 150])}m",
                    memory=f"{rng.choice([512, 1024])}Ki",
                )
                .obj()
            )
        return out

    def _run_mode(self, pods, monkeypatch, flag):
        # max_batch=16: the range gate bounds a batch by its TOTAL load
        # (any assignment is possible), so 16 x 1024 KiB stays inside
        # the 24576 ceiling and the early batches run compressed; the
        # gate then disengages as the resident carry fills, which
        # exercises the lossless mode-flip conversion too
        monkeypatch.setenv("KTPU_CARRY_COMPRESS", flag)
        return _run(
            pods, batch=True, nodes=40, node_cpu="4",
            node_mem="24Mi", max_batch=16, slow_commit=0.01,
        )

    def test_placement_parity_compressed_vs_int32_vs_oracle(
        self, monkeypatch
    ):
        mk = lambda: self._small_unit_pods(300, seed=11)  # noqa: E731
        want, _o, _ = _run(
            mk(), batch=False, nodes=40, node_cpu="4", node_mem="24Mi",
        )
        assert all(want.values())

        on, sched_on, _ = self._run_mode(mk(), monkeypatch, "1")
        off, sched_off, _ = self._run_mode(mk(), monkeypatch, "0")

        assert sched_on.carry_compress_enabled
        assert not sched_off.carry_compress_enabled
        assert on == want, "compressed carry diverged from the oracle"
        assert off == want, "int32 carry diverged from the oracle"
        assert sched_on.carry_divergences == 0
        assert sched_on.pods_fallback == 0
        # the compressed run actually ran compressed (bytes were saved)
        # -- a silently-disengaged gate would pass parity trivially
        assert metrics.carry_compress_bytes_saved.value() > 0
