"""Randomized batch-vs-sequential differential over the FULL score
plugin surface (VERDICT r2 weak #5: score parity rested on one
hand-built scenario).

Clusters mix every device score family at once: distinct capacities
(resource scorers), zones + services (SelectorSpread), PreferNoSchedule
taints (TaintToleration), node images (ImageLocality), preferred node
affinity, soft topology spread, and preferred pod (anti-)affinity with
symmetric existing-pod terms. The sequential path (KeepFirst tie RNG,
score-all) is the oracle; the batch path must place identically.
"""

import random
import time

import pytest

from kubernetes_tpu.api.types import ObjectMeta, Service
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client.client import Client
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.scheduler.scheduler import new_scheduler
from kubernetes_tpu.testing import make_node, make_pod


class _KeepFirstRng:
    def randrange(self, n):
        return 1 if n > 1 else 0

    def randint(self, a, b):
        return b


def _wait_decided(client, sched, count, timeout=90.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        pods, _ = client.list_pods()
        pending = [
            p for p in pods
            if not p.spec.node_name and not p.status.conditions
        ]
        if len(pods) >= count and not pending:
            sched.wait_for_inflight_binds()
            return client.list_pods()[0]
        time.sleep(0.05)
    raise AssertionError("pods not decided in time")


def _build_cluster(rng, client, server):
    zones = ["z1", "z2", "z3"]
    for i in range(10):
        w = (
            make_node(f"n{i}")
            .labels(zone=zones[i % 3], disk="ssd" if i % 4 == 0 else "hdd")
            .capacity(cpu=str(6 + 3 * i), memory=f"{16 + 7 * i}Gi")
        )
        if i % 5 == 2:
            w.taint("best-effort", "true", effect="PreferNoSchedule")
        if i % 3 == 1:
            w.image("registry/app:v1", (i + 1) * 100_000_000)
        client.create_node(w.obj())
    server.create(
        Service(
            metadata=ObjectMeta(name="web", namespace="default"),
            selector={"app": "web"},
        )
    )
    apps = ["web", "db", "cache"]
    for j in range(8):
        w = (
            make_pod(f"ex{j}")
            .node(f"n{rng.randrange(10)}")
            .labels(app=rng.choice(apps))
            .container(cpu="100m", memory="128Mi")
        )
        if rng.random() < 0.4:
            w.preferred_pod_affinity(
                "zone", {"app": rng.choice(apps)},
                weight=rng.choice([1, 7]),
                anti=rng.random() < 0.5,
            )
        client.create_pod(w.obj())


def _build_batch(rng):
    apps = ["web", "db", "cache"]
    out = []
    for i in range(14):
        w = (
            make_pod(f"m{i}")
            .labels(app=rng.choice(apps))
            .creation_timestamp(float(i))
            .container(
                cpu=f"{rng.choice([100, 300, 700])}m",
                memory=f"{rng.choice([128, 384])}Mi",
                image="registry/app:v1" if rng.random() < 0.4 else "",
            )
        )
        roll = rng.random()
        if roll < 0.25:
            w.preferred_node_affinity_in(
                "disk", ["ssd"], weight=rng.choice([1, 5])
            )
        elif roll < 0.45:
            w.preferred_pod_affinity(
                "zone", {"app": rng.choice(apps)},
                weight=rng.choice([1, 9]),
                anti=rng.random() < 0.4,
            )
        elif roll < 0.6:
            w.spread_constraint(
                2, "zone", when_unsatisfiable="ScheduleAnyway",
                match_labels={"app": "web"},
            )
        elif roll < 0.7:
            w.toleration("best-effort", value="true")
        out.append(w.obj())
    return out


def _run(seed, batch):
    rng = random.Random(seed)
    server = APIServer()
    client = Client(server)
    informers = InformerFactory(server)
    sched = new_scheduler(
        client, informers, batch=batch, max_batch=64,
        percentage_of_nodes_to_score=100, rng=_KeepFirstRng(),
    )
    _build_cluster(rng, client, server)
    informers.start()
    informers.wait_for_cache_sync()
    sched.queue.run()
    for p in _build_batch(rng):
        client.create_pod(p)
    sched.start()
    pods = _wait_decided(client, sched, 22)
    sched.stop()
    informers.stop()
    return {
        p.metadata.name: p.spec.node_name
        for p in pods
        if p.metadata.name.startswith("m")
    }


@pytest.mark.parametrize("seed", [2, 13, 37, 71])
def test_full_score_surface_batch_matches_sequential(seed):
    assert _run(seed, batch=True) == _run(seed, batch=False)


def _build_scoped_spread_batch(rng):
    """Hard zone-spread COUPLED with node-pool selectors (VERDICT r4
    missing #6): pair counting must scope to each pod's eligible
    nodes."""
    out = []
    for i in range(20):
        w = (
            make_pod(f"m{i}")
            .labels(app="web")
            .container(cpu="100m", memory="128Mi")
        )
        roll = rng.random()
        if roll < 0.4:
            w.spread_constraint(
                1, "zone", when_unsatisfiable="DoNotSchedule",
                match_labels={"app": "web"},
            ).node_selector(pool="a")
        elif roll < 0.6:
            w.spread_constraint(
                1, "zone", when_unsatisfiable="DoNotSchedule",
                match_labels={"app": "web"},
            ).node_selector(pool="b")
        elif roll < 0.8:
            w.spread_constraint(
                2, "zone", when_unsatisfiable="DoNotSchedule",
                match_labels={"app": "web"},
            )
        out.append(w.obj())
    return out


def _run_scoped(seed, batch):
    rng = random.Random(seed)
    server = APIServer()
    client = Client(server)
    informers = InformerFactory(server)
    sched = new_scheduler(
        client, informers, batch=batch, max_batch=64,
        percentage_of_nodes_to_score=100, rng=_KeepFirstRng(),
    )
    for i in range(18):
        client.create_node(
            make_node(f"n{i}")
            .capacity(cpu="8", memory="16Gi", pods=20)
            .labels(zone=f"z{i % 3}", pool="a" if i % 2 == 0 else "b")
            .obj()
        )
    # seed a few existing matching pods so initial counts differ by pool
    for i in range(5):
        p = (
            make_pod(f"ex{i}").labels(app="web")
            .container(cpu="100m", memory="128Mi")
            .node(f"n{i}")
            .obj()
        )
        client.create_pod(p)
    informers.start()
    informers.wait_for_cache_sync()
    sched.queue.run()
    for p in _build_scoped_spread_batch(rng):
        client.create_pod(p)
    sched.start()
    pods = _wait_decided(client, sched, 20)
    fallback = sched.pods_fallback if batch else None
    sched.stop()
    informers.stop()
    return {
        p.metadata.name: p.spec.node_name
        for p in pods
        if p.metadata.name.startswith("m")
    }, fallback


@pytest.mark.parametrize("seed", [3, 17, 53])
def test_spread_with_node_selector_batch_matches_sequential(seed):
    got_batch, fallback = _run_scoped(seed, batch=True)
    got_seq, _ = _run_scoped(seed, batch=False)
    assert got_batch == got_seq
    # the coupling solves ON DEVICE now (no solver_supported carve-out)
    assert fallback == 0
