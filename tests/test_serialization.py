"""Wire-format codec (api/serialization.py): Kubernetes manifests decode
to typed objects, round-trip, and schedule end-to-end."""

import textwrap
import time

from kubernetes_tpu.api.serialization import (
    load_manifest,
    node_from_dict,
    node_to_dict,
    object_from_dict,
    pod_from_dict,
    pod_to_dict,
)
from kubernetes_tpu.api.types import RESOURCE_CPU, RESOURCE_MEMORY
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client.client import Client
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.scheduler.scheduler import new_scheduler

POD_YAML = textwrap.dedent(
    """
    apiVersion: v1
    kind: Pod
    metadata:
      name: web-0
      labels: {app: web}
    spec:
      schedulerName: default-scheduler
      priority: 10
      nodeSelector: {disk: ssd}
      containers:
        - name: app
          image: registry/app:v1
          resources:
            requests: {cpu: 250m, memory: 512Mi}
          ports:
            - {containerPort: 8080, protocol: TCP}
      tolerations:
        - {key: dedicated, operator: Equal, value: web, effect: NoSchedule}
      topologySpreadConstraints:
        - maxSkew: 1
          topologyKey: topology.kubernetes.io/zone
          whenUnsatisfiable: DoNotSchedule
          labelSelector:
            matchLabels: {app: web}
      affinity:
        podAntiAffinity:
          requiredDuringSchedulingIgnoredDuringExecution:
            - labelSelector:
                matchLabels: {app: web}
              topologyKey: kubernetes.io/hostname
    ---
    apiVersion: v1
    kind: Node
    metadata:
      name: n0
      labels: {disk: ssd, topology.kubernetes.io/zone: z1}
    status:
      capacity: {cpu: "8", memory: 16Gi, pods: 110}
    """
)


def test_pod_decodes_fully(tmp_path):
    path = tmp_path / "m.yaml"
    path.write_text(POD_YAML)
    objs = load_manifest(str(path))
    pod, node = objs
    assert pod.metadata.name == "web-0"
    assert pod.spec.priority == 10
    assert pod.spec.node_selector == {"disk": "ssd"}
    c = pod.spec.containers[0]
    assert c.resources.requests[RESOURCE_CPU] == 250
    assert c.resources.requests[RESOURCE_MEMORY] == 512 * 1024 * 1024
    assert pod.spec.tolerations[0].value == "web"
    assert pod.spec.topology_spread_constraints[0].topology_key == (
        "topology.kubernetes.io/zone"
    )
    anti = pod.spec.affinity.pod_anti_affinity.required_during_scheduling[0]
    assert anti.topology_key == "kubernetes.io/hostname"
    assert node.status.allocatable[RESOURCE_CPU] == 8000


def test_round_trip():
    import yaml

    raw = yaml.safe_load_all(POD_YAML)
    docs = [d for d in raw if d]
    pod = pod_from_dict(docs[0])
    pod2 = pod_from_dict(pod_to_dict(pod))
    assert pod2.spec.node_selector == pod.spec.node_selector
    assert (
        pod2.spec.containers[0].resources.requests
        == pod.spec.containers[0].resources.requests
    )
    # constraint surfaces survive the round-trip
    anti = pod2.spec.affinity.pod_anti_affinity.required_during_scheduling
    assert anti[0].topology_key == "kubernetes.io/hostname"
    assert anti[0].label_selector.match_labels == {"app": "web"}
    assert (
        pod2.spec.topology_spread_constraints[0].label_selector.match_labels
        == {"app": "web"}
    )
    assert pod2.spec.tolerations == pod.spec.tolerations
    node = node_from_dict(docs[1])
    node2 = node_from_dict(node_to_dict(node))
    assert node2.status.allocatable == node.status.allocatable


def test_unknown_kind_rejected():
    import pytest

    with pytest.raises(ValueError, match="unsupported kind"):
        object_from_dict({"kind": "Deployment"})


def test_manifest_objects_schedule_end_to_end(tmp_path):
    path = tmp_path / "m.yaml"
    path.write_text(POD_YAML)
    server = APIServer()
    client = Client(server)
    informers = InformerFactory(server)
    sched = new_scheduler(client, informers, batch=True, max_batch=16)
    for obj in load_manifest(str(path)):
        server.create(obj)
    informers.start()
    informers.wait_for_cache_sync()
    sched.queue.run()
    sched.start()
    deadline = time.time() + 30
    bound = False
    while time.time() < deadline:
        pod = client.get_pod("default", "web-0")
        if pod.spec.node_name:
            bound = True
            break
        time.sleep(0.05)
    sched.stop()
    informers.stop()
    assert bound
    assert pod.spec.node_name == "n0"
