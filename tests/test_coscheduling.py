"""Gang scheduling tests: Permit barrier, all-or-nothing, timeout
rollback (reference mechanism: Permit/WaitingPod, SURVEY.md section 2.2)."""

import time

import pytest

from kubernetes_tpu.api.types import ObjectMeta, POD_GROUP_LABEL, PodGroup
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client.client import Client
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.scheduler.scheduler import new_scheduler
from kubernetes_tpu.testing import make_node, make_pod


def _gang_pod(name, group, cpu="500m", ts=0.0):
    p = (
        make_pod(name).creation_timestamp(ts)
        .container(cpu=cpu, memory="256Mi").obj()
    )
    p.metadata.labels[POD_GROUP_LABEL] = group
    return p


@pytest.fixture(params=[False, True], ids=["sequential", "batch"])
def cluster(request):
    server = APIServer()
    client = Client(server)
    informers = InformerFactory(server)
    sched = new_scheduler(client, informers, batch=request.param)
    yield server, client, informers, sched
    sched.stop()
    informers.stop()


def _wait(fn, timeout=15.0, interval=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(interval)
    return False


class TestGang:
    def test_full_gang_binds_together(self, cluster):
        server, client, informers, sched = cluster
        client.create_node(make_node("n").capacity(cpu="8", memory="16Gi").obj())
        client.create_pod_group(PodGroup(
            metadata=ObjectMeta(name="job", namespace="default"),
            min_member=3, schedule_timeout_seconds=30,
        ))
        informers.start()
        informers.wait_for_cache_sync()
        for i in range(3):
            client.create_pod(_gang_pod(f"g{i}", "job", ts=float(i)))
        sched.start()
        ok = _wait(lambda: all(
            p.spec.node_name for p in client.list_pods()[0]
        ))
        sched.wait_for_inflight_binds()
        assert ok, "gang never fully bound"

    def test_partial_gang_times_out_and_releases(self, cluster):
        server, client, informers, sched = cluster
        client.create_node(make_node("n").capacity(cpu="8", memory="16Gi").obj())
        client.create_pod_group(PodGroup(
            metadata=ObjectMeta(name="job", namespace="default"),
            min_member=3, schedule_timeout_seconds=1,
        ))
        informers.start()
        informers.wait_for_cache_sync()
        # only 2 of 3 members exist: PreFilter fails fast, nothing binds
        for i in range(2):
            client.create_pod(_gang_pod(f"g{i}", "job", ts=float(i)))
        sched.start()
        time.sleep(2.5)
        sched.wait_for_inflight_binds()
        pods, _ = client.list_pods()
        assert all(not p.spec.node_name for p in pods), [
            (p.name, p.spec.node_name) for p in pods
        ]
        # capacity must have been released: a plain pod schedules fine
        client.create_pod(make_pod("plain").container(cpu="7").obj())
        ok = _wait(
            lambda: client.get_pod("default", "plain").spec.node_name != ""
        )
        assert ok, "capacity not released after gang failure"

    def test_gang_members_arriving_late_complete(self, cluster):
        server, client, informers, sched = cluster
        client.create_node(make_node("n").capacity(cpu="8", memory="16Gi").obj())
        client.create_pod_group(PodGroup(
            metadata=ObjectMeta(name="job", namespace="default"),
            min_member=2, schedule_timeout_seconds=30,
        ))
        informers.start()
        informers.wait_for_cache_sync()
        sched.start()
        client.create_pod(_gang_pod("early", "job", ts=0.0))
        time.sleep(0.5)
        # first member alone must not be bound yet (waiting at permit)
        assert not client.get_pod("default", "early").spec.node_name
        client.create_pod(_gang_pod("late", "job", ts=1.0))
        ok = _wait(lambda: all(
            p.spec.node_name for p in client.list_pods()[0]
        ))
        sched.wait_for_inflight_binds()
        assert ok, "gang did not complete when the second member arrived"

    def test_non_gang_pods_unaffected(self, cluster):
        server, client, informers, sched = cluster
        client.create_node(make_node("n").capacity(cpu="4", memory="8Gi").obj())
        informers.start()
        informers.wait_for_cache_sync()
        client.create_pod(make_pod("p").container(cpu="1").obj())
        sched.start()
        ok = _wait(
            lambda: client.get_pod("default", "p").spec.node_name != ""
        )
        assert ok
