"""Sinkhorn OT assignment tests."""

import numpy as np
import jax.numpy as jnp

from kubernetes_tpu.ops.assignment import (
    NO_NODE,
    greedy_assign_scored,
)
from kubernetes_tpu.ops.sinkhorn import refine_scores, sinkhorn_plan


def test_plan_respects_capacities():
    b, n = 6, 3
    score = jnp.zeros((b, n), dtype=jnp.float32)
    feasible = jnp.ones((b, n), dtype=bool)
    slots = jnp.asarray([1.0, 2.0, 3.0])
    active = jnp.ones(b, dtype=bool)
    plan = np.asarray(sinkhorn_plan(score, feasible, slots, active))
    col = plan.sum(axis=0)
    assert (col <= np.asarray(slots) + 0.05).all(), col
    # every pod keeps ~unit mass
    assert np.allclose(plan.sum(axis=1), 1.0, atol=0.05)


def test_infeasible_cells_carry_no_mass():
    score = jnp.zeros((2, 2), dtype=jnp.float32)
    feasible = jnp.asarray([[True, False], [True, True]])
    plan = np.asarray(sinkhorn_plan(
        score, feasible, jnp.asarray([5.0, 5.0]), jnp.ones(2, dtype=bool)
    ))
    assert plan[0, 1] < 1e-6


def test_global_plan_beats_myopic_contention():
    """2 pods, 2 nodes. Node 0 scores higher for both, but has one slot;
    the OT plan routes one pod to node 1 so both place with high mass."""
    score = jnp.asarray([[10.0, 9.0], [10.0, 1.0]], dtype=jnp.float32)
    feasible = jnp.ones((2, 2), dtype=bool)
    slots = jnp.asarray([1.0, 1.0])
    plan = np.asarray(sinkhorn_plan(
        score, feasible, slots, jnp.ones(2, dtype=bool), tau=2.0
    ))
    # pod 1 (who NEEDS node 0 much more) gets node 0; pod 0 shifts to 1
    assert plan[1, 0] > plan[0, 0]
    assert plan[0, 1] > plan[1, 1]


def test_scored_scan_commits_feasible_assignment():
    n, b, r = 4, 6, 2
    alloc = np.zeros((n, r), dtype=np.int32)
    alloc[:, 0] = 2000  # cpu
    alloc[:, 1] = 10  # pods
    requested = np.zeros_like(alloc)
    pod_req = np.zeros((b, r), dtype=np.int32)
    pod_req[:, 0] = 1000
    pod_req[:, 1] = 1
    static = np.ones((b, n), dtype=bool)
    active = np.ones(b, dtype=bool)
    score = refine_scores(
        jnp.zeros((b, n), dtype=jnp.float32),
        jnp.asarray(static),
        jnp.full((n,), 2.0, dtype=jnp.float32),
        jnp.asarray(active),
    )
    assignments, req_out = greedy_assign_scored(
        jnp.asarray(alloc),
        jnp.asarray(requested),
        jnp.ones(n, dtype=bool),
        jnp.asarray(pod_req),
        jnp.asarray(static),
        jnp.asarray(active),
        score,
    )
    a = np.asarray(assignments)
    # 4 nodes x 2 cpu slots = 8 >= 6 pods: all placed, never over capacity
    assert (a != NO_NODE).all()
    assert (np.asarray(req_out)[:, 0] <= 2000).all()
    counts = np.bincount(a, minlength=n)
    assert counts.max() <= 2
