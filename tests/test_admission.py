"""Admission-classifier tests: ingest-time classification, memo reuse on
the dispatch hot path, and the stale-classification edges (a PVC binding
landing mid-queue, a queued pod's volumes mutating) that MUST re-classify
instead of dispatching under the cached class."""

import pytest

from kubernetes_tpu.api.types import (
    CSINode,
    CSINodeDriver,
    ObjectMeta,
    PersistentVolume,
    PersistentVolumeClaim,
)
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client.client import Client
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.scheduler.scheduler import new_scheduler
from kubernetes_tpu.testing import make_node, make_pod


@pytest.fixture
def stack():
    """Pump-mode stack: informer events drain synchronously on the test
    thread, so classification timing is deterministic."""
    server = APIServer()
    client = Client(server)
    informers = InformerFactory(server)
    sched = new_scheduler(client, informers, batch=True, max_batch=16)
    yield server, client, informers, sched
    sched.stop()
    informers.stop()


def _bound_csi_pv(server, claim, volume, driver="ebs.csi.aws.com"):
    server.create(
        PersistentVolumeClaim(
            metadata=ObjectMeta(name=claim, namespace="default"),
            volume_name=volume,
            requested_bytes=1 << 30,
        )
    )
    server.create(
        PersistentVolume(
            metadata=ObjectMeta(name=volume, namespace=""),
            capacity_bytes=1 << 30,
            claim_ref_namespace="default",
            claim_ref_name=claim,
            csi_driver=driver,
            csi_volume_handle=volume,
        )
    )


class TestClassification:
    def test_plain_pod_is_device_class(self, stack):
        _, _, _, sched = stack
        adm = sched.classify_pod(make_pod("p").container(cpu="1").obj())
        assert adm.device_ok
        assert adm.reason == ""
        assert adm.klass == "device"

    def test_numa_annotation_is_host(self, stack):
        _, _, _, sched = stack
        pod = make_pod("p").container(cpu="1").obj()
        pod.metadata.annotations["numa.kubernetes-tpu.io/aligned"] = "2"
        adm = sched.classify_pod(pod)
        assert not adm.device_ok
        assert adm.reason == "numa-aligned"
        assert adm.klass == "host"

    def test_direct_volume_source_is_host_but_counted(self, stack):
        _, _, _, sched = stack
        adm = sched.classify_pod(
            make_pod("p").container(cpu="1").gce_pd("disk-1").obj()
        )
        assert not adm.device_ok
        assert adm.reason == "direct-volume-source"
        assert adm.vol_counts == (("attachable-volumes-gce-pd", 1),)

    def test_constrained_shapes_keep_device_class(self, stack):
        _, _, _, sched = stack
        adm = sched.classify_pod(
            make_pod("p").container(cpu="1")
            .pod_affinity("zone", {"a": "b"}, anti=True).obj()
        )
        assert adm.device_ok
        assert adm.required_anti and adm.affinity_req
        assert adm.klass == "constrained"

    def test_bound_csi_pvc_is_device_with_counts(self, stack):
        server, client, informers, sched = stack
        _bound_csi_pv(server, "c1", "v1")
        informers.pump()
        pod = make_pod("p").container(cpu="1").pvc("c1").obj()
        adm = sched._admission_of(pod)
        assert adm.device_ok, adm.reason
        assert adm.vol_counts == (
            ("attachable-volumes-csi-ebs.csi.aws.com", 1),
        )
        assert adm.has_pvc
        # the in-use accounting memo landed alongside
        assert pod.__dict__["_volcount_memo"] == adm.vol_counts
        # and the pop-time read registered the volume column with the
        # tensor schema (dispatcher-thread registration; classify_pod
        # itself must not grow dims from informer threads)
        dims = sched.tensor_cache.dims
        assert (
            "attachable-volumes-csi-ebs.csi.aws.com" in dims.volume_columns()
        )

    def test_unbound_pvc_is_host(self, stack):
        _, _, _, sched = stack
        adm = sched.classify_pod(
            make_pod("p").container(cpu="1").pvc("nope").obj()
        )
        assert not adm.device_ok
        assert adm.reason == "unbound-pvc"

    def test_memo_reused_on_hot_path(self, stack):
        _, _, _, sched = stack
        pod = make_pod("p").container(cpu="1").obj()
        a1 = sched._admission_of(pod)
        n = sched.admissions_classified
        a2 = sched._admission_of(pod)
        assert a1 is a2
        assert sched.admissions_classified == n


class TestStaleClassification:
    def test_pvc_binding_mid_queue_reclassifies(self, stack):
        """Satellite: a pod classified host-only (unbound claim) whose
        PVC binding lands while it waits in the queue must be
        re-classified at pop time -- the volume-topology generation bump
        invalidates the cached record."""
        server, client, informers, sched = stack
        client.create_node(
            make_node("n0").capacity(cpu="8", memory="16Gi").obj()
        )
        informers.pump()
        client.create_pod(
            make_pod("p").container(cpu="1").pvc("c1").obj()
        )
        informers.pump()
        queued = sched.queue.pending_pods()
        assert len(queued) == 1
        adm = queued[0].__dict__["_admission"]
        assert not adm.device_ok and adm.reason == "unbound-pvc"

        # the binding lands mid-queue (PVC + PV events bump the gen)
        gen_before = sched._volume_topo_gen
        _bound_csi_pv(server, "c1", "v1")
        informers.pump()
        assert sched._volume_topo_gen > gen_before

        # pop-time admission re-classifies instead of trusting the memo
        reclass_before = sched.reclassifications
        adm2 = sched._admission_of(queued[0])
        assert sched.reclassifications == reclass_before + 1
        assert adm2.device_ok, adm2.reason
        assert adm2.vol_counts == (
            ("attachable-volumes-csi-ebs.csi.aws.com", 1),
        )

    def test_mutated_volumes_reclassify(self, stack):
        """Satellite: updating a queued pod's volumes replaces the pod
        object in the queue; the new object is classified on ingest and
        dispatch must route it by the NEW class."""
        server, client, informers, sched = stack
        client.create_node(
            make_node("n0").capacity(cpu="8", memory="16Gi").obj()
        )
        informers.pump()
        client.create_pod(make_pod("p").container(cpu="1").obj())
        informers.pump()
        queued = sched.queue.pending_pods()[0]
        assert queued.__dict__["_admission"].device_ok

        updated = queued.deepcopy()
        updated.spec.volumes = (
            make_pod("tmp").gce_pd("disk-1").obj().spec.volumes
        )
        client.update_pod(updated)
        informers.pump()
        queued2 = sched.queue.pending_pods()[0]
        assert queued2 is not queued
        adm = queued2.__dict__["_admission"]
        assert not adm.device_ok
        assert adm.reason == "direct-volume-source"

        # and the dispatcher actually routes it to the host path
        sched.queue.run()
        n_fallback = sched.pods_fallback
        sched.schedule_batch(timeout=0.1)
        sched.wait_for_inflight_binds()
        assert sched.pods_fallback == n_fallback + 1

    def test_foreign_token_reclassifies(self, stack):
        """A memo written by another scheduler instance (different
        extenders / dims registry) is never trusted."""
        _, _, _, sched = stack
        pod = make_pod("p").container(cpu="1").obj()
        adm = sched.classify_pod(pod)
        adm.token = object()  # simulate a foreign owner
        n = sched.admissions_classified
        adm2 = sched._admission_of(pod)
        assert adm2 is not adm
        assert sched.admissions_classified == n + 1


class TestIngestClassification:
    def test_burst_classified_on_ingest_not_dispatch(self, stack):
        """The dispatch loop must be a memo read: after ingest, popping
        and routing the batch classifies nothing new."""
        server, client, informers, sched = stack
        client.create_node(
            make_node("n0").capacity(cpu="32", memory="64Gi").obj()
        )
        informers.pump()
        for i in range(10):
            client.create_pod(
                make_pod(f"p{i}").container(cpu="100m").obj()
            )
        informers.pump()
        assert sched.admissions_classified >= 10
        n = sched.admissions_classified
        batch = sched.queue.pop_batch(16)
        assert len(batch) == 10
        for pi in batch:
            assert sched._admission_of(pi.pod).device_ok
        assert sched.admissions_classified == n


class TestCSINodeCache:
    def test_csi_node_limits_reach_node_info(self, stack):
        server, client, informers, sched = stack
        client.create_node(
            make_node("n0").capacity(cpu="8", memory="16Gi").obj()
        )
        server.create(
            CSINode(
                metadata=ObjectMeta(name="n0", namespace=""),
                drivers=[
                    CSINodeDriver(
                        name="ebs.csi.aws.com", node_id="n0",
                        allocatable_count=3,
                    )
                ],
            )
        )
        informers.pump()
        ni = sched.cache._nodes["n0"]
        assert ni.csi_volume_limits == {
            "attachable-volumes-csi-ebs.csi.aws.com": 3
        }
        assert ni.volume_limit(
            "attachable-volumes-csi-ebs.csi.aws.com"
        ) == 3
        # unknown driver -> unlimited; in-tree -> reference default
        from kubernetes_tpu.cache.node_info import VOLUME_UNLIMITED

        assert ni.volume_limit(
            "attachable-volumes-csi-other"
        ) == VOLUME_UNLIMITED
        assert ni.volume_limit("attachable-volumes-aws-ebs") == 39

    def test_csi_node_before_node_applies_on_add(self, stack):
        server, client, informers, sched = stack
        server.create(
            CSINode(
                metadata=ObjectMeta(name="late", namespace=""),
                drivers=[
                    CSINodeDriver(
                        name="d", node_id="late", allocatable_count=5
                    )
                ],
            )
        )
        informers.pump()
        client.create_node(
            make_node("late").capacity(cpu="8", memory="16Gi").obj()
        )
        informers.pump()
        ni = sched.cache._nodes["late"]
        assert ni.csi_volume_limits == {"attachable-volumes-csi-d": 5}
