"""Differential tests for the native ingest plane (ISSUE 12).

Every native ingest entry point (_hotpath.c "ingest spine") has a
pure-Python twin with identical semantics, selected by
KTPU_NATIVE_INGEST=0:

  ingest_apply   <-> client/informer._apply_events_py
  ingest_stamp   <-> scheduler/admission.stamp_plain_pods
  pack_gather    <-> tensors/node_tensor._pack_gather_py
  queue_shape    <-> queue/scheduling_queue._queue_shape_py

The randomized suites here drive seeded event streams / pod populations
through BOTH and assert identical informer stores, queue contents,
admission memos, and packed [B, R] rows -- including the
malformed-frame edge. The tier-1 guard at the bottom pins the whole
plane end-to-end: a steady 1k-pod open-loop burst with ZERO
native->Python fallbacks, pack+pop under 10% of wall-clock, and
placements equal to the sequential oracle.
"""

import random
import time

import numpy as np
import pytest

from kubernetes_tpu import native
from kubernetes_tpu.apiserver.server import APIServer, Binding, WatchEvent
from kubernetes_tpu.client.client import Client
from kubernetes_tpu.client.informer import InformerFactory, _apply_events_py
from kubernetes_tpu.framework.interface import PodInfo
from kubernetes_tpu.plugins.queuesort import PrioritySort
from kubernetes_tpu.queue.scheduling_queue import (
    PriorityQueue,
    _queue_shape_py,
)
from kubernetes_tpu.scheduler import admission as adm_mod
from kubernetes_tpu.scheduler.scheduler import new_scheduler
from kubernetes_tpu.tensors.node_tensor import (
    ResourceDims,
    _pack_gather_py,
    pack_pod_batch,
    stamp_pack_row,
)
from kubernetes_tpu.testing import make_node, make_pod
from kubernetes_tpu.utils import metrics

needs_native = pytest.mark.skipif(
    native.hotpath is None, reason="native extension unavailable"
)

MEMO_KEYS = (
    "_req_memo", "_nzr_memo", "_hot_memo", "_packrow", "_band_priority",
)


def _rand_pod(rng, i, plain_bias=0.7):
    """A randomized pod: mostly plain, with every non-plain feature the
    fast-path gate must route to the full classifier."""
    b = (
        make_pod(f"r{i}")
        .creation_timestamp(float(i))
        .container(
            cpu=f"{rng.choice([0, 100, 200, 500])}m",
            memory=f"{rng.choice([0, 128, 256])}Mi",
        )
    )
    if rng.random() < 0.3:
        b = b.container(cpu="50m", memory="64Mi")
    if rng.random() < 0.2:
        b = b.priority(rng.choice([0, 10, 100]))
    if rng.random() < 0.2:
        b = b.node_selector(zone=f"z{rng.randrange(3)}")
    if rng.random() >= plain_bias:
        feature = rng.randrange(6)
        if feature == 0:
            b = b.pvc(f"claim-{i}")
        elif feature == 1:
            b = b.node_affinity_in("zone", ["z1"])
        elif feature == 2:
            b = b.spread_constraint(1, "zone", "DoNotSchedule")
        elif feature == 3:
            from kubernetes_tpu.api.types import POD_GROUP_LABEL

            b = b.labels(**{POD_GROUP_LABEL: "g1"})
        elif feature == 4:
            b = b.container(cpu="10m", host_port=8000 + i % 100)
        else:
            pod = b.obj()
            pod.spec.priority_class_name = "high-prio"
            return pod
    pod = b.obj()
    if rng.random() < 0.15:
        pod.spec.containers[0].resources.requests[
            "example.com/widget"
        ] = rng.randrange(1, 4)
    return pod


def _memo_dict(pod):
    return {k: pod.__dict__.get(k) for k in MEMO_KEYS}


@needs_native
class TestStampDifferential:
    def test_randomized_population_stamps_identically(self):
        rng = random.Random(1234)
        pods_n = [_rand_pod(rng, i) for i in range(300)]
        rng = random.Random(1234)
        pods_p = [_rand_pod(rng, i) for i in range(300)]

        plain = adm_mod.plain_admission(object())
        cfg = adm_mod.ingest_stamp_cfg(plain)
        rest_n = native.hotpath.ingest_stamp(pods_n, cfg)
        rest_p = adm_mod.stamp_plain_pods(pods_p, plain)
        assert list(rest_n) == list(rest_p)
        assert 0 < len(rest_n) < len(pods_n), (
            "population must mix plain and non-plain pods"
        )
        for a, b in zip(pods_n, pods_p):
            assert _memo_dict(a) == _memo_dict(b), a.metadata.name
            assert (a.__dict__.get("_admission") is plain) == (
                b.__dict__.get("_admission") is plain
            )

    def test_stamped_memos_match_the_real_helpers(self):
        """The C-built memos must be indistinguishable from the lazy
        helpers' output -- the commit/accounting paths read them."""
        from kubernetes_tpu.api.types import pod_resource_requests
        from kubernetes_tpu.cache.node_info import (
            non_zero_requests,
            pod_hot_info,
        )

        rng = random.Random(77)
        pods = [_rand_pod(rng, i, plain_bias=1.1) for i in range(50)]
        plain = adm_mod.plain_admission(object())
        rest = native.hotpath.ingest_stamp(
            pods, adm_mod.ingest_stamp_cfg(plain)
        )
        assert not rest
        for pod in pods:
            fresh = make_pod("x").obj()
            fresh.spec = pod.spec  # same spec, no memos
            assert pod.__dict__["_req_memo"] == pod_resource_requests(fresh)
            assert pod.__dict__["_nzr_memo"] == non_zero_requests(fresh)
            assert pod.__dict__["_hot_memo"] == pod_hot_info(fresh)


@needs_native
class TestApplyDifferential:
    def _event_stream(self, seed, n_ops=400):
        """A REAL apiserver transaction stream: creates, binds, status
        updates, deletes -- collected from the watch log."""
        rng = random.Random(seed)
        server = APIServer()
        client = Client(server)
        w = server.watch("Pod", since_rv=0)
        live = []
        for i in range(n_ops):
            op = rng.random()
            if op < 0.5 or not live:
                pod = make_pod(f"e{i}").container(cpu="100m").obj()
                client.create_pod(pod)
                live.append((pod.metadata.namespace, pod.metadata.name))
            elif op < 0.7:
                ns, name = rng.choice(live)
                try:
                    server.bind(Binding(
                        pod_namespace=ns, pod_name=name,
                        target_node=f"n{rng.randrange(8)}",
                    ))
                except Exception:
                    pass
            elif op < 0.85:
                ns, name = rng.choice(live)

                def mut(p):
                    p.status.nominated_node_name = f"n{rng.randrange(8)}"

                try:
                    server.update_pod_status(ns, name, mut)
                except KeyError:
                    pass
            else:
                ns, name = live.pop(rng.randrange(len(live)))
                try:
                    server.delete("Pod", ns, name)
                except KeyError:
                    pass
        evs = w.pending()
        w.stop()
        return evs

    def test_randomized_stream_applies_identically(self):
        evs = self._event_stream(5)
        assert len(evs) > 300
        s_native, s_twin = {}, {}
        d_native = native.hotpath.ingest_apply(s_native, evs)
        # twin runs on undecoded copies of the same events
        evs2 = [
            WatchEvent(e.type, e.object, e.resource_version) for e in evs
        ]
        d_twin = _apply_events_py(s_twin, evs2)
        assert s_native == s_twin
        assert d_native == d_twin
        # decode-once: the native pass memoized every event's key; a
        # second consumer (twin semantics) reuses the records and
        # converges to the same store
        assert all(e.decoded is not None for e in evs)
        s_again = {}
        d_again = _apply_events_py(s_again, evs)
        assert s_again == s_native and d_again == d_native

    def test_ingest_decode_memoizes_shared_records(self):
        evs = self._event_stream(8, n_ops=40)
        keys = native.hotpath.ingest_decode(evs)
        assert keys == [e.decoded for e in evs]
        assert all(
            k == (e.object.metadata.namespace, e.object.metadata.name)
            for k, e in zip(keys, evs)
        )
        # idempotent: a second decode returns the SAME memoized records
        assert native.hotpath.ingest_decode(evs) == keys
        # downstream consumers of the pre-decoded frame converge
        s_native, s_twin = {}, {}
        native.hotpath.ingest_apply(s_native, evs)
        _apply_events_py(s_twin, evs)
        assert s_native == s_twin

    def test_malformed_frame_raises_identically_with_same_prefix(self):
        good = self._event_stream(6, n_ops=20)
        bad = WatchEvent("ADDED", object(), 10_000)
        frame = good[:10] + [bad] + good[10:]
        s_native, s_twin = {}, {}
        with pytest.raises(AttributeError):
            native.hotpath.ingest_apply(s_native, frame)
        frame2 = [
            WatchEvent(e.type, e.object, e.resource_version) for e in frame
        ]
        with pytest.raises(AttributeError):
            _apply_events_py(s_twin, frame2)
        # both applied exactly the prefix before the malformed event
        assert s_native == s_twin
        s_prefix = {}
        _apply_events_py(s_prefix, [
            WatchEvent(e.type, e.object, e.resource_version)
            for e in good[:10]
        ])
        assert s_native == s_prefix

    def test_informer_stores_identical_under_env_toggle(self, monkeypatch):
        """End-to-end: the same server history replicated through an
        informer with the native plane on vs forced off."""
        stores = {}
        for flag in ("1", "0"):
            monkeypatch.setenv("KTPU_NATIVE_INGEST", flag)
            server = APIServer()
            client = Client(server)
            rng = random.Random(9)
            informers = InformerFactory(server)
            inf = informers.pods()
            inf.pump()
            live = []
            for i in range(200):
                if rng.random() < 0.6 or not live:
                    pod = make_pod(f"p{i}").container(cpu="100m").obj()
                    client.create_pod(pod)
                    live.append(pod.metadata.name)
                else:
                    name = live.pop(rng.randrange(len(live)))
                    server.delete("Pod", "default", name)
                if i % 37 == 0:
                    inf.pump()
            inf.pump()
            # uids are a process-global counter (fresh per run): compare
            # the replicated KEY space + per-key bind state
            stores[flag] = {
                k: v.spec.node_name for k, v in inf._store.items()
            }
        assert stores["1"] == stores["0"]


@needs_native
class TestPackDifferential:
    def _dims(self):
        dims = ResourceDims()
        dims.volume_column("attachable-volumes-csi-x")
        return dims

    def _pods(self, seed, n=256):
        rng = random.Random(seed)
        pods = [_rand_pod(rng, i) for i in range(n)]
        for pod in pods:
            if rng.random() < 0.2:
                pod.__dict__["_volcount_memo"] = (
                    ("attachable-volumes-csi-x", rng.randrange(1, 3)),
                )
        return pods

    def test_pack_rows_identical(self, monkeypatch):
        batches = {}
        for flag in ("1", "0"):
            monkeypatch.setenv("KTPU_NATIVE_INGEST", flag)
            batches[flag] = pack_pod_batch(self._pods(21), self._dims())
        a, b = batches["1"], batches["0"]
        assert np.array_equal(a.requests, b.requests)
        assert np.array_equal(a.non_zero_requests, b.non_zero_requests)
        assert np.array_equal(a.priorities, b.priorities)
        assert np.array_equal(a.order, b.order)
        assert np.array_equal(a.unsatisfiable, b.unsatisfiable)

    def test_out_of_range_row_overflows_on_both_paths(self, monkeypatch):
        """A request that does not fit int32 must raise (numpy's
        OverflowError) on BOTH paths -- silent wraparound on the native
        side would corrupt the fit inputs."""
        pod = make_pod("huge").container(cpu="100m", memory="4Ti").obj()
        for flag in ("1", "0"):
            monkeypatch.setenv("KTPU_NATIVE_INGEST", flag)
            with pytest.raises(OverflowError):
                pack_pod_batch([pod], ResourceDims())

    def test_gather_twin_parity_and_memo_reuse(self):
        pods_a = self._pods(33)
        pods_b = self._pods(33)
        for pod in pods_b:  # pre-stamp one side: memo hit path == miss path
            stamp_pack_row(pod)
        b = len(pods_a)
        out = []
        for pods, fn in (
            (pods_a, native.hotpath.pack_gather),
            (pods_b, _pack_gather_py),
        ):
            idx = np.empty(b, dtype=np.int32)
            nzr = np.empty((b, 2), dtype=np.int32)
            prio = np.empty(b, dtype=np.int32)
            cache = {}
            keys = fn(pods, stamp_pack_row, cache, idx, nzr, prio)
            out.append((list(keys), cache, idx, nzr, prio))
        assert out[0][0] == out[1][0]
        assert out[0][1] == out[1][1]
        for x, y in zip(out[0][2:], out[1][2:]):
            assert np.array_equal(x, y)
        # every pod now carries the memo, and it survives re-gather
        assert all("_packrow" in p.__dict__ for p in pods_a)


@needs_native
class TestQueueDifferential:
    def _queue(self):
        ps = PrioritySort()
        t = [0.0]
        return PriorityQueue(
            ps.queue_sort_less,
            now=lambda: t[0],
            sort_key_func=ps.queue_sort_key,
        )

    def _pods(self, seed, n=200):
        rng = random.Random(seed)
        pods = []
        for i in range(n):
            pod = (
                make_pod(f"q{i % (n - 20)}")  # some duplicate keys
                .priority(rng.choice([0, 0, 0, 10, 100]))
                .container(cpu="100m")
                .obj()
            )
            if rng.random() < 0.1:
                pod.status.nominated_node_name = f"n{rng.randrange(4)}"
            pods.append(pod)
        return pods

    def test_bulk_add_matches_per_pod_path(self, monkeypatch):
        drains = {}
        pending = {}
        noms = {}
        for flag in ("1", "0"):
            monkeypatch.setenv("KTPU_NATIVE_INGEST", flag)
            q = self._queue()
            pods = self._pods(55)
            # seed the side containers so the removal semantics run
            q.add(pods[0])
            popped = q.pop()
            q.add_unschedulable_if_not_present(popped, q.scheduling_cycle)
            q.add_many(pods)
            pending[flag] = q.num_pending()
            name_by_uid = {
                p.metadata.uid: p.metadata.name for p in pods
            }
            noms[flag] = {
                name_by_uid[uid]: node
                for uid, node in
                q.nominated_pods.nominated_pod_to_node.items()
            }
            drains[flag] = [
                pi.pod.metadata.name for pi in q.pop_batch(10_000)
            ]
        assert drains["1"] == drains["0"]
        assert pending["1"] == pending["0"]
        assert noms["1"] == noms["0"]

    def test_shape_twin_parity(self):
        pods = self._pods(66)
        a = native.hotpath.queue_shape(pods)
        b = _queue_shape_py(pods)
        assert tuple(map(list, a)) == tuple(map(list, b))


# -- tier-1 guard ---------------------------------------------------------

NUM_NODES = 16
NUM_PODS = 1000


class _KeepFirstRng:
    def randrange(self, n):
        return 1 if n > 1 else 0

    def randint(self, a, b):
        return b


def _fallback_total():
    vals = metrics.ingest_native_fallbacks._values
    return sum(vals.values())


def _wait_all_bound(client, count, timeout=120.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        pods, _ = client.list_pods()
        if sum(1 for p in pods if p.spec.node_name) >= count:
            return pods
        time.sleep(0.05)
    bound = [p for p in client.list_pods()[0] if p.spec.node_name]
    raise AssertionError(f"only {len(bound)}/{count} pods bound")


#: the guard's sustained open-loop offered rate (pods/s): well inside
#: this box's single-stack capacity, so a healthy ingest plane runs the
#: trace at wall-clock == trace duration and its stage share is the
#: fraction of REAL TIME the control-plane front end consumes
GUARD_RATE = 500.0


def _run_burst(seed, *, batch, profile=False):
    rng = random.Random(seed)
    server = APIServer()
    client = Client(server)
    informers = InformerFactory(server)
    sched = new_scheduler(
        client, informers, batch=batch, max_batch=256,
        rng=_KeepFirstRng(),
    )
    for i in range(NUM_NODES):
        client.create_node(
            make_node(f"g{i}")
            .capacity(cpu="64", memory="256Gi", pods=120)
            .obj()
        )
    pods = []
    for i in range(NUM_PODS):
        pods.append(
            make_pod(f"b{i}")
            .creation_timestamp(float(i))
            .container(
                cpu=f"{rng.choice([100, 200, 250])}m",
                memory=f"{rng.choice([128, 256])}Mi",
            )
            .obj()
        )
    informers.start()
    informers.wait_for_cache_sync()
    sched.queue.run()
    if profile:
        sched.profile_stages = True
        sched.warmup()  # compiles off the measured clock (bench protocol)
        # steady throughput posture: a 50ms batch window coalesces the
        # paced arrivals into real batches (the shape the open-loop
        # controller converges to under sustained load) instead of ~100
        # two-pod dispatches, each paying the fixed per-dispatch pack
        # cost the share measurement is NOT about
        sched.batch_window = 0.05
    sched.start()
    t0 = time.perf_counter()
    if batch:
        # open-loop shape: a STEADY paced arrival process through the
        # apiserver's bulk-create path (the ArrivalEngine replay), so
        # wall-clock is the trace duration and the ingest stage share
        # is measured against sustained real time, not a drain sprint
        from kubernetes_tpu.streaming.arrivals import ArrivalEngine

        offsets = np.arange(NUM_PODS, dtype=np.float64) / GUARD_RATE
        engine = ArrivalEngine(client, offsets, lambda i: pods[i])
        engine.start()
        engine.join(timeout=120)
    else:
        for lo in range(0, NUM_PODS, 256):
            client.create_pods_bulk(pods[lo:lo + 256])
    _wait_all_bound(client, NUM_PODS)
    sched.wait_for_inflight_binds()
    elapsed = time.perf_counter() - t0
    placements = {
        p.metadata.name: p.spec.node_name
        for p in client.list_pods()[0]
    }
    sched.stop()
    informers.stop()
    return placements, sched, elapsed


@needs_native
def test_tier1_ingest_guard_no_fallbacks_low_pop_pack_share_oracle_parity():
    """THE tier-1 guard for the ingest plane: a steady 1k-pod open-loop
    burst must (a) never fall back from the native ingest plane to the
    Python twins, (b) keep the pack + pop_batch (+ classify) stage share
    under 10% of scheduling wall-clock with --profile on, and (c) place
    every pod identically to the sequential oracle."""
    fallbacks_before = _fallback_total()
    want, _oracle, _ = _run_burst(42, batch=False)

    # best-of-2 on the stage SHARE only: wall-clock is pinned by the
    # arrival pacing, so CPU steal from a noisy co-tenant inflates the
    # measured share without the ingest plane regressing -- the same
    # reason bench.py reports the median trial. Correctness assertions
    # (parity, fallbacks) must hold on EVERY attempt.
    share = None
    for _attempt in range(2):
        got, sched, elapsed = _run_burst(42, batch=True, profile=True)

        # (c) oracle parity
        assert all(want.values()), "oracle failed to place a fitting pod"
        assert got == want
        assert sched.pods_fallback == 0
        assert sched.pods_solved_on_device == NUM_PODS

        # (a) every ingest call rode the native plane
        assert _fallback_total() == fallbacks_before, (
            "native->Python ingest fallbacks during the burst"
        )

        assert elapsed >= NUM_PODS / GUARD_RATE * 0.9, (
            f"trace replay finished impossibly fast ({elapsed:.2f}s): "
            f"the open-loop pacing did not run"
        )
        stages = sched.stage_seconds
        ingest_s = (
            stages.get("pack", 0.0)
            + stages.get("pop_batch", 0.0)
            + stages.get("classify", 0.0)
        )
        share = min(share, ingest_s / elapsed) if share else (
            ingest_s / elapsed
        )
        if share < 0.10:
            break

    # (b) the host-side ingest share at the sustained rate: pack + pop
    # drain work + classify must consume under 10% of wall-clock --
    # i.e. the control-plane front end has >= 10x headroom over this
    # offered rate before it becomes the bottleneck
    assert share < 0.10, (
        f"pack+pop+classify share {share:.3f} >= 10% of wall-clock on "
        f"both attempts (last stages: {stages})"
    )
