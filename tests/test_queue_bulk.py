"""Queue bulk-drain guarantees (PR 4): the vectorized pop_batch must be
indistinguishable from the per-pod pop loop -- exact priority order,
attempts/scheduling_cycle bookkeeping, window semantics under racing
adds, no starvation, and lazy-deleted heap entries never surfacing."""

import random
import threading
import time

from kubernetes_tpu.framework.interface import PodInfo
from kubernetes_tpu.plugins.queuesort import PrioritySort
from kubernetes_tpu.queue.heap import Heap
from kubernetes_tpu.queue.scheduling_queue import PriorityQueue
from kubernetes_tpu.testing import make_pod

_SORTER = PrioritySort()


def _pq(now=None, sort_key=True):
    kwargs = {}
    if now is not None:
        kwargs["now"] = lambda: now[0]
    if sort_key:
        kwargs["sort_key_func"] = _SORTER.queue_sort_key
    return PriorityQueue(_SORTER.queue_sort_less, **kwargs)


def _random_pods(rng, n):
    return [
        make_pod(f"p-{i}")
        .priority(rng.randint(0, 5))
        .container(cpu="100m")
        .obj()
        for i in range(n)
    ]


# -- randomized differential: bulk drain == per-pod pop loop --------------


def test_bulk_drain_order_matches_per_pod_pop_randomized():
    rng = random.Random(42)
    for trial in range(20):
        n = rng.randint(1, 120)
        pods = _random_pods(rng, n)
        # interleave adds with deletes so lazy-dead entries exist
        doomed = rng.sample(pods, k=rng.randint(0, n // 3))
        q_bulk = _pq()
        q_ref = _pq()
        for q in (q_bulk, q_ref):
            q.add_many(pods)
            for p in doomed:
                q.delete(p)
        ref_names = []
        while True:
            pi = q_ref.pop(timeout=0.0)
            if pi is None:
                break
            ref_names.append(pi.pod.metadata.name)
        batch_size = rng.choice([1, 7, n, n * 2])
        bulk_names = []
        while True:
            batch = q_bulk.pop_batch(batch_size, timeout=0.0)
            if not batch:
                break
            bulk_names.extend(pi.pod.metadata.name for pi in batch)
        assert bulk_names == ref_names, f"trial {trial} diverged"


def test_bulk_drain_against_less_comparator_only():
    """No sort_key (custom QueueSort plugin shape): pop_bulk must take
    the comparator-faithful path and still match pop()."""
    rng = random.Random(7)
    pods = _random_pods(rng, 60)
    q_bulk = _pq(sort_key=False)
    q_ref = _pq(sort_key=False)
    q_bulk.add_many(pods)
    q_ref.add_many(pods)
    ref = [q_ref.pop(timeout=0.0).pod.metadata.name for _ in range(60)]
    got = [
        pi.pod.metadata.name for pi in q_bulk.pop_batch(60, timeout=0.0)
    ]
    assert got == ref


# -- bookkeeping ----------------------------------------------------------


def test_pop_batch_bumps_scheduling_cycle_per_pod():
    """Regression (PR 4 satellite): pods 2..N used to skip the
    scheduling_cycle bump, skewing move_request_cycle gating."""
    q = _pq()
    q.add_many([make_pod(f"c-{i}").obj() for i in range(5)])
    before = q.scheduling_cycle
    batch = q.pop_batch(5, timeout=0.0)
    assert len(batch) == 5
    assert q.scheduling_cycle == before + 5


def test_pop_batch_increments_attempts_once_per_pod():
    q = _pq()
    q.add_many([make_pod(f"a-{i}").obj() for i in range(8)])
    batch = q.pop_batch(8, timeout=0.0)
    assert [pi.attempts for pi in batch] == [1] * 8
    # requeue + re-pop: attempts keeps counting
    for pi in batch[:3]:
        q.add_unschedulable_if_not_present(pi, q.scheduling_cycle)
    q.move_all_to_active_or_backoff_queue("test")
    q.flush_backoff_q_completed()
    # backoff still pending -> force by waiting it out via big window
    deadline = time.monotonic() + 5
    again = []
    while len(again) < 3 and time.monotonic() < deadline:
        q.flush_backoff_q_completed()
        again.extend(q.pop_batch(3, timeout=0.05))
    assert [pi.attempts for pi in again] == [2] * 3


def test_move_request_cycle_gate_sees_batch_pops():
    """A move DURING a batched attempt must route the failed pods to
    backoffQ (lost-wakeup guard), exactly as with per-pod pops."""
    q = _pq()
    q.add_many([make_pod(f"m-{i}").obj() for i in range(4)])
    batch = q.pop_batch(4, timeout=0.0)
    cycle = q.scheduling_cycle
    q.move_all_to_active_or_backoff_queue("concurrent-event")
    for pi in batch:
        q.add_unschedulable_if_not_present(pi, cycle)
    counts = q.num_pending()
    assert counts["unschedulable"] == 0
    assert counts["backoff"] == 4


def test_deleted_entries_never_surface_in_batch():
    q = _pq()
    pods = [make_pod(f"d-{i}").obj() for i in range(30)]
    q.add_many(pods)
    for p in pods[::2]:
        q.delete(p)
    batch = q.pop_batch(30, timeout=0.0)
    names = {pi.pod.metadata.name for pi in batch}
    assert names == {p.metadata.name for p in pods[1::2]}
    assert q.pop_batch(10, timeout=0.0) == []


def test_overwritten_entries_pop_once_with_latest_object():
    q = _pq()
    old = make_pod("dup").priority(1).obj()
    new = make_pod("dup").priority(4).obj()
    q.add(old)
    q.update(old, new)
    batch = q.pop_batch(5, timeout=0.0)
    assert len(batch) == 1
    assert batch[0].pod.spec.priority == 4


# -- window / concurrency -------------------------------------------------


def test_window_collects_racing_add_many():
    """Arrivals during the batch window join the batch (up to
    max_size); the bulk drain must keep waiting out the window instead
    of returning after the first drain."""
    q = _pq()
    q.add(make_pod("first").obj())

    def late_adds():
        time.sleep(0.05)
        q.add_many([make_pod(f"late-{i}").obj() for i in range(10)])

    t = threading.Thread(target=late_adds)
    t.start()
    batch = q.pop_batch(50, timeout=1.0, window=0.5)
    t.join()
    assert len(batch) == 11
    names = [pi.pod.metadata.name for pi in batch]
    assert names[0] == "first"


def test_window_zero_still_drains_available():
    q = _pq()
    q.add_many([make_pod(f"w-{i}").obj() for i in range(20)])
    batch = q.pop_batch(50, timeout=0.0, window=0.0)
    assert len(batch) == 20


def test_max_size_respected_and_no_starvation():
    """Repeated bounded drains return strictly ordered slices and
    eventually empty the queue -- no entry is skipped or starved."""
    rng = random.Random(3)
    pods = _random_pods(rng, 100)
    q = _pq()
    q.add_many(pods)
    seen = []
    while True:
        batch = q.pop_batch(9, timeout=0.0)
        if not batch:
            break
        assert len(batch) <= 9
        seen.extend(batch)
    assert len(seen) == 100
    keys = [_SORTER.queue_sort_key(pi) for pi in seen]
    assert keys == sorted(keys)


def test_concurrent_drains_partition_the_queue():
    """Two racing drainers must partition the backlog (no pod lost, no
    pod handed to both)."""
    pods = [make_pod(f"r-{i}").obj() for i in range(400)]
    q = _pq()
    q.add_many(pods)
    got = [[], []]

    def drain(slot):
        while True:
            batch = q.pop_batch(16, timeout=0.0)
            if not batch:
                return
            got[slot].extend(pi.pod.metadata.name for pi in batch)

    ts = [
        threading.Thread(target=drain, args=(i,)) for i in range(2)
    ]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert len(got[0]) + len(got[1]) == 400
    assert not (set(got[0]) & set(got[1]))


# -- heap-level pop_bulk --------------------------------------------------


def test_heap_pop_bulk_small_drain_from_large_heap():
    """The heappop path (max_n << heap size) and the sorted path must
    return identical prefixes."""
    h1 = Heap(lambda pi: pi.pod.metadata.name, sort_key=_SORTER.queue_sort_key)
    h2 = Heap(lambda pi: pi.pod.metadata.name, sort_key=_SORTER.queue_sort_key)
    rng = random.Random(11)
    for i in range(500):
        pi = PodInfo(
            make_pod(f"h-{i}").priority(rng.randint(0, 9)).obj(),
            float(i),
        )
        h1.add(pi)
        h2.add(pi)
    # 8*small < 500 forces the heappop path on h1; drain h2 fully sorted
    small = h1.pop_bulk(10)
    rest = h2.pop_bulk(500)
    assert [p.pod.metadata.name for p in small] == [
        p.pod.metadata.name for p in rest[:10]
    ]
    assert len(h1) == 490
