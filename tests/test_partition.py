"""Units for the partition ownership layer (scheduler/partition.py):
consistent-hash partitioning, balanced rendezvous assignment, lease
claim/renew/fencing, spill re-stamping, and the apiserver's typed
bind-conflict surface (BindConflict + PartitionAuthority)."""

import time

import pytest

from kubernetes_tpu.api.types import Lease, ObjectMeta, ResourceQuota
from kubernetes_tpu.apiserver.server import (
    APIServer,
    BindConflict,
    Conflict,
    Gone,
)
from kubernetes_tpu.client.client import Client
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.config.types import PartitionConfiguration
from kubernetes_tpu.controllers.quota import QuotaController
from kubernetes_tpu.robustness.faults import (
    FaultInjector,
    FaultPoint,
    FaultProfile,
    PointConfig,
    install_injector,
)
from kubernetes_tpu.scheduler.partition import (
    PartitionAuthority,
    PartitionCoordinator,
    SPILL_COUNT_ANNOTATION,
    SPILL_TARGET_ANNOTATION,
    compute_assignment,
    partition_of_name,
)
from kubernetes_tpu.testing import make_node, make_pod


@pytest.fixture(autouse=True)
def _clean_injector():
    yield
    install_injector(None)


class TestPartitionHash:
    def test_stable_and_in_range(self):
        for p in (1, 2, 3, 7):
            for name in ("n1", "node-42", "zone-a", ""):
                k = partition_of_name(name, p)
                assert 0 <= k < p
                assert k == partition_of_name(name, p)  # stable

    def test_single_partition_is_zero(self):
        assert partition_of_name("anything", 1) == 0
        assert partition_of_name("anything", 0) == 0

    def test_spreads(self):
        ks = {partition_of_name(f"node-{i}", 4) for i in range(100)}
        assert ks == {0, 1, 2, 3}


class TestGangGroupHoming:
    """ROADMAP item-4e: gang pods home by GROUP key, not per-pod uid,
    so a gang never splits across stacks (a uid-split gang cannot reach
    quorum on either side and pays multi-hop spill convergence)."""

    def _coord(self, num_partitions=4):
        server = APIServer()
        return PartitionCoordinator(
            Client(server), _FakeSched(),
            _config(num_partitions=num_partitions), "s1",
        )

    def test_zero_cross_stack_gang_splits(self):
        """THE regression pin: 20 gangs x 8 members with random uids
        all home to exactly one partition per gang, and the gangs
        themselves still spread across partitions (the group hash is a
        real hash, not a constant)."""
        from kubernetes_tpu.api.types import POD_GROUP_LABEL

        c = self._coord(num_partitions=4)
        homes = {}
        for g in range(20):
            parts = set()
            for m in range(8):
                pod = (
                    make_pod(f"gang{g}-m{m}")
                    .container(cpu="100m")
                    .obj()
                )
                pod.metadata.labels[POD_GROUP_LABEL] = f"group-{g}"
                parts.add(c.pod_partition(pod))
            assert len(parts) == 1, (
                f"gang group-{g} split across partitions {parts}"
            )
            homes[g] = parts.pop()
        assert len(set(homes.values())) > 1, (
            "every gang landed on one partition -- the group hash is "
            "degenerate"
        )

    def test_group_key_is_namespaced(self):
        from kubernetes_tpu.api.types import POD_GROUP_LABEL

        c = self._coord(num_partitions=7)
        pods = {}
        for ns in ("team-a", "team-b"):
            pod = make_pod("g-m0", ns).container(cpu="100m").obj()
            pod.metadata.labels[POD_GROUP_LABEL] = "shared-name"
            pods[ns] = c.pod_partition(pod)
        # same label in different namespaces = different gangs: they
        # may hash anywhere, but each must equal its own recomputation
        for ns, k in pods.items():
            assert k == partition_of_name("%s/shared-name" % ns, 7)

    def test_non_gang_pods_keep_uid_hash(self):
        c = self._coord(num_partitions=5)
        pod = make_pod("plain").container(cpu="100m").obj()
        assert c.pod_partition(pod) == partition_of_name(
            pod.metadata.uid, 5
        )

    def test_spill_annotation_still_overrides_gang_hash(self):
        """A spilled gang member follows its re-stamp: spill is the
        explicit unplaceable-pod escape and must keep working for
        gangs (siblings fail quorum on the same stack and follow to
        the same ring successor)."""
        from kubernetes_tpu.api.types import POD_GROUP_LABEL

        c = self._coord(num_partitions=4)
        pod = make_pod("gang-spilled").container(cpu="100m").obj()
        pod.metadata.labels[POD_GROUP_LABEL] = "g0"
        home = c.pod_partition(pod)
        target = (home + 1) % 4
        pod.metadata.annotations[SPILL_TARGET_ANNOTATION] = str(target)
        assert c.pod_partition(pod) == target


class TestAssignment:
    def test_covers_every_partition(self):
        a = compute_assignment(8, ["a", "b", "c"])
        assert sorted(a) == list(range(8))

    def test_balanced_cap(self):
        for p, m in ((2, 2), (4, 2), (8, 3), (5, 5)):
            members = [f"s{i}" for i in range(m)]
            a = compute_assignment(p, members)
            counts = {mem: 0 for mem in members}
            for owner in a.values():
                counts[owner] += 1
            cap = -(-p // m)
            assert max(counts.values()) <= cap
            # with P >= M every member gets work
            if p >= m:
                assert min(counts.values()) >= 1

    def test_deterministic_and_order_independent(self):
        a1 = compute_assignment(6, ["x", "y", "z"])
        a2 = compute_assignment(6, ["z", "x", "y"])
        assert a1 == a2

    def test_dead_member_partitions_split_with_bounded_collateral(self):
        before = compute_assignment(8, ["a", "b", "c", "d"])
        after = compute_assignment(8, ["a", "b", "c"])
        orphans = {k for k, o in before.items() if o == "d"}
        moved = {k for k in range(8) if before[k] != after.get(k)}
        # every orphan lands on a survivor
        assert orphans <= moved
        for k in orphans:
            assert after[k] in ("a", "b", "c")
        # movement beyond the orphans is the balance-cap rebalance only:
        # bounded by the member count, NOT proportional to P (the
        # "split the orphaned range without reshuffling the world"
        # property a full rehash would violate)
        assert len(moved - orphans) <= 3, (before, after)
        # and the survivors stay balanced under the new cap
        counts = {m: 0 for m in ("a", "b", "c")}
        for owner in after.values():
            counts[owner] += 1
        assert max(counts.values()) <= 3


def _config(**kw):
    defaults = dict(
        enabled=True, num_partitions=2,
        lease_duration_seconds=0.5, retry_period_seconds=0.05,
    )
    defaults.update(kw)
    return PartitionConfiguration(**defaults)


class _FakeSched:
    """The minimal scheduler surface the coordinator touches outside
    adoption (spill bookkeeping + crash flag)."""

    def __init__(self):
        self.pods_spilled = 0
        self.crashed = False
        self.profiles = {}


class TestCoordinatorLeases:
    def test_claims_all_when_alone(self):
        server = APIServer()
        c = PartitionCoordinator(
            Client(server), _FakeSched(), _config(num_partitions=3), "s1"
        )
        # no adoption machinery on the fake sched: short-circuit it
        c._adopt_partition = lambda k: None
        c.step()
        assert sorted(c.held) == [0, 1, 2]
        assert all(c.holds_partition(k) for k in (0, 1, 2))
        assert c.may_bind("node-x")

    def test_two_coordinators_split_and_fence(self):
        server = APIServer()
        cfgs = _config(num_partitions=4)
        cs = []
        for ident in ("s1", "s2"):
            c = PartitionCoordinator(
                Client(server), _FakeSched(), cfgs, ident
            )
            c._adopt_partition = lambda k: None
            c._drop_partition = lambda k: None
            cs.append(c)
        # a few alternating rounds converge to a 2/2 split
        for _ in range(6):
            for c in cs:
                c.step()
        held = [sorted(c.held) for c in cs]
        assert len(held[0]) == 2 and len(held[1]) == 2
        assert sorted(held[0] + held[1]) == [0, 1, 2, 3]
        # fencing: each holds exactly its own partitions
        for c, other in (cs, reversed(cs)):
            for k in c.held:
                assert c.holds_partition(k)
                assert not other.holds_partition(k)

    def test_renew_failure_drops_held_locally_and_sibling_adopts(self):
        server = APIServer()
        cfgs = _config(num_partitions=2)
        cs = []
        for ident in ("s1", "s2"):
            c = PartitionCoordinator(
                Client(server), _FakeSched(), cfgs, ident
            )
            c._adopt_partition = lambda k: None
            c._drop_partition = lambda k: None
            cs.append(c)
        for _ in range(4):
            for c in cs:
                c.step()
        assert len(cs[0].held) == 1 and len(cs[1].held) == 1
        victim, survivor = cs
        victim.fault_injector = FaultInjector(FaultProfile(
            "kill", seed=0,
            points={FaultPoint.LEASE_RENEW_FAIL: PointConfig(rate=1.0)},
        ))
        deadline = time.time() + 10
        while time.time() < deadline and (
            len(survivor.held) < 2 or victim.held
        ):
            victim.step()
            survivor.step()
            time.sleep(0.05)
        assert sorted(survivor.held) == [0, 1], "survivor never adopted"
        assert not victim.held, "deposed stack never dropped locally"
        assert survivor.takeovers >= 1

    def test_fence_hosts_probes_per_partition(self):
        server = APIServer()
        c = PartitionCoordinator(
            Client(server), _FakeSched(), _config(num_partitions=2), "s1"
        )
        c._adopt_partition = lambda k: None
        c.step()
        hosts = [f"n{i}" for i in range(6)]
        assert c.fence_hosts(hosts) == set()
        # seize one partition lease out from under it
        k = c.node_partition(hosts[0])

        def mutate(obj: Lease) -> None:
            obj.holder_identity = "intruder"
            obj.renew_time = time.monotonic()
            obj.lease_duration_seconds = 30.0

        server.guaranteed_update(
            "Lease", "kube-system", f"ksp-partition-{k}", mutate
        )
        fenced = c.fence_hosts(hosts)
        assert fenced == {
            i for i, h in enumerate(hosts) if c.node_partition(h) == k
        }


class TestSingletonWriterElection:
    """ISSUE 17 satellite: quota ``sync_all``'s absolute used-rewrite
    must run in exactly ONE stack of a multi-active deployment -- the
    stack holding the lowest live-held partition -- and fail over when
    the elected stack's leases lapse."""

    def _stacks(self, server, num_partitions=4):
        # long lease duration: the election reads lease ground truth,
        # and a 0.5s TTL would depose everyone mid-assert
        cfgs = _config(
            num_partitions=num_partitions, lease_duration_seconds=30.0,
        )
        out = []
        for ident in ("s1", "s2"):
            c = PartitionCoordinator(
                Client(server), _FakeSched(), cfgs, ident
            )
            c._adopt_partition = lambda k: None
            c._drop_partition = lambda k: None
            out.append(c)
        for _ in range(6):
            for c in out:
                c.step()
        return out

    def _depose(self, server, coord):
        """Force-expire every lease the stack holds (crash simulation:
        the holder stops renewing)."""
        for k in list(coord.held):
            server.guaranteed_update(
                "Lease", coord.config.resource_namespace,
                coord._lease_name(k),
                lambda le: setattr(
                    le, "renew_time", le.renew_time - 1e6
                ),
            )

    def test_exactly_one_writer_and_failover(self):
        server = APIServer()
        c1, c2 = self._stacks(server)
        assert sorted(list(c1.held) + list(c2.held)) == [0, 1, 2, 3]
        elected = [
            c for c in (c1, c2) if c.elected_singleton_writer()
        ]
        assert len(elected) == 1, "election must be exclusive"
        lowest = min(list(c1.held) + list(c2.held))
        assert lowest in elected[0].held
        # depose the writer: the survivor takes over, the deposed
        # stack's next fresh read flips False
        loser = c2 if elected[0] is c1 else c1
        self._depose(server, elected[0])
        assert loser.elected_singleton_writer()
        assert not elected[0].elected_singleton_writer()

    def test_sync_all_runs_in_one_stack_only(self):
        """Two full quota stacks over one apiserver: only the elected
        writer performs the absolute ``status.used`` rewrite; the
        bystander books ``syncs_skipped_not_writer`` and leaves the
        object untouched -- until the writer's leases lapse and the
        roles swap."""
        server = APIServer()
        c1, c2 = self._stacks(server)
        writer = c1 if c1.elected_singleton_writer() else c2
        bystander = c2 if writer is c1 else c1

        client = Client(server)
        client.create_resource_quota(ResourceQuota(
            metadata=ObjectMeta(name="quota", namespace="t1"),
            hard={"pods": 10, "cpu": 10000},
        ))
        for i in range(3):
            p = (
                make_pod(f"b{i}").node(f"node-{i}")
                .container(cpu="100m", memory="128Mi").obj()
            )
            p.metadata.namespace = "t1"
            client.create_pod(p)

        stacks = {}
        for coord in (writer, bystander):
            inf = InformerFactory(server)
            qc = QuotaController(coord.client, inf)
            qc.partition_coordinator = coord
            inf.pump()
            stacks[coord.identity] = qc

        def corrupt(q):
            q.status.used = {"pods": 99}

        server.guaranteed_update("ResourceQuota", "t1", "quota", corrupt)
        qb = stacks[bystander.identity]
        qb.sync_all()
        assert qb.syncs_skipped_not_writer == 1
        assert server.get(
            "ResourceQuota", "t1", "quota"
        ).status.used == {"pods": 99}, (
            "non-elected stack must not rewrite used"
        )
        qa = stacks[writer.identity]
        qa.sync_all()
        assert qa.syncs_skipped_not_writer == 0
        assert server.get(
            "ResourceQuota", "t1", "quota"
        ).status.used["pods"] == 3

        # failover: the writer's leases lapse, the bystander inherits
        # the rewrite and the deposed stack starts skipping
        self._depose(server, writer)
        server.guaranteed_update("ResourceQuota", "t1", "quota", corrupt)
        qb.sync_all()
        assert qb.syncs_skipped_not_writer == 1  # no new skip
        assert server.get(
            "ResourceQuota", "t1", "quota"
        ).status.used["pods"] == 3
        qa.sync_all()
        assert qa.syncs_skipped_not_writer == 1


class TestSpill:
    def _pod_on_server(self, server, name="sp-1"):
        client = Client(server)
        pod = make_pod(name).container(cpu="100m", memory="128Mi").obj()
        client.create_pod(pod)
        return client, pod

    def test_spill_stamps_target_and_count(self):
        server = APIServer()
        client, pod = self._pod_on_server(server)
        sched = _FakeSched()
        c = PartitionCoordinator(
            client, sched, _config(num_partitions=3), "s1"
        )
        home = c.pod_partition(pod)
        c.held = {home: 1}
        assert c.try_spill(pod)
        live = client.get_pod("default", pod.metadata.name)
        target = int(live.metadata.annotations[SPILL_TARGET_ANNOTATION])
        assert target != home and target not in c.held
        assert live.metadata.annotations[SPILL_COUNT_ANNOTATION] == "1"
        assert sched.pods_spilled == 1
        # the re-stamped pod's home partition IS the spill target
        assert c.pod_partition(live) == target

    def test_spill_exhausts_after_visiting_every_partition(self):
        server = APIServer()
        client, pod = self._pod_on_server(server)
        sched = _FakeSched()
        c = PartitionCoordinator(
            client, sched, _config(num_partitions=3), "s1"
        )
        c.held = {0: 1}
        live = pod
        for _ in range(2):  # P - 1 hops available
            assert c.try_spill(live)
            live = client.get_pod("default", pod.metadata.name)
        assert not c.try_spill(live), "spilled past every partition"
        assert sched.pods_spilled == 2

    def test_no_spill_single_partition_or_all_held(self):
        server = APIServer()
        client, pod = self._pod_on_server(server)
        sched = _FakeSched()
        c1 = PartitionCoordinator(
            client, sched, _config(num_partitions=1), "s1"
        )
        assert not c1.try_spill(pod)
        c2 = PartitionCoordinator(
            client, sched, _config(num_partitions=2), "s1"
        )
        c2.held = {0: 1, 1: 1}
        assert not c2.try_spill(pod)

    def test_selector_spill_goes_straight_to_owner(self):
        """ROADMAP item-5 residual: a nodeSelector pod that NO_NODEs on
        its home stack spills DIRECTLY to the partition owning its
        selector-matching nodes (one hop), not to the ring successor."""
        server = APIServer()
        client = Client(server)
        pod = (
            make_pod("sel-pod").container(cpu="100m", memory="128Mi")
            .node_selector(disktype="ssd").obj()
        )
        client.create_pod(pod)
        sched = _FakeSched()
        c = PartitionCoordinator(
            client, sched, _config(num_partitions=3), "s1"
        )
        home = c.pod_partition(pod)
        c.held = {home: 1}
        # put every selector-matching node in the partition the ring
        # would visit LAST, and plain nodes everywhere else
        owner = (home + 2) % 3
        matched = plain = 0
        i = 0
        while matched < 4 or plain < 6:
            name = f"sel-node-{i}"
            i += 1
            k = partition_of_name(name, 3)
            if k == owner and matched < 4:
                client.create_node(
                    make_node(name).label("disktype", "ssd")
                    .capacity(cpu="4", memory="8Gi").obj()
                )
                matched += 1
            elif k != owner and plain < 6:
                client.create_node(
                    make_node(name).capacity(cpu="4", memory="8Gi").obj()
                )
                plain += 1
        assert c.try_spill(pod)
        live = client.get_pod("default", pod.metadata.name)
        target = int(live.metadata.annotations[SPILL_TARGET_ANNOTATION])
        assert target == owner, (
            f"spill went to {target}, owner is {owner} "
            f"(ring successor would be {(home + 1) % 3})"
        )
        assert c.spill_hint_hits == 1
        # a plain pod (no selector) keeps ring order
        pod2 = make_pod("plain-pod").container(cpu="100m").obj()
        client.create_pod(pod2)
        home2 = c.pod_partition(pod2)
        assert c.try_spill(pod2)
        live2 = client.get_pod("default", "plain-pod")
        t2 = int(live2.metadata.annotations[SPILL_TARGET_ANNOTATION])
        ring = next(
            k for s in range(1, 3)
            for k in [(home2 + s) % 3] if k not in c.held
        )
        assert t2 == ring
        assert c.spill_hint_hits == 1

    def test_hint_hop_still_gives_every_partition_a_look(self):
        """A hint hop desynchronizes the ring walk; the visited-set
        annotation must keep the guarantee: after the hint owner also
        NO_NODEs, the NEXT spill offers the remaining partition (not a
        re-visit of home that exhausts the hop budget)."""
        from kubernetes_tpu.scheduler.partition import (
            SPILL_VISITED_ANNOTATION,
        )

        server = APIServer()
        client = Client(server)
        pod = (
            make_pod("cov-pod").container(cpu="100m", memory="128Mi")
            .node_selector(disktype="ssd").obj()
        )
        client.create_pod(pod)
        c = PartitionCoordinator(
            client, _FakeSched(), _config(num_partitions=3), "s1"
        )
        home = c.pod_partition(pod)
        hint_owner = (home + 2) % 3  # ring would visit it LAST
        third = (home + 1) % 3
        c.held = {home: 1}
        i = 0
        made = 0
        while made < 3:
            name = f"cov-node-{i}"
            i += 1
            if partition_of_name(name, 3) == hint_owner:
                client.create_node(
                    make_node(name).label("disktype", "ssd")
                    .capacity(cpu="4", memory="8Gi").obj()
                )
                made += 1
        assert c.try_spill(pod)
        live = client.get_pod("default", "cov-pod")
        assert int(
            live.metadata.annotations[SPILL_TARGET_ANNOTATION]
        ) == hint_owner
        # the hint owner's stack fails it too: the remaining partition
        # must be offered, not the already-tried home
        c2 = PartitionCoordinator(
            client, _FakeSched(), _config(num_partitions=3), "s2"
        )
        c2.held = {hint_owner: 1}
        assert c2.try_spill(live)
        live = client.get_pod("default", "cov-pod")
        assert int(
            live.metadata.annotations[SPILL_TARGET_ANNOTATION]
        ) == third
        visited = {
            int(k) for k in
            live.metadata.annotations[SPILL_VISITED_ANNOTATION].split(",")
        }
        assert visited == {home, hint_owner, third}

    def test_spill_aborts_on_already_bound(self):
        from kubernetes_tpu.api.types import Binding

        server = APIServer()
        client, pod = self._pod_on_server(server)
        client.create_node(
            make_node("nX").capacity(cpu="4", memory="8Gi", pods=10).obj()
        )
        client.bind(Binding(
            pod_namespace="default", pod_name=pod.metadata.name,
            pod_uid=pod.metadata.uid, target_node="nX",
        ))
        sched = _FakeSched()
        c = PartitionCoordinator(
            client, sched, _config(num_partitions=3), "s1"
        )
        c.held = {c.pod_partition(pod): 1}
        live = client.get_pod("default", pod.metadata.name)
        assert c.try_spill(live)  # handled: nothing left to do
        assert sched.pods_spilled == 0  # but not counted as a spill
        live2 = client.get_pod("default", pod.metadata.name)
        assert SPILL_TARGET_ANNOTATION not in live2.metadata.annotations


class TestTypedConflictsAndAuthority:
    def test_already_bound_is_typed(self):
        from kubernetes_tpu.api.types import Binding

        server = APIServer()
        client = Client(server)
        pod = make_pod("c1").container(cpu="100m", memory="128Mi").obj()
        client.create_pod(pod)
        client.bind(Binding(
            pod_namespace="default", pod_name="c1",
            pod_uid=pod.metadata.uid, target_node="nA",
        ))
        with pytest.raises(BindConflict) as ei:
            client.bind(Binding(
                pod_namespace="default", pod_name="c1",
                pod_uid=pod.metadata.uid, target_node="nB",
            ))
        assert ei.value.kind == "already-bound"
        assert ei.value.current_node == "nA"
        assert isinstance(ei.value, Conflict)  # old handlers still catch

    def test_bind_assumed_bulk_authority_remaps_indexes(self):
        server = APIServer()
        client = Client(server)
        cfg = _config(num_partitions=2)
        server.install_partition_authority(
            PartitionAuthority(server, cfg, clock=time.monotonic)
        )
        # stack s1 holds only partition 0 (live lease); s2 holds 1
        now = time.monotonic()
        for k, holder in ((0, "s1"), (1, "s2")):
            server.create(Lease(
                metadata=ObjectMeta(
                    name=f"ksp-partition-{k}", namespace="kube-system"
                ),
                holder_identity=holder, lease_duration_seconds=30.0,
                renew_time=now,
            ))
        nodes_p0 = [
            f"n{i}" for i in range(20) if partition_of_name(f"n{i}", 2) == 0
        ][:2]
        nodes_p1 = [
            f"n{i}" for i in range(20) if partition_of_name(f"n{i}", 2) == 1
        ][:2]
        assumed = []
        want_conflict = []
        for i, node in enumerate(
            [nodes_p0[0], nodes_p1[0], nodes_p0[1], nodes_p1[1]]
        ):
            pod = make_pod(f"b{i}").container(
                cpu="100m", memory="128Mi"
            ).obj()
            client.create_pod(pod)
            clone = pod.assumed_clone()
            clone.spec.node_name = node
            assumed.append(clone)
            if partition_of_name(node, 2) == 1:
                want_conflict.append(i)
        errors = server.bind_assumed_bulk(assumed, binder="s1")
        got = sorted(i for i, _e in errors)
        assert got == want_conflict
        for _i, e in errors:
            assert isinstance(e, BindConflict)
            assert e.kind == "foreign-partition"
        # owned slots actually bound
        for i, a in enumerate(assumed):
            live = client.get_pod("default", a.metadata.name)
            if i in want_conflict:
                assert not live.spec.node_name
            else:
                assert live.spec.node_name == a.spec.node_name

    def test_expired_foreign_lease_allows_bind(self):
        server = APIServer()
        cfg = _config(num_partitions=1)
        auth = PartitionAuthority(server, cfg, clock=time.monotonic)
        server.create(Lease(
            metadata=ObjectMeta(
                name="ksp-partition-0", namespace="kube-system"
            ),
            holder_identity="dead-stack", lease_duration_seconds=0.01,
            renew_time=time.monotonic() - 10.0,
        ))
        assert auth.check("adopter", "any-node") is None
        # a LIVE foreign holder refuses
        def mutate(obj):
            obj.renew_time = time.monotonic()
            obj.lease_duration_seconds = 30.0

        server.guaranteed_update(
            "Lease", "kube-system", "ksp-partition-0", mutate
        )
        assert auth.check("adopter", "any-node") == "foreign-partition"
        assert auth.check("dead-stack", "any-node") is None


class TestWatchCursor:
    def test_multiple_watchers_share_one_log(self):
        server = APIServer()
        w1 = server.watch("Pod")
        w2 = server.watch("Pod")
        for i in range(5):
            server.create(
                make_pod(f"w{i}").container(cpu="1m", memory="1Mi").obj()
            )
        assert len(w1.pending()) == 5
        assert len(w2.pending()) == 5  # independent cursors, one log
        assert w1.pending() == []
        w1.stop()
        w2.stop()

    def test_lagged_watcher_goes_gone_after_trim(self):
        server = APIServer(watch_history_limit=10)
        w = server.watch("Pod")
        for i in range(30):  # trims fire; the idle cursor falls behind
            server.create(
                make_pod(f"g{i}").container(cpu="1m", memory="1Mi").obj()
            )
        with pytest.raises(Gone):
            w.pending()

    def test_live_watcher_survives_trims(self):
        server = APIServer(watch_history_limit=10)
        w = server.watch("Pod")
        seen = 0
        for i in range(40):
            server.create(
                make_pod(f"l{i}").container(cpu="1m", memory="1Mi").obj()
            )
            seen += len(w.pending())
        assert seen == 40
        w.stop()


class TestPartitionConfig:
    def test_loader_parses_partition_block(self):
        from kubernetes_tpu.config.loader import load_config_from_dict
        from kubernetes_tpu.config.validation import validate_config

        cfg = load_config_from_dict({
            "partition": {
                "enabled": True,
                "numPartitions": 4,
                "leaseDuration": "750ms",
                "retryPeriod": 0.05,
                "zoneAligned": True,
                "resourcePrefix": "my-part",
            }
        })
        pt = cfg.partition
        assert pt.enabled and pt.num_partitions == 4
        assert pt.lease_duration_seconds == pytest.approx(0.75)
        assert pt.retry_period_seconds == pytest.approx(0.05)
        assert pt.zone_aligned
        assert pt.resource_prefix == "my-part"
        assert validate_config(cfg) == []

    def test_validation_rejects_bad_partition(self):
        from kubernetes_tpu.config.loader import load_config_from_dict
        from kubernetes_tpu.config.validation import validate_config

        cfg = load_config_from_dict(
            {"partition": {"enabled": True, "numPartitions": 0}}
        )
        assert any("numPartitions" in e for e in validate_config(cfg))
        cfg = load_config_from_dict({
            "partition": {
                "enabled": True, "leaseDuration": 0.1, "retryPeriod": 0.2,
            }
        })
        assert any("retryPeriod" in e for e in validate_config(cfg))
        cfg = load_config_from_dict({
            "partition": {"enabled": True},
            "leaderElection": {"leaderElect": True},
        })
        assert any("mutually exclusive" in e for e in validate_config(cfg))

    def test_band_priority_class_parses(self):
        from kubernetes_tpu.config.loader import load_config_from_dict

        cfg = load_config_from_dict({
            "streaming": {"enabled": True, "bandPriorityClass": "critical"}
        })
        assert cfg.streaming.band_priority_class == "critical"
