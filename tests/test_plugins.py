"""Table-driven plugin tests (reference pattern: each plugin's *_test.go
builds NodeInfo/pods via the wrapper DSL and calls Filter/Score directly)."""

import pytest

from kubernetes_tpu.cache.node_info import NodeInfo
from kubernetes_tpu.cache.snapshot import new_snapshot
from kubernetes_tpu.framework.interface import CycleState, StatusCode
from kubernetes_tpu.plugins import (
    imagelocality,
    nodeaffinity,
    nodename,
    nodeports,
    noderesources,
    nodeunschedulable,
    tainttoleration,
)
from kubernetes_tpu.scheduler.generic import SNAPSHOT_STATE_KEY
from kubernetes_tpu.testing import make_node, make_pod


def _state_with_snapshot(pods, nodes):
    snap = new_snapshot(pods, nodes)
    state = CycleState()
    state.write(SNAPSHOT_STATE_KEY, snap)
    return state, snap


# --- NodeResourcesFit ---------------------------------------------------


class TestFit:
    def _filter(self, pod, node_info, args=None):
        plugin = noderesources.Fit(args)
        state = CycleState()
        plugin.pre_filter(state, pod)
        return plugin.filter(state, pod, node_info)

    def test_fits(self):
        ni = NodeInfo(make_node("n").capacity(cpu="4", memory="8Gi").obj())
        pod = make_pod("p").container(cpu="2", memory="4Gi").obj()
        assert self._filter(pod, ni) is None

    def test_insufficient_cpu_and_memory(self):
        ni = NodeInfo(make_node("n").capacity(cpu="1", memory="1Gi").obj())
        pod = make_pod("p").container(cpu="2", memory="4Gi").obj()
        status = self._filter(pod, ni)
        assert status.code == StatusCode.UNSCHEDULABLE
        assert "Insufficient cpu" in status.reasons
        assert "Insufficient memory" in status.reasons

    def test_counts_existing_usage(self):
        ni = NodeInfo(make_node("n").capacity(cpu="4", memory="8Gi").obj())
        ni.add_pod(make_pod("existing").container(cpu="3", memory="1Gi").node("n").obj())
        pod = make_pod("p").container(cpu="2", memory="1Gi").obj()
        status = self._filter(pod, ni)
        assert status is not None and "Insufficient cpu" in status.reasons

    def test_init_container_max(self):
        ni = NodeInfo(make_node("n").capacity(cpu="4", memory="8Gi").obj())
        pod = make_pod("p").container(cpu="1", memory="1Gi").obj()
        from kubernetes_tpu.api.types import Container, ResourceRequirements

        pod.spec.init_containers.append(
            Container(
                name="init",
                resources=ResourceRequirements(requests={"cpu": 5000}),
            )
        )
        status = self._filter(pod, ni)
        assert status is not None and "Insufficient cpu" in status.reasons

    def test_pod_count_limit(self):
        ni = NodeInfo(make_node("n").capacity(cpu="40", memory="80Gi", pods=1).obj())
        ni.add_pod(make_pod("existing").container(cpu="1", memory="1Gi").node("n").obj())
        pod = make_pod("p").container(cpu="1", memory="1Gi").obj()
        status = self._filter(pod, ni)
        assert status is not None and status.reasons[0].startswith("Too many pods")

    def test_scalar_resources(self):
        node = make_node("n").capacity(cpu="4", memory="8Gi").obj()
        node.status.allocatable["nvidia.com/gpu"] = 2
        ni = NodeInfo(node)
        pod = make_pod("p").container(cpu="1", memory="1Gi").obj()
        pod.spec.containers[0].resources.requests["nvidia.com/gpu"] = 4
        status = self._filter(pod, ni)
        assert status is not None and "Insufficient nvidia.com/gpu" in status.reasons

    def test_zero_request_only_pod_count(self):
        ni = NodeInfo(make_node("n").capacity(cpu="0", memory="0", pods=10).obj())
        pod = make_pod("p").obj()  # no containers, no requests
        assert self._filter(pod, ni) is None


# --- scorers ------------------------------------------------------------


def test_least_allocated_prefers_empty():
    nodes = [
        make_node("empty").capacity(cpu="4", memory="8Gi").obj(),
        make_node("busy").capacity(cpu="4", memory="8Gi").obj(),
    ]
    busy_pod = make_pod("busy-pod").container(cpu="3", memory="6Gi").node("busy").obj()
    state, _ = _state_with_snapshot([busy_pod], nodes)
    plugin = noderesources.LeastAllocated()
    pod = make_pod("p").container(cpu="1", memory="2Gi").obj()
    s_empty, _ = plugin.score(state, pod, "empty")
    s_busy, _ = plugin.score(state, pod, "busy")
    assert s_empty > s_busy


def test_balanced_allocation():
    nodes = [make_node("n").capacity(cpu="4", memory="8Gi").obj()]
    state, _ = _state_with_snapshot([], nodes)
    plugin = noderesources.BalancedAllocation()
    # perfectly balanced: 50% cpu, 50% mem
    pod = make_pod("p").container(cpu="2", memory="4Gi").obj()
    score, _ = plugin.score(state, pod, "n")
    assert score == 100
    # overcommitted -> 0
    pod2 = make_pod("p2").container(cpu="8", memory="1Gi").obj()
    score2, _ = plugin.score(state, pod2, "n")
    assert score2 == 0


def test_most_allocated_prefers_full():
    nodes = [
        make_node("empty").capacity(cpu="4", memory="8Gi").obj(),
        make_node("busy").capacity(cpu="4", memory="8Gi").obj(),
    ]
    busy_pod = make_pod("b").container(cpu="2", memory="4Gi").node("busy").obj()
    state, _ = _state_with_snapshot([busy_pod], nodes)
    plugin = noderesources.MostAllocated()
    pod = make_pod("p").container(cpu="1", memory="2Gi").obj()
    s_empty, _ = plugin.score(state, pod, "empty")
    s_busy, _ = plugin.score(state, pod, "busy")
    assert s_busy > s_empty


def test_requested_to_capacity_ratio_default_shape():
    nodes = [make_node("n").capacity(cpu="4", memory="8Gi").obj()]
    state, _ = _state_with_snapshot([], nodes)
    plugin = noderesources.RequestedToCapacityRatio(None)
    pod = make_pod("p").container(cpu="2", memory="4Gi").obj()
    score, status = plugin.score(state, pod, "n")
    assert status is None
    assert score == 50  # 50% utilization on default 0->0, 100->10 curve


# --- NodeName / NodePorts / NodeUnschedulable ---------------------------


def test_node_name():
    plugin = nodename.NodeName()
    ni = NodeInfo(make_node("n1").obj())
    ok = make_pod("p").node("n1").obj()
    # NodeName filter reads spec.node_name as the *requested* hostname
    assert plugin.filter(CycleState(), ok, ni) is None
    bad = make_pod("p2").node("other").obj()
    assert plugin.filter(CycleState(), bad, ni).code == StatusCode.UNSCHEDULABLE


def test_node_ports_conflict():
    plugin = nodeports.NodePorts()
    ni = NodeInfo(make_node("n").capacity(cpu="4", memory="8Gi").obj())
    ni.add_pod(
        make_pod("existing").container(cpu="1", memory="1Gi", host_port=80).node("n").obj()
    )
    pod = make_pod("p").container(cpu="1", memory="1Gi", host_port=80).obj()
    state = CycleState()
    plugin.pre_filter(state, pod)
    assert plugin.filter(state, pod, ni).code == StatusCode.UNSCHEDULABLE
    pod2 = make_pod("p2").container(cpu="1", memory="1Gi", host_port=81).obj()
    state2 = CycleState()
    plugin.pre_filter(state2, pod2)
    assert plugin.filter(state2, pod2, ni) is None


def test_node_unschedulable():
    plugin = nodeunschedulable.NodeUnschedulable()
    ni = NodeInfo(make_node("n").unschedulable().obj())
    pod = make_pod("p").obj()
    status = plugin.filter(CycleState(), pod, ni)
    assert status.code == StatusCode.UNSCHEDULABLE_AND_UNRESOLVABLE
    tolerant = (
        make_pod("p2")
        .toleration(
            key="node.kubernetes.io/unschedulable",
            operator="Exists",
            effect="NoSchedule",
        )
        .obj()
    )
    assert plugin.filter(CycleState(), tolerant, ni) is None


# --- NodeAffinity -------------------------------------------------------


def test_node_affinity_filter():
    plugin = nodeaffinity.NodeAffinity()
    zone1 = NodeInfo(make_node("z1").label("zone", "z1").obj())
    zone2 = NodeInfo(make_node("z2").label("zone", "z2").obj())
    pod = make_pod("p").node_affinity_in("zone", ["z1"]).obj()
    assert plugin.filter(CycleState(), pod, zone1) is None
    assert plugin.filter(CycleState(), pod, zone2).code == StatusCode.UNSCHEDULABLE
    # plain nodeSelector
    pod2 = make_pod("p2").node_selector(zone="z2").obj()
    assert plugin.filter(CycleState(), pod2, zone1) is not None
    assert plugin.filter(CycleState(), pod2, zone2) is None


def test_node_affinity_preferred_score():
    nodes = [
        make_node("z1").label("zone", "z1").obj(),
        make_node("z2").label("zone", "z2").obj(),
    ]
    state, _ = _state_with_snapshot([], nodes)
    plugin = nodeaffinity.NodeAffinity()
    pod = make_pod("p").preferred_node_affinity_in("zone", ["z1"], weight=5).obj()
    s1, _ = plugin.score(state, pod, "z1")
    s2, _ = plugin.score(state, pod, "z2")
    assert s1 == 5 and s2 == 0


# --- TaintToleration ----------------------------------------------------


def test_taint_toleration_filter():
    plugin = tainttoleration.TaintToleration()
    tainted = NodeInfo(make_node("t").taint("dedicated", "gpu", "NoSchedule").obj())
    pod = make_pod("p").obj()
    status = plugin.filter(CycleState(), pod, tainted)
    assert status.code == StatusCode.UNSCHEDULABLE_AND_UNRESOLVABLE
    tolerant = make_pod("p2").toleration(key="dedicated", value="gpu").obj()
    assert plugin.filter(CycleState(), tolerant, tainted) is None


def test_taint_toleration_prefer_no_schedule_score():
    nodes = [
        make_node("clean").obj(),
        make_node("pref").taint("soft", "x", "PreferNoSchedule").obj(),
    ]
    state, snap = _state_with_snapshot([], nodes)
    plugin = tainttoleration.TaintToleration()
    pod = make_pod("p").obj()
    plugin.pre_score(state, pod, snap.list_node_infos())
    from kubernetes_tpu.framework.interface import NodeScore

    scores = []
    for name in ("clean", "pref"):
        s, _ = plugin.score(state, pod, name)
        scores.append(NodeScore(name, s))
    plugin.normalize_score(state, pod, scores)
    by = {ns.name: ns.score for ns in scores}
    assert by["clean"] == 100 and by["pref"] == 0


# --- ImageLocality ------------------------------------------------------


def test_image_locality_prefers_node_with_image():
    big = 500 * 1024 * 1024
    nodes = [
        make_node("has").image("myimage", big).obj(),
        make_node("hasnot").obj(),
    ]
    state, _ = _state_with_snapshot([], nodes)
    plugin = imagelocality.ImageLocality()
    pod = make_pod("p").container(cpu="1", memory="1Gi", image="myimage").obj()
    s_has, _ = plugin.score(state, pod, "has")
    s_not, _ = plugin.score(state, pod, "hasnot")
    assert s_has > s_not
    assert s_not == 0
