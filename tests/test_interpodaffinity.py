"""InterPodAffinity plugin tests (reference pattern:
interpodaffinity/filtering_test.go, scoring_test.go)."""

from kubernetes_tpu.cache.snapshot import new_snapshot
from kubernetes_tpu.framework.interface import CycleState, NodeScore, StatusCode
from kubernetes_tpu.plugins.interpodaffinity import InterPodAffinity
from kubernetes_tpu.scheduler.generic import SNAPSHOT_STATE_KEY
from kubernetes_tpu.testing import make_node, make_pod


def _nodes():
    return [
        make_node("n1").labels(zone="z1", host="n1").obj(),
        make_node("n2").labels(zone="z1", host="n2").obj(),
        make_node("n3").labels(zone="z2", host="n3").obj(),
    ]


def _run_filter(pod, pods, nodes):
    snap = new_snapshot(pods, nodes)
    state = CycleState()
    state.write(SNAPSHOT_STATE_KEY, snap)
    pl = InterPodAffinity()
    assert pl.pre_filter(state, pod) is None
    return (
        {ni.node_name: pl.filter(state, pod, ni) for ni in snap.list_node_infos()},
        state,
        snap,
        pl,
    )


class TestFilterAffinity:
    def test_affinity_to_existing_pod_zone(self):
        pods = [make_pod("store").node("n1").labels(app="store").obj()]
        pod = (
            make_pod("web").labels(app="web")
            .pod_affinity("zone", {"app": "store"})
            .obj()
        )
        results, *_ = _run_filter(pod, pods, _nodes())
        assert results["n1"] is None
        assert results["n2"] is None  # same zone
        assert results["n3"] is not None

    def test_affinity_unmatched_is_unresolvable(self):
        pods = [make_pod("store").node("n1").labels(app="store").obj()]
        pod = (
            make_pod("web").labels(app="web")
            .pod_affinity("zone", {"app": "nothing"})
            .obj()
        )
        results, *_ = _run_filter(pod, pods, _nodes())
        for status in results.values():
            assert status is not None
            assert status.code == StatusCode.UNSCHEDULABLE_AND_UNRESOLVABLE

    def test_first_pod_self_affinity_allowed(self):
        # No pod matches, but the pod matches its own affinity terms:
        # allowed everywhere (filtering.go:494).
        pod = (
            make_pod("web").labels(app="web")
            .pod_affinity("zone", {"app": "web"})
            .obj()
        )
        results, *_ = _run_filter(pod, [], _nodes())
        assert all(v is None for v in results.values())

    def test_first_pod_without_self_match_blocked(self):
        pod = (
            make_pod("web").labels(app="web")
            .pod_affinity("zone", {"app": "store"})
            .obj()
        )
        results, *_ = _run_filter(pod, [], _nodes())
        assert all(v is not None for v in results.values())


class TestFilterAntiAffinity:
    def test_incoming_anti_affinity(self):
        pods = [make_pod("a").node("n1").labels(app="a").obj()]
        pod = (
            make_pod("b").labels(app="b")
            .pod_affinity("host", {"app": "a"}, anti=True)
            .obj()
        )
        results, *_ = _run_filter(pod, pods, _nodes())
        assert results["n1"] is not None
        assert results["n1"].code == StatusCode.UNSCHEDULABLE
        assert results["n2"] is None
        assert results["n3"] is None

    def test_existing_pod_anti_affinity_symmetry(self):
        # existing pod on n1 has anti-affinity to app=web in its zone:
        # incoming web pod must avoid all of z1.
        existing = (
            make_pod("guard").node("n1").labels(app="guard")
            .pod_affinity("zone", {"app": "web"}, anti=True)
            .obj()
        )
        pod = make_pod("web").labels(app="web").obj()
        results, *_ = _run_filter(pod, [existing], _nodes())
        assert results["n1"] is not None
        assert results["n2"] is not None
        assert results["n3"] is None

    def test_namespace_scoping(self):
        other = make_pod("a", namespace="other").node("n1").labels(app="a").obj()
        pod = (
            make_pod("b").labels(app="b")
            .pod_affinity("host", {"app": "a"}, anti=True)
            .obj()
        )
        results, *_ = _run_filter(pod, [other], _nodes())
        # anti-affinity term defaults to pod's own namespace -> no match
        assert all(v is None for v in results.values())


class TestPreFilterExtensions:
    def test_add_remove_updates_counts(self):
        pods = []
        pod = (
            make_pod("b").labels(app="b")
            .pod_affinity("host", {"app": "a"}, anti=True)
            .obj()
        )
        results, state, snap, pl = _run_filter(pod, pods, _nodes())
        assert all(v is None for v in results.values())
        added = make_pod("a").node("n2").labels(app="a").obj()
        ext = pl.pre_filter_extensions()
        ext.add_pod(state, pod, added, snap.get_node_info("n2"))
        assert pl.filter(state, pod, snap.get_node_info("n2")) is not None
        ext.remove_pod(state, pod, added, snap.get_node_info("n2"))
        assert pl.filter(state, pod, snap.get_node_info("n2")) is None


class TestScore:
    def _score(self, pod, pods, nodes, args=None):
        snap = new_snapshot(pods, nodes)
        state = CycleState()
        state.write(SNAPSHOT_STATE_KEY, snap)
        pl = InterPodAffinity(args)
        infos = snap.list_node_infos()
        assert pl.pre_score(state, pod, infos) is None
        scores = []
        for ni in infos:
            raw, status = pl.score(state, pod, ni.node_name)
            assert status is None
            scores.append(NodeScore(ni.node_name, raw))
        assert pl.normalize_score(state, pod, scores) is None
        return {ns.name: ns.score for ns in scores}

    def test_preferred_affinity_prefers_colocated_zone(self):
        pods = [make_pod("store").node("n1").labels(app="store").obj()]
        pod = (
            make_pod("web").labels(app="web")
            .preferred_pod_affinity("zone", {"app": "store"}, weight=5)
            .obj()
        )
        by_node = self._score(pod, pods, _nodes())
        assert by_node["n1"] == by_node["n2"] == 100
        assert by_node["n3"] == 0

    def test_preferred_anti_affinity_avoids_zone(self):
        pods = [make_pod("noisy").node("n1").labels(app="noisy").obj()]
        pod = (
            make_pod("quiet").labels(app="quiet")
            .preferred_pod_affinity("zone", {"app": "noisy"}, weight=3, anti=True)
            .obj()
        )
        by_node = self._score(pod, pods, _nodes())
        assert by_node["n3"] == 100
        assert by_node["n1"] == by_node["n2"] == 0

    def test_hard_affinity_symmetric_weight(self):
        # existing pod has REQUIRED affinity matching the incoming pod:
        # incoming pod is drawn toward it with hardPodAffinityWeight.
        existing = (
            make_pod("store").node("n3").labels(app="store")
            .pod_affinity("zone", {"app": "web"})
            .obj()
        )
        pod = make_pod("web").labels(app="web").obj()
        by_node = self._score(pod, [existing], _nodes(), {"hard_pod_affinity_weight": 10})
        assert by_node["n3"] == 100
        assert by_node["n1"] == 0

    def test_no_affinity_anywhere_scores_flat(self):
        pods = [make_pod("p").node("n1").labels(app="p").obj()]
        pod = make_pod("q").labels(app="q").obj()
        by_node = self._score(pod, pods, _nodes())
        assert set(by_node.values()) == {0}
