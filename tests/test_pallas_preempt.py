"""Pallas preemption kernel (ops/pallas_preempt.py) vs the XLA batched
kernel (ops/preemption._preempt_batch_kernel): randomized differential
parity in interpreter mode on the no-PDB path the kernel serves."""

import numpy as np
import pytest

from kubernetes_tpu.ops.pallas_preempt import pallas_preempt_solve
from kubernetes_tpu.ops.preemption import _preempt_batch_kernel


def _random_wave(seed, n=64, v=16, r=4, b=32, m=0):
    rng = np.random.default_rng(seed)
    alloc = np.zeros((n, r), np.int32)
    alloc[:, 0] = 32000
    alloc[:, 1] = 64 << 20
    alloc[:, 3] = 110
    prio = np.full((n, v), -(1 << 31) + 1, np.int64)
    start = np.zeros((n, v), np.float64)
    req = np.zeros((n, v, r), np.int32)
    active = np.zeros((n, v), bool)
    base = np.zeros((n, r), np.int32)
    for i in range(n):
        k = rng.integers(4, v)
        # MoreImportantPod order: priority desc
        prios = np.sort(rng.integers(-5, 50, k))[::-1]
        for j in range(k):
            active[i, j] = True
            prio[i, j] = prios[j]
            start[i, j] = rng.random() * 100
            req[i, j, 0] = rng.choice([1000, 3000, 5000])
            req[i, j, 1] = rng.choice([1, 2, 6]) << 20
            req[i, j, 3] = 1
        base[i] = req[i].sum(axis=0)
    pods_req = np.zeros((b, r), np.int32)
    pods_req[:, 0] = rng.choice([3000, 8000], b)
    pods_req[:, 1] = rng.choice([2, 6], b) << 20
    pods_req[:, 3] = 1
    pods_prio = np.sort(rng.integers(10, 100, b))[::-1].astype(np.int32)
    candidate = rng.random((b, n)) > 0.2
    if m:
        nom_req = np.zeros((m, r), np.int32)
        nom_req[:, 0] = 2000
        nom_req[:, 3] = 1
        nom_prio = rng.integers(20, 90, m).astype(np.int32)
        nom_node = rng.integers(0, n, m).astype(np.int32)
    else:
        nom_req = np.zeros((8, r), np.int32)
        nom_prio = np.full(8, -(1 << 31) + 1, np.int32)
        nom_node = np.full(8, -1, np.int32)
    return (
        alloc, base, prio, start, req, active,
        nom_req, nom_prio, nom_node, pods_req, pods_prio, candidate,
    )


@pytest.mark.parametrize("seed", [0, 5, 17])
@pytest.mark.parametrize("m", [0, 4])
def test_pallas_preempt_matches_xla(seed, m):
    (alloc, base, prio, start, req, active,
     nom_req, nom_prio, nom_node, pods_req, pods_prio,
     candidate) = _random_wave(seed, m=m)
    b = pods_req.shape[0]
    v = prio.shape[1]

    prio32 = np.clip(prio, -(1 << 31), (1 << 31) - 2).astype(np.int32)
    x_chosen, x_vic, x_viol, x_nviol = _preempt_batch_kernel(
        alloc, base, prio32, start.astype(np.float32), req, active,
        np.zeros((alloc.shape[0], v, 1), bool), np.zeros(1, np.int32),
        nom_req, nom_prio, nom_node,
        pods_req, pods_prio, candidate, np.ones(b, bool),
        num_pdbs=0,
    )

    rows, inverse = np.unique(candidate, axis=0, return_inverse=True)
    u_pad = 8 * -(-rows.shape[0] // 8)
    rows_p = np.zeros((u_pad, candidate.shape[1]), bool)
    rows_p[: rows.shape[0]] = rows
    active_bits = np.zeros(active.shape[0], dtype=np.int32)
    for vi in range(v):
        active_bits |= active[:, vi].astype(np.int32) << vi
    p_packed, _state = pallas_preempt_solve(
        alloc, base, prio32, start.astype(np.float32), req, active_bits,
        nom_req, nom_prio, nom_node,
        pods_req, pods_prio, rows_p,
        inverse.reshape(-1).astype(np.int32), np.ones(b, bool),
        interpret=True,
    )
    p_packed = np.asarray(p_packed)
    p_chosen = p_packed[0]
    bits = (
        p_packed[1].astype(np.uint32)
        | (p_packed[2].astype(np.uint32) << 16)
    )
    p_vic = ((bits[:, None] >> np.arange(v)[None, :]) & 1).astype(bool)

    np.testing.assert_array_equal(np.asarray(x_chosen), p_chosen)
    np.testing.assert_array_equal(np.asarray(x_vic), p_vic)


def test_wrapper_chunk_chain_matches_xla(monkeypatch):
    """Drive the FULL preempt_batch_device wrapper (candidate dedup,
    512-chunk state chaining, bitmask reassembly) in interpreter mode
    against the XLA path on a >512-pod wave."""
    import kubernetes_tpu.ops.preemption as OP

    n, v, r, b = 48, 8, 4, 600
    rng = np.random.default_rng(3)
    pack = OP.PreemptionPack()
    pack.node_names = [f"n{i}" for i in range(n)]
    pack.node_index = {f"n{i}": i for i in range(n)}
    pack.pods_by_node = [[] for _ in range(n)]
    pack.alloc = np.tile(
        np.array([[32000, 64 << 20, 0, 110]], np.int32), (n, 1)
    )
    pack.base_requested = np.zeros((n, r), np.int32)
    pack.prio = np.full((n, v), -(1 << 31) + 1, np.int64)
    pack.start_rel = np.zeros((n, v))
    pack.req = np.zeros((n, v, r), np.int32)
    pack.active = np.zeros((n, v), bool)
    for i in range(n):
        k = rng.integers(3, v)
        prios = np.sort(rng.integers(0, 40, k))[::-1]
        for j in range(k):
            pack.active[i, j] = True
            pack.prio[i, j] = prios[j]
            pack.start_rel[i, j] = rng.random() * 10
            pack.req[i, j, 0] = rng.choice([2000, 4000])
            pack.req[i, j, 3] = 1
        pack.base_requested[i] = pack.req[i].sum(axis=0)
        pack.base_requested[i, 0] += 24000  # mostly full
    pack.pdb_match = np.zeros((n, v, 1), bool)
    pack.pdb_allowed = np.zeros(1, np.int32)
    pack.v_max = v
    pack.generation = 0

    pods_req = np.zeros((b, r), np.int32)
    pods_req[:, 0] = rng.choice([3000, 6000], b)
    pods_req[:, 3] = 1
    pods_prio = np.sort(rng.integers(50, 90, b))[::-1].astype(np.int32)
    candidate = rng.random((b, n)) > 0.1
    nom = np.zeros((0, r), np.int32)
    nomi = np.zeros(0, np.int32)

    x = OP.preempt_batch_device(
        pack, pods_req, pods_prio, candidate, nom, nomi, nomi
    )
    monkeypatch.setattr(OP, "FORCE_PALLAS_INTERPRET", True)
    p = OP.preempt_batch_device(
        pack, pods_req, pods_prio, candidate, nom, nomi, nomi
    )
    np.testing.assert_array_equal(x[0], p[0])  # chosen
    np.testing.assert_array_equal(x[1], p[1])  # victims
    assert (x[0] >= 0).sum() > 0, "wave must place some preemptors"
