"""Multi-tenant fairness plane (ISSUE 15): ResourceQuota admission at
the scheduling gate, typed-QuotaExceeded parking with event-driven
wakes, refund-on-failure ledger integrity (randomized differential
against a full watch-history replay, under the ha-chaos profile), the
DRF dominant-share solve-order bias, the plain-pod native-ingest guard
with tenancy armed, and the two satellites (PodQuarantined honored at
relist; legacy-mesh untyped crash-loop containment)."""

import random
import time

import numpy as np
import pytest

from kubernetes_tpu.api.types import (
    ObjectMeta,
    PodCondition,
    ResourceQuota,
    pod_resource_requests,
)
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client.client import Client
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.controllers.quota import QuotaController
from kubernetes_tpu.scheduler.scheduler import new_scheduler
from kubernetes_tpu.scheduler.tenancy import (
    TenantShareTracker,
    arm_tenancy,
    fair_order,
)
from kubernetes_tpu.testing import make_node, make_pod


def _mk_quota(ns, **hard):
    return ResourceQuota(
        metadata=ObjectMeta(name="quota", namespace=ns), hard=dict(hard)
    )


def _pod_in(ns, name, cpu="100m", memory="128Mi", priority=0):
    p = make_pod(name).container(cpu=cpu, memory=memory).obj()
    p.metadata.namespace = ns
    p.spec.priority = priority
    return p


def _wait(pred, timeout=20.0, interval=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


@pytest.fixture
def cluster():
    server = APIServer()
    client = Client(server)
    informers = InformerFactory(server)
    sched = new_scheduler(client, informers, batch=True, max_batch=64)
    qc = arm_tenancy(sched, client, informers)
    yield server, client, informers, sched, qc
    qc.stop()
    sched.stop()
    informers.stop()


class TestQuotaLedger:
    def test_charge_deny_refund_roundtrip(self):
        server = APIServer()
        client = Client(server)
        informers = InformerFactory(server)
        qc = QuotaController(client, informers)
        client.create_resource_quota(_mk_quota("t1", pods=2, cpu=1000))
        p1, p2, p3 = (_pod_in("t1", n, cpu="400m") for n in "abc")
        for p in (p1, p2, p3):
            client.create_pod(p)
        informers.pump()
        assert qc.try_admit(p1) == ""
        assert qc.try_admit(p2) == ""
        assert "exceeded quota" in qc.try_admit(p3)
        used = client.get("ResourceQuota", "t1", "quota").status.used
        assert used == {"pods": 2, "cpu": 800}
        # idempotent: a charged pod re-admits without double-charging
        assert qc.try_admit(p1) == ""
        assert client.get(
            "ResourceQuota", "t1", "quota"
        ).status.used == {"pods": 2, "cpu": 800}
        # exactly-once refund
        assert qc.refund(p1, reason="requeue") is True
        assert qc.refund(p1, reason="requeue") is False
        used = client.get("ResourceQuota", "t1", "quota").status.used
        assert used == {"pods": 1, "cpu": 400}
        assert qc.try_admit(p3) == ""

    def test_multi_quota_partial_charge_refunded_on_deny(self):
        """Quota A grants, quota B denies: A's units come back (the
        can_disrupt give-back discipline)."""
        server = APIServer()
        client = Client(server)
        informers = InformerFactory(server)
        qc = QuotaController(client, informers)
        client.create_resource_quota(_mk_quota("t1", pods=10))
        qb = _mk_quota("t1", cpu=100)
        qb.metadata.name = "cpu-cap"
        client.create_resource_quota(qb)
        p = _pod_in("t1", "big", cpu="400m")
        client.create_pod(p)
        informers.pump()
        assert "exceeded quota" in qc.try_admit(p)
        # neither quota retains spend from the denied attempt
        assert client.get(
            "ResourceQuota", "t1", "quota"
        ).status.used.get("pods", 0) == 0
        assert client.get(
            "ResourceQuota", "t1", "cpu-cap"
        ).status.used.get("cpu", 0) == 0

    def test_no_quota_namespace_is_free(self):
        server = APIServer()
        client = Client(server)
        informers = InformerFactory(server)
        qc = QuotaController(client, informers)
        informers.pump()
        assert qc.try_admit(_pod_in("anywhere", "p")) == ""
        assert qc.admissions_granted == 0  # the fast path books nothing

    def test_deleted_pod_never_leaks_a_charge(self):
        """The charge-store vs delete race: a pod deleted between the
        gate pop and the charge registration must not strand spend --
        the post-store liveness re-read refunds it."""
        server = APIServer()
        client = Client(server)
        informers = InformerFactory(server)
        qc = QuotaController(client, informers)
        client.create_resource_quota(_mk_quota("t1", pods=5))
        p = _pod_in("t1", "ghost")
        client.create_pod(p)
        informers.pump()
        client.delete_pod("t1", "ghost")
        informers.pump()  # the delete handler ran, found no charge
        assert qc.try_admit(p) == ""  # gate still held the popped obj
        assert client.get(
            "ResourceQuota", "t1", "quota"
        ).status.used.get("pods", 0) == 0
        assert qc.charged_uids() == set()

    def test_quota_created_mid_run_adopts_existing_usage(self):
        """A ResourceQuota created over a namespace with bound pods
        must start from the real usage, not zero -- otherwise the cap
        silently overspends until a restart."""
        server = APIServer()
        client = Client(server)
        informers = InformerFactory(server)
        qc = QuotaController(client, informers)
        for i in range(3):
            p = _pod_in("t1", f"b{i}")
            p.spec.node_name = "n0"
            client.create_pod(p)
        informers.pump()
        qc.sync_all()  # adopts the bound pods into the ledger
        client.create_resource_quota(_mk_quota("t1", pods=4))
        informers.pump()
        qc.drain_resync()
        used = client.get("ResourceQuota", "t1", "quota").status.used
        assert used == {"pods": 3}, used
        # only ONE more pod fits under the adopted usage
        p4 = _pod_in("t1", "p4")
        p5 = _pod_in("t1", "p5")
        client.create_pod(p4)
        client.create_pod(p5)
        informers.pump()
        assert qc.try_admit(p4) == ""
        assert "exceeded quota" in qc.try_admit(p5)


class TestQuotaParking:
    def _settle(self, client, informers, sched, n_nodes=4):
        for i in range(n_nodes):
            client.create_node(
                make_node(f"n{i}").capacity(cpu="16", memory="32Gi").obj()
            )
        informers.start()
        informers.wait_for_cache_sync()
        sched.queue.run()

    def test_park_and_wake_on_quota_raise(self, cluster):
        server, client, informers, sched, qc = cluster
        client.create_resource_quota(_mk_quota("t1", pods=1))
        self._settle(client, informers, sched)
        qc.sync_all()
        qc.start()
        client.create_pod(_pod_in("t1", "p1"))
        client.create_pod(_pod_in("t1", "p2"))
        sched.start()
        assert _wait(
            lambda: sched.queue.quota_parked_count() == 1
            and sum(
                1 for p in client.list_pods()[0] if p.spec.node_name
            ) == 1
        )
        # the typed condition is on the apiserver
        parked = [
            p for p in client.list_pods()[0] if not p.spec.node_name
        ][0]
        assert _wait(lambda: any(
            c.reason == "QuotaExceeded"
            for p in client.list_pods()[0] if not p.spec.node_name
            for c in p.status.conditions
        ))
        # a cluster event must NOT wake the parked pod
        client.create_node(
            make_node("late").capacity(cpu="16", memory="32Gi").obj()
        )
        time.sleep(0.5)
        assert sched.queue.quota_parked_count() == 1
        # raising the hard cap is the wake event
        client.update_resource_quota_status(
            "t1", "quota", lambda o: setattr(o, "hard", {"pods": 2})
        )
        assert _wait(lambda: all(
            p.spec.node_name for p in client.list_pods()[0]
        ))
        assert sched.queue.quota_parked_count() == 0
        assert parked.metadata.name in {
            p.metadata.name
            for p in client.list_pods()[0] if p.spec.node_name
        }

    def test_wake_on_usage_drop(self, cluster):
        server, client, informers, sched, qc = cluster
        client.create_resource_quota(_mk_quota("t1", pods=1))
        self._settle(client, informers, sched)
        qc.sync_all()
        qc.start()
        client.create_pod(_pod_in("t1", "p1"))
        sched.start()
        assert _wait(lambda: bool(
            client.get_pod("t1", "p1").spec.node_name
        ))
        client.create_pod(_pod_in("t1", "p2"))
        assert _wait(lambda: sched.queue.quota_parked_count() == 1)
        # deleting the bound pod refunds its charge -> wake
        client.delete_pod("t1", "p1")
        assert _wait(lambda: bool(
            client.get_pod("t1", "p2").spec.node_name
        ))
        used = client.get("ResourceQuota", "t1", "quota").status.used
        assert used == {"pods": 1}

    def test_unschedulable_pod_refunds_charge(self, cluster):
        """A charged pod that solves NO_NODE requeues UNCHARGED (used
        never counts parked-unschedulable pods), so a sibling in the
        same namespace can take the headroom."""
        server, client, informers, sched, qc = cluster
        client.create_resource_quota(_mk_quota("t1", pods=1))
        self._settle(client, informers, sched, n_nodes=1)
        qc.sync_all()
        qc.start()
        # does not fit anywhere, but passes quota (pods=1)
        client.create_pod(_pod_in("t1", "huge", cpu="64", memory="1Ti"))
        sched.start()
        assert _wait(lambda: qc.admissions_granted >= 1, timeout=25)
        assert _wait(lambda: qc.refunds >= 1, timeout=25)
        assert _wait(lambda: client.get(
            "ResourceQuota", "t1", "quota"
        ).status.used.get("pods", 0) == 0, timeout=25)


def _replay_bound_usage(server, quotas_by_ns):
    """Replay the FULL Pod watch history: per-namespace bound usage at
    every event, asserting it never exceeds any quota's hard caps.
    Returns the final per-namespace bound usage."""
    watch = server.watch("Pod", since_rv=0)
    bound: dict = {}  # uid -> (ns, usage)
    usage_by_ns: dict = {}

    def apply(ns, usage, sign):
        tot = usage_by_ns.setdefault(ns, {})
        for name, qty in usage.items():
            tot[name] = tot.get(name, 0) + sign * qty

    for ev in watch.pending():
        pod = ev.object
        uid = pod.metadata.uid
        ns = pod.metadata.namespace
        if ev.type in ("ADDED", "MODIFIED"):
            if pod.spec.node_name and uid not in bound:
                from kubernetes_tpu.controllers.quota import (
                    quota_pod_usage,
                )

                u = quota_pod_usage(pod)
                bound[uid] = (ns, u)
                apply(ns, u, +1)
        elif ev.type == "DELETED":
            entry = bound.pop(uid, None)
            if entry is not None:
                apply(entry[0], entry[1], -1)
        for q in quotas_by_ns.get(ns, []):
            tot = usage_by_ns.get(ns, {})
            for name, hard in q.hard.items():
                assert tot.get(name, 0) <= hard, (
                    f"overspend in {ns}: {name}={tot.get(name, 0)} > "
                    f"hard {hard} at rv {ev.resource_version}"
                )
    watch.stop()
    return usage_by_ns


class TestLedgerDifferential:
    def test_randomized_churn_ledger_matches_replay(self):
        """Seeded multi-namespace churn (bursts, deletes, quota raises)
        under the ha-chaos profile (api_unavailable, watch truncation,
        bind conflicts): at quiescence every quota's used equals the
        apiserver-truth recount of bound pods, and the full
        watch-history replay shows ZERO overspend at every point."""
        from kubernetes_tpu.robustness.faults import (
            FaultInjector, install_injector, load_profile,
        )

        rng = random.Random(1234)
        server = APIServer()
        client = Client(server)
        informers = InformerFactory(server)
        sched = new_scheduler(client, informers, batch=True, max_batch=64)
        qc = arm_tenancy(sched, client, informers)
        namespaces = [f"t{k}" for k in range(6)]
        quotas_by_ns = {}
        for ns in namespaces:
            q = _mk_quota(ns, pods=rng.randint(3, 8), cpu=4000)
            client.create_resource_quota(q)
            quotas_by_ns[ns] = [q]
        for i in range(6):
            client.create_node(
                make_node(f"n{i}").capacity(cpu="16", memory="32Gi").obj()
            )
        install_injector(
            FaultInjector(load_profile("ha-chaos", seed=77))
        )
        try:
            informers.start()
            informers.wait_for_cache_sync()
            sched.queue.run()
            qc.sync_all()
            qc.start()
            sched.start()
            created = []
            for round_i in range(5):
                for _ in range(rng.randint(5, 15)):
                    ns = rng.choice(namespaces)
                    name = f"p{len(created)}"
                    client.create_pod(
                        _pod_in(ns, name, cpu=f"{rng.randint(1, 4)}00m")
                    )
                    created.append((ns, name))
                time.sleep(0.3)
                # delete a random slice (bound or pending alike)
                for _ in range(rng.randint(0, 5)):
                    if not created:
                        break
                    ns, name = created.pop(
                        rng.randrange(len(created))
                    )
                    try:
                        client.delete_pod(ns, name)
                    except KeyError:
                        pass
                if round_i == 2:
                    # mid-run quota raise: parked pods must wake
                    for ns in namespaces[:2]:
                        client.update_resource_quota_status(
                            ns, "quota",
                            lambda o: setattr(o, "hard", {
                                **o.hard,
                                "pods": o.hard["pods"] + 3,
                            }),
                        )
                        quotas_by_ns[ns] = [
                            client.get("ResourceQuota", ns, "quota")
                        ]
            # quiesce: chaos points are bounded, so the system settles
            install_injector(None)
            time.sleep(2.0)
            sched.wait_for_inflight_binds(timeout=30)
            _wait(
                lambda: not sched._pending_exists()
                and sched.queue.active_count() == 0,
                timeout=20,
            )
            time.sleep(1.0)
            # (a) ledger == apiserver-truth recount, zero in-flight
            for ns in namespaces:
                q = client.get("ResourceQuota", ns, "quota")
                recount: dict = {}
                for p in client.list_pods()[0]:
                    if (
                        p.metadata.namespace == ns and p.spec.node_name
                        and p.metadata.deletion_timestamp is None
                    ):
                        from kubernetes_tpu.controllers.quota import (
                            quota_pod_usage,
                        )

                        for rname, qty in quota_pod_usage(p).items():
                            recount[rname] = recount.get(rname, 0) + qty
                for rname, hard in q.hard.items():
                    assert q.status.used.get(rname, 0) == recount.get(
                        rname, 0
                    ), (
                        f"{ns}.{rname}: ledger "
                        f"{q.status.used.get(rname, 0)} != recount "
                        f"{recount.get(rname, 0)}"
                    )
                    assert q.status.used.get(rname, 0) <= hard
            # (b) zero overspend over the whole history
            final = _replay_bound_usage(server, {
                ns: [client.get("ResourceQuota", ns, "quota")]
                for ns in namespaces
            })
            for ns in namespaces:
                q = client.get("ResourceQuota", ns, "quota")
                for rname in q.hard:
                    assert q.status.used.get(rname, 0) == final.get(
                        ns, {}
                    ).get(rname, 0)
        finally:
            install_injector(None)
            qc.stop()
            sched.stop()
            informers.stop()


class TestFairOrder:
    def _pods(self, spec):
        """spec: list of (ns, cpu_milli, priority)."""
        out = []
        for i, (ns, cpu, prio) in enumerate(spec):
            p = _pod_in(ns, f"f{i}", cpu=f"{cpu}m", priority=prio)
            pod_resource_requests(p)
            out.append(p)
        return out

    def test_under_served_tenant_places_first(self):
        tt = TenantShareTracker()
        tt.set_capacity(10_000, 1 << 30)
        # "heavy" already holds 40% of cluster cpu
        heavy = _pod_in("heavy", "bound", cpu="4000m")
        tt.note_bound([heavy])
        pods = self._pods(
            [("heavy", 100, 0)] * 3 + [("light", 100, 0)] * 3
        )
        order = fair_order(
            np.arange(6, dtype=np.int32), pods,
            np.zeros(6, dtype=np.int32), tt,
        )
        ns_seq = [pods[int(i)].metadata.namespace for i in order]
        assert ns_seq[:3] == ["light"] * 3

    def test_priority_dominates_share(self):
        tt = TenantShareTracker()
        tt.set_capacity(10_000, 1 << 30)
        tt.note_bound([_pod_in("a", "bound", cpu="5000m")])
        # tenant a's pod has HIGHER priority: it must still go first
        pods = self._pods([("a", 100, 50), ("b", 100, 0)])
        order = fair_order(
            np.asarray([0, 1], dtype=np.int32), pods,
            np.asarray([50, 0], dtype=np.int32), tt,
        )
        assert [int(i) for i in order] == [0, 1]

    def test_virtual_share_interleaves_equal_tenants(self):
        """Equal starting shares: the merge round-robins (each placed
        pod advances its tenant's virtual share past the other's)."""
        tt = TenantShareTracker()
        tt.set_capacity(10_000, 1 << 30)
        pods = self._pods(
            [("a", 500, 0)] * 3 + [("b", 500, 0)] * 3
        )
        order = fair_order(
            np.arange(6, dtype=np.int32), pods,
            np.zeros(6, dtype=np.int32), tt,
        )
        ns_seq = [pods[int(i)].metadata.namespace for i in order]
        assert ns_seq == ["a", "b", "a", "b", "a", "b"]

    def test_mixed_resource_tenants_seed_per_axis_usage(self):
        """The virtual progression seeds from each tenant's ACTUAL
        per-axis usage, not the dominant share smeared across both
        axes: A (50% cpu / ~0% mem) still outranks B (40% / 40%) on a
        mem-dominant comparison once B's true mem usage counts."""
        tt = TenantShareTracker()
        tt.set_capacity(10_000, 10_000)
        # A: 50% cpu, ~0% mem (dominant share 0.50, cpu-pinned)
        a_bound = _pod_in("a", "abound", cpu="5000m")
        a_bound.spec.containers[0].resources.requests["memory"] = 0
        pod_resource_requests(a_bound)
        tt.note_bound([a_bound])
        # B: 52% on BOTH axes (dominant share 0.52)
        b_bound = _pod_in("b", "bbound", cpu="5200m")
        b_bound.spec.containers[0].resources.requests["memory"] = (
            5200 * 1024
        )
        pod_resource_requests(b_bound)
        tt.note_bound([b_bound])
        # mem-only contenders: A's dominant share stays cpu-pinned at
        # 0.50 no matter how many it places (its mem axis starts near
        # ZERO), so all four A pods lead. A share-smeared seed would
        # start A's virtual mem at 50% of capacity, cross B's 0.52
        # after two placements, and wrongly hand B the middle slots.
        pods = []
        for i, ns in enumerate(["a", "b", "a", "b", "a", "a"]):
            p = _pod_in(ns, f"m{i}", cpu="0")
            p.spec.containers[0].resources.requests["memory"] = (
                100 * 1024
            )
            pod_resource_requests(p)
            pods.append(p)
        order = fair_order(
            np.arange(6, dtype=np.int32), pods,
            np.zeros(6, dtype=np.int32), tt,
        )
        ns_seq = [pods[int(i)].metadata.namespace for i in order]
        assert ns_seq == ["a", "a", "a", "a", "b", "b"], ns_seq

    def test_single_tenant_fast_path_returns_base(self):
        tt = TenantShareTracker()
        tt.set_capacity(10_000, 1 << 30)
        pods = self._pods([("only", 100, 0)] * 4)
        base = np.asarray([2, 0, 3, 1], dtype=np.int32)
        order = fair_order(
            base, pods, np.zeros(4, dtype=np.int32), tt
        )
        assert order is base

    def test_fifo_within_tenant_preserved(self):
        tt = TenantShareTracker()
        tt.set_capacity(10_000, 1 << 30)
        pods = self._pods(
            [("a", 100, 0), ("b", 100, 0), ("a", 100, 0), ("b", 100, 0)]
        )
        order = [int(i) for i in fair_order(
            np.arange(4, dtype=np.int32), pods,
            np.zeros(4, dtype=np.int32), tt,
        )]
        assert order.index(0) < order.index(2)
        assert order.index(1) < order.index(3)


class TestDRFBiasE2E:
    def test_contended_capacity_splits_fairly(self):
        """Two tenants, one with existing usage, contending for a
        cluster that fits half the burst: the under-served tenant must
        take at least its fair share of the contended binds."""
        server = APIServer()
        client = Client(server)
        informers = InformerFactory(server)
        sched = new_scheduler(client, informers, batch=True, max_batch=64)
        arm_tenancy(sched, client, informers, quota=False)
        try:
            # 2 nodes x 8 pods capacity = 16 slots
            for i in range(2):
                client.create_node(
                    make_node(f"n{i}")
                    .capacity(cpu="4", memory="16Gi", pods=8)
                    .obj()
                )
            informers.start()
            informers.wait_for_cache_sync()
            sched.queue.run()
            # heavy's previous usage: 6 pre-bound pods
            for i in range(6):
                p = _pod_in("heavy", f"pre{i}", cpu="400m")
                p.spec.node_name = f"n{i % 2}"
                client.create_pod(p)
            # the contended burst: heavy first in FIFO order, then light
            for i in range(10):
                client.create_pod(_pod_in("heavy", f"h{i}", cpu="400m"))
            for i in range(10):
                client.create_pod(_pod_in("light", f"l{i}", cpu="400m"))
            sched.start()
            _wait(
                lambda: sum(
                    1 for p in client.list_pods()[0] if p.spec.node_name
                ) >= 16,
                timeout=30,
            )
            sched.wait_for_inflight_binds()
            bound_light = sum(
                1 for p in client.list_pods()[0]
                if p.spec.node_name and p.metadata.namespace == "light"
                and p.metadata.name.startswith("l")
            )
            # 10 contended slots (16 - 6 pre-bound): FIFO alone would
            # give heavy all 10; DRF must hand light at least half
            assert bound_light >= 5, f"light bound only {bound_light}"
        finally:
            sched.stop()
            informers.stop()


class TestPlainPodIngestGuard:
    def test_native_ingest_stays_fallback_free_with_tenancy_armed(self):
        """Tier-1 guard: arming the fairness plane must not knock plain
        pods off the native ingest fast path -- tenant identity is the
        namespace the decode already materialized, so ingest_stamp runs
        unchanged and books zero fallbacks."""
        from kubernetes_tpu import native as _native
        from kubernetes_tpu.utils import metrics

        server = APIServer()
        client = Client(server)
        informers = InformerFactory(server)
        sched = new_scheduler(client, informers, batch=True, max_batch=64)
        arm_tenancy(sched, client, informers)
        try:
            fallbacks0 = sum(
                metrics.ingest_native_fallbacks.value(site=s)
                for s in (
                    "classify-stamp", "informer-apply", "queue-shape",
                    "pack-gather",
                )
            )
            pods = []
            for i in range(64):
                p = _pod_in(f"tenant-{i % 8}", f"plain{i}", cpu="250m")
                pods.append(p)
            sched.classify_pods_bulk(pods)
            fallbacks1 = sum(
                metrics.ingest_native_fallbacks.value(site=s)
                for s in (
                    "classify-stamp", "informer-apply", "queue-shape",
                    "pack-gather",
                )
            )
            assert fallbacks1 == fallbacks0
            plain = sched._plain_admission_record()
            for p in pods:
                assert "_packrow" in p.__dict__
                assert "_req_memo" in p.__dict__
                if _native.ingest_fn("ingest_stamp")[0] is not None:
                    # the shared read-only record serves every plain pod
                    assert p.__dict__["_admission"] is plain
        finally:
            sched.stop()
            informers.stop()


class TestQuarantineRelist:
    def test_persisted_condition_parks_at_relist(self):
        """ROADMAP item 6c: a restarted scheduler relists a pending pod
        still carrying PodQuarantined=True -- it must re-park, never
        re-enter batches, until a REAL spec update releases it."""
        from kubernetes_tpu.robustness.containment import (
            QUARANTINE_CONDITION,
        )

        server = APIServer()
        client = Client(server)
        # the pod was parked by the PREVIOUS incarnation
        poisoned = make_pod("poison").container(cpu="100m").obj()
        poisoned.status.conditions.append(PodCondition(
            type=QUARANTINE_CONDITION, status="True",
            reason="QuarantineBudgetExhausted",
        ))
        client.create_pod(poisoned)
        client.create_pod(make_pod("healthy").container(cpu="100m").obj())
        client.create_node(
            make_node("n0").capacity(cpu="16", memory="32Gi").obj()
        )
        informers = InformerFactory(server)
        sched = new_scheduler(client, informers, batch=True, max_batch=64)
        try:
            informers.start()
            informers.wait_for_cache_sync()
            sched.queue.run()
            sched.start()
            assert _wait(lambda: bool(
                client.get_pod("default", "healthy").spec.node_name
            ))
            sched.wait_for_inflight_binds()
            assert sched.queue.quarantine_parked_count() == 1
            assert not client.get_pod("default", "poison").spec.node_name
            # cluster events never wake it
            client.create_node(
                make_node("n1").capacity(cpu="16", memory="32Gi").obj()
            )
            time.sleep(0.5)
            assert sched.queue.quarantine_parked_count() == 1
            # a REAL spec update (operator intervention) releases it
            # (guaranteed_update is copy-on-write: nested collections
            # are REPLACED, never mutated in place)
            client.server.guaranteed_update(
                "Pod", "default", "poison",
                lambda p: setattr(
                    p.metadata, "labels",
                    {**p.metadata.labels, "fixed": "yes"},
                ),
            )
            assert _wait(lambda: bool(
                client.get_pod("default", "poison").spec.node_name
            ))
        finally:
            sched.stop()
            informers.stop()


class TestLegacyMeshCrashLoop:
    def test_untyped_persistent_mesh_failure_trips_detector(
        self, monkeypatch
    ):
        """ROADMAP item 6a: on the KTPU_MESH_DELTA=0 legacy mesh path,
        an untyped persistent mesh failure falls whole to the
        sequential floor ONCE; the identical batch failing again trips
        the crash-loop detector and routes to containment (bisection /
        quarantine) instead of storming the floor on every retry."""
        import jax
        from jax.sharding import Mesh

        from kubernetes_tpu.framework.interface import PodInfo
        from kubernetes_tpu.utils import metrics

        monkeypatch.setenv("KTPU_MESH_DELTA", "0")
        server = APIServer()
        client = Client(server)
        informers = InformerFactory(server)
        mesh = Mesh(
            np.array(jax.devices()[:1]), axis_names=("nodes",)
        )
        sched = new_scheduler(
            client, informers, batch=True, max_batch=64, mesh=mesh
        )
        assert sched.mesh_delta is False
        client.create_node(
            make_node("n0").capacity(cpu="1", memory="1Gi").obj()
        )
        try:
            informers.start()
            informers.wait_for_cache_sync()
            sched.queue.run()

            def boom(*_a, **_k):
                raise RuntimeError("persistent untyped mesh failure")

            monkeypatch.setattr(sched, "_mesh_solve", boom)
            # two pods that also fail the sequential oracle (no
            # capacity), so the same batch re-enters
            infos = [
                PodInfo(
                    _pod_in("default", f"m{i}", cpu="8000m"), float(i)
                )
                for i in range(2)
            ]
            for pi in infos:
                client.create_pod(pi.pod)
            informers.pump()
            seq0 = metrics.solver_fallbacks.value(
                tier="sequential", reason="mesh_solve_error"
            )
            loops0 = metrics.exhausted_crashloops.value()
            # first fall: the transient-tolerant sequential floor
            assert sched._dispatch_solve(list(infos), 0) is None
            assert metrics.solver_fallbacks.value(
                tier="sequential", reason="mesh_solve_error"
            ) == seq0 + 1
            assert metrics.exhausted_crashloops.value() == loops0
            # the identical batch falling again is a crash loop:
            # containment takes it (bisection isolates the members into
            # quarantine holds), the floor is NOT hit a second time
            assert sched._dispatch_solve(list(infos), 0) is None
            assert metrics.exhausted_crashloops.value() >= loops0 + 1
            assert metrics.solver_fallbacks.value(
                tier="sequential", reason="mesh_solve_error"
            ) == seq0 + 1
            assert sched.quarantine.isolations >= 1
        finally:
            sched.stop()
            informers.stop()


class _HalfCoord:
    """Stub partition coordinator owning an explicit node set (queue-
    side responsibility stays open: these tests only exercise the
    cache/tenancy side of the partition gates)."""

    def __init__(self, owned):
        self.owned = set(owned)

    def wants_pod(self, pod):
        return True

    def owns_node(self, name):
        return name in self.owned

    def owns_node_obj(self, node):
        return node.metadata.name in self.owned


class TestClusterWideShares:
    """Residual 7(a) (ISSUE 18): partitioned-mode DRF dominant shares
    fold sibling stacks' bind echoes (the cache-side echo path sees
    them even though the partitioned cache drops them) and divide by
    CLUSTER capacity, not the stack's N/P-row slice."""

    def _stack(self, server, owned):
        client = Client(server)
        informers = InformerFactory(server)
        sched = new_scheduler(client, informers, batch=True, max_batch=16)
        sched.partition_coordinator = _HalfCoord(owned)
        arm_tenancy(sched, client, informers, quota=False)
        return client, informers, sched

    def test_foreign_bind_echo_folds_into_shares(self):
        server = APIServer()
        client, informers, sched = self._stack(server, {"node-0"})
        try:
            for i in range(2):
                client.create_node(
                    make_node(f"node-{i}")
                    .capacity(cpu="16", memory="32Gi").obj()
                )
            # our commit on the owned node + a sibling stack's commit on
            # the foreign node, arriving as plain bind echoes
            ours = _pod_in("tenant-a", "p-ours", cpu="1000m")
            ours.spec.node_name = "node-0"
            theirs = _pod_in("tenant-b", "p-theirs", cpu="1000m")
            theirs.spec.node_name = "node-1"
            client.create_pod(ours)
            client.create_pod(theirs)
            informers.pump()
            tt = sched.tenant_shares
            tt.refresh_capacity(None)  # node feed wins; nt unused
            used, cap_cpu, cap_mem = tt.usage_and_caps(
                ["tenant-a", "tenant-b"]
            )
            assert used["tenant-a"][0] == 1000
            assert used["tenant-b"][0] == 1000, (
                "sibling-stack bind echo must fold into the shares"
            )
            # denominator is BOTH nodes, not the owned slice
            assert cap_cpu == 32000
            assert abs(tt.share("tenant-b") - 1000 / 32000) < 1e-9
            # the foreign pod must NOT have entered the partitioned cache
            assert "node-1" not in sched.cache._nodes
        finally:
            sched.stop()
            informers.stop()

    def test_uid_double_echo_dedup_and_unbind_retires(self):
        tt = TenantShareTracker()
        tt.set_capacity(10_000, 1 << 30)
        pod = _pod_in("a", "p", cpu="5000m")
        tt.note_bound([pod])
        tt.note_bound([pod])  # relist re-echo of the same bind
        assert tt.share("a") == 0.5
        tt.note_unbound([pod])
        assert tt.share("a") == 0.0
        # a genuine re-bind after the unbind counts again
        tt.note_bound([pod])
        assert tt.share("a") == 0.5

    def test_two_stacks_converge_to_cluster_truth(self):
        server = APIServer()
        s1 = self._stack(server, {"node-0", "node-1"})
        s2 = self._stack(server, {"node-2", "node-3"})
        try:
            client = s1[0]
            for i in range(4):
                client.create_node(
                    make_node(f"node-{i}")
                    .capacity(cpu="10", memory="16Gi").obj()
                )
            for i in range(4):
                p = _pod_in(
                    "tenant-a" if i % 2 == 0 else "tenant-b",
                    f"b{i}", cpu="2000m",
                )
                p.spec.node_name = f"node-{i}"
                client.create_pod(p)
            for _c, informers, _s in (s1, s2):
                informers.pump()
            views = []
            for _c, _i, sched in (s1, s2):
                tt = sched.tenant_shares
                tt.refresh_capacity(None)
                views.append(
                    tt.usage_and_caps(["tenant-a", "tenant-b"])
                )
            assert views[0] == views[1], (
                "both stacks must see identical cluster-wide usage"
            )
            used, cap_cpu, _ = views[0]
            assert used["tenant-a"] == (4000, used["tenant-a"][1])
            assert used["tenant-b"][0] == 4000
            assert cap_cpu == 40000
            # node retirement shrinks the shared denominator everywhere
            client.delete_node("node-3")
            for _c, informers, _s in (s1, s2):
                informers.pump()
            for _c, _i, sched in (s1, s2):
                tt = sched.tenant_shares
                tt.refresh_capacity(None)
                assert tt.usage_and_caps([])[1] == 30000
        finally:
            for _c, informers, sched in (s1, s2):
                sched.stop()
                informers.stop()
