"""Cluster-lifecycle chaos (PR 6): node flaps, spot-reclamation storms,
rolling drain waves -- and the machinery that makes them survivable
(PodRespawner, ClusterLifecycleDriver, the lifecycle-chaos profile).

The storm e2e at the bottom is the acceptance shape: a full scheduler
stack under the builtin ``lifecycle-chaos`` profile with the driver
performing real node surgery mid-burst -- everything converges bound,
each pod incarnation binds at most once (asserted against the full
watch history), and the churn is visible in the lifecycle counters.
"""

import time

import pytest

from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client.client import Client
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.robustness.faults import (
    FaultInjector,
    FaultPoint,
    FaultProfile,
    PointConfig,
    builtin_profiles,
    install_injector,
    load_profile,
)
from kubernetes_tpu.robustness.lifecycle import (
    ClusterLifecycleDriver,
    PodRespawner,
    cold_replacement,
    respawn_clone,
)
from kubernetes_tpu.scheduler.scheduler import new_scheduler
from kubernetes_tpu.testing import make_node, make_pod


@pytest.fixture(autouse=True)
def _clean_injector():
    yield
    install_injector(None)


def _env():
    server = APIServer()
    client = Client(server)
    return server, client


def test_lifecycle_chaos_profile_registered():
    profiles = builtin_profiles()
    assert "lifecycle-chaos" in profiles
    p = profiles["lifecycle-chaos"]
    assert FaultPoint.NODE_FLAP in p.points
    assert FaultPoint.RECLAIM_STORM in p.points
    # every point heals: bounded fires so a chaos run converges
    assert all(c.max_fires is not None for c in p.points.values())
    # the loader resolves it with a seed override
    assert load_profile("lifecycle-chaos", seed=7).seed == 7


class TestClones:
    def test_respawn_clone_is_a_fresh_incarnation(self):
        pod = make_pod("w").labels(app="x").node("n5").container(cpu="1").obj()
        pod.__dict__["_admission"] = object()  # scheduler memo stamp
        clone = respawn_clone(pod)
        assert clone.metadata.name == "w"
        assert clone.metadata.uid != pod.metadata.uid
        assert clone.spec.node_name == ""
        assert clone.status.phase != "Running"
        assert "_admission" not in clone.__dict__
        assert pod.spec.node_name == "n5"  # original untouched

    def test_cold_replacement_is_a_new_instance(self):
        node = make_node("n").capacity(cpu="8").obj()
        node.spec.unschedulable = True
        cold = cold_replacement(node)
        assert cold.metadata.name == "n"
        assert cold.metadata.uid != node.metadata.uid
        assert not cold.spec.unschedulable
        assert cold.status.conditions == []


class TestPodRespawner:
    def test_deleted_pod_respawns_pending(self):
        server, client = _env()
        client.create_pod(make_pod("w0").node("n0").container(cpu="1").obj())
        rs = PodRespawner(client)
        rs.start()
        try:
            client.delete_pod("default", "w0")
            deadline = time.time() + 5
            while time.time() < deadline:
                try:
                    p = client.get_pod("default", "w0")
                    break
                except KeyError:
                    time.sleep(0.01)
            else:
                raise AssertionError("pod never respawned")
            assert p.spec.node_name == ""
            assert rs.respawned == 1
        finally:
            rs.stop()

    def test_filter_excludes_pods(self):
        server, client = _env()
        client.create_pod(make_pod("keep").container(cpu="1").obj())
        rs = PodRespawner(
            client, should_respawn=lambda pod: pod.metadata.name != "keep"
        )
        rs.start()
        try:
            client.delete_pod("default", "keep")
            time.sleep(0.3)
            with pytest.raises(KeyError):
                client.get_pod("default", "keep")
            assert rs.respawned == 0
        finally:
            rs.stop()


class TestClusterLifecycleDriver:
    def _cluster(self, n):
        server, client = _env()
        for i in range(n):
            client.create_node(
                make_node(f"cn-{i}").capacity(cpu="8", memory="16Gi").obj()
            )
        return server, client

    def test_flap_kills_node_and_pods_then_restores(self):
        server, client = self._cluster(4)
        client.create_pod(
            make_pod("on0").node("cn-0").container(cpu="1").obj()
        )
        inj = FaultInjector(FaultProfile(
            "flap-once", seed=3,
            points={FaultPoint.NODE_FLAP: PointConfig(rate=1.0, max_fires=1)},
        ))
        drv = ClusterLifecycleDriver(
            client, injector=inj, flap_down_seconds=30.0,
        )
        drv.tick()
        assert drv.flaps == 1
        assert drv.down_count() == 1
        nodes, _ = client.list_nodes()
        assert len(nodes) == 3
        dead = next(n for n in ("cn-0", "cn-1", "cn-2", "cn-3")
                    if n not in {x.metadata.name for x in nodes})
        if dead == "cn-0":
            # the pod went with its node -- and respawned pending
            assert drv.pods_killed == 1
            assert drv.pods_respawned == 1
            p = client.get_pod("default", "on0")
            assert p.spec.node_name == ""
        # stop() force-restores everything still down: full capacity back
        drv.stop()
        assert drv.down_count() == 0
        nodes, _ = client.list_nodes()
        assert {x.metadata.name for x in nodes} == {
            "cn-0", "cn-1", "cn-2", "cn-3"
        }
        # the replacement is COLD: a new instance, not a resurrection
        restored = client.get_node(dead)
        assert not restored.spec.taints
        assert restored.status.conditions == []

    def test_storm_reclaims_fraction_and_never_double_kills(self):
        server, client = self._cluster(10)
        inj = FaultInjector(FaultProfile(
            "storm-once", seed=5,
            points={
                FaultPoint.RECLAIM_STORM: PointConfig(rate=1.0, max_fires=1),
            },
        ))
        drv = ClusterLifecycleDriver(
            client, injector=inj, storm_fraction=0.3,
            storm_down_seconds=30.0,
        )
        drv.tick()
        assert drv.storms == 1
        assert drv.nodes_reclaimed == 3
        assert drv.down_count() == 3
        assert len(client.list_nodes()[0]) == 7
        # max_fires=1: the next tick must not fire again
        drv.tick()
        assert drv.storms == 1
        drv.stop()
        assert len(client.list_nodes()[0]) == 10

    def test_node_filter_protects_nodes(self):
        server, client = self._cluster(3)
        inj = FaultInjector(FaultProfile(
            "flap", seed=1,
            points={FaultPoint.NODE_FLAP: PointConfig(rate=1.0, max_fires=3)},
        ))
        drv = ClusterLifecycleDriver(
            client, injector=inj, flap_down_seconds=30.0,
            node_filter=lambda n: n.metadata.name != "cn-0",
        )
        for _ in range(3):
            drv.tick()
        names = {x.metadata.name for x in client.list_nodes()[0]}
        assert "cn-0" in names  # protected node never chosen
        drv.stop()


def _bind_transitions_by_uid(server):
    """unbound->bound transitions per pod INCARNATION (uid), replayed
    from the full watch history: the exactly-once bind assertion that
    stays valid under kill+respawn churn, generalizing the name-keyed
    test_ha_failover harness."""
    w = server.watch("Pod", since_rv=0)
    node = {}
    transitions = {}
    for ev in w.pending():
        pod = ev.object
        uid = pod.metadata.uid
        if ev.type == "DELETED":
            node.pop(uid, None)
            continue
        prev = node.get(uid, "")
        cur = pod.spec.node_name or ""
        if not prev and cur:
            transitions[uid] = transitions.get(uid, 0) + 1
        node[uid] = cur
    w.stop()
    return transitions


@pytest.mark.slow
class TestLifecycleChaosStorm:
    def test_storm_e2e_converges_under_lifecycle_chaos(self):
        """The acceptance e2e: 600 pods onto 48 nodes while the
        lifecycle-chaos profile flaps nodes and fires a reclamation
        storm mid-burst. Everything live converges bound, each
        incarnation binds at most once, and the churn is observable."""
        server = APIServer()
        client = Client(server)
        informers = InformerFactory(server)
        sched = new_scheduler(client, informers, batch=True, max_batch=128)
        for i in range(48):
            client.create_node(
                make_node(f"node-{i}")
                .capacity(cpu="32", memory="64Gi", pods=110)
                .obj()
            )
        informers.start()
        informers.wait_for_cache_sync()
        sched.queue.run()

        inj = FaultInjector(load_profile("lifecycle-chaos", seed=42))
        install_injector(inj)  # solver-fault sprinkle rides along
        drv = ClusterLifecycleDriver(
            client, injector=inj, tick_interval=0.1,
            flap_down_seconds=0.5, storm_fraction=0.1,
            storm_down_seconds=1.0,
        )
        sched.start()
        drv.start()
        names = [f"w-{i}" for i in range(600)]
        try:
            for n in names:
                client.create_pod(
                    make_pod(n).container(cpu="250m", memory="256Mi").obj()
                )
            deadline = time.time() + 180
            while time.time() < deadline:
                pods, _ = client.list_pods()
                if pods and all(p.spec.node_name for p in pods):
                    break
                time.sleep(0.2)
        finally:
            drv.stop()
        # post-chaos: the cluster is whole again; any pod left pending
        # (respawned during the final storm) places on restored capacity
        deadline = time.time() + 60
        while time.time() < deadline:
            pods, _ = client.list_pods()
            if pods and all(p.spec.node_name for p in pods):
                break
            time.sleep(0.2)
        sched.wait_for_inflight_binds()
        pods, _ = client.list_pods()
        unbound = [p.metadata.name for p in pods if not p.spec.node_name]
        assert not unbound, f"unbound after chaos: {unbound[:10]}"
        assert {p.metadata.name for p in pods} == set(names)
        # the chaos actually happened
        assert drv.flaps > 0
        assert drv.storms == 1
        assert drv.nodes_reclaimed >= drv.flaps
        assert len(client.list_nodes()[0]) == 48  # full capacity back
        # exactly-once binds per incarnation, from the watch history
        transitions = _bind_transitions_by_uid(server)
        doubles = {u: c for u, c in transitions.items() if c > 1}
        assert not doubles, f"double-bound incarnations: {doubles}"
        # membership churn rode the slot scatters, not full repacks:
        # cold joins/retires land as O(changed-row) patches (a storm
        # bigger than the scatter bucket may legitimately re-upload,
        # which is counted -- never silent)
        assert sched.membership_row_patches > 0
        sched.stop()
        informers.stop()
