"""Device-side InterPodAffinity: differential tests against the host
oracle plugin (the strongest parity check, SURVEY.md section 4 tier 5) and
end-to-end within-batch behavior on the BatchScheduler."""

import random
import time

import numpy as np
import jax.numpy as jnp
import pytest

from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.cache.snapshot import new_snapshot
from kubernetes_tpu.client.client import Client
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.framework.interface import CycleState
from kubernetes_tpu.ops.affinity import pack_affinity_batch
from kubernetes_tpu.ops.assignment import affinity_node_ok, row_node_values
from kubernetes_tpu.plugins.interpodaffinity import InterPodAffinity
from kubernetes_tpu.scheduler.scheduler import new_scheduler
from kubernetes_tpu.tensors import NodeTensorCache
from kubernetes_tpu.testing import make_node, make_pod


def _device_feasible(af, b_index, n_cap):
    """The scan's affinity feasibility for pod ``b_index`` against the
    INITIAL counts (i.e. before any batch placement) -- exactly what the
    sequential PreFilter+Filter computes."""
    vals_aff = row_node_values(
        jnp.asarray(af.node_value), jnp.asarray(af.row_key_aff)
    )
    vals_anti = row_node_values(
        jnp.asarray(af.node_value), jnp.asarray(af.row_key_anti)
    )
    vals_exist = row_node_values(
        jnp.asarray(af.node_value), jnp.asarray(af.row_key_exist)
    )
    ok = affinity_node_ok(
        jnp.asarray(af.counts_aff),
        jnp.asarray(af.counts_anti),
        jnp.asarray(af.counts_exist),
        vals_aff, vals_anti, vals_exist,
        jnp.asarray(af.pod_aff_rows[b_index]),
        jnp.asarray(af.pod_self_match[b_index]),
        jnp.asarray(af.pod_anti_rows[b_index]),
        jnp.asarray(af.pod_exist_match[b_index]),
    )
    return np.asarray(ok)[:n_cap]


def _oracle_feasible(pod, snapshot):
    plugin = InterPodAffinity()
    state = CycleState()
    state.write("__snapshot__", snapshot)
    plugin.pre_filter(state, pod)
    out = {}
    for ni in snapshot.list_node_infos():
        out[ni.node_name] = plugin.filter(state, pod, ni) is None
    return out


def _random_cluster(rng, num_nodes=10, num_existing=25):
    apps = ["web", "db", "cache", "batch"]
    nodes = [
        make_node(f"n{i}")
        .labels(zone=f"z{i % 3}", rack=f"r{i % 2}")
        .capacity(cpu="16", memory="32Gi")
        .obj()
        for i in range(num_nodes)
    ]
    existing = []
    for i in range(num_existing):
        p = (
            make_pod(f"e{i}")
            .node(f"n{rng.randrange(num_nodes)}")
            .labels(app=rng.choice(apps))
            .container(cpu="100m", memory="128Mi")
        )
        roll = rng.random()
        if roll < 0.2:
            p = p.pod_affinity(
                rng.choice(["zone", "rack"]),
                {"app": rng.choice(apps)},
                anti=True,
            )
        elif roll < 0.3:
            p = p.pod_affinity("zone", {"app": rng.choice(apps)})
        existing.append(p.obj())
    return existing, nodes


def _random_batch(rng, count=12):
    apps = ["web", "db", "cache", "batch"]
    out = []
    for i in range(count):
        p = (
            make_pod(f"p{i}")
            .labels(app=rng.choice(apps))
            .container(cpu="100m", memory="128Mi")
        )
        roll = rng.random()
        if roll < 0.35:
            p = p.pod_affinity(
                rng.choice(["zone", "rack"]), {"app": rng.choice(apps)}
            )
        elif roll < 0.7:
            p = p.pod_affinity(
                rng.choice(["zone", "rack"]),
                {"app": rng.choice(apps)},
                anti=True,
            )
        if 0.3 < roll < 0.45:
            p = p.pod_affinity("rack", {"app": rng.choice(apps)}, anti=True)
        out.append(p.obj())
    return out


class TestAffinityPackParity:
    @pytest.mark.parametrize("seed", [1, 7, 42, 99])
    def test_initial_feasibility_matches_oracle(self, seed):
        rng = random.Random(seed)
        existing, nodes = _random_cluster(rng)
        snap = new_snapshot(existing, nodes)
        nt = NodeTensorCache().update(snap)
        batch = _random_batch(rng)
        af = pack_affinity_batch(batch, snap, nt)
        assert af is not None
        for b, pod in enumerate(batch):
            want = _oracle_feasible(pod, snap)
            got = _device_feasible(af, b, nt.capacity)
            for ni in snap.list_node_infos():
                j = nt.row(ni.node_name)
                assert bool(got[j]) == want[ni.node_name], (
                    f"seed={seed} pod={pod.metadata.name} "
                    f"node={ni.node_name}: device={bool(got[j])} "
                    f"oracle={want[ni.node_name]}"
                )

    def test_first_pod_escape(self):
        # affinity to its own label on an empty cluster: schedulable
        # (filtering.go:494)
        nodes = [make_node("a").labels(zone="z1").obj()]
        pod = (
            make_pod("self")
            .labels(app="web")
            .pod_affinity("zone", {"app": "web"})
            .obj()
        )
        snap = new_snapshot([], nodes)
        nt = NodeTensorCache().update(snap)
        af = pack_affinity_batch([pod], snap, nt)
        got = _device_feasible(af, 0, nt.capacity)
        assert bool(got[0])

    def test_no_escape_for_non_self_matching_pod(self):
        nodes = [make_node("a").labels(zone="z1").obj()]
        pod = (
            make_pod("lonely")
            .labels(app="web")
            .pod_affinity("zone", {"app": "db"})
            .obj()
        )
        snap = new_snapshot([], nodes)
        nt = NodeTensorCache().update(snap)
        af = pack_affinity_batch([pod], snap, nt)
        got = _device_feasible(af, 0, nt.capacity)
        assert not bool(got[0])


def _wait_all_decided(client, sched, count, timeout=30.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        pods, _ = client.list_pods()
        if len(pods) >= count and all(
            p.spec.node_name or p.status.conditions for p in pods
        ):
            sched.wait_for_inflight_binds()
            return client.list_pods()[0]
        time.sleep(0.05)
    raise AssertionError("pods not decided in time")


class TestEndToEndDeviceAffinity:
    def _cluster(self, max_batch=32):
        server = APIServer()
        client = Client(server)
        informers = InformerFactory(server)
        sched = new_scheduler(
            client, informers, batch=True, max_batch=max_batch
        )
        return server, client, informers, sched

    def test_anti_affinity_spreads_within_batch_on_device(self):
        server, client, informers, sched = self._cluster()
        for name, zone in (("a", "z1"), ("b", "z2"), ("c", "z3")):
            client.create_node(
                make_node(name).labels(zone=zone)
                .capacity(cpu="8", memory="16Gi").obj()
            )
        informers.start()
        informers.wait_for_cache_sync()
        sched.queue.run()
        # three self-anti-affinity pods -> one per zone; a fourth is
        # unschedulable
        for i in range(4):
            client.create_pod(
                make_pod(f"p{i}")
                .labels(app="db")
                .creation_timestamp(float(i))
                .container(cpu="100m", memory="128Mi")
                .pod_affinity("zone", {"app": "db"}, anti=True)
                .obj()
            )
        sched.start()
        pods = _wait_all_decided(client, sched, 4)
        sched.stop()
        informers.stop()
        bound_zones = sorted(
            {"a": "z1", "b": "z2", "c": "z3"}[p.spec.node_name]
            for p in pods
            if p.spec.node_name
        )
        assert bound_zones == ["z1", "z2", "z3"]
        unbound = [p for p in pods if not p.spec.node_name]
        assert len(unbound) == 1
        assert sched.pods_fallback == 0
        assert sched.pods_solved_on_device >= 4

    def test_affinity_follows_within_batch_on_device(self):
        server, client, informers, sched = self._cluster()
        for name, zone in (("a", "z1"), ("b", "z2")):
            client.create_node(
                make_node(name).labels(zone=zone)
                .capacity(cpu="8", memory="16Gi").obj()
            )
        informers.start()
        informers.wait_for_cache_sync()
        sched.queue.run()
        # high-priority db pod lands somewhere; follower requires affinity
        # to it and must land in the same zone
        client.create_pod(
            make_pod("leader").labels(app="db").priority(10)
            .creation_timestamp(0.0)
            .container(cpu="100m", memory="128Mi").obj()
        )
        client.create_pod(
            make_pod("follower").labels(app="web")
            .creation_timestamp(1.0)
            .container(cpu="100m", memory="128Mi")
            .pod_affinity("zone", {"app": "db"})
            .obj()
        )
        sched.start()
        pods = _wait_all_decided(client, sched, 2)
        sched.stop()
        informers.stop()
        by_name = {p.metadata.name: p for p in pods}
        assert by_name["leader"].spec.node_name
        assert (
            by_name["follower"].spec.node_name
            == by_name["leader"].spec.node_name
        )
        assert sched.pods_fallback == 0

    def test_existing_anti_affinity_no_longer_disables_batching(self):
        server, client, informers, sched = self._cluster()
        for name, zone in (("a", "z1"), ("b", "z2")):
            client.create_node(
                make_node(name).labels(zone=zone)
                .capacity(cpu="8", memory="16Gi").obj()
            )
        informers.start()
        informers.wait_for_cache_sync()
        sched.queue.run()
        # a guard pod with required anti-affinity already runs on node a
        client.create_pod(
            make_pod("guard").node("a").labels(app="db")
            .container(cpu="100m", memory="128Mi")
            .pod_affinity("zone", {"app": "db"}, anti=True)
            .obj()
        )
        informers.pump()
        # a plain batch pod matching the guard's selector must avoid z1;
        # unrelated pods still batch on device
        for i in range(6):
            client.create_pod(
                make_pod(f"w{i}").labels(app="web")
                .container(cpu="100m", memory="128Mi").obj()
            )
        client.create_pod(
            make_pod("rival").labels(app="db")
            .container(cpu="100m", memory="128Mi").obj()
        )
        sched.start()
        pods = _wait_all_decided(client, sched, 8)
        sched.stop()
        informers.stop()
        by_name = {p.metadata.name: p for p in pods}
        assert by_name["rival"].spec.node_name == "b"
        assert all(
            by_name[f"w{i}"].spec.node_name for i in range(6)
        )
        assert sched.pods_fallback == 0
        assert sched.pods_solved_on_device >= 7
