"""Fenced HA failover e2e (PR-2 acceptance): two SchedulerApp instances
leader-elected over one apiserver. The leader is killed mid-burst (its
renews fail permanently via a targeted lease_renew_fail injector). While
it is deposed-but-live (lease expired, renew deadline not yet passed) it
keeps dispatching -- and every commit it attempts hits the commit-time
fence (lease ownership verified immediately before every bulk bind) and
aborts + requeues instead of binding. The standby then seizes the lease
and drains the backlog: 100% of pods bound, every pod EXACTLY once
(asserted against the apiserver's full watch history), fencing aborts
visible in metrics."""

import time

import pytest

from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.config.types import (
    KubeSchedulerConfiguration,
    LeaderElectionConfiguration,
)
from kubernetes_tpu.robustness.faults import (
    FaultInjector,
    FaultPoint,
    FaultProfile,
    PointConfig,
    install_injector,
)
from kubernetes_tpu.scheduler.app import SchedulerApp
from kubernetes_tpu.testing import make_node, make_pod
from kubernetes_tpu.utils import metrics


@pytest.fixture(autouse=True)
def _clean_injector():
    yield
    install_injector(None)


def _wait(predicate, timeout, step=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(step)
    return predicate()


def _le_config():
    return KubeSchedulerConfiguration(
        leader_election=LeaderElectionConfiguration(
            leader_elect=True,
            lease_duration_seconds=0.5,
            # deliberately > leaseDuration: the deposed leader stays
            # LIVE for seconds after its lease expires -- the fencing
            # window a real deployment's clock/renew skew opens,
            # compressed
            renew_deadline_seconds=6.0,
            retry_period_seconds=0.05,
        )
    )


def _bound_count(client, names):
    pods, _ = client.list_pods()
    return sum(
        1 for p in pods if p.spec.node_name and p.metadata.name in names
    )


def _bind_transitions(server):
    """Per-pod count of unbound->bound transitions replayed from the
    full watch history -- the ground-truth zero-double-bind assertion."""
    w = server.watch("Pod", since_rv=0)
    node = {}
    transitions = {}
    for ev in w.pending():
        pod = ev.object
        name = pod.metadata.name
        prev = node.get(name, "")
        cur = pod.spec.node_name or ""
        if ev.type == "DELETED":
            node.pop(name, None)
            continue
        if not prev and cur:
            transitions[name] = transitions.get(name, 0) + 1
        node[name] = cur
    w.stop()
    return transitions


def test_leader_killed_mid_batch_standby_drains_with_fencing():
    server = APIServer()
    app1 = SchedulerApp(config=_le_config(), server=server)
    client = app1.client
    for i in range(16):
        client.create_node(
            make_node(f"n{i}").capacity(cpu="32", memory="64Gi", pods=110).obj()
        )

    app1.start()
    assert _wait(lambda: app1.elector.is_leader, 10), "no initial leader"
    # fencing is wired: the committer verifies the lease per bulk bind
    assert app1.sched.fencing_check is not None

    wave1 = [f"p{i}" for i in range(160)]
    for n in wave1:
        client.create_pod(
            make_pod(n).container(cpu="100m", memory="128Mi").obj()
        )
    # the leader is mid-burst when the kill lands
    assert _wait(lambda: _bound_count(client, set(wave1)) >= 20, 90), (
        "leader never made progress"
    )

    fences0 = metrics.fencing_aborts.value()
    renew0 = metrics.lease_renew_failures.value()
    # the kill: every subsequent renew by the leader fails (targeted
    # injector -- a standby's elector would stay healthy)
    t_kill = time.perf_counter()
    app1.elector.fault_injector = FaultInjector(FaultProfile(
        "leader-kill", seed=0,
        points={FaultPoint.LEASE_RENEW_FAIL: PointConfig(rate=1.0)},
    ))
    # wave 1 still finishes: commits that happen while the lease is
    # still live are legitimate
    assert _wait(lambda: not app1.elector.holds_lease(), 15), (
        "lease never expired after the kill"
    )
    assert metrics.lease_renew_failures.value() > renew0
    assert app1.elector.is_leader, (
        "leader abdicated before the renew deadline -- no fencing window"
    )

    # -- the fencing window: deposed-but-live leader, no standby yet ----
    # It is the ONLY live scheduler, its loop still dispatches, and
    # every commit must hit the fence: abort + requeue, nothing binds.
    wave2 = [f"q{i}" for i in range(64)]
    for n in wave2:
        client.create_pod(
            make_pod(n).container(cpu="100m", memory="128Mi").obj()
        )
    assert _wait(
        lambda: metrics.fencing_aborts.value() > fences0, 20
    ), "deposed leader never hit the fence"
    assert _bound_count(client, set(wave2)) == 0, (
        "a deposed leader committed binds past the fence"
    )

    # -- failover: the standby seizes the expired lease and drains ------
    app2 = SchedulerApp(config=_le_config(), server=server)
    app2.start()
    assert _wait(lambda: app2.elector.is_leader, 20), (
        "standby never took over"
    )
    takeover_s = time.perf_counter() - t_kill
    nameset = set(wave1) | set(wave2)
    assert _wait(lambda: _bound_count(client, nameset) == 224, 120), (
        f"only {_bound_count(client, nameset)}/224 bound after failover"
    )
    # the deposed leader abdicates once its renew deadline passes
    assert _wait(lambda: not app1.elector.is_leader, 30), (
        "deposed leader never abdicated"
    )
    assert takeover_s < 60

    app1.sched.wait_for_inflight_binds()
    app2.sched.wait_for_inflight_binds()

    # zero double-binds, asserted against apiserver state: every pod has
    # exactly one unbound->bound transition in the full watch history
    transitions = _bind_transitions(server)
    assert sorted(transitions) == sorted(nameset)
    assert all(v == 1 for v in transitions.values()), {
        k: v for k, v in transitions.items() if v != 1
    }
    pods, _ = client.list_pods()
    per_node = {}
    for p in pods:
        assert p.spec.node_name, f"{p.metadata.name} unbound"
        per_node[p.spec.node_name] = per_node.get(p.spec.node_name, 0) + 1
    assert all(v <= 110 for v in per_node.values())

    app2.stop()
    app1.stop()
