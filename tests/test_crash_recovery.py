"""Restart e2e (PR-2 acceptance): the scheduler crashes between assume
and bind (injected crash_between_assume_and_bind -- no cleanup runs, the
in-flight pods stay assumed-but-unbound), and a fresh incarnation
rebuilds from a full relist: adopts every pod the dead instance bound,
requeues the in-flight ones, and every pod ends bound EXACTLY once."""

import time

from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client.client import Client
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.robustness.faults import (
    FaultInjector,
    FaultPoint,
    FaultProfile,
    PointConfig,
    install_injector,
)
from kubernetes_tpu.scheduler.resilience import recover_on_startup
from kubernetes_tpu.scheduler.scheduler import new_scheduler
from kubernetes_tpu.testing import make_node, make_pod
from kubernetes_tpu.utils import metrics

import pytest


@pytest.fixture(autouse=True)
def _clean_injector():
    yield
    install_injector(None)


def _wait(predicate, timeout, step=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(step)
    return predicate()


def _bound_count(client, names):
    pods, _ = client.list_pods()
    return sum(
        1 for p in pods if p.spec.node_name and p.metadata.name in names
    )


def _bind_transitions(server):
    """Per-pod count of unbound->bound transitions, replayed from the
    full watch history -- the ground-truth exactly-once assertion."""
    w = server.watch("Pod", since_rv=0)
    node = {}
    transitions = {}
    for ev in w.pending():
        pod = ev.object
        name = pod.metadata.name
        prev = node.get(name, "")
        cur = pod.spec.node_name or ""
        if ev.type == "DELETED":
            node.pop(name, None)
            continue
        if not prev and cur:
            transitions[name] = transitions.get(name, 0) + 1
        node[name] = cur
    w.stop()
    return transitions


def test_crash_between_assume_and_bind_then_restart_recovers():
    server = APIServer()
    client = Client(server)
    for i in range(8):
        client.create_node(
            make_node(f"n{i}").capacity(cpu="16", memory="32Gi", pods=60).obj()
        )

    # -- incarnation 1: binds wave 1, then dies mid-commit of wave 2 -----
    informers1 = InformerFactory(server)
    sched1 = new_scheduler(client, informers1, batch=True, max_batch=16)
    informers1.start()
    informers1.wait_for_cache_sync()
    sched1.start()

    wave1 = [f"w1-{i}" for i in range(20)]
    for n in wave1:
        client.create_pod(make_pod(n).container(cpu="100m", memory="128Mi").obj())
    assert _wait(lambda: _bound_count(client, set(wave1)) == 20, 90), (
        "wave 1 never bound"
    )

    install_injector(FaultInjector(FaultProfile(
        "crash", seed=0,
        points={
            FaultPoint.CRASH_BETWEEN_ASSUME_AND_BIND: PointConfig(
                rate=1.0, max_fires=1
            )
        },
    )))
    wave2 = [f"w2-{i}" for i in range(20)]
    for n in wave2:
        client.create_pod(make_pod(n).container(cpu="100m", memory="128Mi").obj())
    assert _wait(lambda: sched1.crashed, 60), "crash point never fired"
    # the dead incarnation ran NO cleanup: its cache still carries the
    # crashed bulk as assumed, and those pods are unbound at the API
    time.sleep(0.5)  # let any non-crashed in-flight batches land
    stranded = 20 - _bound_count(client, set(wave2))
    assert stranded > 0, "crash stranded nothing; the scenario is vacuous"
    informers1.stop()  # the process is gone

    # -- incarnation 2: fresh everything over the same apiserver ---------
    install_injector(None)  # a restarted process has no injected fault
    a0 = metrics.pods_adopted_on_restart.value()
    informers2 = InformerFactory(server)
    sched2 = new_scheduler(client, informers2, batch=True, max_batch=16)
    informers2.start()
    informers2.wait_for_cache_sync()
    report = recover_on_startup(sched2, client)
    # adopts every pod the previous incarnation bound...
    bound_now = _bound_count(client, set(wave1) | set(wave2))
    assert report.adopted == bound_now
    assert metrics.pods_adopted_on_restart.value() == a0 + bound_now
    assert sched2.cache.pod_count() == bound_now
    # ...and requeues the ones that died mid-flight
    assert report.requeued == stranded

    sched2.start()
    allnames = set(wave1) | set(wave2)
    assert _wait(lambda: _bound_count(client, allnames) == 40, 120), (
        f"only {_bound_count(client, allnames)}/40 bound after restart"
    )
    sched2.wait_for_inflight_binds()

    # exactly-once: every pod has exactly one unbound->bound transition
    # in the full watch history (no double-bind across the crash)
    transitions = _bind_transitions(server)
    assert sorted(transitions) == sorted(allnames)
    assert all(v == 1 for v in transitions.values()), {
        k: v for k, v in transitions.items() if v != 1
    }
    # capacity respected across the handover
    pods, _ = client.list_pods()
    per_node = {}
    for p in pods:
        per_node[p.spec.node_name] = per_node.get(p.spec.node_name, 0) + 1
    assert all(v <= 60 for v in per_node.values())

    sched2.stop()
    informers2.stop()
