"""The scheduler binary surface (cmd/kube-scheduler analogue): flag
parsing, config/policy layering, feature gates."""

import pytest

from kubernetes_tpu.__main__ import build_parser, parse_feature_gates


def test_flags_parse():
    args = build_parser().parse_args(
        [
            "--config", "cfg.yaml",
            "--healthz-bind-address", "127.0.0.1:10251",
            "--leader-elect",
            "--feature-gates", "TPUBatchSolver=true,EvenPodsSpread=false",
            "--percentage-of-nodes-to-score", "50",
            "-v",
        ]
    )
    assert args.config == "cfg.yaml"
    assert args.leader_elect is True
    assert args.percentage_of_nodes_to_score == 50


def test_feature_gates_parse():
    assert parse_feature_gates("A=true, B=false") == {"A": True, "B": False}
    assert parse_feature_gates("") == {}
    with pytest.raises(SystemExit):
        parse_feature_gates("A=maybe")


def test_unknown_gate_rejected():
    from kubernetes_tpu.config.loader import (
        DEFAULT_FEATURE_GATES,
        FeatureGate,
    )

    gates = FeatureGate(DEFAULT_FEATURE_GATES)
    with pytest.raises(ValueError, match="unknown feature gate"):
        gates.set_from_map({"NoSuchGate": True})


def test_binary_boots_and_serves(tmp_path):
    """python -m kubernetes_tpu boots, serves /healthz, schedules a pod
    through the in-proc control plane, and shuts down."""
    import time

    from kubernetes_tpu.config.types import KubeSchedulerConfiguration
    from kubernetes_tpu.scheduler.app import SchedulerApp
    from kubernetes_tpu.testing import make_node, make_pod

    app = SchedulerApp(config=KubeSchedulerConfiguration())
    try:
        host, port = app.start_serving()
        app.client.create_node(
            make_node("n").capacity(cpu="4", memory="8Gi").obj()
        )
        app.start()
        app.client.create_pod(make_pod("p").container(cpu="1").obj())

        import urllib.request

        body = urllib.request.urlopen(
            f"http://{host}:{port}/healthz", timeout=5
        ).read()
        assert body == b"ok"

        deadline = time.time() + 30
        bound = False
        while time.time() < deadline:
            pod = app.client.get_pod("default", "p")
            if pod.spec.node_name:
                bound = True
                break
            time.sleep(0.05)
        metrics_body = urllib.request.urlopen(
            f"http://{host}:{port}/metrics", timeout=5
        ).read().decode()
    finally:
        app.stop()
    assert bound
    assert "scheduler_schedule_attempts_total" in metrics_body
