import random

import pytest

from kubernetes_tpu.cache.cache import SchedulerCache
from kubernetes_tpu.cache.snapshot import Snapshot
from kubernetes_tpu.config.types import KubeSchedulerProfile
from kubernetes_tpu.framework.interface import CycleState, FitError
from kubernetes_tpu.framework.runtime import Framework
from kubernetes_tpu.plugins import new_in_tree_registry
from kubernetes_tpu.queue.scheduling_queue import PriorityQueue
from kubernetes_tpu.scheduler.generic import GenericScheduler
from kubernetes_tpu.scheduler.provider import minimal_plugins
from kubernetes_tpu.testing import make_node, make_pod


def _make(percentage=0, nominated=None):
    cache = SchedulerCache()
    gs = GenericScheduler(
        cache,
        Snapshot(),
        percentage_of_nodes_to_score=percentage,
        nominated_pods_lister=nominated,
        rng=random.Random(42),
    )
    fw = Framework(new_in_tree_registry(), minimal_plugins(), client=None)
    return cache, gs, fw


def test_num_feasible_nodes_adaptive():
    _, gs, _ = _make(percentage=0)
    assert gs.num_feasible_nodes_to_find(50) == 50  # below min -> all
    assert gs.num_feasible_nodes_to_find(100) == 100
    # 5000 nodes: 50 - 5000/125 = 10% -> 500
    assert gs.num_feasible_nodes_to_find(5000) == 500
    # huge cluster hits the 5% floor
    assert gs.num_feasible_nodes_to_find(100_000) == 5000
    # percentage >= 100 disables truncation
    _, gs2, _ = _make(percentage=100)
    assert gs2.num_feasible_nodes_to_find(5000) == 5000
    # small result floors at 100
    _, gs3, _ = _make(percentage=1)
    assert gs3.num_feasible_nodes_to_find(5000) == 100


def test_select_host_ties_deterministic_with_seed():
    _, gs, _ = _make()
    pl = [("a", 10), ("b", 10), ("c", 5)]
    picks = {gs.select_host(pl) for _ in range(50)}
    assert picks <= {"a", "b"}
    assert len(picks) == 2  # both ties get picked over 50 draws


def test_schedule_picks_feasible_best():
    cache, gs, fw = _make()
    cache.add_node(make_node("small").capacity(cpu="1", memory="2Gi").obj())
    cache.add_node(make_node("big").capacity(cpu="8", memory="32Gi").obj())
    pod = make_pod("p").container(cpu="2", memory="4Gi").obj()
    result = gs.schedule(fw, CycleState(), pod)
    assert result.suggested_host == "big"
    assert result.feasible_nodes == 1


def test_schedule_no_nodes_raises_fit_error():
    _, gs, fw = _make()
    with pytest.raises(FitError) as exc:
        gs.schedule(fw, CycleState(), make_pod("p").obj())
    assert exc.value.num_all_nodes == 0


def test_schedule_no_fit_collects_statuses():
    cache, gs, fw = _make()
    cache.add_node(make_node("n1").capacity(cpu="1", memory="1Gi").obj())
    pod = make_pod("p").container(cpu="4", memory="4Gi").obj()
    with pytest.raises(FitError) as exc:
        gs.schedule(fw, CycleState(), pod)
    statuses = exc.value.filtered_nodes_statuses
    assert "n1" in statuses
    assert "Insufficient cpu" in statuses["n1"].reasons


def test_nominated_pods_two_pass_filtering():
    """A node with a higher-priority nominated pod must reject a pod that
    only fits without the nominated pod (generic_scheduler.go:598-616)."""
    queue = PriorityQueue(lambda a, b: a.timestamp < b.timestamp)
    cache, gs, fw = _make(nominated=queue)
    cache.add_node(make_node("n1").capacity(cpu="4", memory="8Gi").obj())
    # nominated pod (from a previous preemption) takes 3 cpu
    nominated = make_pod("nom").priority(100).container(cpu="3", memory="1Gi").obj()
    nominated.status.nominated_node_name = "n1"
    queue.update_nominated_pod_for_node(nominated, "n1")

    # incoming lower-priority pod needing 2 cpu: fits alone, not with nom
    pod = make_pod("p").priority(0).container(cpu="2", memory="1Gi").obj()
    with pytest.raises(FitError):
        gs.schedule(fw, CycleState(), pod)

    # a pod that fits alongside the nominated pod passes both passes
    small = make_pod("small").priority(0).container(cpu="1", memory="1Gi").obj()
    result = gs.schedule(fw, CycleState(), small)
    assert result.suggested_host == "n1"


def test_round_robin_start_index_advances_under_truncation():
    """With search truncation active, successive cycles start filtering at
    different nodes (generic_scheduler.go:456 nextStartNodeIndex)."""
    cache, gs, fw = _make(percentage=40)
    for i in range(150):
        cache.add_node(make_node(f"n{i:03d}").capacity(cpu="4", memory="8Gi").obj())
    pod = make_pod("p").container(cpu="1", memory="1Gi").obj()
    # 150 * 40% = 60 -> floored to MIN_FEASIBLE_NODES_TO_FIND = 100
    assert gs.num_feasible_nodes_to_find(150) == 100
    gs.schedule(fw, CycleState(), pod)
    assert gs.next_start_node_index == 100
    gs.schedule(fw, CycleState(), make_pod("p2").container(cpu="1", memory="1Gi").obj())
    assert gs.next_start_node_index == (100 + 100) % 150
