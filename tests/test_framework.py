"""Framework runtime tests with inline fake plugins
(reference framework/v1alpha1/framework_test.go pattern)."""

import pytest

from kubernetes_tpu.cache.node_info import NodeInfo
from kubernetes_tpu.config.types import Plugin as PluginRef, Plugins, PluginSet
from kubernetes_tpu.framework.interface import (
    CycleState,
    Plugin,
    Status,
    StatusCode,
)
from kubernetes_tpu.framework.registry import Registry
from kubernetes_tpu.framework.runtime import Framework
from kubernetes_tpu.testing import make_node, make_pod


class FakeFilterPlugin(Plugin):
    NAME = "FakeFilter"

    def __init__(self, fail_nodes=()):
        self.fail_nodes = set(fail_nodes)
        self.calls = 0

    def filter(self, state, pod, node_info):
        self.calls += 1
        if node_info.node_name in self.fail_nodes:
            return Status.unschedulable("blocked")
        return None


class FakeScorePlugin(Plugin):
    NAME = "FakeScore"

    def __init__(self, scores=None):
        self.scores = scores or {}

    def score(self, state, pod, node_name):
        return self.scores.get(node_name, 0), None

    def normalize_score(self, state, pod, scores):
        max_s = max((ns.score for ns in scores), default=0) or 1
        for ns in scores:
            ns.score = ns.score * 100 // max_s
        return None


class FakePermitWait(Plugin):
    NAME = "FakePermitWait"

    def permit(self, state, pod, node_name):
        return Status.wait(), 0.2


def _framework(plugins_cfg, registry_entries):
    registry = Registry()
    for name, factory in registry_entries.items():
        registry.register(name, factory)
    return Framework(registry, plugins_cfg)


def test_filter_pipeline():
    fp = FakeFilterPlugin(fail_nodes={"bad"})
    plugins = Plugins(filter=PluginSet(enabled=[PluginRef("FakeFilter")]))
    fw = _framework(plugins, {"FakeFilter": lambda args, h: fp})
    pod = make_pod("p").obj()
    good = NodeInfo(make_node("good").capacity(cpu="1", memory="1Gi").obj())
    bad = NodeInfo(make_node("bad").capacity(cpu="1", memory="1Gi").obj())
    assert fw.run_filter_plugins(CycleState(), pod, good) == {}
    statuses = fw.run_filter_plugins(CycleState(), pod, bad)
    assert statuses["FakeFilter"].code == StatusCode.UNSCHEDULABLE


def test_score_normalize_and_weight():
    sp = FakeScorePlugin(scores={"n1": 10, "n2": 20})
    plugins = Plugins(score=PluginSet(enabled=[PluginRef("FakeScore", weight=2)]))
    fw = _framework(plugins, {"FakeScore": lambda args, h: sp})
    scores, status = fw.run_score_plugins(CycleState(), make_pod("p").obj(), ["n1", "n2"])
    assert status is None
    by_name = {ns.name: ns.score for ns in scores["FakeScore"]}
    # normalized to [50, 100] then x2 weight
    assert by_name == {"n1": 100, "n2": 200}


def test_score_out_of_range_rejected():
    class BadScore(Plugin):
        NAME = "Bad"

        def score(self, state, pod, node_name):
            return 1000, None

    plugins = Plugins(score=PluginSet(enabled=[PluginRef("Bad")]))
    fw = _framework(plugins, {"Bad": lambda args, h: BadScore()})
    _, status = fw.run_score_plugins(CycleState(), make_pod("p").obj(), ["n1"])
    assert status is not None and status.code == StatusCode.ERROR


def test_permit_wait_then_allow():
    import threading

    plugins = Plugins(permit=PluginSet(enabled=[PluginRef("FakePermitWait")]))
    fw = _framework(plugins, {"FakePermitWait": lambda a, h: FakePermitWait()})
    pod = make_pod("p").obj()
    status = fw.run_permit_plugins(CycleState(), pod, "n1")
    assert status.code == StatusCode.WAIT
    wp = fw.get_waiting_pod(pod.metadata.uid)
    assert wp is not None

    threading.Timer(0.02, lambda: wp.allow("FakePermitWait")).start()
    assert fw.wait_on_permit(pod) is None


def test_permit_wait_timeout_rejects():
    plugins = Plugins(permit=PluginSet(enabled=[PluginRef("FakePermitWait")]))
    fw = _framework(plugins, {"FakePermitWait": lambda a, h: FakePermitWait()})
    pod = make_pod("p").obj()
    fw.run_permit_plugins(CycleState(), pod, "n1")
    status = fw.wait_on_permit(pod)
    assert status is not None and status.code == StatusCode.UNSCHEDULABLE


def test_unknown_plugin_rejected():
    plugins = Plugins(filter=PluginSet(enabled=[PluginRef("Nope")]))
    with pytest.raises(ValueError, match="not registered"):
        _framework(plugins, {})


def test_plugin_missing_extension_point_rejected():
    plugins = Plugins(score=PluginSet(enabled=[PluginRef("FakeFilter")]))
    with pytest.raises(ValueError, match="does not implement"):
        _framework(plugins, {"FakeFilter": lambda a, h: FakeFilterPlugin()})


def test_cycle_state_clone():
    class St:
        def __init__(self, v):
            self.v = v

        def clone(self):
            return St(self.v)

    cs = CycleState()
    cs.write("k", St(1))
    c2 = cs.clone()
    assert c2.read("k").v == 1
    assert c2.read("k") is not cs.read("k")
    with pytest.raises(KeyError):
        cs.read("missing")


def test_plugins_apply_merge():
    defaults = Plugins(filter=PluginSet(enabled=[PluginRef("A"), PluginRef("B")]))
    custom = Plugins(
        filter=PluginSet(enabled=[PluginRef("C")], disabled=[PluginRef("A")])
    )
    merged = defaults.apply(custom)
    assert [p.name for p in merged.filter.enabled] == ["B", "C"]
    star = Plugins(filter=PluginSet(disabled=[PluginRef("*")]))
    merged = defaults.apply(star)
    assert merged.filter.enabled == []
