"""Tier-1 guard for the SHARDED mesh delta path (PR 9): the mesh
dispatch rides the same device-resident-carry + generation-handshake +
per-shard delta-scatter machinery as the single-device path.

- a steady 1k-pod burst on a simulated 2-device mesh performs AT MOST
  one full [N, R] node-state upload (``state_uploads`` must not scale
  with batch count), with zero handshake divergences, and places every
  pod IDENTICALLY to the sequential oracle;
- the randomized event-stream differential (interleaved membership
  churn, external pod churn, bind failures) extends to the sharded
  carry: after the stream settles, the device-resident ``req_state``
  must equal a fresh full pack of the host snapshot per node name, and
  the resident arrays must actually live sharded over the node axis.

Tests run on the virtual 8-device CPU mesh from conftest; a 2-device
sub-mesh keeps the GSPMD compiles cheap while still exercising real
cross-shard argmax collectives and shard-local scatters.
"""

import random
import time

import numpy as np
import pytest

import jax

from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client.client import Client
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.scheduler.scheduler import new_scheduler
from kubernetes_tpu.testing import make_node, make_pod

NUM_NODES = 16
NUM_PODS = 1000


def _mesh(n=2):
    from jax.sharding import Mesh

    devices = jax.devices()
    if len(devices) < n:
        pytest.skip(f"need {n} devices, have {len(devices)}")
    return Mesh(np.array(devices[:n]), axis_names=("nodes",))


class _KeepFirstRng:
    """Deterministic tie-break for the sequential oracle (selectHost
    reservoir sampling): always keep the first candidate == the device
    argmax's lowest-index rule."""

    def randrange(self, n):
        return 1 if n > 1 else 0

    def randint(self, a, b):
        return b


def _wait_all_bound(client, count, timeout=180.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        pods, _ = client.list_pods()
        bound = [p for p in pods if p.spec.node_name]
        if len(bound) >= count:
            return pods
        time.sleep(0.05)
    bound = [p for p in client.list_pods()[0] if p.spec.node_name]
    raise AssertionError(f"only {len(bound)}/{count} pods bound")


def _run(seed, *, mesh, warmup=False):
    """Drive a seeded 1k-pod burst; with ``warmup`` (mesh runs) the
    solver variants compile first and the returned dict carries the
    mesh jit-cache size before/after the measured burst (the
    zero-mid-run-recompile probe, covering BOTH mesh tiers -- they
    share the one jitted mesh solver)."""
    rng = random.Random(seed)
    server = APIServer()
    client = Client(server)
    informers = InformerFactory(server)
    sched = new_scheduler(
        client, informers, batch=mesh is not None, max_batch=256,
        mesh=mesh, rng=_KeepFirstRng(),
    )
    for i in range(NUM_NODES):
        client.create_node(
            make_node(f"m{i}")
            .capacity(cpu="64", memory="256Gi", pods=120)
            .obj()
        )
    pods = []
    for i in range(NUM_PODS):
        pods.append(
            make_pod(f"b{i}")
            .creation_timestamp(float(i))
            .container(
                cpu=f"{rng.choice([100, 200, 250])}m",
                memory=f"{rng.choice([128, 256])}Mi",
            )
            .obj()
        )
    informers.start()
    informers.wait_for_cache_sync()
    sched.queue.run()
    probe = {}
    if warmup and mesh is not None:
        from kubernetes_tpu.ops.assignment import mesh_packed_cache_size

        sched.warmup()
        probe["cache_before"] = mesh_packed_cache_size(mesh)
    for p in pods:
        client.create_pod(p)
    sched.start()
    _wait_all_bound(client, NUM_PODS)
    sched.wait_for_inflight_binds()
    if warmup and mesh is not None:
        from kubernetes_tpu.ops.assignment import mesh_packed_cache_size

        probe["cache_after"] = mesh_packed_cache_size(mesh)
    placements = {
        p.metadata.name: p.spec.node_name
        for p in client.list_pods()[0]
    }
    sched.stop()
    informers.stop()
    return placements, sched, probe


def _assert_steady_guard(sched):
    """The PR-9 steady-state invariants, tier-independent."""
    # the whole burst rode the sharded device path
    assert sched.pods_fallback == 0
    assert sched.pods_solved_on_device == NUM_PODS
    assert sched.batches_solved >= 2, (
        "burst completed in one batch; the guard needs a multi-batch "
        "steady state to prove anything"
    )
    # THE guard: full [N, R] uploads do not scale with batch count on
    # the mesh either -- one cold upload, then pure per-shard reuse
    assert sched.state_uploads <= 1, (
        f"{sched.state_uploads} full node-state uploads for "
        f"{sched.batches_solved} mesh batches -- the sharded carry is "
        f"not resident"
    )
    assert sched.state_reuses >= sched.batches_solved - 1
    assert sched.carry_divergences == 0
    # steady-state link traffic is bounded by churn (zero churn here)
    assert sched.delta_rows_uploaded == 0


def test_mesh_steady_burst_uploads_bounded_and_oracle_parity():
    """Steady burst on the default mesh path -- the shard_map'd PALLAS
    tier (PR 10) -- AND on the GSPMD XLA twin (KTPU_MESH_PALLAS=0):
    both must place every pod identically to the sequential oracle,
    hold the PR-9 carry invariants, and hit ZERO mid-run recompiles
    against the warmed signature set."""
    mesh = _mesh(2)
    want, _oracle, _ = _run(42, mesh=None)
    got, sched, probe = _run(42, mesh=mesh, warmup=True)

    assert sched.mesh_delta, "mesh delta path is off"
    # zero placement divergence vs the sequential oracle
    assert all(want.values()), "oracle failed to place a fitting pod"
    assert got == want
    # the greedy burst must have solved on the shard_map'd Pallas tier
    assert sched.mesh_solver_tier == "pallas", (
        f"tier {sched.mesh_solver_tier!r}, "
        f"by_tier={sched.ladder.solves_by_tier}"
    )
    _assert_steady_guard(sched)
    # zero mid-run recompiles: warmup compiled every layout BOTH tiers
    # can hit; a new signature inside the burst is a regression
    assert probe["cache_after"] == probe["cache_before"], probe


def test_mesh_xla_twin_burst_parity(monkeypatch):
    """The same steady burst pinned to the GSPMD XLA twin
    (KTPU_MESH_PALLAS=0 preserves the pre-PR-10 behavior): identical
    placements, same carry invariants, zero mid-run recompiles."""
    monkeypatch.setenv("KTPU_MESH_PALLAS", "0")
    mesh = _mesh(2)
    want, _oracle, _ = _run(42, mesh=None)
    got_twin, sched, probe = _run(42, mesh=mesh, warmup=True)
    assert got_twin == want
    assert sched.mesh_solver_tier == "xla"
    assert sched.ladder.solves_by_tier.get("pallas", 0) == 0
    _assert_steady_guard(sched)
    assert probe["cache_after"] == probe["cache_before"], probe


def test_mesh_event_stream_differential_sharded_carry(monkeypatch):
    """The PR-5 randomized event-stream differential extended to the
    SHARDED carry: interleaved pod bursts, external pod deletes, a bind
    failure, and membership churn (a cold node joining mid-stream) on a
    2-device mesh must leave the device-resident ``req_state`` equal to
    a fresh full pack of the settled host snapshot -- per node name,
    across both shards -- with membership riding the slot scatter (no
    extra full upload) and every resident array actually node-sharded.
    """
    from kubernetes_tpu.cache.snapshot import Snapshot
    from kubernetes_tpu.tensors import NodeTensorCache

    mesh = _mesh(2)
    rng = random.Random(20260803)
    server = APIServer()
    client = Client(server)
    informers = InformerFactory(server)
    sched = new_scheduler(
        client, informers, batch=True, max_batch=32, mesh=mesh,
    )
    for i in range(8):
        client.create_node(
            make_node(f"dm-n{i}")
            .capacity(cpu="64", memory="128Gi", pods=200)
            .obj()
        )
    informers.start()
    informers.wait_for_cache_sync()
    sched.queue.run()

    # one injected bind failure: the host diverges from the mirrored
    # expectation (the scatter-fix / counted-divergence case)
    orig_bulk = client.bind_assumed_bulk
    calls = {"n": 0}

    def flaky_bulk(assumed):
        calls["n"] += 1
        if calls["n"] == 3 and assumed:
            errs = orig_bulk(assumed[1:])
            return [(0, RuntimeError("synthetic bind failure"))] + [
                (i + 1, e) for i, e in errs
            ]
        return orig_bulk(assumed)

    monkeypatch.setattr(client, "bind_assumed_bulk", flaky_bulk)

    seq = 0
    uploads_after_cold = None
    for k in range(8):
        for _ in range(rng.randint(3, 8)):
            seq += 1
            client.create_pod(
                make_pod(f"dm-p{seq}")
                .container(
                    cpu=f"{rng.choice([100, 250, 500])}m",
                    memory="128Mi",
                )
                .obj()
            )
        if k == 3:
            # external churn: a controller deletes a bound pod behind
            # the scheduler's back
            bound = [
                p for p in client.list_pods()[0] if p.spec.node_name
            ]
            if bound:
                victim = rng.choice(bound)
                client.delete_pod(
                    victim.metadata.namespace, victim.metadata.name
                )
        if k == 5:
            # membership churn: a cold node claims a headroom slot --
            # on the mesh this must ride the shard-local slot scatter,
            # never a full re-upload
            client.create_node(
                make_node("dm-cold")
                .capacity(cpu="64", memory="128Gi", pods=200)
                .obj()
            )
            deadline = time.time() + 10
            while time.time() < deadline:
                if "dm-cold" in sched.cache._nodes:
                    break
                time.sleep(0.02)
            uploads_after_cold = sched.state_uploads
        deadline = time.time() + 30
        while time.time() < deadline:
            if sched.schedule_batch(timeout=0.2):
                break
    monkeypatch.setattr(client, "bind_assumed_bulk", orig_bulk)
    for _ in range(10):
        sched.schedule_batch(timeout=0.1)
    sched.wait_for_inflight_binds(timeout=60)

    # one quiet batch reconciles any leftover external change
    client.create_pod(
        make_pod("dm-final").container(cpu="100m", memory="64Mi").obj()
    )
    deadline = time.time() + 30
    while time.time() < deadline:
        if sched.schedule_batch(timeout=0.2):
            break
    sched.wait_for_inflight_binds(timeout=60)

    ds = sched._dev
    assert ds.req_dev is not None, "sharded carry was dropped"
    # the resident state actually lives sharded over the node axis
    shard_rows = ds.req_dev.addressable_shards[0].data.shape[0]
    assert shard_rows * 2 == ds.req_dev.shape[0], (
        "resident req_state is not sharded over the 2-device mesh"
    )
    assert (
        ds.alloc_dev.addressable_shards[0].data.shape[0] * 2
        == ds.alloc_dev.shape[0]
    )

    # membership churn rode the slot scatter: no additional full upload
    # after the one the cold node observed
    assert uploads_after_cold is not None
    assert sched.state_uploads == uploads_after_cold, (
        "the cold node's slot claim forced a full upload on the mesh"
    )
    assert sched.membership_row_patches >= 1

    # the differential: device carry == fresh full pack, per name
    dev_req = np.asarray(ds.req_dev)
    dev_nzr = np.asarray(ds.nzr_dev)
    names = sched.tensor_cache._names
    snap2 = Snapshot()
    sched.cache.update_snapshot(snap2)
    fresh = NodeTensorCache(
        sched.tensor_cache.dims, sched.tensor_cache.topology
    ).update(snap2)
    assert sorted(n for n in names if n) == sorted(fresh.names)
    for name in names:
        if not name:
            continue
        i = names.index(name)
        j = fresh.row(name)
        assert np.array_equal(dev_req[i], fresh.requested[j]), (
            f"sharded req_state row for {name} diverged from the full "
            f"pack: {dev_req[i]} != {fresh.requested[j]}"
        )
        assert np.array_equal(
            dev_nzr[i], fresh.non_zero_requested[j]
        ), f"sharded nzr_state row for {name} diverged"

    # the stream drove the interesting paths -- on the PALLAS mesh
    # tier (the differential's scatters, membership patches, and
    # divergence repairs must all compose with the shard_map'd solver)
    assert calls["n"] >= 3
    assert sched.pods_fallback == 0
    assert sched.mesh_solver_tier == "pallas", (
        f"differential ran on tier {sched.mesh_solver_tier!r}"
    )
    sched.stop()
    informers.stop()


def test_mask_rows_shard_threshold_split_parity(monkeypatch):
    """The [U, N] mask rows ship as their own column-sharded bool
    operand only ABOVE ``MESH_MASK_SHARD_MIN_BYTES`` (below it, a
    second device_put operand's link round trip costs more than the
    bytes save and the rows stay in the replicated buffer). Both forms
    must solve identically on both mesh tiers -- the cutoff is a pure
    link-cost decision, never a semantic one."""
    import kubernetes_tpu.ops.assignment as assignment
    from kubernetes_tpu.ops.assignment import solve_packed
    from kubernetes_tpu.ops.host_masks import mask_rows_upload

    mesh = _mesh(2)
    n, r, b, u = 256, 4, 64, 8
    rng = np.random.default_rng(3)
    alloc = np.zeros((n, r), dtype=np.int32)
    alloc[:, 0] = rng.choice([4000, 8000], n)
    alloc[:, 1] = rng.choice([8, 16], n) * 1024 * 1024
    alloc[:, 3] = 110
    pod_req = np.zeros((b, r), dtype=np.int32)
    pod_req[:, 0] = rng.choice([100, 250, 500], b)
    pod_req[:, 1] = rng.choice([128, 256], b) * 1024
    pod_req[:, 3] = 1
    rows = rng.random((u, n)) > 0.2
    pieces = lambda: [  # noqa: E731 - rebuilt per call (device_put consumes)
        ("req", pod_req),
        ("nzr", pod_req[:, :2].copy()),
        ("midx", rng.integers(0, u, b).astype(np.int32)),
        ("active", np.ones(b, dtype=np.int32)),
        ("rows", mask_rows_upload(rows, mesh)),
        ("alloc", alloc),
        ("valid", np.ones(n, dtype=np.int32)),
        ("req_state", np.zeros((n, r), dtype=np.int32)),
        ("nzr_state", np.zeros((n, 2), dtype=np.int32)),
    ]
    rng = np.random.default_rng(3)  # same midx stream per variant
    results = {}
    for cutoff, tier in ((0, True), (0, False), (1 << 30, True)):
        rng = np.random.default_rng(3)
        monkeypatch.setattr(
            assignment, "MESH_MASK_SHARD_MIN_BYTES", cutoff
        )
        out = solve_packed(
            pieces(), None, None, None, None,
            allow_pallas=tier, mesh=mesh,
        )
        results[(cutoff, tier)] = np.asarray(out[0])
    # cutoff 0 => rows forced onto the sharded operand; 1<<30 => rows
    # forced into the buffer: identical placements either way, on
    # either tier
    assert np.array_equal(results[(0, True)], results[(0, False)])
    assert np.array_equal(results[(0, True)], results[(1 << 30, True)])


def test_mesh_pallas_fault_falls_back_to_xla_twin():
    """Breaker e2e for the mesh ladder [pallas-shard_map, xla]: an
    injected device fault on the Pallas attempt routes the SAME
    dispatch to the GSPMD XLA twin (batch completes, nothing falls to
    the sequential path), a forced-open pallas breaker keeps routing
    every later batch to the twin, and the carry ledger stays intact
    through both -- still one cold upload, zero divergences, oracle
    placement parity."""
    from kubernetes_tpu.robustness.faults import (
        FaultInjector,
        FaultPoint,
        FaultProfile,
        PointConfig,
        install_injector,
    )
    from kubernetes_tpu.robustness.ladder import TIER_PALLAS, TIER_XLA

    from kubernetes_tpu.utils import metrics

    mesh = _mesh(2)
    rng = random.Random(7)
    server = APIServer()
    client = Client(server)
    informers = InformerFactory(server)
    sched = new_scheduler(
        client, informers, batch=True, max_batch=64, mesh=mesh,
        rng=_KeepFirstRng(),
    )
    oracle_server = APIServer()
    oracle_client = Client(oracle_server)
    oracle_informers = InformerFactory(oracle_server)
    oracle = new_scheduler(
        oracle_client, oracle_informers, batch=False, rng=_KeepFirstRng(),
    )
    for i in range(8):
        client.create_node(
            make_node(f"bf-n{i}")
            .capacity(cpu="64", memory="128Gi", pods=200).obj()
        )
        oracle_client.create_node(
            make_node(f"bf-n{i}")
            .capacity(cpu="64", memory="128Gi", pods=200).obj()
        )
    informers.start()
    informers.wait_for_cache_sync()
    sched.queue.run()
    oracle_informers.start()
    oracle_informers.wait_for_cache_sync()
    oracle.queue.run()
    sched.start()
    oracle.start()
    total = 0

    def burst(tag, n):
        nonlocal total
        for i in range(n):
            spec = (
                make_pod(f"bf-{tag}-{i}")
                .creation_timestamp(float(total + i))
                .container(
                    cpu=f"{rng.choice([100, 250, 500])}m",
                    memory="128Mi",
                )
            )
            client.create_pod(spec.obj())
            oracle_client.create_pod(spec.obj())
        total += n
        _wait_all_bound(client, total)
        _wait_all_bound(oracle_client, total)
        sched.wait_for_inflight_binds(timeout=60)

    charged_key = dict(tier=TIER_XLA, reason=f"{TIER_PALLAS}_error")
    charged_before = metrics.solver_fallbacks.value(**charged_key)
    try:
        # phase 1: one injected fault BURST sized to exhaust the pallas
        # tier's in-place retries (ladder retry policy) -- the first
        # batch's pallas attempt must step down to the XLA twin inside
        # the SAME dispatch, with the failure charged to the pallas
        # breaker; the injector then heals, so later batches solve on
        # pallas again
        max_fires = sched.ladder.config.retry.max_attempts
        install_injector(FaultInjector(FaultProfile(
            name="mesh-pallas-fault", seed=0,
            points={FaultPoint.DEVICE_SOLVE: PointConfig(
                rate=1.0, max_fires=max_fires
            )},
        )))
        burst("p1", 100)
        by_tier = dict(sched.ladder.solves_by_tier)
        assert by_tier.get(TIER_XLA, 0) >= 1, (
            f"the faulted batch did not land on the XLA twin: {by_tier}"
        )
        assert by_tier.get(TIER_PALLAS, 0) >= 1, (
            f"the healed injector never let pallas solve again: {by_tier}"
        )
        assert sched.pods_fallback == 0, (
            "a pallas fault fell through to the sequential path "
            "instead of the XLA twin"
        )
        assert metrics.solver_fallbacks.value(**charged_key) > (
            charged_before
        ), "the fault was not charged to the pallas tier"

        # phase 2: pallas breaker OPEN -- batches route straight to the
        # twin while it cools off, nothing sequential
        install_injector(None)
        sched.ladder.breakers[TIER_PALLAS].force_open()
        assert not sched.ladder.breakers[TIER_PALLAS].allow()
        xla_before = sched.ladder.solves_by_tier.get(TIER_XLA, 0)
        burst("p2", 100)
        assert sched.ladder.solves_by_tier.get(TIER_XLA, 0) > xla_before
        assert sched.pods_fallback == 0

        # the carry ledger survived the faults: one cold upload total,
        # zero divergences, and placement parity with the sequential
        # oracle held across the tier hops
        assert sched.state_uploads <= 1
        assert sched.carry_divergences == 0
        got = {
            p.metadata.name: p.spec.node_name
            for p in client.list_pods()[0]
        }
        want = {
            p.metadata.name: p.spec.node_name
            for p in oracle_client.list_pods()[0]
        }
        assert all(want.values()), "oracle failed to place a fitting pod"
        assert got == want
    finally:
        install_injector(None)
        sched.stop()
        informers.stop()
        oracle.stop()
        oracle_informers.stop()
