"""Tier-1 guard for the SHARDED mesh delta path (PR 9): the mesh
dispatch rides the same device-resident-carry + generation-handshake +
per-shard delta-scatter machinery as the single-device path.

- a steady 1k-pod burst on a simulated 2-device mesh performs AT MOST
  one full [N, R] node-state upload (``state_uploads`` must not scale
  with batch count), with zero handshake divergences, and places every
  pod IDENTICALLY to the sequential oracle;
- the randomized event-stream differential (interleaved membership
  churn, external pod churn, bind failures) extends to the sharded
  carry: after the stream settles, the device-resident ``req_state``
  must equal a fresh full pack of the host snapshot per node name, and
  the resident arrays must actually live sharded over the node axis.

Tests run on the virtual 8-device CPU mesh from conftest; a 2-device
sub-mesh keeps the GSPMD compiles cheap while still exercising real
cross-shard argmax collectives and shard-local scatters.
"""

import random
import time

import numpy as np
import pytest

import jax

from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client.client import Client
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.scheduler.scheduler import new_scheduler
from kubernetes_tpu.testing import make_node, make_pod

NUM_NODES = 16
NUM_PODS = 1000


def _mesh(n=2):
    from jax.sharding import Mesh

    devices = jax.devices()
    if len(devices) < n:
        pytest.skip(f"need {n} devices, have {len(devices)}")
    return Mesh(np.array(devices[:n]), axis_names=("nodes",))


class _KeepFirstRng:
    """Deterministic tie-break for the sequential oracle (selectHost
    reservoir sampling): always keep the first candidate == the device
    argmax's lowest-index rule."""

    def randrange(self, n):
        return 1 if n > 1 else 0

    def randint(self, a, b):
        return b


def _wait_all_bound(client, count, timeout=180.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        pods, _ = client.list_pods()
        bound = [p for p in pods if p.spec.node_name]
        if len(bound) >= count:
            return pods
        time.sleep(0.05)
    bound = [p for p in client.list_pods()[0] if p.spec.node_name]
    raise AssertionError(f"only {len(bound)}/{count} pods bound")


def _run(seed, *, mesh):
    rng = random.Random(seed)
    server = APIServer()
    client = Client(server)
    informers = InformerFactory(server)
    sched = new_scheduler(
        client, informers, batch=mesh is not None, max_batch=256,
        mesh=mesh, rng=_KeepFirstRng(),
    )
    for i in range(NUM_NODES):
        client.create_node(
            make_node(f"m{i}")
            .capacity(cpu="64", memory="256Gi", pods=120)
            .obj()
        )
    pods = []
    for i in range(NUM_PODS):
        pods.append(
            make_pod(f"b{i}")
            .creation_timestamp(float(i))
            .container(
                cpu=f"{rng.choice([100, 200, 250])}m",
                memory=f"{rng.choice([128, 256])}Mi",
            )
            .obj()
        )
    informers.start()
    informers.wait_for_cache_sync()
    sched.queue.run()
    for p in pods:
        client.create_pod(p)
    sched.start()
    _wait_all_bound(client, NUM_PODS)
    sched.wait_for_inflight_binds()
    placements = {
        p.metadata.name: p.spec.node_name
        for p in client.list_pods()[0]
    }
    sched.stop()
    informers.stop()
    return placements, sched


def test_mesh_steady_burst_uploads_bounded_and_oracle_parity():
    mesh = _mesh(2)
    want, _oracle = _run(42, mesh=None)
    got, sched = _run(42, mesh=mesh)

    assert sched.mesh_delta, "mesh delta path is off"
    # zero placement divergence vs the sequential oracle
    assert all(want.values()), "oracle failed to place a fitting pod"
    assert got == want

    # the whole burst rode the sharded device path
    assert sched.pods_fallback == 0
    assert sched.pods_solved_on_device == NUM_PODS
    assert sched.batches_solved >= 2, (
        "burst completed in one batch; the guard needs a multi-batch "
        "steady state to prove anything"
    )

    # THE guard: full [N, R] uploads do not scale with batch count on
    # the mesh either -- one cold upload, then pure per-shard reuse
    assert sched.state_uploads <= 1, (
        f"{sched.state_uploads} full node-state uploads for "
        f"{sched.batches_solved} mesh batches -- the sharded carry is "
        f"not resident"
    )
    assert sched.state_reuses >= sched.batches_solved - 1
    assert sched.carry_divergences == 0
    # steady-state link traffic is bounded by churn (zero churn here)
    assert sched.delta_rows_uploaded == 0


def test_mesh_event_stream_differential_sharded_carry(monkeypatch):
    """The PR-5 randomized event-stream differential extended to the
    SHARDED carry: interleaved pod bursts, external pod deletes, a bind
    failure, and membership churn (a cold node joining mid-stream) on a
    2-device mesh must leave the device-resident ``req_state`` equal to
    a fresh full pack of the settled host snapshot -- per node name,
    across both shards -- with membership riding the slot scatter (no
    extra full upload) and every resident array actually node-sharded.
    """
    from kubernetes_tpu.cache.snapshot import Snapshot
    from kubernetes_tpu.tensors import NodeTensorCache

    mesh = _mesh(2)
    rng = random.Random(20260803)
    server = APIServer()
    client = Client(server)
    informers = InformerFactory(server)
    sched = new_scheduler(
        client, informers, batch=True, max_batch=32, mesh=mesh,
    )
    for i in range(8):
        client.create_node(
            make_node(f"dm-n{i}")
            .capacity(cpu="64", memory="128Gi", pods=200)
            .obj()
        )
    informers.start()
    informers.wait_for_cache_sync()
    sched.queue.run()

    # one injected bind failure: the host diverges from the mirrored
    # expectation (the scatter-fix / counted-divergence case)
    orig_bulk = client.bind_assumed_bulk
    calls = {"n": 0}

    def flaky_bulk(assumed):
        calls["n"] += 1
        if calls["n"] == 3 and assumed:
            errs = orig_bulk(assumed[1:])
            return [(0, RuntimeError("synthetic bind failure"))] + [
                (i + 1, e) for i, e in errs
            ]
        return orig_bulk(assumed)

    monkeypatch.setattr(client, "bind_assumed_bulk", flaky_bulk)

    seq = 0
    uploads_after_cold = None
    for k in range(8):
        for _ in range(rng.randint(3, 8)):
            seq += 1
            client.create_pod(
                make_pod(f"dm-p{seq}")
                .container(
                    cpu=f"{rng.choice([100, 250, 500])}m",
                    memory="128Mi",
                )
                .obj()
            )
        if k == 3:
            # external churn: a controller deletes a bound pod behind
            # the scheduler's back
            bound = [
                p for p in client.list_pods()[0] if p.spec.node_name
            ]
            if bound:
                victim = rng.choice(bound)
                client.delete_pod(
                    victim.metadata.namespace, victim.metadata.name
                )
        if k == 5:
            # membership churn: a cold node claims a headroom slot --
            # on the mesh this must ride the shard-local slot scatter,
            # never a full re-upload
            client.create_node(
                make_node("dm-cold")
                .capacity(cpu="64", memory="128Gi", pods=200)
                .obj()
            )
            deadline = time.time() + 10
            while time.time() < deadline:
                if "dm-cold" in sched.cache._nodes:
                    break
                time.sleep(0.02)
            uploads_after_cold = sched.state_uploads
        deadline = time.time() + 30
        while time.time() < deadline:
            if sched.schedule_batch(timeout=0.2):
                break
    monkeypatch.setattr(client, "bind_assumed_bulk", orig_bulk)
    for _ in range(10):
        sched.schedule_batch(timeout=0.1)
    sched.wait_for_inflight_binds(timeout=60)

    # one quiet batch reconciles any leftover external change
    client.create_pod(
        make_pod("dm-final").container(cpu="100m", memory="64Mi").obj()
    )
    deadline = time.time() + 30
    while time.time() < deadline:
        if sched.schedule_batch(timeout=0.2):
            break
    sched.wait_for_inflight_binds(timeout=60)

    ds = sched._dev
    assert ds.req_dev is not None, "sharded carry was dropped"
    # the resident state actually lives sharded over the node axis
    shard_rows = ds.req_dev.addressable_shards[0].data.shape[0]
    assert shard_rows * 2 == ds.req_dev.shape[0], (
        "resident req_state is not sharded over the 2-device mesh"
    )
    assert (
        ds.alloc_dev.addressable_shards[0].data.shape[0] * 2
        == ds.alloc_dev.shape[0]
    )

    # membership churn rode the slot scatter: no additional full upload
    # after the one the cold node observed
    assert uploads_after_cold is not None
    assert sched.state_uploads == uploads_after_cold, (
        "the cold node's slot claim forced a full upload on the mesh"
    )
    assert sched.membership_row_patches >= 1

    # the differential: device carry == fresh full pack, per name
    dev_req = np.asarray(ds.req_dev)
    dev_nzr = np.asarray(ds.nzr_dev)
    names = sched.tensor_cache._names
    snap2 = Snapshot()
    sched.cache.update_snapshot(snap2)
    fresh = NodeTensorCache(
        sched.tensor_cache.dims, sched.tensor_cache.topology
    ).update(snap2)
    assert sorted(n for n in names if n) == sorted(fresh.names)
    for name in names:
        if not name:
            continue
        i = names.index(name)
        j = fresh.row(name)
        assert np.array_equal(dev_req[i], fresh.requested[j]), (
            f"sharded req_state row for {name} diverged from the full "
            f"pack: {dev_req[i]} != {fresh.requested[j]}"
        )
        assert np.array_equal(
            dev_nzr[i], fresh.non_zero_requested[j]
        ), f"sharded nzr_state row for {name} diverged"

    # the stream drove the interesting paths
    assert calls["n"] >= 3
    assert sched.pods_fallback == 0
    sched.stop()
    informers.stop()
