"""Tier-1 perf guard (fast smoke): the device path must carry a basic
burst AND a CSI-PV burst with ZERO host fallbacks, so a host-path cliff
(the 54 pods/s SchedulingCSIPVs regression shape) fails CI loudly
instead of silently degrading BENCHMARKS.json."""

import time

import pytest

from kubernetes_tpu.api.types import (
    CSINode,
    CSINodeDriver,
    ObjectMeta,
    PersistentVolume,
    PersistentVolumeClaim,
)
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client.client import Client
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.scheduler.scheduler import new_scheduler
from kubernetes_tpu.testing import make_node, make_pod


@pytest.fixture
def stack():
    server = APIServer()
    client = Client(server)
    informers = InformerFactory(server)
    sched = new_scheduler(client, informers, batch=True, max_batch=32)
    yield server, client, informers, sched
    sched.stop()
    informers.stop()


def _wait_all_bound(client, count, timeout=60.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        pods, _ = client.list_pods()
        bound = [p for p in pods if p.spec.node_name]
        if len(bound) >= count:
            return pods
        time.sleep(0.05)
    raise AssertionError(
        f"only {len([p for p in client.list_pods()[0] if p.spec.node_name])}"
        f"/{count} pods bound"
    )


def test_basic_workload_zero_fallback(stack):
    server, client, informers, sched = stack
    for i in range(6):
        client.create_node(
            make_node(f"n{i}").capacity(cpu="16", memory="32Gi").obj()
        )
    informers.start()
    informers.wait_for_cache_sync()
    sched.queue.run()
    for i in range(24):
        client.create_pod(
            make_pod(f"p{i}").container(cpu="250m", memory="256Mi").obj()
        )
    sched.start()
    _wait_all_bound(client, 24)
    sched.wait_for_inflight_binds()
    assert sched.pods_fallback == 0
    assert sched.pods_solved_on_device >= 24


def test_csi_pv_workload_zero_fallback(stack):
    """The acceptance shape of the volume-count columns: every pod
    carries a bound CSI PV, the nodes advertise CSINode attach limits,
    and the whole burst rides the device path end to end."""
    server, client, informers, sched = stack
    for i in range(6):
        client.create_node(
            make_node(f"n{i}").capacity(cpu="16", memory="32Gi").obj()
        )
        server.create(
            CSINode(
                metadata=ObjectMeta(name=f"n{i}", namespace=""),
                drivers=[
                    CSINodeDriver(
                        name="ebs.csi.aws.com", node_id=f"n{i}",
                        allocatable_count=8,
                    )
                ],
            )
        )
    for i in range(24):
        cn, vn = f"pvc-{i}", f"pv-{i}"
        server.create(
            PersistentVolumeClaim(
                metadata=ObjectMeta(name=cn, namespace="default"),
                volume_name=vn,
                requested_bytes=1 << 30,
            )
        )
        server.create(
            PersistentVolume(
                metadata=ObjectMeta(name=vn, namespace=""),
                capacity_bytes=1 << 30,
                claim_ref_namespace="default",
                claim_ref_name=cn,
                csi_driver="ebs.csi.aws.com",
                csi_volume_handle=vn,
            )
        )
    informers.start()
    informers.wait_for_cache_sync()
    sched.queue.run()
    for i in range(24):
        client.create_pod(
            make_pod(f"p{i}")
            .container(cpu="250m", memory="256Mi")
            .pvc(f"pvc-{i}")
            .obj()
        )
    sched.start()
    _wait_all_bound(client, 24)
    sched.wait_for_inflight_binds()
    assert sched.pods_fallback == 0, (
        "CSI-PV pods fell off the device path"
    )
    assert sched.volume_reject_retries == 0
    assert sched.pods_solved_on_device >= 24
    # attach limits respected AND accounted in the cache
    per_node = {}
    for name, ni in sched.cache._nodes.items():
        used = ni.volume_in_use.get(
            "attachable-volumes-csi-ebs.csi.aws.com", 0
        )
        per_node[name] = used
        assert used <= 8
    assert sum(per_node.values()) == 24
