"""Flight-recorder tracing plane (ISSUE 13): the batch span spine, the
chaos-reconstruction contract (the dump alone explains what happened,
no log parsing), Chrome-trace export, the P-squared live quantile
sketch, the jit-cache watchdog, and the <1% always-on overhead guard.
"""

import json
import threading
import time

import numpy as np
import pytest

from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client.client import Client
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.robustness.circuit import RetryPolicy
from kubernetes_tpu.robustness.faults import (
    FaultInjector,
    FaultPoint,
    install_injector,
    load_profile,
)
from kubernetes_tpu.robustness.ladder import (
    RobustnessConfig,
    TIER_HOST_GREEDY,
    TIER_PALLAS,
    TIER_XLA,
)
from kubernetes_tpu.robustness.lifecycle import ClusterLifecycleDriver
from kubernetes_tpu.scheduler.scheduler import new_scheduler
from kubernetes_tpu.testing import make_node, make_pod
from kubernetes_tpu.utils import flightrecorder, metrics
from kubernetes_tpu.utils.quantiles import P2Quantile, QuantileSet

DEVICE_TIERS = (TIER_PALLAS, TIER_XLA, TIER_HOST_GREEDY)


@pytest.fixture(autouse=True)
def _clean():
    flightrecorder.RECORDER.reset()
    yield
    install_injector(None)
    flightrecorder.stop_trace()
    flightrecorder.ENABLED = True


def _mk_cluster(num_nodes=48, max_batch=128, retry_attempts=1):
    server = APIServer()
    client = Client(server)
    informers = InformerFactory(server)
    sched = new_scheduler(
        client, informers, batch=True, max_batch=max_batch,
        robustness_config=RobustnessConfig(
            solve_timeout_seconds=5.0,
            failure_threshold=2,
            cooloff_seconds=0.3,
            probe_batches=1,
            # one attempt per tier: every injected solve fault becomes a
            # breaker-routed fallback instead of being absorbed by the
            # in-place retry, so the reconstruction claim is non-vacuous
            retry=RetryPolicy(
                max_attempts=retry_attempts, backoff_seconds=0.01,
                max_backoff_seconds=0.05,
            ),
        ),
    )
    for i in range(num_nodes):
        client.create_node(
            make_node(f"node-{i}")
            .capacity(cpu="32", memory="64Gi", pods=110)
            .obj()
        )
    informers.start()
    informers.wait_for_cache_sync()
    sched.queue.run()
    return server, client, informers, sched


def _wait_all_bound(client, timeout):
    deadline = time.time() + timeout
    while time.time() < deadline:
        pods, _ = client.list_pods()
        if pods and all(p.spec.node_name for p in pods):
            return True
        time.sleep(0.1)
    return False


# -- P-squared sketch ----------------------------------------------------

class TestP2Quantile:
    def test_rejects_degenerate_quantiles(self):
        with pytest.raises(ValueError):
            P2Quantile(0.0)
        with pytest.raises(ValueError):
            P2Quantile(1.0)

    def test_small_stream_is_exact(self):
        est = P2Quantile(0.5)
        for x in (3.0, 1.0, 2.0):
            est.observe(x)
        assert est.value() == 2.0

    @pytest.mark.parametrize("q", [0.5, 0.99])
    @pytest.mark.parametrize("dist", ["uniform", "lognormal"])
    def test_tracks_numpy_percentile(self, q, dist):
        rng = np.random.default_rng(42)
        if dist == "uniform":
            xs = rng.uniform(0.0, 1.0, size=20_000)
        else:
            # the latency-like shape: heavy right tail
            xs = rng.lognormal(mean=-2.0, sigma=0.7, size=20_000)
        est = P2Quantile(q)
        for x in xs:
            est.observe(float(x))
        exact = float(np.quantile(xs, q))
        spread = float(np.quantile(xs, 0.999)) - float(np.min(xs))
        # within 5% of the full spread (P2's documented regime for
        # unimodal streams; typically far closer)
        assert abs(est.value() - exact) <= 0.05 * spread

    def test_quantile_set_threadsafe_and_resettable(self):
        qs = QuantileSet((0.5, 0.99))
        threads = [
            threading.Thread(
                target=lambda: qs.observe_many([0.1] * 1000)
            )
            for _ in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert qs.count == 4000
        assert qs.value(0.5) == pytest.approx(0.1)
        qs.reset()
        assert qs.count == 0
        assert qs.value(0.99) == 0.0


# -- recorder core -------------------------------------------------------

class TestFlightRecorder:
    def test_span_ring_bounded_and_ids_monotonic(self):
        rec = flightrecorder.FlightRecorder(
            span_capacity=4, mark_capacity=4
        )
        for i in range(10):
            span = rec.begin_batch(i, pods=[(f"u{i}", 0.01, 1)])
            span.stage("pack", 0.001)
            span.finish(tier="xla")
            rec.mark("fault", point=f"p{i}")
        d = rec.dump()
        assert len(d["spans"]) == 4
        assert len(d["marks"]) == 4
        assert [s["batch_id"] for s in d["spans"]] == [7, 8, 9, 10]
        # every surviving mark is the newest four
        assert [m["point"] for m in d["marks"]] == [
            "p6", "p7", "p8", "p9"
        ]

    def test_dump_is_json_serializable(self):
        rec = flightrecorder.FlightRecorder()
        span = rec.begin_batch(2, pods=[("u1", 0.5, 3), ("u2", 0.0, 1)])
        span.note(carry="reuse", delta_rows=7, custom_field="x")
        span.bump("placed", 2)
        span.finish(tier="xla")
        rec.mark("breaker", tier="xla", from_state="closed",
                 to_state="open")
        parsed = json.loads(rec.dump_json())
        s = parsed["spans"][0]
        assert s["tier"] == "xla"
        assert s["carry"] == "reuse"
        assert s["placed"] == 2
        assert s["extra"] == {"custom_field": "x"}
        assert s["pods"][0] == {
            "uid": "u1", "queue_wait_ms": 500.0, "attempts": 3
        }
        assert parsed["marks"][0]["kind"] == "breaker"

    def test_disabled_returns_null_span(self):
        flightrecorder.ENABLED = False
        span = flightrecorder.begin_batch(5, pods=[("u", 0, 1)])
        assert not span  # falsy NullSpan
        span.stage("pack", 0.1)
        span.note(tier="xla")
        span.finish()
        before = len(flightrecorder.RECORDER.dump()["marks"])
        flightrecorder.mark("fault", point="x")
        assert len(flightrecorder.RECORDER.dump()["marks"]) == before
        flightrecorder.ENABLED = True

    def test_dump_to_file(self, tmp_path, monkeypatch):
        monkeypatch.setattr(flightrecorder, "DUMP_DIR", str(tmp_path))
        rec = flightrecorder.FlightRecorder()
        rec.begin_batch(1, pods=[]).finish(tier="xla")
        path = rec.dump_to_file("unit")
        with open(path) as f:
            assert json.load(f)["spans"][0]["tier"] == "xla"


# -- chrome trace buffer -------------------------------------------------

class TestChromeTrace:
    def test_events_only_when_armed(self):
        flightrecorder.trace_span("pack", time.perf_counter(), 0.001)
        assert flightrecorder.stop_trace() == []
        flightrecorder.start_trace()
        t0 = time.perf_counter()
        flightrecorder.trace_span("pack", t0, 0.002)
        flightrecorder.trace_instant("autobatch_grow",
                                     args={"cap": 512})
        events = flightrecorder.stop_trace()
        kinds = [e["ph"] for e in events]
        # two metadata thread-name events + one X + one i
        assert kinds.count("X") == 1
        assert kinds.count("i") == 1
        x = next(e for e in events if e["ph"] == "X")
        assert x["name"] == "pack"
        assert x["dur"] == pytest.approx(2000.0)  # microseconds

    def test_export_is_valid_chrome_trace(self, tmp_path):
        flightrecorder.start_trace()
        t0 = time.perf_counter()
        flightrecorder.trace_span("device_solve", t0, 0.01,
                                  track="device")
        flightrecorder.trace_span("commit", t0 + 0.01, 0.002)
        flightrecorder.trace_instant("autobatch_shrink")
        out = tmp_path / "trace.json"
        n = flightrecorder.export_chrome_trace(str(out))
        with open(out) as f:
            doc = json.load(f)
        assert isinstance(doc["traceEvents"], list)
        assert len(doc["traceEvents"]) == n
        for ev in doc["traceEvents"]:
            assert "ph" in ev and "pid" in ev and "tid" in ev
            if ev["ph"] in ("X", "i"):
                assert "ts" in ev and "name" in ev
            if ev["ph"] == "X":
                assert ev["dur"] >= 0
        # the thread metadata names the device track
        meta = [
            e for e in doc["traceEvents"] if e["ph"] == "M"
        ]
        assert any(e["args"]["name"] == "device" for e in meta)
        # disarmed after export
        assert not flightrecorder.trace_active()


# -- the spine on a real burst -------------------------------------------

class TestBatchSpanSpine:
    def test_burst_produces_linked_spans(self):
        server, client, informers, sched = _mk_cluster(
            num_nodes=16, max_batch=64, retry_attempts=3
        )
        sched.start()
        names = [f"sp-{i}" for i in range(150)]
        for n in names:
            client.create_pod(
                make_pod(n).container(cpu="100m", memory="128Mi").obj()
            )
        assert _wait_all_bound(client, 60)
        sched.wait_for_inflight_binds()
        sched.stop()
        informers.stop()

        d = flightrecorder.RECORDER.dump()
        solved = [
            s for s in d["spans"]
            if s["tier"] in DEVICE_TIERS and s["routed"] is None
        ]
        assert solved, "no device-tier spans recorded"
        # per-batch record: size, pad shape, carry decision, stage
        # timings, commit outcome
        placed_total = 0
        for s in solved:
            assert s["size"] > 0
            assert s["padded"] >= s["size"]
            assert s["carry"] in ("reuse", "delta", "upload")
            assert "pack" in s["stages_ms"]
            assert "device_solve" in s["stages_ms"]
            assert "commit" in s["stages_ms"]
            assert s["t_end"] is not None
            placed_total += s["placed"]
        assert placed_total == len(names)
        # per-pod linkage: every created pod's uid joins to exactly one
        # solving batch (none were requeued in this clean burst)
        pods, _ = client.list_pods()
        uid_of = {p.metadata.name: p.metadata.uid for p in pods}
        seen = {}
        for s in solved:
            for link in s["pods"]:
                seen.setdefault(link["uid"], []).append(
                    (s["batch_id"], link["attempts"])
                )
        for n in names:
            assert uid_of[n] in seen, f"pod {n} not linked to a batch"
            assert seen[uid_of[n]][0][1] >= 1  # attempt count recorded
        # the first batch uploaded state; spans carry the decision
        assert any(s["carry"] == "upload" for s in solved)

    def test_jit_watch_counts_and_marks_recompiles(self, monkeypatch):
        from kubernetes_tpu.scheduler import batch as batch_mod

        sizes = {"solve_packed": 3}
        monkeypatch.setattr(
            "kubernetes_tpu.ops.assignment.jit_cache_sizes",
            lambda mesh=None: dict(sizes),
        )
        w = batch_mod._JitCacheWatch()
        before = metrics.jit_compiles.value(signature="solve_packed")
        w.refresh()  # warmup-era growth: counted, not marked
        assert (
            metrics.jit_compiles.value(signature="solve_packed")
            == before + 3
        )
        marks0 = [
            m for m in flightrecorder.RECORDER.dump()["marks"]
            if m["kind"] == "jit_recompile"
        ]
        assert not marks0
        w.seal()
        sizes["solve_packed"] = 5  # a mid-run recompile
        w.refresh()
        assert (
            metrics.jit_compiles.value(signature="solve_packed")
            == before + 5
        )
        marks = [
            m for m in flightrecorder.RECORDER.dump()["marks"]
            if m["kind"] == "jit_recompile"
        ]
        assert len(marks) == 1
        assert marks[0]["signature"] == "solve_packed"
        assert marks[0]["compiles"] == 2

    def test_live_quantile_gauges_track_bound_pods(self):
        metrics.pod_to_bind_sketch.reset()
        server, client, informers, sched = _mk_cluster(
            num_nodes=16, max_batch=64, retry_attempts=3
        )
        sched.start()
        for i in range(200):
            client.create_pod(
                make_pod(f"q-{i}").container(cpu="50m").obj()
            )
        assert _wait_all_bound(client, 60)
        sched.wait_for_inflight_binds()
        sched.stop()
        informers.stop()
        assert metrics.pod_to_bind_sketch.count == 200
        p50 = metrics.pod_to_bind_quantile.value(q="0.5")
        p99 = metrics.pod_to_bind_quantile.value(q="0.99")
        assert 0.0 < p50 <= p99 < 60.0
        # the gauges expose the sketch through the labeled-callback path
        lines = metrics.pod_to_bind_quantile.collect()
        assert any('q="0.99"' in ln for ln in lines if "#" not in ln)


# -- the acceptance e2e: chaos reconstruction from the dump alone --------

class TestChaosReconstruction:
    def test_lifecycle_chaos_reconstructs_from_dump(self):
        """Run the builtin lifecycle-chaos profile (hotter DEVICE_SOLVE
        sprinkle so breaker-routed fallbacks actually occur) with the
        lifecycle driver flapping nodes mid-burst, then reconstruct --
        from the flight-recorder dump ALONE, after a JSON round trip --
        every batch's solver tier, each breaker-routed fallback, and
        each fault point fired, asserted against the injector's own
        ledger and the ladder's tier counts. No log parsing."""
        server, client, informers, sched = _mk_cluster(
            num_nodes=32, max_batch=128, retry_attempts=1
        )
        # seed 3: the device_solve stream fires on its first three
        # draws, so even a small burst (few dispatches) sees faults
        profile = load_profile("lifecycle-chaos", seed=3)
        # hotter solver sprinkle: with 1 attempt/tier each fire IS a
        # breaker-routed fallback (fallback marks must be non-empty for
        # the reconstruction claim to mean anything)
        profile.points[FaultPoint.DEVICE_SOLVE].rate = 0.5
        profile.points[FaultPoint.DEVICE_SOLVE].max_fires = 6
        inj = FaultInjector(profile)
        install_injector(inj)

        fallbacks_before = dict(metrics.solver_fallbacks._values)
        tiers_before = dict(sched.ladder.solves_by_tier)

        drv = ClusterLifecycleDriver(
            client, injector=inj, tick_interval=0.1,
            flap_down_seconds=0.4, storm_fraction=0.1,
            storm_down_seconds=0.8,
        )
        sched.start()
        drv.start()
        names = [f"lc-{i}" for i in range(300)]
        try:
            for n in names:
                client.create_pod(
                    make_pod(n).container(cpu="250m", memory="256Mi")
                    .obj()
                )
            assert _wait_all_bound(client, 120), "burst did not bind"
        finally:
            drv.stop()
        assert _wait_all_bound(client, 60)
        sched.wait_for_inflight_binds()
        sched.stop()
        informers.stop()

        # the dump, through a JSON round trip: everything below reads
        # ONLY this document (plus the ledgers it is checked against)
        d = json.loads(flightrecorder.RECORDER.dump_json())

        # (1) every batch's solver tier: span counts per device tier
        # equal the ladder's own tally (delta over this test). A span
        # keeps its tier even when a LATER stage failed (garbage
        # download, recovery) -- the ladder counted that solve too, so
        # the join keys on tier alone.
        span_tiers = {}
        for s in d["spans"]:
            if s["tier"] in DEVICE_TIERS:
                span_tiers[s["tier"]] = span_tiers.get(s["tier"], 0) + 1
        for tier in DEVICE_TIERS:
            expect = (
                sched.ladder.solves_by_tier.get(tier, 0)
                - tiers_before.get(tier, 0)
            )
            assert span_tiers.get(tier, 0) == expect, (
                f"tier {tier}: {span_tiers.get(tier, 0)} spans vs "
                f"{expect} ladder solves"
            )
        assert sum(span_tiers.values()) > 0

        # (2) each breaker-routed fallback: marks per (tier, reason)
        # equal the metric delta
        fb_marks = {}
        for m in d["marks"]:
            if m["kind"] == "fallback":
                key = (m["tier"], m["reason"])
                fb_marks[key] = fb_marks.get(key, 0) + 1
        assert fb_marks, "chaos produced no fallbacks; tune the profile"
        seen_keys = set(fb_marks)
        for key, count in metrics.solver_fallbacks._values.items():
            labels = dict(key)
            k = (labels["tier"], labels["reason"])
            delta = count - fallbacks_before.get(key, 0.0)
            if delta:
                seen_keys.add(k)
        for k in seen_keys:
            key = (("reason", k[1]), ("tier", k[0]))
            delta = (
                metrics.solver_fallbacks._values.get(key, 0.0)
                - fallbacks_before.get(key, 0.0)
            )
            assert fb_marks.get(k, 0) == delta, (
                f"fallback {k}: {fb_marks.get(k, 0)} marks vs "
                f"{delta} metric"
            )

        # (3) each fault point fired: marks per point equal the
        # injector's OWN ledger, for every point
        fault_marks = {}
        for m in d["marks"]:
            if m["kind"] == "fault":
                fault_marks[m["point"]] = (
                    fault_marks.get(m["point"], 0) + 1
                )
        for point in FaultPoint.ALL:
            assert fault_marks.get(point, 0) == inj.fired_count(point), (
                f"fault {point}: {fault_marks.get(point, 0)} marks vs "
                f"ledger {inj.fired_count(point)}"
            )
        assert fault_marks.get(FaultPoint.DEVICE_SOLVE, 0) > 0
        assert fault_marks.get(FaultPoint.NODE_FLAP, 0) > 0

        # and the chaos is attributable per batch: some span carries a
        # non-reuse carry decision (flaps forced membership patches or
        # uploads), and commit outcomes account every pod disposition
        assert any(s["carry"] != "reuse" for s in d["spans"] if s["carry"])


# -- the tier-1 overhead guard -------------------------------------------

class TestTraceOverheadGuard:
    def test_always_on_spine_under_one_percent(self):
        """Deterministic self-time bound: the recorder ops a real
        1k-pod burst performs, costed at the measured per-op rate, must
        stay under 1% of the burst's pop+pack+solve+download+commit
        wall clock. (The microbench's wall-clock A/B rides in
        tools/bench_hotpath.py bench_trace_overhead; on a loaded 2-core
        box its noise floor is above a 1% effect, so the guard asserts
        the self-time share, which is stable.)"""
        from tools.bench_hotpath import _time_mark_ops, _time_span_ops

        HOT = ("pop_batch", "pack", "device_solve", "download", "commit")
        server, client, informers, sched = _mk_cluster(
            num_nodes=64, max_batch=256, retry_attempts=3
        )
        spans_before = flightrecorder.RECORDER._next_id
        marks_before = len(flightrecorder.RECORDER.dump()["marks"])
        stage_before = dict(sched.stage_seconds)
        sched.start()
        for i in range(1000):
            client.create_pod(
                make_pod(f"ov-{i}").container(cpu="10m", memory="16Mi")
                .obj()
            )
        assert _wait_all_bound(client, 120)
        sched.wait_for_inflight_binds()
        sched.stop()
        informers.stop()
        after = sched.stage_seconds
        hot_s = sum(
            after.get(k, 0.0) - stage_before.get(k, 0.0) for k in HOT
        )
        n_spans = flightrecorder.RECORDER._next_id - spans_before
        n_marks = (
            len(flightrecorder.RECORDER.dump()["marks"]) - marks_before
        )
        assert n_spans > 0 and hot_s > 0

        rec = flightrecorder.FlightRecorder()
        links = [(f"uid-{i}", 0.001, 1) for i in range(256)]
        span_us = min(
            _time_span_ops(rec, links, HOT, 1000) for _ in range(3)
        )
        mark_us = min(_time_mark_ops(rec, 5000) for _ in range(3))
        self_s = (
            n_spans * span_us + max(n_marks, 0) * mark_us
        ) / 1e6
        share = self_s / hot_s
        assert share < 0.01, (
            f"spine self-time {self_s * 1e3:.2f}ms is "
            f"{share * 100:.2f}% of {hot_s * 1e3:.0f}ms hot path"
        )
