"""Native label matcher (kubernetes_tpu/native/_hotpath.c) vs the
pure-Python reference implementation: randomized differential fuzzing.

The native module is the SURVEY section 2.4 host data plane; semantics
must be bit-identical to api/selectors.py's Python path.
"""

import random

import pytest

from kubernetes_tpu.api.selectors import (
    compile_selector,
    label_selector_as_dict_matches,
    labels_match_mask,
    labels_match_selector,
    labels_match_selector_py,
)
from kubernetes_tpu.api.types import LabelSelector, LabelSelectorRequirement
from kubernetes_tpu.native import hotpath

KEYS = ["app", "tier", "zone", "color", ""]
VALUES = ["a", "b", "c", "", "x" * 64]
OPS = ["In", "NotIn", "Exists", "DoesNotExist"]


def _random_labels(rng):
    return {
        rng.choice(KEYS): rng.choice(VALUES)
        for _ in range(rng.randrange(0, 4))
    }


def _random_selector(rng):
    return LabelSelector(
        match_labels=_random_labels(rng),
        match_expressions=[
            LabelSelectorRequirement(
                key=rng.choice(KEYS),
                operator=rng.choice(OPS),
                values=[rng.choice(VALUES) for _ in range(rng.randrange(0, 3))],
            )
            for _ in range(rng.randrange(0, 3))
        ],
    )


def test_native_module_built():
    assert hotpath is not None, "native matcher failed to build"


@pytest.mark.parametrize("seed", range(5))
def test_differential_match(seed):
    rng = random.Random(seed)
    for _ in range(2000):
        labels = _random_labels(rng)
        selector = _random_selector(rng)
        assert labels_match_selector(labels, selector) == (
            labels_match_selector_py(labels, selector)
        ), (labels, selector)


def test_match_mask_agrees_with_scalar():
    rng = random.Random(7)
    selector = _random_selector(rng)
    labels_list = [_random_labels(rng) for _ in range(500)]
    mask = labels_match_mask(labels_list, selector)
    for labels, bit in zip(labels_list, mask):
        assert bool(bit) == labels_match_selector_py(labels, selector)


def test_dict_covers_semantics():
    assert not label_selector_as_dict_matches({}, {"a": "b"})  # empty: nothing
    assert label_selector_as_dict_matches({"a": "b"}, {"a": "b", "c": "d"})
    assert not label_selector_as_dict_matches({"a": "x"}, {"a": "b"})


def test_nil_selector_matches_nothing():
    assert not labels_match_selector({"a": "b"}, None)


def test_empty_selector_matches_everything():
    assert labels_match_selector({"a": "b"}, LabelSelector())
    assert labels_match_selector({}, LabelSelector())


def test_unknown_operator_raises():
    sel = LabelSelector(
        match_expressions=[
            LabelSelectorRequirement(key="a", operator="Bogus", values=[])
        ]
    )
    with pytest.raises(ValueError, match="unknown label selector operator"):
        labels_match_selector({"a": "b"}, sel)
    with pytest.raises(ValueError, match="unknown label selector operator"):
        labels_match_selector_py({"a": "b"}, sel)


def test_compile_cached_on_selector():
    sel = LabelSelector(match_labels={"a": "b"})
    assert compile_selector(sel) is compile_selector(sel)


def test_unknown_operator_not_reached_matches_python():
    """Short-circuit parity: an unknown operator behind a failing
    match_labels is never evaluated on either path."""
    sel = LabelSelector(
        match_labels={"a": "b"},
        match_expressions=[
            LabelSelectorRequirement(key="x", operator="Bogus", values=[])
        ],
    )
    assert labels_match_selector({"a": "x"}, sel) is False
    assert labels_match_selector_py({"a": "x"}, sel) is False
