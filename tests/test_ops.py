"""Differential tests: JAX masks/scores/assignment vs the sequential
oracle plugins on identical snapshots (SURVEY.md section 4 tier 5, the
strongest parity check)."""

import random

import numpy as np
import jax.numpy as jnp
import pytest

from kubernetes_tpu.cache.snapshot import new_snapshot
from kubernetes_tpu.framework.interface import CycleState
from kubernetes_tpu.ops import (
    GreedyConfig,
    balanced_allocation_score,
    fit_mask,
    greedy_assign,
    least_allocated_score,
)
from kubernetes_tpu.ops.assignment import NO_NODE
from kubernetes_tpu.plugins import noderesources
from kubernetes_tpu.scheduler.generic import SNAPSHOT_STATE_KEY
from kubernetes_tpu.tensors import NodeTensorCache, ResourceDims, pack_pod_batch
from kubernetes_tpu.testing import make_node, make_pod


def _random_cluster(rng, num_nodes=12, num_existing=20):
    nodes = [
        make_node(f"n{i}")
        .capacity(
            cpu=str(rng.choice([2, 4, 8, 16])),
            memory=f"{rng.choice([4, 8, 16, 32])}Gi",
            pods=rng.choice([5, 10, 20]),
        )
        .obj()
        for i in range(num_nodes)
    ]
    pods = [
        make_pod(f"e{i}")
        .node(f"n{rng.randrange(num_nodes)}")
        .container(
            cpu=f"{rng.choice([100, 250, 500, 1000])}m",
            memory=f"{rng.choice([128, 256, 512, 1024])}Mi",
        )
        .obj()
        for i in range(num_existing)
    ]
    return pods, nodes


def _pending(rng, count):
    out = []
    for i in range(count):
        p = (
            make_pod(f"p{i}")
            .creation_timestamp(float(i))
            .container(
                cpu=f"{rng.choice([100, 250, 500, 1000, 2000])}m",
                memory=f"{rng.choice([128, 256, 512, 1024, 2048])}Mi",
            )
            .obj()
        )
        p.spec.priority = rng.choice([0, 0, 0, 5, 10])
        out.append(p)
    return out


@pytest.fixture
def rng():
    return random.Random(42)


class TestFitMaskParity:
    def test_matches_sequential_fit(self, rng):
        existing, nodes = _random_cluster(rng)
        snap = new_snapshot(existing, nodes)
        nt = NodeTensorCache().update(snap)
        pending = _pending(rng, 15)
        batch = pack_pod_batch(pending, nt.dims)

        mask = np.asarray(
            fit_mask(
                jnp.asarray(nt.allocatable),
                jnp.asarray(nt.requested),
                jnp.asarray(batch.requests),
                jnp.asarray(nt.valid),
            )
        )

        plugin = noderesources.Fit()
        state = CycleState()
        for b, pod in enumerate(pending):
            plugin.pre_filter(state, pod)
            for ni in snap.list_node_infos():
                want = plugin.filter(state, pod, ni) is None
                got = bool(mask[b, nt.row(ni.node_name)])
                assert got == want, (pod.name, ni.node_name)

    def test_zero_request_pod_fits_everywhere_with_pod_slots(self, rng):
        nodes = [make_node("n").capacity(cpu="1", memory="1Gi", pods=1).obj()]
        snap = new_snapshot([], nodes)
        nt = NodeTensorCache().update(snap)
        batch = pack_pod_batch([make_pod("z").obj()], nt.dims)
        mask = np.asarray(
            fit_mask(
                jnp.asarray(nt.allocatable),
                jnp.asarray(nt.requested),
                jnp.asarray(batch.requests),
                jnp.asarray(nt.valid),
            )
        )
        assert mask[0, 0]
        # padding rows never fit
        assert not mask[0, 1:].any()


class TestScoreParity:
    def _tensor_scores(self, fn, nt, batch):
        return np.asarray(
            fn(
                jnp.asarray(nt.allocatable[:, :2]),
                jnp.asarray(nt.non_zero_requested),
                jnp.asarray(batch.non_zero_requests),
            )
        )

    def test_least_and_balanced_match_oracle(self, rng):
        existing, nodes = _random_cluster(rng)
        snap = new_snapshot(existing, nodes)
        nt = NodeTensorCache().update(snap)
        pending = _pending(rng, 10)
        batch = pack_pod_batch(pending, nt.dims)

        least = self._tensor_scores(least_allocated_score, nt, batch)
        balanced = self._tensor_scores(balanced_allocation_score, nt, batch)

        state = CycleState()
        state.write(SNAPSHOT_STATE_KEY, snap)
        lp = noderesources.LeastAllocated()
        bp = noderesources.BalancedAllocation()
        for b, pod in enumerate(pending):
            for ni in snap.list_node_infos():
                j = nt.row(ni.node_name)
                want, status = lp.score(state, pod, ni.node_name)
                assert status is None
                assert int(least[b, j]) == want, ("least", pod.name, ni.node_name)
                want, status = bp.score(state, pod, ni.node_name)
                assert status is None
                # balanced may differ by 1 where the oracle's float64
                # truncation lands differently than exact math
                assert abs(int(balanced[b, j]) - want) <= 1, (
                    "balanced", pod.name, ni.node_name,
                )


class TestGreedyAssign:
    def _solve(self, nt, batch, active=None):
        b = batch.size
        order = batch.order
        static = np.ones((b, nt.capacity), dtype=bool)
        act = np.ones(b, dtype=bool) if active is None else active
        assignments, req_out, nzr_out = greedy_assign(
            jnp.asarray(nt.allocatable),
            jnp.asarray(nt.requested),
            jnp.asarray(nt.non_zero_requested),
            jnp.asarray(nt.valid),
            jnp.asarray(batch.requests[order]),
            jnp.asarray(batch.non_zero_requests[order]),
            jnp.asarray(static[order]),
            jnp.asarray(act[order]),
        )
        return np.asarray(assignments), np.asarray(req_out), np.asarray(nzr_out)

    def test_capacity_never_double_booked(self, rng):
        # 1 node with room for exactly 2 pods; 4 pods in batch
        nodes = [make_node("n").capacity(cpu="2", memory="4Gi", pods=10).obj()]
        snap = new_snapshot([], nodes)
        nt = NodeTensorCache().update(snap)
        pods = [
            make_pod(f"p{i}").creation_timestamp(float(i))
            .container(cpu="1", memory="1Gi").obj()
            for i in range(4)
        ]
        batch = pack_pod_batch(pods, nt.dims)
        assignments, req_out, _ = self._solve(nt, batch)
        assert (assignments == 0).sum() == 2
        assert (assignments == NO_NODE).sum() == 2
        assert req_out[0, 0] == 2000  # cpu fully booked, not over

    def test_step_optimality_vs_oracle(self, rng):
        """Each batched decision achieves the oracle's max total score given
        the same already-assigned prefix (parity modulo tie-break RNG)."""
        existing, nodes = _random_cluster(rng)
        snap = new_snapshot(existing, nodes)
        nt = NodeTensorCache().update(snap)
        pending = _pending(rng, 20)
        batch = pack_pod_batch(pending, nt.dims)
        assignments, _, _ = self._solve(nt, batch)

        # Oracle replay: walk pods in solve order, computing plugin scores
        # against the *current* snapshot, following the solver's choices.
        lp = noderesources.LeastAllocated()
        bp = noderesources.BalancedAllocation()
        fit = noderesources.Fit()
        for k, b in enumerate(batch.order):
            pod = batch.pods[b]
            choice = int(assignments[k])
            state = CycleState()
            state.write(SNAPSHOT_STATE_KEY, snap)
            fit.pre_filter(state, pod)
            feasible = [
                ni for ni in snap.list_node_infos()
                if fit.filter(state, pod, ni) is None
            ]
            if choice == NO_NODE:
                assert not feasible, pod.name
                continue
            chosen_name = nt.names[choice]
            assert chosen_name in {ni.node_name for ni in feasible}, pod.name

            def total(name):
                l, _ = lp.score(state, pod, name)
                bl, _ = bp.score(state, pod, name)
                return l + bl

            best = max(total(ni.node_name) for ni in feasible)
            # +-1 tolerance per the balanced float64-truncation artifact
            assert total(chosen_name) >= best - 1, pod.name
            # follow the solver's decision
            pod_copy = pod.deepcopy()
            pod_copy.spec.node_name = chosen_name
            snap.get_node_info(chosen_name).add_pod(pod_copy)

    def test_priority_order_wins_scarce_capacity(self):
        nodes = [make_node("n").capacity(cpu="1", memory="1Gi", pods=10).obj()]
        snap = new_snapshot([], nodes)
        nt = NodeTensorCache().update(snap)
        low = make_pod("low").creation_timestamp(0.0).container(cpu="1").obj()
        high = make_pod("high").creation_timestamp(1.0).container(cpu="1").obj()
        high.spec.priority = 100
        batch = pack_pod_batch([low, high], nt.dims)
        assignments, _, _ = self._solve(nt, batch)
        # solve order puts high first; low misses out
        by_pod = {batch.pods[b].name: int(assignments[k])
                  for k, b in enumerate(batch.order)}
        assert by_pod["high"] == 0
        assert by_pod["low"] == NO_NODE

    def test_inactive_padding_rows_ignored(self):
        nodes = [make_node("n").capacity(cpu="4", memory="4Gi", pods=10).obj()]
        snap = new_snapshot([], nodes)
        nt = NodeTensorCache().update(snap)
        pods = [make_pod("p").container(cpu="1").obj(),
                make_pod("pad").container(cpu="1").obj()]
        batch = pack_pod_batch(pods, nt.dims)
        active = np.array([True, False])
        assignments, req_out, _ = self._solve(nt, batch, active)
        assert int(assignments[0]) == 0
        assert int(assignments[1]) == NO_NODE
        assert req_out[0, 0] == 1000  # inactive pod did not book capacity


class TestFitAdviceSemantics:
    """ADVICE round-1: the all-zero-request shortcut is per POD, not per
    dimension (fit.go: ``allocatable < requested + request`` is checked for
    every dimension once any request is non-zero)."""

    def _solve(self, allocatable, requested, pod_req):
        n = len(allocatable)
        a = jnp.asarray(np.array(allocatable, dtype=np.int32))
        r = jnp.asarray(np.array(requested, dtype=np.int32))
        nzr = jnp.zeros((n, 2), dtype=jnp.int32)
        valid = jnp.ones(n, dtype=bool)
        preq = jnp.asarray(np.array([pod_req], dtype=np.int32))
        pnzr = jnp.zeros((1, 2), dtype=jnp.int32)
        sm = jnp.ones((1, n), dtype=bool)
        active = jnp.ones(1, dtype=bool)
        out, _, _ = greedy_assign(a, r, nzr, valid, preq, pnzr, sm, active)
        return int(np.asarray(out)[0])

    def test_zero_request_dim_on_overcommitted_node_rejects(self):
        # node over-committed on cpu (nominated-pod overlay can do this);
        # pod requests 0 cpu but >0 memory -> reference rejects
        got = self._solve(
            allocatable=[[1000, 1024, 0, 10]],
            requested=[[1500, 0, 0, 1]],
            pod_req=[0, 512, 0, 1],
        )
        assert got == NO_NODE

    def test_all_zero_request_pod_only_checks_pod_count(self):
        got = self._solve(
            allocatable=[[1000, 1024, 0, 10]],
            requested=[[1500, 0, 0, 1]],
            pod_req=[0, 0, 0, 1],
        )
        assert got == 0

    def test_all_zero_request_pod_rejected_when_pod_slots_full(self):
        got = self._solve(
            allocatable=[[1000, 1024, 0, 1]],
            requested=[[0, 0, 0, 1]],
            pod_req=[0, 0, 0, 1],
        )
        assert got == NO_NODE

    def test_unrequested_scalar_on_overcommitted_node_still_fits(self):
        # scalar columns are only checked when requested (fit.go iterates
        # podRequest.ScalarResources); an over-committed extended resource
        # must not reject a pod that doesn't ask for it
        got = self._solve(
            allocatable=[[1000, 1024, 0, 10, 4]],
            requested=[[0, 0, 0, 1, 5]],
            pod_req=[500, 0, 0, 1, 0],
        )
        assert got == 0

    def test_requested_scalar_on_overcommitted_node_rejects(self):
        got = self._solve(
            allocatable=[[1000, 1024, 0, 10, 4]],
            requested=[[0, 0, 0, 1, 4]],
            pod_req=[500, 0, 0, 1, 1],
        )
        assert got == NO_NODE
