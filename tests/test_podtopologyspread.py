"""PodTopologySpread plugin tests (reference pattern:
podtopologyspread/filtering_test.go, scoring_test.go)."""

from kubernetes_tpu.cache.snapshot import new_snapshot
from kubernetes_tpu.framework.interface import CycleState, NodeScore
from kubernetes_tpu.plugins.podtopologyspread import (
    PRE_FILTER_STATE_KEY,
    PodTopologySpread,
)
from kubernetes_tpu.scheduler.generic import SNAPSHOT_STATE_KEY
from kubernetes_tpu.testing import make_node, make_pod


def _cluster_zones():
    """2 zones x 2 nodes; app=web pods spread zone1=2 (1+1), zone2=1."""
    nodes = [
        make_node("n1a").labels(zone="zone1", host="n1a").obj(),
        make_node("n1b").labels(zone="zone1", host="n1b").obj(),
        make_node("n2a").labels(zone="zone2", host="n2a").obj(),
        make_node("n2b").labels(zone="zone2", host="n2b").obj(),
    ]
    pods = [
        make_pod("p1").node("n1a").labels(app="web").obj(),
        make_pod("p2").node("n1b").labels(app="web").obj(),
        make_pod("p3").node("n2a").labels(app="web").obj(),
    ]
    return pods, nodes


def _run_filter(pod, pods, nodes):
    snap = new_snapshot(pods, nodes)
    state = CycleState()
    state.write(SNAPSHOT_STATE_KEY, snap)
    pl = PodTopologySpread()
    assert pl.pre_filter(state, pod) is None
    results = {}
    for ni in snap.list_node_infos():
        results[ni.node_name] = pl.filter(state, pod, ni)
    return results, state, snap, pl


class TestFilter:
    def test_zone_spread_max_skew_1(self):
        pods, nodes = _cluster_zones()
        pod = (
            make_pod("new")
            .labels(app="web")
            .spread_constraint(1, "zone", match_labels={"app": "web"})
            .obj()
        )
        results, *_ = _run_filter(pod, pods, nodes)
        # zone1 has 2 matches, zone2 has 1 (min). 2+1-1=2 > 1 -> zone1 out.
        assert results["n1a"] is not None
        assert results["n1b"] is not None
        assert results["n2a"] is None
        assert results["n2b"] is None

    def test_node_missing_topology_key_unschedulable(self):
        pods, nodes = _cluster_zones()
        nodes.append(make_node("nx").obj())  # no zone label
        pod = (
            make_pod("new")
            .labels(app="web")
            .spread_constraint(1, "zone", match_labels={"app": "web"})
            .obj()
        )
        results, *_ = _run_filter(pod, pods, nodes)
        assert results["nx"] is not None

    def test_non_matching_incoming_pod_no_self_skew(self):
        pods, nodes = _cluster_zones()
        # incoming pod does not match its own selector: selfMatch=0, so
        # zone1 skew = 2+0-1 = 1 <= 1 -> fits everywhere.
        pod = (
            make_pod("new")
            .labels(app="db")
            .spread_constraint(1, "zone", match_labels={"app": "web"})
            .obj()
        )
        results, *_ = _run_filter(pod, pods, nodes)
        assert all(v is None for v in results.values())

    def test_no_constraints_passes(self):
        pods, nodes = _cluster_zones()
        pod = make_pod("new").labels(app="web").obj()
        results, *_ = _run_filter(pod, pods, nodes)
        assert all(v is None for v in results.values())

    def test_hostname_spread(self):
        pods, nodes = _cluster_zones()
        pod = (
            make_pod("new")
            .labels(app="web")
            .spread_constraint(1, "host", match_labels={"app": "web"})
            .obj()
        )
        results, *_ = _run_filter(pod, pods, nodes)
        # per-host matches: n1a=1 n1b=1 n2a=1 n2b=0(min). skew for used
        # hosts = 1+1-0 = 2 > 1 -> only n2b fits.
        assert results["n2b"] is None
        assert results["n1a"] is not None

    def test_namespace_scoping(self):
        pods, nodes = _cluster_zones()
        for p in pods:
            p.metadata.namespace = "other"
        pod = (
            make_pod("new")  # default namespace: no pods match
            .labels(app="web")
            .spread_constraint(1, "zone", match_labels={"app": "web"})
            .obj()
        )
        results, *_ = _run_filter(pod, pods, nodes)
        assert all(v is None for v in results.values())


class TestPreFilterExtensions:
    def test_add_remove_pod_updates_counts(self):
        pods, nodes = _cluster_zones()
        pod = (
            make_pod("new")
            .labels(app="web")
            .spread_constraint(1, "zone", match_labels={"app": "web"})
            .obj()
        )
        results, state, snap, pl = _run_filter(pod, pods, nodes)
        ext = pl.pre_filter_extensions()
        # virtually add a matching pod to zone2 -> zone2 now 2, min becomes 2
        extra = make_pod("extra").node("n2b").labels(app="web").obj()
        ni = snap.get_node_info("n2b")
        ext.add_pod(state, pod, extra, ni)
        s = state.read(PRE_FILTER_STATE_KEY)
        assert s.tp_pair_to_match_num[("zone", "zone2")] == 2
        assert s.tp_key_to_critical_paths["zone"].min_match_num() == 2
        # zone1: 2+1-2=1 <= 1 -> now fits
        assert pl.filter(state, pod, snap.get_node_info("n1a")) is None
        # remove it again -> zone2 back to 1
        ext.remove_pod(state, pod, extra, ni)
        s = state.read(PRE_FILTER_STATE_KEY)
        assert s.tp_pair_to_match_num[("zone", "zone2")] == 1
        assert pl.filter(state, pod, snap.get_node_info("n1a")) is not None

    def test_clone_isolates_state(self):
        pods, nodes = _cluster_zones()
        pod = (
            make_pod("new")
            .labels(app="web")
            .spread_constraint(1, "zone", match_labels={"app": "web"})
            .obj()
        )
        _, state, snap, pl = _run_filter(pod, pods, nodes)
        cloned = state.clone()
        extra = make_pod("extra").node("n2b").labels(app="web").obj()
        pl.pre_filter_extensions().add_pod(
            cloned, pod, extra, snap.get_node_info("n2b")
        )
        orig = state.read(PRE_FILTER_STATE_KEY)
        assert orig.tp_pair_to_match_num[("zone", "zone2")] == 1


class TestScore:
    def _score(self, pod, pods, nodes):
        snap = new_snapshot(pods, nodes)
        state = CycleState()
        state.write(SNAPSHOT_STATE_KEY, snap)
        pl = PodTopologySpread()
        infos = snap.list_node_infos()
        assert pl.pre_score(state, pod, infos) is None
        scores = []
        for ni in infos:
            raw, status = pl.score(state, pod, ni.node_name)
            assert status is None
            scores.append(NodeScore(ni.node_name, raw))
        assert pl.normalize_score(state, pod, scores) is None
        return {ns.name: ns.score for ns in scores}

    def test_soft_spread_prefers_less_loaded_zone(self):
        pods, nodes = _cluster_zones()
        pod = (
            make_pod("new")
            .labels(app="web")
            .spread_constraint(
                1, "zone", when_unsatisfiable="ScheduleAnyway",
                match_labels={"app": "web"},
            )
            .obj()
        )
        by_node = self._score(pod, pods, nodes)
        assert by_node["n2a"] > by_node["n1a"]
        assert by_node["n2b"] == by_node["n2a"]

    def test_no_soft_constraints_all_max(self):
        pods, nodes = _cluster_zones()
        pod = make_pod("new").labels(app="web").obj()
        by_node = self._score(pod, pods, nodes)
        # no constraints: raw scores all 0, maxMinDiff heuristic yields 0s
        assert set(by_node.values()) == {0}

    def test_node_without_key_scores_zero(self):
        pods, nodes = _cluster_zones()
        nodes.append(make_node("nx").obj())
        pod = (
            make_pod("new")
            .labels(app="web")
            .spread_constraint(
                1, "zone", when_unsatisfiable="ScheduleAnyway",
                match_labels={"app": "web"},
            )
            .obj()
        )
        by_node = self._score(pod, pods, nodes)
        assert by_node["nx"] == 0
        assert by_node["n2a"] > 0
