"""Randomized batch-vs-sequential differential for the volume-count
device columns (same pattern as tests/test_score_differential.py): pods
whose attachable-volume limits used to force the sequential host path
now solve on device via the ``[N, R]`` volume columns, and must place
IDENTICALLY to the host oracle (CSILimits / in-tree unique-handle sets),
including:

- the over-capacity reject case (more volumes than the cluster's attach
  slots -> the same pods stay unschedulable on both paths), and
- the CSINode-absent migration fallback (no CSINode -> no limit known ->
  both paths admit; csi.go:72).

Handles are distinct per pod, where the additive device counting and the
oracle's per-node-unique sets provably agree; shared-handle pods are the
documented conservative case (device rejects re-check on the host path).
"""

import random
import time

import pytest

from kubernetes_tpu.api.types import (
    CSINode,
    CSINodeDriver,
    ObjectMeta,
    PersistentVolume,
    PersistentVolumeClaim,
)
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client.client import Client
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.scheduler.scheduler import new_scheduler
from kubernetes_tpu.testing import make_node, make_pod

NUM_NODES = 8
NUM_PODS = 20


class _KeepFirstRng:
    def randrange(self, n):
        return 1 if n > 1 else 0

    def randint(self, a, b):
        return b


def _build_cluster(server, client, *, csi_limit, with_csi_nodes, rng):
    for i in range(NUM_NODES):
        client.create_node(
            make_node(f"n{i}")
            .capacity(cpu=str(8 + 2 * i), memory=f"{16 + 5 * i}Gi")
            .obj()
        )
        if with_csi_nodes:
            server.create(
                CSINode(
                    metadata=ObjectMeta(name=f"n{i}", namespace=""),
                    drivers=[
                        CSINodeDriver(
                            name="ebs.csi.aws.com",
                            node_id=f"n{i}",
                            allocatable_count=csi_limit,
                        )
                    ],
                )
            )


def _build_pods(server, rng):
    """Pods with 1-2 bound countable PVs each: mostly CSI, some in-tree
    EBS via PV. Distinct handles per pod. Creation timestamps fix the
    solve order on both paths."""
    pods = []
    for i in range(NUM_PODS):
        w = (
            make_pod(f"m{i}")
            .creation_timestamp(float(i))
            .container(
                cpu=f"{rng.choice([100, 200, 400])}m",
                memory=f"{rng.choice([128, 256])}Mi",
            )
        )
        for k in range(rng.choice([1, 1, 2])):
            cn = f"pvc-m{i}-{k}"
            vn = f"pv-m{i}-{k}"
            server.create(
                PersistentVolumeClaim(
                    metadata=ObjectMeta(name=cn, namespace="default"),
                    volume_name=vn,
                    requested_bytes=1 << 30,
                )
            )
            pv = PersistentVolume(
                metadata=ObjectMeta(name=vn, namespace=""),
                capacity_bytes=1 << 30,
                claim_ref_namespace="default",
                claim_ref_name=cn,
            )
            if rng.random() < 0.75:
                pv.csi_driver = "ebs.csi.aws.com"
                pv.csi_volume_handle = vn
            else:
                pv.aws_ebs_volume_id = vn
            server.create(pv)
            w.pvc(cn)
        pods.append(w.obj())
    return pods


def _wait_decided(client, sched, count, timeout=120.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        pods, _ = client.list_pods()
        pending = [
            p for p in pods
            if not p.spec.node_name and not p.status.conditions
        ]
        if len(pods) >= count and not pending:
            sched.wait_for_inflight_binds()
            return client.list_pods()[0]
        time.sleep(0.05)
    raise AssertionError("pods not decided in time")


def _run(seed, *, batch, csi_limit, with_csi_nodes):
    rng = random.Random(seed)
    server = APIServer()
    client = Client(server)
    informers = InformerFactory(server)
    sched = new_scheduler(
        client, informers, batch=batch, max_batch=32,
        rng=_KeepFirstRng(),
    )
    _build_cluster(
        server, client, csi_limit=csi_limit,
        with_csi_nodes=with_csi_nodes, rng=rng,
    )
    pods = _build_pods(server, rng)
    informers.start()
    informers.wait_for_cache_sync()
    sched.queue.run()
    for p in pods:
        client.create_pod(p)
    sched.start()
    decided = _wait_decided(client, sched, NUM_PODS)
    placements = {
        p.metadata.name: p.spec.node_name
        for p in decided
        if p.metadata.name.startswith("m")
    }
    stats = {
        "fallback": getattr(sched, "pods_fallback", None),
        "on_device": getattr(sched, "pods_solved_on_device", None),
        "vol_retries": getattr(sched, "volume_reject_retries", None),
    }
    sched.stop()
    informers.stop()
    return placements, stats


@pytest.mark.parametrize("seed", [7, 23])
def test_volume_columns_match_host_oracle(seed):
    """Capacity-comfortable case: every pod fits under the per-node
    attach limits; batch placements must equal the sequential oracle's,
    with zero host fallbacks on the batch side."""
    want, _ = _run(seed, batch=False, csi_limit=6, with_csi_nodes=True)
    got, stats = _run(seed, batch=True, csi_limit=6, with_csi_nodes=True)
    assert all(want.values()), "oracle failed to place a fitting pod"
    assert got == want
    assert stats["fallback"] == 0, stats


@pytest.mark.parametrize("seed", [11])
def test_over_capacity_rejects_match(seed):
    """Attach slots < total volumes: the SAME pods must end up
    unschedulable on both paths (the batch path re-checks device rejects
    on the host oracle before declaring failure)."""
    want, _ = _run(seed, batch=False, csi_limit=1, with_csi_nodes=True)
    got, stats = _run(seed, batch=True, csi_limit=1, with_csi_nodes=True)
    assert any(not v for v in want.values()), (
        "expected an over-capacity reject in the oracle run"
    )
    assert got == want
    # rejects were re-checked on the host path, not failed blind
    assert stats["vol_retries"] >= 1, stats


@pytest.mark.parametrize("seed", [5])
def test_csi_node_absent_falls_open(seed):
    """No CSINode objects -> no limits known -> both paths admit
    everything (nodevolumelimits/csi.go:72), still identically placed
    and fully on device."""
    want, _ = _run(seed, batch=False, csi_limit=0, with_csi_nodes=False)
    got, stats = _run(seed, batch=True, csi_limit=0, with_csi_nodes=False)
    assert all(want.values())
    assert got == want
    assert stats["fallback"] == 0, stats
