"""Volume plugin tests (reference pattern: volumerestrictions /
volumezone / nodevolumelimits / volume_binding *_test.go)."""

import pytest

from kubernetes_tpu.api.types import (
    CSINode,
    CSINodeDriver,
    NodeSelector,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    ObjectMeta,
    PersistentVolume,
    PersistentVolumeClaim,
    StorageClass,
)
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.cache.node_info import NodeInfo
from kubernetes_tpu.cache.snapshot import new_snapshot
from kubernetes_tpu.client.client import Client
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.framework.interface import CycleState, StatusCode
from kubernetes_tpu.plugins import volumes
from kubernetes_tpu.scheduler.generic import SNAPSHOT_STATE_KEY
from kubernetes_tpu.testing import make_node, make_pod


class _Handle:
    def __init__(self, informers, client=None):
        self.informers = informers
        self.client = client


@pytest.fixture
def env():
    server = APIServer()
    client = Client(server)
    informers = InformerFactory(server)
    handle = _Handle(informers, client)
    return server, client, informers, handle


def _pump(informers):
    informers.pump()


def _cluster_meta(name, namespace=""):
    return ObjectMeta(name=name, namespace=namespace)


class TestVolumeRestrictions:
    def test_gce_pd_rw_conflict(self):
        pl = volumes.VolumeRestrictions()
        existing = make_pod("a").gce_pd("disk-1").obj()
        ni = NodeInfo(make_node("n").obj())
        ni.add_pod(existing)
        pod = make_pod("b").gce_pd("disk-1").obj()
        status = pl.filter(CycleState(), pod, ni)
        assert status is not None and status.code == StatusCode.UNSCHEDULABLE

    def test_gce_pd_ro_ok(self):
        pl = volumes.VolumeRestrictions()
        existing = make_pod("a").gce_pd("disk-1", read_only=True).obj()
        ni = NodeInfo(make_node("n").obj())
        ni.add_pod(existing)
        pod = make_pod("b").gce_pd("disk-1", read_only=True).obj()
        assert pl.filter(CycleState(), pod, ni) is None

    def test_ebs_always_conflicts(self):
        pl = volumes.VolumeRestrictions()
        existing = make_pod("a").ebs("vol-1").obj()
        ni = NodeInfo(make_node("n").obj())
        ni.add_pod(existing)
        pod = make_pod("b").ebs("vol-1").obj()
        assert pl.filter(CycleState(), pod, ni) is not None


class TestVolumeZone:
    def test_pv_zone_mismatch(self, env):
        server, client, informers, handle = env
        client.create(PersistentVolumeClaim(
            metadata=ObjectMeta(name="claim", namespace="default"),
            volume_name="pv-1",
        ))
        pv = PersistentVolume(metadata=_cluster_meta("pv-1"))
        pv.metadata.labels["topology.kubernetes.io/zone"] = "z1"
        client.create(pv)
        informers.persistent_volume_claims()
        informers.persistent_volumes()
        informers.storage_classes()
        _pump(informers)

        pl = volumes.VolumeZone(handle)
        pod = make_pod("p").pvc("claim").obj()
        good = NodeInfo(
            make_node("n1").label("topology.kubernetes.io/zone", "z1").obj()
        )
        bad = NodeInfo(
            make_node("n2").label("topology.kubernetes.io/zone", "z2").obj()
        )
        unlabeled = NodeInfo(make_node("n3").obj())
        assert pl.filter(CycleState(), pod, good) is None
        status = pl.filter(CycleState(), pod, bad)
        assert status is not None
        assert status.code == StatusCode.UNSCHEDULABLE_AND_UNRESOLVABLE
        assert pl.filter(CycleState(), pod, unlabeled) is None


class TestCSILimits:
    def test_limit_enforced(self, env):
        server, client, informers, handle = env
        for i in range(3):
            client.create(PersistentVolumeClaim(
                metadata=ObjectMeta(name=f"c{i}", namespace="default"),
                volume_name=f"pv{i}",
            ))
            client.create(PersistentVolume(
                metadata=_cluster_meta(f"pv{i}"),
                csi_driver="ebs.csi.aws.com",
                csi_volume_handle=f"h{i}",
            ))
        client.create(CSINode(
            metadata=_cluster_meta("n"),
            drivers=[CSINodeDriver(name="ebs.csi.aws.com", allocatable_count=2)],
        ))
        informers.persistent_volume_claims()
        informers.persistent_volumes()
        informers.csi_nodes()
        _pump(informers)

        pl = volumes.CSILimits(handle)
        ni = NodeInfo(make_node("n").obj())
        ni.add_pod(make_pod("e0").pvc("c0").obj())
        ni.add_pod(make_pod("e1").pvc("c1").obj())
        pod = make_pod("new").pvc("c2").obj()
        status = pl.filter(CycleState(), pod, ni)
        assert status is not None and status.code == StatusCode.UNSCHEDULABLE
        # same handle already in use does not count twice
        again = make_pod("again").pvc("c0").obj()
        assert pl.filter(CycleState(), again, ni) is None


class TestVolumeBinding:
    def _mk(self, env, *, binding_mode, with_pv=True, pv_zone=None,
            provisioner="kubernetes.io/no-provisioner"):
        server, client, informers, handle = env
        client.create(StorageClass(
            metadata=_cluster_meta("sc"),
            provisioner=provisioner,
            volume_binding_mode=binding_mode,
        ))
        client.create(PersistentVolumeClaim(
            metadata=ObjectMeta(name="claim", namespace="default"),
            storage_class_name="sc",
            requested_bytes=1 << 30,
        ))
        if with_pv:
            pv = PersistentVolume(
                metadata=_cluster_meta("pv-a"),
                storage_class_name="sc",
                capacity_bytes=2 << 30,
            )
            if pv_zone:
                pv.node_affinity = NodeSelector(node_selector_terms=[
                    NodeSelectorTerm(match_expressions=[
                        NodeSelectorRequirement(
                            key="zone", operator="In", values=[pv_zone]
                        )
                    ])
                ])
            client.create(pv)
        for acc in ("persistent_volume_claims", "persistent_volumes",
                    "storage_classes"):
            getattr(informers, acc)()
        _pump(informers)
        return volumes.VolumeBinding(handle)

    def test_unbound_immediate_unresolvable(self, env):
        pl = self._mk(env, binding_mode="Immediate")
        pod = make_pod("p").pvc("claim").obj()
        status = pl.filter(CycleState(), pod, NodeInfo(make_node("n").obj()))
        assert status is not None
        assert status.code == StatusCode.UNSCHEDULABLE_AND_UNRESOLVABLE

    def test_wait_mode_matches_pv_with_node_affinity(self, env):
        pl = self._mk(env, binding_mode="WaitForFirstConsumer", pv_zone="z1")
        pod = make_pod("p").pvc("claim").obj()
        good = NodeInfo(make_node("n1").labels(zone="z1").obj())
        bad = NodeInfo(make_node("n2").labels(zone="z2").obj())
        assert pl.filter(CycleState(), pod, good) is None
        assert pl.filter(CycleState(), pod, bad) is not None

    def test_wait_mode_no_pv_no_provisioner_unschedulable(self, env):
        pl = self._mk(env, binding_mode="WaitForFirstConsumer", with_pv=False)
        pod = make_pod("p").pvc("claim").obj()
        status = pl.filter(CycleState(), pod, NodeInfo(make_node("n").obj()))
        assert status is not None and status.code == StatusCode.UNSCHEDULABLE

    def test_wait_mode_dynamic_provisioner_ok(self, env):
        pl = self._mk(env, binding_mode="WaitForFirstConsumer",
                      with_pv=False, provisioner="pd.csi.storage.gke.io")
        pod = make_pod("p").pvc("claim").obj()
        assert pl.filter(CycleState(), pod, NodeInfo(make_node("n").obj())) is None

    def test_pre_bind_binds_pv(self, env):
        server, client, informers, handle = env
        pl = self._mk(env, binding_mode="WaitForFirstConsumer")
        pod = make_pod("p").pvc("claim").obj()
        node = make_node("n").obj()
        snap = new_snapshot([], [node])
        state = CycleState()
        state.write(SNAPSHOT_STATE_KEY, snap)
        assert pl.pre_bind(state, pod, "n") is None
        pv = server.get("PersistentVolume", "", "pv-a")
        assert pv.claim_ref_name == "claim"
        pvc = server.get("PersistentVolumeClaim", "default", "claim")
        assert pvc.volume_name == "pv-a"
        assert pvc.phase == "Bound"


class TestPVReservation:
    def test_one_pv_cannot_satisfy_two_claims(self, env):
        server, client, informers, handle = env
        client.create(StorageClass(
            metadata=_cluster_meta("sc"),
            provisioner="kubernetes.io/no-provisioner",
            volume_binding_mode="WaitForFirstConsumer",
        ))
        for name in ("claim-a", "claim-b"):
            client.create(PersistentVolumeClaim(
                metadata=ObjectMeta(name=name, namespace="default"),
                storage_class_name="sc", requested_bytes=1 << 30,
            ))
        client.create(PersistentVolume(
            metadata=_cluster_meta("only-pv"),
            storage_class_name="sc", capacity_bytes=4 << 30,
        ))
        for acc in ("persistent_volume_claims", "persistent_volumes",
                    "storage_classes"):
            getattr(informers, acc)()
        _pump(informers)
        pl = volumes.VolumeBinding(handle)
        pod = make_pod("p").pvc("claim-a").pvc("claim-b").obj()
        status = pl.filter(CycleState(), pod, NodeInfo(make_node("n").obj()))
        assert status is not None  # only one PV: second claim can't bind
        assert status.code == StatusCode.UNSCHEDULABLE


class TestBoundPVNodeAffinity:
    def test_bound_claim_respects_pv_affinity(self, env):
        server, client, informers, handle = env
        pv = PersistentVolume(
            metadata=_cluster_meta("pv-b"),
            storage_class_name="sc",
            capacity_bytes=1 << 30,
            claim_ref_namespace="default",
            claim_ref_name="claim",
            node_affinity=NodeSelector(node_selector_terms=[
                NodeSelectorTerm(match_expressions=[
                    NodeSelectorRequirement(
                        key="zone", operator="In", values=["z1"]
                    )
                ])
            ]),
        )
        client.create(pv)
        client.create(PersistentVolumeClaim(
            metadata=ObjectMeta(name="claim", namespace="default"),
            volume_name="pv-b",
        ))
        for acc in ("persistent_volume_claims", "persistent_volumes",
                    "storage_classes"):
            getattr(informers, acc)()
        _pump(informers)
        pl = volumes.VolumeBinding(handle)
        pod = make_pod("p").pvc("claim").obj()
        good = NodeInfo(make_node("n1").labels(zone="z1").obj())
        bad = NodeInfo(make_node("n2").labels(zone="z2").obj())
        assert pl.filter(CycleState(), pod, good) is None
        status = pl.filter(CycleState(), pod, bad)
        assert status is not None
        assert status.code == StatusCode.UNSCHEDULABLE_AND_UNRESOLVABLE
