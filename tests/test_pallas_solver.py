"""Pallas solver kernel (ops/pallas_solver.py) vs the XLA scan
(ops/assignment.py): randomized differential parity in interpreter mode.

On the chip the kernel is the greedy packed path's default
(KTPU_PALLAS=0 opts out); measured 4.5x faster per solve than the XLA
lowering with bit-identical outputs.
"""

import numpy as np
import pytest

from kubernetes_tpu.ops.assignment import GreedyConfig, greedy_assign_compact
from kubernetes_tpu.ops.pallas_solver import pallas_greedy_solve


def _random_problem(seed, n=256, b=256, r=6):
    rng = np.random.default_rng(seed)
    alloc = np.zeros((n, r), np.int32)
    alloc[:, 0] = rng.choice([2000, 4000, 8000], n)
    alloc[:, 1] = rng.choice([4, 8, 16], n) * 1024 * 1024
    alloc[:, 2] = rng.choice([0, 1 << 20], n)
    alloc[:, 3] = rng.choice([3, 40, 110], n)
    if r > 4:
        alloc[:, 4] = rng.choice([0, 8], n)  # scalar/extended resource
    requested = np.zeros_like(alloc)
    requested[:, 0] = rng.integers(0, 2000, n)
    requested[:, 3] = rng.integers(0, 3, n)
    nzr = np.zeros((n, 2), np.int32)
    nzr[:, 0] = requested[:, 0]
    nzr[:, 1] = rng.integers(0, 1 << 22, n)
    valid = rng.random(n) > 0.05
    pod_req = np.zeros((b, r), np.int32)
    pod_req[:, 0] = rng.choice([0, 100, 500, 1500], b)
    pod_req[:, 1] = rng.choice([0, 128, 512], b) * 1024
    pod_req[:, 3] = 1
    if r > 4:
        pod_req[:, 4] = rng.choice([0, 0, 0, 1], b)
    pod_nzr = np.maximum(pod_req[:, :2], [100, 200 * 1024]).astype(np.int32)
    rows = rng.random((8, n)) > 0.2
    midx = rng.integers(0, 8, b).astype(np.int32)
    active = rng.random(b) > 0.1
    return (
        alloc, requested, nzr, valid, pod_req, pod_nzr, rows, midx, active
    )


@pytest.mark.parametrize("seed", [0, 7, 21, 99])
@pytest.mark.parametrize(
    "config",
    [
        GreedyConfig(),
        GreedyConfig(
            least_allocated_weight=0,
            balanced_allocation_weight=0,
            most_allocated_weight=1,
        ),
    ],
)
def test_pallas_matches_xla_scan(seed, config):
    args = _random_problem(seed)
    a1, r1, z1 = greedy_assign_compact(*args, config=config)
    a2, r2, z2 = pallas_greedy_solve(*args, config=config, interpret=True)
    assert np.array_equal(np.asarray(a1), np.asarray(a2))
    assert np.array_equal(np.asarray(r1), np.asarray(r2))
    assert np.array_equal(np.asarray(z1), np.asarray(z2))


def test_multi_chunk_grid(seed=3):
    """Batches beyond one SMEM chunk walk the grid; state carries
    across chunks."""
    args = _random_problem(seed, n=256, b=2048, r=4)
    a1, r1, z1 = greedy_assign_compact(*args, config=GreedyConfig())
    a2, r2, z2 = pallas_greedy_solve(
        *args, config=GreedyConfig(), interpret=True
    )
    assert np.array_equal(np.asarray(a1), np.asarray(a2))
    assert np.array_equal(np.asarray(r1), np.asarray(r2))
    assert np.array_equal(np.asarray(z1), np.asarray(z2))


@pytest.mark.parametrize("seed", [1, 13])
@pytest.mark.parametrize(
    "cfg",
    [
        GreedyConfig(),
        GreedyConfig(
            least_allocated_weight=0,
            balanced_allocation_weight=0,
            most_allocated_weight=1,
        ),
    ],
)
def test_shard_candidate_kernel_matches_jnp_step(seed, cfg):
    """The per-shard candidate kernel (the mesh Pallas tier's TPU step
    body, ops/pallas_solver.pallas_shard_candidate) vs the jnp step the
    shard_map twin runs on non-TPU backends: identical (best score,
    lowest-index argmax) per pod over randomized shard-local state --
    the bit-parity that makes the cross-shard combine exact on either
    body."""
    import jax.numpy as jnp

    from kubernetes_tpu.ops.assignment import _combined_score, _fits
    from kubernetes_tpu.ops.pallas_solver import pallas_shard_candidate

    n, r, u = 128, 6, 8
    (alloc, requested, nzr, valid, pod_req, pod_nzr, rows, midx,
     _active) = _random_problem(seed, n=n, b=16, r=r)
    for k in range(16):
        free = jnp.asarray(alloc - requested)
        fits = _fits(free, jnp.asarray(pod_req[k]))
        feasible = (
            fits & jnp.asarray(rows[midx[k]]) & jnp.asarray(valid)
        )
        score = _combined_score(
            jnp.asarray(alloc[:, :2]), jnp.asarray(nzr),
            jnp.asarray(pod_nzr[k]), cfg,
        )
        masked = jnp.where(feasible, score, -jnp.inf)
        best_t = float(jnp.max(masked))
        idx_t = int(jnp.min(jnp.where(
            masked == jnp.max(masked), jnp.arange(n), 1 << 30
        )))
        best_k, idx_k = pallas_shard_candidate(
            jnp.asarray(alloc.T), jnp.asarray(requested.T),
            jnp.asarray(nzr.T),
            jnp.asarray(valid.astype(np.int32))[None, :],
            jnp.asarray(rows.astype(np.int32)),
            jnp.asarray(pod_req[k]), jnp.asarray(pod_nzr[k]),
            jnp.asarray(np.int32(midx[k])),
            config=cfg, interpret=True,
        )
        if bool(jnp.any(feasible)):
            assert float(best_k) == best_t and int(idx_k) == idx_t, (
                seed, k, float(best_k), best_t, int(idx_k), idx_t
            )
        else:
            assert float(best_k) == best_t == float("-inf")
