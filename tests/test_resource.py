from kubernetes_tpu.api.resource import (
    format_cpu,
    format_memory,
    parse_cpu,
    parse_memory,
    parse_quantity,
)


def test_parse_cpu():
    assert parse_cpu("1") == 1000
    assert parse_cpu("100m") == 100
    assert parse_cpu("2500m") == 2500
    assert parse_cpu(0.5) == 500
    assert parse_cpu("0.1") == 100
    assert parse_cpu(4) == 4000


def test_parse_memory():
    assert parse_memory("128Mi") == 128 * 1024 * 1024
    assert parse_memory("1Gi") == 1024**3
    assert parse_memory("1G") == 10**9
    assert parse_memory("500") == 500
    assert parse_memory("1Ki") == 1024
    assert parse_memory("2Ti") == 2 * 1024**4


def test_parse_quantity_suffixes():
    assert parse_quantity("1k") == 1000
    assert parse_quantity("1M") == 1e6
    assert parse_quantity("10") == 10
    assert parse_quantity("1.5") == 1.5
    # scientific notation
    assert parse_quantity("1e3") == 1000


def test_format_roundtrip():
    assert format_cpu(1000) == "1"
    assert format_cpu(250) == "250m"
    assert format_memory(1024**3) == "1Gi"
    assert format_memory(123) == "123"
