"""Preemption tests (reference pattern: preemption in
generic_scheduler_test.go + test/integration/scheduler/preemption_test.go)."""

import time

import pytest

from kubernetes_tpu.api.types import LabelSelector, PodDisruptionBudget
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.cache.snapshot import new_snapshot
from kubernetes_tpu.client.client import Client
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.framework.interface import CycleState, FitError, Status
from kubernetes_tpu.scheduler.preemption import (
    Preemptor,
    filter_pods_with_pdb_violation,
    pick_one_node_for_preemption,
    Victims,
)
from kubernetes_tpu.scheduler.scheduler import new_scheduler
from kubernetes_tpu.testing import make_node, make_pod


def _make_preemptor_env(pods, nodes, plugins=None):
    """In-memory algorithm + framework against a static snapshot."""
    from kubernetes_tpu.cache.cache import SchedulerCache
    from kubernetes_tpu.framework.runtime import Framework
    from kubernetes_tpu.plugins import new_in_tree_registry
    from kubernetes_tpu.scheduler.generic import GenericScheduler
    from kubernetes_tpu.scheduler.provider import default_plugins

    cache = SchedulerCache()
    for n in nodes:
        cache.add_node(n)
    for p in pods:
        cache.add_pod(p)
    snapshot = new_snapshot([], [])
    algorithm = GenericScheduler(cache, snapshot)
    registry = new_in_tree_registry()
    fw = Framework(
        registry,
        default_plugins(),
        snapshot_provider=lambda: snapshot,
    )
    return algorithm, fw


def _schedule_fail(algorithm, fw, pod):
    state = CycleState()
    with pytest.raises(FitError) as exc:
        algorithm.schedule(fw, state, pod)
    return state, exc.value


class TestSelectVictims:
    def test_evicts_lowest_priority_first(self):
        node = make_node("n").capacity(cpu="2", memory="4Gi").obj()
        low = make_pod("low").node("n").container(cpu="1").obj()
        mid = make_pod("mid").node("n").container(cpu="1").obj()
        low.spec.priority, mid.spec.priority = 0, 5
        algorithm, fw = _make_preemptor_env([low, mid], [node])
        preemptor_pod = make_pod("high").container(cpu="1").obj()
        preemptor_pod.spec.priority = 10
        state, fit_err = _schedule_fail(algorithm, fw, preemptor_pod)

        p = Preemptor(algorithm, None, None)
        ni = algorithm.snapshot.get_node_info("n")
        victims, violations, fits = p.select_victims_on_node(
            fw, state, preemptor_pod, ni, []
        )
        assert fits
        # mid is reprieved (removing low frees 1 cpu), low is the victim
        assert [v.name for v in victims] == ["low"]
        assert violations == 0

    def test_no_preemption_when_pod_too_big(self):
        node = make_node("n").capacity(cpu="2", memory="4Gi").obj()
        low = make_pod("low").node("n").container(cpu="1").obj()
        algorithm, fw = _make_preemptor_env([low], [node])
        preemptor_pod = make_pod("huge").container(cpu="64").obj()
        preemptor_pod.spec.priority = 10
        state, fit_err = _schedule_fail(algorithm, fw, preemptor_pod)
        p = Preemptor(algorithm, None, None)
        ni = algorithm.snapshot.get_node_info("n")
        _, _, fits = p.select_victims_on_node(fw, state, preemptor_pod, ni, [])
        assert not fits

    def test_equal_priority_not_preempted(self):
        node = make_node("n").capacity(cpu="1", memory="4Gi").obj()
        peer = make_pod("peer").node("n").container(cpu="1").obj()
        peer.spec.priority = 10
        algorithm, fw = _make_preemptor_env([peer], [node])
        preemptor_pod = make_pod("same").container(cpu="1").obj()
        preemptor_pod.spec.priority = 10
        state, fit_err = _schedule_fail(algorithm, fw, preemptor_pod)
        p = Preemptor(algorithm, None, None)
        ni = algorithm.snapshot.get_node_info("n")
        _, _, fits = p.select_victims_on_node(fw, state, preemptor_pod, ni, [])
        assert not fits


class TestPDB:
    def test_pdb_budget_splits_violating(self):
        pdb = PodDisruptionBudget(
            selector=LabelSelector(match_labels={"app": "db"})
        )
        pdb.status.disruptions_allowed = 1
        pods = [
            make_pod(f"db{i}").labels(app="db").obj() for i in range(3)
        ]
        violating, non_violating = filter_pods_with_pdb_violation(pods, [pdb])
        assert len(non_violating) == 1  # first one spends the budget
        assert len(violating) == 2

    def test_unlabeled_pods_never_violate(self):
        pdb = PodDisruptionBudget(selector=LabelSelector())
        pdb.status.disruptions_allowed = 0
        pods = [make_pod("x").obj()]
        violating, non_violating = filter_pods_with_pdb_violation(pods, [pdb])
        assert not violating


class TestPickNode:
    def _victims(self, *prios, violations=0, start=None):
        pods = []
        for i, pr in enumerate(sorted(prios, reverse=True)):
            p = make_pod(f"v{pr}-{i}").obj()
            p.spec.priority = pr
            p.status.start_time = (start or 100.0) + i
            pods.append(p)
        return Victims(pods, violations)

    def test_free_lunch_wins(self):
        choice = pick_one_node_for_preemption(
            {"a": self._victims(5), "b": Victims([], 0)}
        )
        assert choice == "b"

    def test_min_pdb_violations(self):
        choice = pick_one_node_for_preemption(
            {"a": self._victims(1, violations=1), "b": self._victims(5)}
        )
        assert choice == "b"

    def test_min_highest_priority(self):
        choice = pick_one_node_for_preemption(
            {"a": self._victims(10), "b": self._victims(5)}
        )
        assert choice == "b"

    def test_min_priority_sum(self):
        choice = pick_one_node_for_preemption(
            {"a": self._victims(5, 5), "b": self._victims(5, 1)}
        )
        assert choice == "b"

    def test_min_victim_count(self):
        choice = pick_one_node_for_preemption(
            {"a": self._victims(5, 5, 5), "b": self._victims(5, 5)}
        )
        assert choice == "b"


class TestEndToEnd:
    def test_preempt_then_schedule(self):
        server = APIServer()
        client = Client(server)
        informers = InformerFactory(server)
        sched = new_scheduler(client, informers)
        client.create_node(make_node("n").capacity(cpu="2", memory="4Gi").obj())
        informers.start()
        informers.wait_for_cache_sync()
        # fill the node with two low-priority pods
        for i in range(2):
            client.create_pod(
                make_pod(f"low{i}").container(cpu="1").obj()
            )
        t = sched.start()
        deadline = time.time() + 10
        while time.time() < deadline:
            pods, _ = client.list_pods()
            if all(p.spec.node_name for p in pods):
                break
            time.sleep(0.05)
        # high-priority pod arrives: a victim gets deleted, pod nominated
        high = make_pod("high").container(cpu="1").obj()
        high.spec.priority = 100
        client.create_pod(high)
        deadline = time.time() + 10
        bound = False
        while time.time() < deadline:
            try:
                hp = client.get_pod("default", "high")
            except KeyError:
                break
            if hp.spec.node_name:
                bound = True
                break
            time.sleep(0.05)
        sched.stop()
        informers.stop()
        assert bound, "high-priority pod never bound after preemption"
        pods, _ = client.list_pods()
        low_alive = [p for p in pods if p.name.startswith("low")]
        assert len(low_alive) == 1  # exactly one victim deleted
