"""Soak mode (ROADMAP item-2 residual c): ``bench.py --mode soak``
replays a long diurnal trace through the SLO-adaptive stack and reports
**SLO-violation-minutes** -- time out of SLO, not one end-of-run
percentile that averages the diurnal peak against the trough.

The e2e here is the tier-1-VISIBLE variant of the real soak: a
miniature diurnal run (seconds, not hours) through the exact
``bench.soak_once`` code path, kept under the ``slow`` marker so the
tier-1 sweep collects but does not execute it. The bucket-scoring unit
below runs everywhere."""

import pytest

import bench


@pytest.mark.slow
def test_miniature_diurnal_soak_binds_all_and_scores_buckets():
    rec = bench.soak_once(
        rate=300.0,
        duration_s=8.0,
        bucket_s=2.0,
        slo_s=1.0,
        num_nodes=100,
        max_batch=256,
        trace_seed=1,
    )
    assert rec.get("error") is None
    assert rec["completed"]
    assert rec["bound"] == rec["pods"] > 0
    assert rec["violated_buckets"] == sum(
        1 for b in rec["buckets"] if b["violated"]
    )
    assert rec["slo_violation_minutes"] == pytest.approx(
        rec["violated_buckets"] * rec["bucket_seconds"] / 60.0
    )
    # the diurnal shape actually varied the offered load across buckets
    counts = [b["pods"] for b in rec["buckets"]]
    assert max(counts) > min(counts)
    # a healthy small run on an idle box stays inside the budget
    assert all(b["unbound"] == 0 for b in rec["buckets"])


def test_soak_mode_registered_in_bench_cli():
    """The CLI surface: --mode soak parses and dispatches (unit: just
    the argparse contract, not a run)."""
    import argparse

    # mirror main()'s parser wiring for the mode choice
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--mode", default="burst", choices=("burst", "open-loop", "soak")
    )
    args = ap.parse_args(["--mode", "soak"])
    assert args.mode == "soak"
    assert callable(bench.run_soak_bench)
    assert callable(bench.soak_once)
