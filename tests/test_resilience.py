"""Control-plane resilience units (PR 2): the 410 Gone watch-truncation
contract, the assumed-pod TTL sweeper (formerly dead cache path), the
cache<->apiserver drift checker, idempotent same-node re-binds, and
startup crash recovery."""

import time

import pytest

from kubernetes_tpu.api.types import Binding
from kubernetes_tpu.apiserver.server import APIServer, Gone
from kubernetes_tpu.client.client import Client
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.robustness.faults import (
    FaultInjector,
    FaultPoint,
    FaultProfile,
    PointConfig,
    install_injector,
)
from kubernetes_tpu.scheduler.resilience import (
    ControlPlaneReconciler,
    recover_on_startup,
)
from kubernetes_tpu.scheduler.scheduler import new_scheduler
from kubernetes_tpu.testing import make_node, make_pod
from kubernetes_tpu.utils import metrics


@pytest.fixture(autouse=True)
def _clean_injector():
    yield
    install_injector(None)


# ---------------------------------------------------------------------------
# 410 Gone: truncated watch replay must signal, not silently skip
# ---------------------------------------------------------------------------


class TestWatchGone:
    def test_truncated_replay_raises_gone(self):
        server = APIServer(watch_history_limit=8)
        for i in range(30):  # several trims
            server.create(make_pod(f"p{i}").obj())
        with pytest.raises(Gone):
            server.watch("Pod", since_rv=1)

    def test_replay_within_window_still_works(self):
        server = APIServer(watch_history_limit=8)
        for i in range(30):
            server.create(make_pod(f"p{i}").obj())
        rv = server.current_rv()
        server.create(make_pod("tail").obj())
        w = server.watch("Pod", since_rv=rv)
        evs = w.pending()
        assert [e.object.metadata.name for e in evs] == ["tail"]

    def test_untruncated_history_never_gone(self):
        server = APIServer()
        for i in range(10):
            server.create(make_pod(f"p{i}").obj())
        w = server.watch("Pod", since_rv=0)
        assert len(w.pending()) == 10

    def test_injected_gone_fires(self):
        server = APIServer()
        install_injector(FaultInjector(FaultProfile(
            "trunc", seed=0,
            points={
                FaultPoint.WATCH_HISTORY_TRUNCATED: PointConfig(
                    rate=1.0, max_fires=1
                )
            },
        )))
        with pytest.raises(Gone):
            server.watch("Pod", since_rv=server.current_rv())
        # the point healed: the next open succeeds
        server.watch("Pod", since_rv=server.current_rv())

    def test_informer_relists_through_injected_gone(self):
        """An informer whose relist hits 410 Gone (injected) must list
        again and converge -- no event silently lost, watch_gone
        metered."""
        server = APIServer()
        client = Client(server)
        informers = InformerFactory(server)
        pods_inf = informers.pods()
        pods_inf.pump()  # initial sync
        before_gone = metrics.watch_gone.value(kind="Pod")
        client.create_pod(make_pod("a").container(cpu="1m").obj())
        # force a relist (watch_drop) whose first watch open gets 410
        install_injector(FaultInjector(FaultProfile(
            "drop+gone", seed=0,
            points={
                FaultPoint.WATCH_DROP: PointConfig(rate=1.0, max_fires=1),
                FaultPoint.WATCH_HISTORY_TRUNCATED: PointConfig(
                    rate=1.0, max_fires=1
                ),
            },
        )))
        pods_inf.pump()  # drop -> relist -> Gone -> list again
        install_injector(None)
        client.create_pod(make_pod("b").container(cpu="1m").obj())
        pods_inf.pump()
        assert {p.metadata.name for p in pods_inf.list()} == {"a", "b"}
        assert metrics.watch_gone.value(kind="Pod") > before_gone
        assert pods_inf.synced


# ---------------------------------------------------------------------------
# idempotent same-node re-bind (crash-recovery contract)
# ---------------------------------------------------------------------------


class TestIdempotentRebind:
    def test_same_node_rebind_is_silent_success(self):
        server = APIServer()
        pod = make_pod("p").container(cpu="1m").obj()
        server.create(pod)
        binding = Binding(
            pod_namespace="default", pod_name="p",
            pod_uid=pod.metadata.uid, target_node="n1",
        )
        bound = server.bind(binding)
        rv = bound.metadata.resource_version
        w = server.watch("Pod", since_rv=server.current_rv())
        again = server.bind(binding)  # retried commit that already landed
        assert again.spec.node_name == "n1"
        assert again.metadata.resource_version == rv  # no write
        assert w.pending() == []  # no duplicate event

    def test_other_node_rebind_still_conflicts(self):
        from kubernetes_tpu.apiserver.server import Conflict

        server = APIServer()
        pod = make_pod("p").container(cpu="1m").obj()
        server.create(pod)
        server.bind(Binding(
            pod_namespace="default", pod_name="p",
            pod_uid=pod.metadata.uid, target_node="n1",
        ))
        with pytest.raises(Conflict):
            server.bind(Binding(
                pod_namespace="default", pod_name="p",
                pod_uid=pod.metadata.uid, target_node="n2",
            ))

    def test_bind_assumed_bulk_same_node_is_success(self):
        server = APIServer()
        pod = make_pod("p").container(cpu="1m").obj()
        server.create(pod)
        assumed = pod.assumed_clone()
        assumed.spec.node_name = "n1"
        assert server.bind_assumed_bulk([assumed]) == []
        # the whole "transaction replayed after a crash" shape
        assert server.bind_assumed_bulk([assumed]) == []


# ---------------------------------------------------------------------------
# the sweeper: assumed-pod TTL expiry wired in (formerly dead code)
# ---------------------------------------------------------------------------


def _mk_sched(num_nodes=4, ttl=0.05):
    server = APIServer()
    client = Client(server)
    informers = InformerFactory(server)
    sched = new_scheduler(
        client, informers, batch=False, cache_ttl_seconds=ttl,
    )
    for i in range(num_nodes):
        client.create_node(
            make_node(f"n{i}").capacity(cpu="8", memory="16Gi", pods=30).obj()
        )
    return server, client, informers, sched


class TestAssumedPodSweep:
    def test_expired_assumed_pod_forgotten_and_requeued(self):
        """A pod assumed + finish_binding'd whose confirmation never
        arrives (still pending at the apiserver) expires after the TTL:
        forgotten from the cache, requeued, metered."""
        server, client, informers, sched = _mk_sched(ttl=0.05)
        informers.pump()
        pod = make_pod("stuck").container(cpu="100m").obj()
        client.create_pod(pod)
        assumed = pod.assumed_clone()
        assumed.spec.node_name = "n0"
        sched.cache.assume_pod(assumed)
        sched.cache.finish_binding(assumed)
        before = metrics.assumed_pods_expired.value()
        rec = ControlPlaneReconciler(sched, client, sweep_interval=0.01)
        time.sleep(0.08)  # past the TTL
        expired = rec.sweep_assumed_once()
        assert [p.metadata.name for p in expired] == ["stuck"]
        assert metrics.assumed_pods_expired.value() == before + 1
        assert sched.cache.get_pod(assumed) is None
        # requeued: the pod is poppable again
        pi = sched.queue.pop(timeout=1.0)
        assert pi is not None and pi.pod.metadata.name == "stuck"

    def test_expired_but_actually_bound_pod_readopted(self):
        """The bind landed but its watch confirmation was lost: expiry
        must re-adopt from apiserver truth, not requeue a running pod."""
        server, client, informers, sched = _mk_sched(ttl=0.05)
        informers.pump()
        pod = make_pod("landed").container(cpu="100m").obj()
        client.create_pod(pod)
        assumed = pod.assumed_clone()
        assumed.spec.node_name = "n0"
        sched.cache.assume_pod(assumed)
        sched.cache.finish_binding(assumed)
        server.bind_assumed_bulk([assumed])  # the bind actually landed
        rec = ControlPlaneReconciler(sched, client, sweep_interval=0.01)
        time.sleep(0.08)
        rec.sweep_assumed_once()
        cached = sched.cache.get_pod(assumed)
        assert cached is not None and cached.spec.node_name == "n0"
        assert not sched.cache.is_assumed_pod(assumed)  # confirmed now
        assert sched.queue.pop(timeout=0.1) is None  # NOT requeued

    def test_unexpired_assumed_pod_untouched(self):
        server, client, informers, sched = _mk_sched(ttl=30.0)
        informers.pump()
        pod = make_pod("inflight").container(cpu="100m").obj()
        client.create_pod(pod)
        assumed = pod.assumed_clone()
        assumed.spec.node_name = "n0"
        sched.cache.assume_pod(assumed)
        sched.cache.finish_binding(assumed)
        rec = ControlPlaneReconciler(sched, client, sweep_interval=0.01)
        assert rec.sweep_assumed_once() == []
        assert sched.cache.is_assumed_pod(assumed)


class TestNodeRemovedFastExpiry:
    """PR-6 satellite: deleting a node with in-flight assumed pods must
    route them through the sweeper on its NEXT pass -- not after the
    30s assume TTL -- and meter the requeues."""

    def test_node_delete_fast_expires_and_requeues(self):
        # TTL is huge: only the node-removal fast path can expire
        server, client, informers, sched = _mk_sched(ttl=3600.0)
        informers.pump()
        pod = make_pod("stranded").container(cpu="100m").obj()
        client.create_pod(pod)
        assumed = pod.assumed_clone()
        assumed.spec.node_name = "n0"
        sched.cache.assume_pod(assumed)
        sched.cache.finish_binding(assumed)
        from kubernetes_tpu.api.types import Node, ObjectMeta

        sched.cache.remove_node(Node(metadata=ObjectMeta(name="n0")))
        before = metrics.node_removed_requeues.value()
        rec = ControlPlaneReconciler(sched, client, sweep_interval=0.01)
        expired = rec.sweep_assumed_once()
        assert [p.metadata.name for p in expired] == ["stranded"]
        assert metrics.node_removed_requeues.value() == before + 1
        assert sched.cache.get_pod(assumed) is None
        pi = sched.queue.pop(timeout=1.0)
        assert pi is not None and pi.pod.metadata.name == "stranded"

    def test_node_delete_before_finish_binding_expires_on_finish(self):
        """The bind is still in flight when the node dies: expiry must
        wait for finish_binding (racing the committer would corrupt its
        bookkeeping), then fire on the next sweep, not after the TTL."""
        server, client, informers, sched = _mk_sched(ttl=3600.0)
        informers.pump()
        pod = make_pod("midbind").container(cpu="100m").obj()
        client.create_pod(pod)
        assumed = pod.assumed_clone()
        assumed.spec.node_name = "n1"
        sched.cache.assume_pod(assumed)
        from kubernetes_tpu.api.types import Node, ObjectMeta

        sched.cache.remove_node(Node(metadata=ObjectMeta(name="n1")))
        rec = ControlPlaneReconciler(sched, client, sweep_interval=0.01)
        # not expirable yet: the committer still owns the pod
        assert rec.sweep_assumed_once() == []
        assert sched.cache.is_assumed_pod(assumed)
        sched.cache.finish_binding(assumed)
        expired = rec.sweep_assumed_once()
        assert [p.metadata.name for p in expired] == ["midbind"]

    def test_bound_to_deleted_node_readopted_not_requeued(self):
        """The bind LANDED before the node died: apiserver truth says
        bound, so the sweeper re-adopts (the lifecycle harness owns the
        kill+respawn of pods on dead nodes) and the requeue metric does
        not move."""
        server, client, informers, sched = _mk_sched(ttl=3600.0)
        informers.pump()
        pod = make_pod("landed2").container(cpu="100m").obj()
        client.create_pod(pod)
        assumed = pod.assumed_clone()
        assumed.spec.node_name = "n2"
        sched.cache.assume_pod(assumed)
        sched.cache.finish_binding(assumed)
        server.bind_assumed_bulk([assumed])
        from kubernetes_tpu.api.types import Node, ObjectMeta

        sched.cache.remove_node(Node(metadata=ObjectMeta(name="n2")))
        before = metrics.node_removed_requeues.value()
        rec = ControlPlaneReconciler(sched, client, sweep_interval=0.01)
        rec.sweep_assumed_once()
        assert metrics.node_removed_requeues.value() == before
        cached = sched.cache.get_pod(assumed)
        assert cached is not None and cached.spec.node_name == "n2"
        assert sched.queue.pop(timeout=0.1) is None


# ---------------------------------------------------------------------------
# drift checker
# ---------------------------------------------------------------------------


class TestDriftChecker:
    def test_heals_pod_missing_from_cache(self):
        server, client, informers, sched = _mk_sched()
        informers.pump()
        pod = make_pod("ghost").container(cpu="100m").obj()
        client.create_pod(pod)
        bound = server.bind(Binding(
            pod_namespace="default", pod_name="ghost",
            pod_uid=pod.metadata.uid, target_node="n1",
        ))
        # cache never hears about it (no pump): divergence
        before = metrics.cache_drift.value(kind="pod", action="readopt")
        rec = ControlPlaneReconciler(sched, client)
        report = rec.check_drift_once()
        assert report.pods_readopted == 1
        assert metrics.cache_drift.value(
            kind="pod", action="readopt"
        ) == before + 1
        assert sched.cache.get_pod(bound) is not None
        # converged: the next check finds nothing
        assert rec.check_drift_once().total() == 0

    def test_heals_phantom_pod_in_cache(self):
        """A pod the cache believes is placed but the apiserver shows
        pending (cache corruption): evicted from the cache AND given
        back to the queue."""
        server, client, informers, sched = _mk_sched()
        informers.pump()
        pod = make_pod("phantom").container(cpu="100m").obj()
        client.create_pod(pod)  # pending at the apiserver
        placed = pod.assumed_clone()
        placed.spec.node_name = "n2"
        sched.cache.add_pod(placed)  # cache wrongly holds it as placed
        rec = ControlPlaneReconciler(sched, client)
        report = rec.check_drift_once()
        assert report.pods_evicted == 1 and report.pods_requeued == 1
        assert sched.cache.get_pod(placed) is None
        pi = sched.queue.pop(timeout=1.0)
        assert pi is not None and pi.pod.metadata.name == "phantom"

    def test_heals_deleted_pod_still_in_cache(self):
        server, client, informers, sched = _mk_sched()
        informers.pump()
        pod = make_pod("gone").container(cpu="100m").obj()
        placed = pod.assumed_clone()
        placed.spec.node_name = "n0"
        sched.cache.add_pod(placed)  # never existed at the apiserver
        rec = ControlPlaneReconciler(sched, client)
        report = rec.check_drift_once()
        assert report.pods_evicted == 1 and report.pods_requeued == 0
        assert sched.cache.get_pod(placed) is None

    def test_assumed_pods_never_healed(self):
        """The assumed overlay is the scheduler's own in-flight state --
        the drift checker must leave it alone."""
        server, client, informers, sched = _mk_sched()
        informers.pump()
        pod = make_pod("inflight").container(cpu="100m").obj()
        client.create_pod(pod)
        assumed = pod.assumed_clone()
        assumed.spec.node_name = "n0"
        sched.cache.assume_pod(assumed)
        rec = ControlPlaneReconciler(sched, client)
        report = rec.check_drift_once()
        assert report.pods_evicted == 0
        assert sched.cache.is_assumed_pod(assumed)

    def test_heals_node_drift_both_directions(self):
        server, client, informers, sched = _mk_sched(num_nodes=3)
        informers.pump()
        # cache misses a node and holds a deleted one
        from kubernetes_tpu.api.types import Node, ObjectMeta

        sched.cache.remove_node(
            Node(metadata=ObjectMeta(name="n0", namespace=""))
        )
        client.delete_node("n2")
        # no pump: the cache still holds n2, is missing n0
        rec = ControlPlaneReconciler(sched, client)
        report = rec.check_drift_once()
        assert report.nodes_added == 1 and report.nodes_removed == 1
        assert set(sched.cache.known_node_names()) == {"n0", "n1"}

    def test_sweeper_thread_heals_within_interval(self):
        """The acceptance shape: an injected divergence heals within one
        sweep interval of the running reconciler thread."""
        server, client, informers, sched = _mk_sched()
        informers.pump()
        pod = make_pod("ghost").container(cpu="100m").obj()
        client.create_pod(pod)
        server.bind(Binding(
            pod_namespace="default", pod_name="ghost",
            pod_uid=pod.metadata.uid, target_node="n1",
        ))
        before = metrics.cache_drift.value(kind="pod", action="readopt")
        rec = ControlPlaneReconciler(
            sched, client, sweep_interval=0.02, drift_interval=0.05
        )
        rec.start()
        try:
            deadline = time.time() + 2.0
            while (
                sched.cache.get_pod(pod) is None and time.time() < deadline
            ):
                time.sleep(0.01)
            assert sched.cache.get_pod(pod) is not None
            assert metrics.cache_drift.value(
                kind="pod", action="readopt"
            ) == before + 1
        finally:
            rec.stop()


# ---------------------------------------------------------------------------
# startup crash recovery
# ---------------------------------------------------------------------------


class TestStartupRecovery:
    def test_adopts_bound_and_requeues_pending(self):
        server = APIServer()
        client = Client(server)
        # a previous incarnation bound 3 pods and left 2 in flight
        for i in range(3):
            p = make_pod(f"bound-{i}").container(cpu="100m").obj()
            client.create_pod(p)
            server.bind(Binding(
                pod_namespace="default", pod_name=p.metadata.name,
                pod_uid=p.metadata.uid, target_node=f"n{i}",
            ))
        for i in range(2):
            client.create_pod(
                make_pod(f"inflight-{i}").container(cpu="100m").obj()
            )
        for i in range(3):
            client.create_node(
                make_node(f"n{i}").capacity(cpu="8", memory="16Gi").obj()
            )
        informers = InformerFactory(server)
        sched = new_scheduler(client, informers, batch=False)
        informers.pump()
        a0 = metrics.pods_adopted_on_restart.value()
        r0 = metrics.pods_requeued_on_restart.value()
        report = recover_on_startup(sched, client)
        assert report.adopted == 3
        assert report.requeued == 2
        assert report.healed == 0  # the informer sync already adopted
        assert metrics.pods_adopted_on_restart.value() == a0 + 3
        assert metrics.pods_requeued_on_restart.value() == r0 + 2
        assert sched.cache.pod_count() == 3

    def test_heals_bound_pod_missed_by_sync(self):
        server = APIServer()
        client = Client(server)
        p = make_pod("missed").container(cpu="100m").obj()
        client.create_pod(p)
        server.bind(Binding(
            pod_namespace="default", pod_name="missed",
            pod_uid=p.metadata.uid, target_node="n0",
        ))
        informers = InformerFactory(server)
        sched = new_scheduler(client, informers, batch=False)
        # NO informer pump: simulate the sync miss
        report = recover_on_startup(sched, client)
        assert report.adopted == 1 and report.healed == 1
        assert sched.cache.pod_count() == 1

    def test_foreign_scheduler_pods_not_requeued(self):
        server = APIServer()
        client = Client(server)
        p = make_pod("other").container(cpu="100m").obj()
        p.spec.scheduler_name = "someone-elses-scheduler"
        client.create_pod(p)
        informers = InformerFactory(server)
        sched = new_scheduler(client, informers, batch=False)
        informers.pump()
        report = recover_on_startup(sched, client)
        assert report.requeued == 0
