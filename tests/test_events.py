"""API event recorder: Scheduled / FailedScheduling / Preempted Event
objects stored and listable via the apiserver (reference profile.go:39
Recorder; scheduler.go:378, :544).
"""

import time

from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client.client import Client
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.scheduler.scheduler import new_scheduler
from kubernetes_tpu.testing import make_node, make_pod


def _cluster(max_batch=16):
    server = APIServer()
    client = Client(server)
    informers = InformerFactory(server)
    sched = new_scheduler(client, informers, batch=True, max_batch=max_batch)
    return server, client, informers, sched


def _events_by_reason(client, reason):
    events, _ = client.list_events()
    return [e for e in events if e.reason == reason]


def test_scheduled_and_failed_events():
    server, client, informers, sched = _cluster()
    client.create_node(make_node("n").capacity(cpu="2", memory="4Gi").obj())
    informers.start()
    informers.wait_for_cache_sync()
    sched.queue.run()
    client.create_pod(make_pod("fits").container(cpu="1").obj())
    client.create_pod(make_pod("toobig").container(cpu="64").obj())
    sched.start()
    deadline = time.time() + 15
    while time.time() < deadline:
        sched.event_broadcaster.flush()
        if _events_by_reason(client, "Scheduled") and _events_by_reason(
            client, "FailedScheduling"
        ):
            break
        time.sleep(0.05)
    sched.stop()
    informers.stop()

    scheduled = _events_by_reason(client, "Scheduled")
    assert scheduled, "no Scheduled event recorded"
    ev = scheduled[0]
    assert ev.involved_object.name == "fits"
    assert ev.type == "Normal"
    assert ev.source == "default-scheduler"
    assert "Successfully assigned default/fits to n" in ev.message

    failed = _events_by_reason(client, "FailedScheduling")
    assert failed, "no FailedScheduling event recorded"
    assert failed[0].involved_object.name == "toobig"
    assert failed[0].type == "Warning"


def test_failed_scheduling_aggregates_count():
    server, client, informers, sched = _cluster()
    client.create_node(make_node("n").capacity(cpu="1", memory="1Gi").obj())
    informers.start()
    informers.wait_for_cache_sync()
    sched.queue.run()
    client.create_pod(make_pod("big").container(cpu="64").obj())
    sched.start()
    deadline = time.time() + 20
    count = 0
    while time.time() < deadline:
        # repeated retries (backoff flush) re-fail the same pod
        sched.queue.move_all_to_active_or_backoff_queue("test")
        sched.event_broadcaster.flush()
        failed = _events_by_reason(client, "FailedScheduling")
        if failed and failed[0].count >= 2:
            count = failed[0].count
            break
        time.sleep(0.1)
    sched.stop()
    informers.stop()
    assert count >= 2
    # aggregation: repeats bumped count instead of new objects
    assert len(_events_by_reason(client, "FailedScheduling")) == 1


def test_preempted_event_on_victim():
    server, client, informers, sched = _cluster()
    client.create_node(make_node("n").capacity(cpu="2", memory="4Gi").obj())
    informers.start()
    informers.wait_for_cache_sync()
    sched.queue.run()
    client.create_pod(
        make_pod("victim").container(cpu="2").priority(0).obj()
    )
    sched.start()
    deadline = time.time() + 15
    while time.time() < deadline:
        pods, _ = client.list_pods()
        if any(p.spec.node_name for p in pods):
            break
        time.sleep(0.05)
    client.create_pod(
        make_pod("high").container(cpu="2").priority(100).obj()
    )
    deadline = time.time() + 20
    while time.time() < deadline:
        sched.event_broadcaster.flush()
        if _events_by_reason(client, "Preempted"):
            break
        time.sleep(0.05)
    sched.stop()
    informers.stop()
    preempted = _events_by_reason(client, "Preempted")
    assert preempted, "no Preempted event recorded"
    assert preempted[0].involved_object.name == "victim"
    assert "Preempted by default/high on node n" in preempted[0].message
