"""Chaos e2e for the solver degradation ladder (marked fast): a churn
workload under injected device-solve failures, forced solve timeouts,
garbage results, a bind-conflict burst, and watch drops. The
availability contract under test: every pod still binds, nothing
crashes, and the degradation is observable -- breaker
open -> half-open -> closed transitions and per-tier fallback counts
appear in metrics."""

import threading
import time

import pytest

from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client.client import Client
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.robustness.circuit import CLOSED, RetryPolicy
from kubernetes_tpu.robustness.faults import (
    FaultInjector,
    FaultPoint,
    FaultProfile,
    PointConfig,
    install_injector,
)
from kubernetes_tpu.robustness.ladder import (
    RobustnessConfig,
    TIER_XLA,
)
from kubernetes_tpu.scheduler.scheduler import new_scheduler
from kubernetes_tpu.testing import make_node, make_pod
from kubernetes_tpu.utils import metrics


@pytest.fixture(autouse=True)
def _clean_injector():
    yield
    install_injector(None)


@pytest.fixture
def thread_crashes(monkeypatch):
    """Capture uncaught exceptions on ANY thread: 'zero unhandled
    exceptions' is an assertion, not a hope."""
    crashes = []
    monkeypatch.setattr(
        threading, "excepthook", lambda args: crashes.append(args)
    )
    return crashes


def _mk_cluster(num_nodes=64, max_batch=128):
    server = APIServer()
    client = Client(server)
    informers = InformerFactory(server)
    sched = new_scheduler(
        client, informers, batch=True, max_batch=max_batch,
        robustness_config=RobustnessConfig(
            solve_timeout_seconds=5.0,
            failure_threshold=2,
            cooloff_seconds=0.3,
            probe_batches=1,
            retry=RetryPolicy(
                max_attempts=3, backoff_seconds=0.01,
                max_backoff_seconds=0.05,
            ),
        ),
    )
    for i in range(num_nodes):
        client.create_node(
            make_node(f"node-{i}")
            .capacity(cpu="32", memory="64Gi", pods=110)
            .obj()
        )
    informers.start()
    informers.wait_for_cache_sync()
    sched.queue.run()
    return server, client, informers, sched


def _wait_bound(client, names, timeout):
    deadline = time.time() + timeout
    outstanding = set(names)
    while time.time() < deadline and outstanding:
        pods, _ = client.list_pods()
        bound = {p.metadata.name for p in pods if p.spec.node_name}
        outstanding -= bound
        if outstanding:
            time.sleep(0.1)
    return outstanding


class TestChaosChurn:
    def test_churn_binds_everything_under_chaos(self, thread_crashes):
        """The acceptance shape: 1k-pod churn with 20% device-solve
        failures + injected solve timeouts + one bind-conflict burst +
        garbage results -- 100% of pods bind, no unhandled exceptions,
        and the metrics show a full breaker cycle and per-tier fallback
        counts."""
        server, client, informers, sched = _mk_cluster()
        install_injector(FaultInjector(FaultProfile(
            "chaos-e2e", seed=1234,
            points={
                # 20% of device solves raise; heals after 24 fires
                FaultPoint.DEVICE_SOLVE: PointConfig(rate=0.2, max_fires=24),
                # a few solves hang past the 5s watchdog deadline
                FaultPoint.DEVICE_SOLVE_HANG: PointConfig(
                    rate=0.08, max_fires=2, hang_seconds=8.0
                ),
                # a few downloads return garbage indices
                FaultPoint.SOLVE_GARBAGE: PointConfig(
                    rate=0.1, max_fires=4
                ),
                # one bind-conflict burst (absorbed by bind retry)
                FaultPoint.BIND_CONFLICT: PointConfig(
                    rate=1.0, max_fires=2
                ),
                # the pod watch stream drops occasionally
                FaultPoint.WATCH_DROP: PointConfig(
                    rate=0.02, max_fires=3
                ),
            },
        )))
        faults_before = {
            p: metrics.faults_injected.value(point=p)
            for p in FaultPoint.ALL
        }

        sched.start()
        # churn: three waves of creates with a delete burst in between
        names = []
        for i in range(400):
            names.append(f"w1-{i}")
            client.create_pod(
                make_pod(f"w1-{i}").container(cpu="250m", memory="512Mi")
                .obj()
            )
        assert not _wait_bound(client, names, 120), "wave 1 did not bind"
        # delete a slice (churn), then two more waves
        for i in range(0, 100):
            client.delete_pod("default", f"w1-{i}")
        names2 = []
        for w, count in (("w2", 300), ("w3", 300)):
            for i in range(count):
                names2.append(f"{w}-{i}")
                client.create_pod(
                    make_pod(f"{w}-{i}")
                    .container(cpu="250m", memory="512Mi").obj()
                )
        assert not _wait_bound(client, names2, 120), "churn waves did not bind"
        sched.wait_for_inflight_binds()

        # -- availability: 100% of live pods bound, nothing crashed ------
        pods, _ = client.list_pods()
        unbound = [p.metadata.name for p in pods if not p.spec.node_name]
        assert not unbound, f"unbound after chaos: {unbound[:10]}"
        assert not thread_crashes, [str(c.exc_value) for c in thread_crashes]

        # -- the chaos actually happened --------------------------------
        assert (
            metrics.faults_injected.value(point=FaultPoint.DEVICE_SOLVE)
            > faults_before[FaultPoint.DEVICE_SOLVE]
        )
        assert (
            metrics.faults_injected.value(point=FaultPoint.BIND_CONFLICT)
            > faults_before[FaultPoint.BIND_CONFLICT]
        )

        # -- degradation is observable: per-tier fallback counts ---------
        fallback_lines = [
            line for line in metrics.solver_fallbacks.collect()
            if not line.startswith("#")
        ]
        assert fallback_lines, "no solver_fallback_total samples"
        # at least one batch was handled below the device tier
        assert any(
            t != TIER_XLA and n > 0
            for t, n in sched.ladder.solves_by_tier.items()
        ) or sched.pods_fallback > 0

        # -- force one DETERMINISTIC full breaker cycle ------------------
        # (the seeded 20% stream makes transitions likely, not certain:
        # drive closed -> open -> half-open -> closed explicitly)
        # heal first: chaos may have left the breaker open/half-open --
        # clean batches walk it back to closed via the probe path
        install_injector(None)
        deadline = time.time() + 20
        i = 0
        while (
            sched.ladder.breakers[TIER_XLA].state != CLOSED
            and time.time() < deadline
        ):
            client.create_pod(
                make_pod(f"heal-{i}").container(cpu="100m").obj()
            )
            _wait_bound(client, [f"heal-{i}"], 10)
            i += 1
            time.sleep(0.2)
        assert sched.ladder.breakers[TIER_XLA].state == CLOSED
        t0 = {
            (f, t): metrics.breaker_transitions.value(
                tier=TIER_XLA, from_state=f, to_state=t
            )
            for f, t in (
                ("closed", "open"), ("open", "half_open"),
                ("half_open", "closed"),
            )
        }
        install_injector(FaultInjector(FaultProfile(
            "force-cycle", seed=0,
            points={
                FaultPoint.DEVICE_SOLVE: PointConfig(rate=1.0, max_fires=6)
            },
        )))
        # 6 fires / 3 retry attempts = 2 consecutive tier failures =
        # failure_threshold -> the xla breaker opens; both batches still
        # complete via the host tier
        for i in range(2):
            client.create_pod(
                make_pod(f"cycle-a{i}").container(cpu="100m").obj()
            )
            assert not _wait_bound(client, [f"cycle-a{i}"], 30)
        deadline = time.time() + 10
        while (
            metrics.breaker_transitions.value(
                tier=TIER_XLA, from_state="closed", to_state="open"
            ) <= t0[("closed", "open")]
            and time.time() < deadline
        ):
            time.sleep(0.05)
        time.sleep(0.4)  # past cool-off: next batch is the probe
        client.create_pod(make_pod("cycle-probe").container(cpu="100m").obj())
        assert not _wait_bound(client, ["cycle-probe"], 30)
        deadline = time.time() + 10
        while (
            sched.ladder.breakers[TIER_XLA].state != CLOSED
            and time.time() < deadline
        ):
            time.sleep(0.05)
        assert (
            metrics.breaker_transitions.value(
                tier=TIER_XLA, from_state="closed", to_state="open"
            ) > t0[("closed", "open")]
        ), "breaker never opened"
        assert (
            metrics.breaker_transitions.value(
                tier=TIER_XLA, from_state="open", to_state="half_open"
            ) > t0[("open", "half_open")]
        ), "breaker never half-opened"
        assert (
            metrics.breaker_transitions.value(
                tier=TIER_XLA, from_state="half_open", to_state="closed"
            ) > t0[("half_open", "closed")]
        ), "breaker never closed after probe"
        assert not thread_crashes, [str(c.exc_value) for c in thread_crashes]

        sched.stop()
        informers.stop()
        assert not sched.commit_degraded

    def test_device_down_everything_still_binds(self, thread_crashes):
        """The floor of the ladder: EVERY device solve fails, the host
        tiers carry the whole workload."""
        server, client, informers, sched = _mk_cluster(
            num_nodes=16, max_batch=64
        )
        install_injector(FaultInjector(FaultProfile(
            "device-down", seed=0,
            points={FaultPoint.DEVICE_SOLVE: PointConfig(rate=1.0)},
        )))
        sched.start()
        names = [f"p{i}" for i in range(120)]
        for n in names:
            client.create_pod(
                make_pod(n).container(cpu="100m", memory="128Mi").obj()
            )
        assert not _wait_bound(client, names, 60)
        sched.wait_for_inflight_binds()
        assert not thread_crashes, [str(c.exc_value) for c in thread_crashes]
        # the device tier never completed a solve; the host tiers did
        assert sched.ladder.solves_by_tier["host_greedy"] > 0
        assert sched.ladder.solves_by_tier[TIER_XLA] == 0
        sched.stop()
        informers.stop()
