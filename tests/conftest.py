"""Test configuration.

Tests run on a virtual 8-device CPU mesh so multi-chip sharding paths are
exercised without TPU hardware (the driver separately dry-runs the
multi-chip path via __graft_entry__.dryrun_multichip). The env vars must
be set before jax initializes its backends; additionally the installed
axon TPU plugin force-prepends itself to jax_platforms regardless of
JAX_PLATFORMS, so the config is also pinned programmatically.
"""

import os
import sys

# -- tier-0 syntax gate --------------------------------------------------
# ast-parse the whole tree before pytest collects anything: an
# uncollectable module then fails the run fast with its file name
# instead of 21 opaque collection errors (tools/check_syntax.py).
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_REPO_ROOT, "tools"))
import check_syntax  # noqa: E402

_syntax_failures = check_syntax.check_tree(base_dir=_REPO_ROOT)
# native-extension probe (tier-0 like the ast gate): the extension must
# either build+import whole or degrade to the pure-Python twins cleanly
# (hotpath None, ingest plane inactive, fallbacks counted) -- a crash or
# a half-exported stale .so fails the run here, with a name, instead of
# surfacing as dozens of opaque test failures
_syntax_failures += check_syntax.probe_native_extension(base_dir=_REPO_ROOT)
if _syntax_failures:
    _lines = "\n".join(f"  {p}: {e}" for p, e in _syntax_failures)
    raise SystemExit(
        f"tier-0 syntax gate failed ({len(_syntax_failures)} file(s) do "
        f"not parse on Python {sys.version.split()[0]}):\n{_lines}"
    )

os.environ.setdefault("JAX_PLATFORMS", "cpu")
prev = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in prev:
    os.environ["XLA_FLAGS"] = (
        prev + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    # tier-1 runs with -m 'not slow'; the lifecycle storm e2es opt out
    # of the tier-1 budget via this marker
    config.addinivalue_line(
        "markers", "slow: long-running e2e excluded from tier-1"
    )
