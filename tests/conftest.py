"""Test configuration.

Tests run on a virtual 8-device CPU mesh so multi-chip sharding paths are
exercised without TPU hardware (the driver separately dry-runs the
multi-chip path via __graft_entry__.dryrun_multichip). The env vars must
be set before jax initializes its backends; additionally the installed
axon TPU plugin force-prepends itself to jax_platforms regardless of
JAX_PLATFORMS, so the config is also pinned programmatically.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
prev = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in prev:
    os.environ["XLA_FLAGS"] = (
        prev + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
