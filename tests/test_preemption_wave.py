"""Batched device preemption waves (PR 11): the wave solver ladder
(pallas tier -> jnp twin -> host-oracle floor), the shared
DisruptionController PDB gate with refund-on-deny, nominatedNodeName
end-to-end semantics, drain-via-preemption, and the preemption-chaos
profile.

Covers the ISSUE-11 satellites:
- randomized differential: the device wave (one kernel round trip with
  the in-scan nomination carry) vs the sequential HOST oracle folding
  nominations through the queue (_add_nominated_pods) -- placements and
  victim sets equal per seed, with and without PDB budgets, with
  pre-existing nominated pods;
- tier-1 guard: a saturated 1k-pod burst with a high-priority tail --
  every high-band pod binds, zero PDB overspend (the budget is never
  driven negative in the full watch history), and the device carry
  stays warm across the wave (state_uploads <= 1 after victims commit);
- preemption-chaos e2e: wave-solve faults + a bind-conflict burst +
  slow-dying victims; the storm still binds 100% of the high band with
  exactly-once binds per pod incarnation;
- drain-via-preemption: strictly fewer evictions than the whole-node
  baseline, paced by the same budget;
- metrics book what actually happened (an aborted wave books nothing).
"""

import random
import threading
import time

import pytest

from kubernetes_tpu.api.types import LabelSelector, PodDisruptionBudget
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.cache.cache import SchedulerCache
from kubernetes_tpu.cache.snapshot import Snapshot
from kubernetes_tpu.client.client import Client
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.controllers import DisruptionController, NodeDrainer
from kubernetes_tpu.framework.interface import CycleState, FitError
from kubernetes_tpu.framework.runtime import Framework
from kubernetes_tpu.plugins import new_in_tree_registry
from kubernetes_tpu.queue.scheduling_queue import PriorityQueue
from kubernetes_tpu.robustness.faults import (
    FaultInjector,
    FaultPoint,
    FaultProfile,
    PointConfig,
    builtin_profiles,
    install_injector,
    load_profile,
)
from kubernetes_tpu.robustness.lifecycle import PodRespawner
from kubernetes_tpu.scheduler.generic import GenericScheduler
from kubernetes_tpu.scheduler.preemption import Preemptor
from kubernetes_tpu.scheduler.provider import default_plugins
from kubernetes_tpu.scheduler.scheduler import new_scheduler
from kubernetes_tpu.testing import make_node, make_pod
from kubernetes_tpu.utils import metrics


@pytest.fixture(autouse=True)
def _clean_injector():
    yield
    install_injector(None)


# -- harness ---------------------------------------------------------------


def _env(pods, nodes):
    cache = SchedulerCache()
    for n in nodes:
        cache.add_node(n)
    for p in pods:
        cache.add_pod(p)
    snapshot = Snapshot()
    cache.update_snapshot(snapshot)
    algorithm = GenericScheduler(cache, snapshot)
    fw = Framework(
        new_in_tree_registry(),
        default_plugins(),
        snapshot_provider=lambda: snapshot,
    )
    return algorithm, fw


def _fail(algorithm, fw, pod):
    state = CycleState()
    with pytest.raises(FitError) as exc:
        algorithm.schedule(fw, state, pod)
    return exc.value


def _queue(fw):
    return PriorityQueue(
        fw.queue_sort_less_func(), sort_key_func=fw.queue_sort_key_func()
    )


def _random_cluster(rng, with_pdbs):
    nodes = []
    for i in range(12):
        w = make_node(f"n{i}").capacity(
            cpu=str(rng.choice([2, 4, 8])), memory="16Gi", pods=32
        )
        if rng.random() < 0.2:
            w.label("disk", "ssd")
        if rng.random() < 0.15:
            w.taint("dedicated", "infra")
        nodes.append(w.obj())
    pods = []
    t0 = time.time() - 10_000
    # near-fill every node so the wave always needs victims
    for i, n in enumerate(nodes):
        cap_milli = n.status.allocatable["cpu"]
        p = (
            make_pod(f"fill{i}")
            .node(n.metadata.name)
            # leave <1000m free so every wave pod (>=1000m) must preempt
            .container(cpu=f"{cap_milli - 500}m", memory="8Gi")
            .labels(app=rng.choice(["a", "b", "c"]))
            .priority(rng.choice([0, 5]))
            .obj()
        )
        p.status.start_time = t0 + rng.randrange(10_000)
        pods.append(p)
    for j in range(30):
        node = f"n{rng.randrange(12)}"
        p = (
            make_pod(f"p{j}")
            .node(node)
            .container(
                cpu=f"{rng.choice([250, 500, 1000, 2000])}m",
                memory=f"{rng.choice([128, 512, 1024])}Mi",
            )
            .labels(app=rng.choice(["a", "b", "c"]))
            .priority(rng.choice([0, 0, 5, 10, 50]))
            .obj()
        )
        p.status.start_time = t0 + rng.randrange(10_000)
        pods.append(p)
    pdbs = []
    if with_pdbs:
        for app, budget in (("a", 1), ("b", 0)):
            pdbs.append(
                PodDisruptionBudget(
                    selector=LabelSelector(match_labels={"app": app}),
                )
            )
            pdbs[-1].status.disruptions_allowed = budget
            pdbs[-1].metadata.name = f"pdb-{app}"
            pdbs[-1].metadata.namespace = "default"
    return nodes, pods, pdbs


def _bind_transitions_by_uid(server):
    """unbound->bound transitions per pod INCARNATION (uid), replayed
    from the full watch history (the PR-6/PR-8 exactly-once harness)."""
    w = server.watch("Pod", since_rv=0)
    node = {}
    transitions = {}
    for ev in w.pending():
        pod = ev.object
        uid = pod.metadata.uid
        if ev.type == "DELETED":
            node.pop(uid, None)
            continue
        prev = node.get(uid, "")
        cur = pod.spec.node_name or ""
        if not prev and cur:
            transitions[uid] = transitions.get(uid, 0) + 1
        node[uid] = cur
    w.stop()
    return transitions


def _pdb_never_negative(server):
    """Replay the FULL PodDisruptionBudget watch history: the
    zero-overspend pin. Every status write the shared can_disrupt gate
    (and the reconcile loop) ever made must leave disruptionsAllowed
    >= 0 -- a negative value is a budget spent past zero."""
    w = server.watch("PodDisruptionBudget", since_rv=0)
    floor = 0
    for ev in w.pending():
        if ev.type == "DELETED":
            continue
        floor = min(floor, ev.object.status.disruptions_allowed)
    w.stop()
    return floor >= 0


# -- profile + config registration ----------------------------------------


def test_preemption_chaos_profile_registered():
    profiles = builtin_profiles()
    assert "preemption-chaos" in profiles
    p = profiles["preemption-chaos"]
    assert FaultPoint.PREEMPT_SOLVE in p.points
    assert FaultPoint.BIND_CONFLICT in p.points
    assert FaultPoint.VICTIM_SLOW_DEATH in p.points
    # slow death needs a grace: the delayed delete must actually land
    assert p.points[FaultPoint.VICTIM_SLOW_DEATH].hang_seconds > 0
    # every point heals: bounded fires so a chaos run converges
    assert all(c.max_fires is not None for c in p.points.values())
    assert load_profile("preemption-chaos", seed=7).seed == 7


def test_preemption_chaos_profile_validates_in_config():
    from kubernetes_tpu.config.loader import load_config_from_dict
    from kubernetes_tpu.config.validation import validate_config

    cfg = load_config_from_dict(
        {
            "faultInjection": {
                "enabled": True,
                "profile": "preemption-chaos",
                "seed": 3,
            }
        }
    )
    assert validate_config(cfg) == []
    bad = load_config_from_dict(
        {
            "faultInjection": {
                "enabled": True,
                "profile": "preemption-chaos-typo",
            }
        }
    )
    errs = validate_config(bad)
    assert any("preemption-chaos-typo" in e for e in errs)


# -- randomized differential: wave kernel vs host oracle -------------------


@pytest.mark.parametrize("seed", range(3))
@pytest.mark.parametrize("with_pdbs", [False, True])
def test_wave_matches_host_oracle(seed, with_pdbs):
    """The whole WAVE -- priority-desc failed-pod group, in-scan
    nomination carry, pre-existing nominated pods -- against the
    sequential host oracle folding every nomination through the queue.
    Placement and victim sets must be equal per pod."""
    rng = random.Random(seed)
    nodes, pods, pdbs = _random_cluster(rng, with_pdbs)
    algorithm, fw = _env(pods, nodes)

    # pre-existing nominations: two pending pods virtually occupying
    # capacity (one big enough to matter, one tiny)
    nominated = []
    for i, (cpu, prio) in enumerate((("1", 90), ("250m", 60))):
        np_ = (
            make_pod(f"nom{i}")
            .container(cpu=cpu, memory="256Mi")
            .priority(prio)
            .obj()
        )
        nominated.append((np_, f"n{rng.randrange(12)}"))

    # the wave: priority-desc failed pods of mixed shapes
    wave = []
    for j in range(6):
        wave.append(
            make_pod(f"wave{j}")
            .container(
                cpu=f"{rng.choice([1000, 1500, 2000])}m",
                memory=f"{rng.choice([512, 1024])}Mi",
            )
            .priority(rng.choice([100, 80, 80, 40]))
            .obj()
        )
    wave.sort(key=lambda p: -p.spec.priority)
    items = [(p, _fail(algorithm, fw, p)) for p in wave]

    # -- device wave ------------------------------------------------------
    queue_dev = _queue(fw)
    for np_, node in nominated:
        queue_dev.update_nominated_pod_for_node(np_, node)
    dev = Preemptor(algorithm, queue_dev, None)
    pot_cache = {}
    pot_list = []
    for p, fe in items:
        key = id(fe.filtered_nodes_statuses)
        if key not in pot_cache:
            pot_cache[key] = dev.nodes_where_preemption_might_help(fe)
        pot_list.append(pot_cache[key])
    answers, tier = dev._device_answers(
        [p for p, _ in items], pot_list, pdbs
    )
    assert tier in ("pallas", "xla")

    # -- host oracle with the queue nomination fold -----------------------
    queue_host = _queue(fw)
    for np_, node in nominated:
        queue_host.update_nominated_pod_for_node(np_, node)
    algorithm.nominated_pods_lister = queue_host
    try:
        host = Preemptor(algorithm, queue_host, None)
        expected = host._host_wave_answers(fw, items, pdbs)
    finally:
        algorithm.nominated_pods_lister = None

    for k, ((dn, dv, _), (hn, hv, _)) in enumerate(zip(answers, expected)):
        assert dn == hn, f"pod {k}: device {dn!r} != host {hn!r}"
        assert {p.metadata.name for p in dv} == {
            p.metadata.name for p in hv
        }, f"pod {k}: victim sets differ on {dn}"


def test_wave_breaker_falls_back_to_jnp_twin():
    """A faulted wave solve charges the tier's breaker and the SAME
    dispatch completes on the next tier; with every device tier down the
    host-oracle floor still answers (and books the host tier)."""
    rng = random.Random(5)
    nodes, pods, pdbs = _random_cluster(rng, False)
    algorithm, fw = _env(pods, nodes)
    queue = _queue(fw)
    algorithm.nominated_pods_lister = queue
    try:
        pre = Preemptor(algorithm, queue, None)
        wave = [
            make_pod(f"w{j}").container(cpu="1500m", memory="512Mi")
            .priority(100).obj()
            for j in range(3)
        ]
        items = [(p, _fail(algorithm, fw, p)) for p in wave]
        pots = [pre.nodes_where_preemption_might_help(items[0][1])] * 3

        # fault EVERY device attempt: on CPU only the jnp twin is
        # offered, so the ladder exhausts and the floor answers
        install_injector(FaultInjector(FaultProfile(
            name="wave-down", seed=0,
            points={FaultPoint.PREEMPT_SOLVE: PointConfig(rate=1.0)},
        )))
        from kubernetes_tpu.robustness.ladder import LadderExhausted

        with pytest.raises(LadderExhausted):
            pre._device_answers([p for p, _ in items], pots, pdbs)
        # the wave driver's floor: host answers with the queue fold
        answers = pre._host_wave_answers(fw, items, pdbs)
        assert any(node for node, _, _ in answers)

        # faults healed: the twin answers again (one ladder failure is
        # below the default breaker threshold of 3, so the tier stayed
        # closed) and agrees with the host floor
        install_injector(None)
        answers2, tier2 = pre._device_answers(
            [p for p, _ in items], pots, pdbs
        )
        assert tier2 in ("pallas", "xla")
        assert [a[0] for a in answers] == [a[0] for a in answers2]
    finally:
        algorithm.nominated_pods_lister = None


# -- metrics book what actually happened -----------------------------------


class _StubProf:
    def get_waiting_pod(self, uid):
        return None

    recorder = None


def test_aborted_wave_books_no_victims(monkeypatch):
    """An eviction transaction that fails books NOTHING: no victim
    counters, budget refunded, None sentinel so callers requeue with
    backoff (the PR-5 count-what-actually-happened rule)."""
    rng = random.Random(11)
    nodes, pods, _ = _random_cluster(rng, False)

    server = APIServer()
    client = Client(server)
    for n in nodes:
        client.create_node(n)
    for p in pods:
        client.create_pod(p)
    informers = InformerFactory(server)
    algorithm, fw = _env(pods, nodes)
    queue = _queue(fw)
    dc = DisruptionController(client, informers)
    pdb = PodDisruptionBudget(
        selector=LabelSelector(match_labels={"app": "a"}),
        max_unavailable=50,
    )
    pdb.metadata.name = "budget"
    pdb.metadata.namespace = "default"
    client.create_pdb(pdb)
    informers.start()
    informers.wait_for_cache_sync()
    dc.sync_all()
    budget0 = client.list_pdbs()[0][0].status.disruptions_allowed
    assert budget0 > 0

    pre = Preemptor(algorithm, queue, client, disruption=dc)
    wave = [
        make_pod(f"w{j}").container(cpu="1500m", memory="512Mi")
        .priority(100).obj()
        for j in range(2)
    ]
    for p in wave:
        client.create_pod(p)
    items = [(p, _fail(algorithm, fw, p)) for p in wave]

    def boom(keys, missing_out=None):
        raise RuntimeError("api down")

    monkeypatch.setattr(client, "delete_pods_bulk", boom)
    v0 = dict(pre.victims_by_tier)
    selected0 = metrics.victims_selected.value(tier="xla")
    results, uids = pre.preempt_batch(_StubProf(), items)
    assert uids is None  # transaction failed: backoff sentinel
    assert pre.victims_by_tier == v0  # nothing booked
    assert metrics.victims_selected.value(tier="xla") == selected0
    # every grant refunded: the budget is exactly where it started
    assert (
        client.list_pdbs()[0][0].status.disruptions_allowed == budget0
    )
    informers.stop()


def test_budget_deny_refunds_and_skips_nomination():
    """A zero-budget PDB over every victim: the wave selects victims but
    the shared gate denies the spend -- no nomination, no eviction, the
    denial counted, sibling-PDB grants refunded, and the budget never
    negative."""
    server = APIServer()
    client = Client(server)
    nodes = [
        make_node(f"n{i}").capacity(cpu="2", memory="8Gi", pods=10).obj()
        for i in range(3)
    ]
    pods = []
    for i, n in enumerate(nodes):
        p = (
            make_pod(f"fill{i}").node(n.metadata.name)
            .container(cpu="2", memory="1Gi")
            .labels(app="guarded").priority(0).obj()
        )
        p.status.start_time = time.time() - 100
        pods.append(p)
    for n in nodes:
        client.create_node(n)
    for p in pods:
        client.create_pod(p)
    informers = InformerFactory(server)
    algorithm, fw = _env(pods, nodes)
    queue = _queue(fw)
    dc = DisruptionController(client, informers)
    pdb = PodDisruptionBudget(
        selector=LabelSelector(match_labels={"app": "guarded"}),
        min_available=3,  # every pod protected: zero budget
    )
    pdb.metadata.name = "frozen"
    pdb.metadata.namespace = "default"
    client.create_pdb(pdb)
    informers.start()
    informers.wait_for_cache_sync()
    dc.sync_all()
    assert client.list_pdbs()[0][0].status.disruptions_allowed == 0

    pre = Preemptor(algorithm, queue, client, disruption=dc)
    high = make_pod("high").container(cpu="1").priority(100).obj()
    client.create_pod(high)
    fe = _fail(algorithm, fw, high)
    denials0 = pre.budget_denials
    # the kernel models the zero budget (victims go violating-first,
    # reference last-resort semantics) and still proposes a node; the
    # shared gate is the last line of defense that actually refuses to
    # spend past zero -- nomination and eviction must both be dropped
    results, uids = pre.preempt_batch(_StubProf(), [(high, fe)])
    assert results == [""]  # no nomination survived the deny
    assert uids == []
    assert pre.budget_denials == denials0 + 1
    assert queue.nominated_pods_for_node("n0") == []
    # nothing evicted, budget intact and never negative
    assert len(client.list_pods()[0]) == 4
    assert client.list_pdbs()[0][0].status.disruptions_allowed == 0
    assert _pdb_never_negative(server)
    informers.stop()


# -- nominatedNodeName end-to-end ------------------------------------------


def test_nominations_cleared_on_node_delete():
    """Deleting the nominated node clears the nomination (the queue map
    stops reserving phantom capacity) and re-arms the nominee."""
    server = APIServer()
    client = Client(server)
    informers = InformerFactory(server)
    sched = new_scheduler(client, informers, batch=True, max_batch=16)
    for i in range(2):
        client.create_node(
            make_node(f"n{i}").capacity(cpu="2", memory="8Gi").obj()
        )
    informers.start()
    informers.wait_for_cache_sync()
    sched.queue.run()
    pend = make_pod("pend").container(cpu="1").priority(50).obj()
    client.create_pod(pend)
    # park it with a nomination (as a wave would)
    deadline = time.time() + 10
    while sched.queue.active_count() == 0 and time.time() < deadline:
        time.sleep(0.01)
    cleared0 = metrics.nominations_cleared.value()
    sched.queue.update_nominated_pod_for_node(pend, "n1")
    # the API-side status write a wave's record_scheduling_failure makes
    def set_nom(p):
        p.status.nominated_node_name = "n1"

    client.update_pod_status("default", "pend", set_nom)
    assert [p.metadata.name for p in sched.queue.nominated_pods_for_node("n1")]
    client.delete_node("n1")
    deadline = time.time() + 10
    while (
        sched.queue.nominated_pods_for_node("n1")
        and time.time() < deadline
    ):
        time.sleep(0.01)
    assert sched.queue.nominated_pods_for_node("n1") == []
    assert metrics.nominations_cleared.value() >= cleared0 + 1
    # the API status cleared too -- otherwise the queue map re-installs
    # the phantom reservation from status on the next update echo
    deadline = time.time() + 10
    while (
        client.get_pod("default", "pend").status.nominated_node_name
        and time.time() < deadline
    ):
        time.sleep(0.01)
    assert client.get_pod("default", "pend").status.nominated_node_name == ""
    # poke an update through the informer: the re-add must NOT resurrect
    client.update_pod_status("default", "pend", lambda p: None)
    deadline = time.time() + 2
    while time.time() < deadline:
        if sched.queue.nominated_pods_for_node("n1"):
            break
        time.sleep(0.01)
    assert sched.queue.nominated_pods_for_node("n1") == []
    sched.stop()
    informers.stop()


# -- tier-1 guard: saturated burst + high-priority tail --------------------


def _e2e(num_nodes, node_cpu, pods_cap=32, max_batch=256):
    server = APIServer()
    client = Client(server)
    informers = InformerFactory(server)
    sched = new_scheduler(client, informers, batch=True, max_batch=max_batch)
    for i in range(num_nodes):
        client.create_node(
            make_node(f"n{i}")
            .capacity(cpu=node_cpu, memory="64Gi", pods=pods_cap)
            .obj()
        )
    return server, client, informers, sched


def _wait_named_bound(client, names, deadline_s):
    deadline = time.time() + deadline_s
    names = set(names)
    while time.time() < deadline:
        pods, _ = client.list_pods()
        bound = {
            p.metadata.name
            for p in pods
            if p.metadata.name in names and p.spec.node_name
        }
        if bound == names:
            return True
        time.sleep(0.05)
    return False


def test_high_priority_tail_guard():
    """Tier-1 guard: 1k low-priority pods saturate the cluster; a
    40-pod high-priority tail must ALL bind via the batched wave, with
    zero PDB overspend (full watch-history pin), no budget denials
    (ample budget), and the device carry warm across the wave
    (state_uploads <= 1 after the victims commit)."""
    server, client, informers, sched = _e2e(50, "20", pods_cap=40)
    dc = DisruptionController(client, informers)
    sched.preemptor.disruption = dc
    pdb = PodDisruptionBudget(
        selector=LabelSelector(match_labels={"app": "low"}),
        max_unavailable=80,
    )
    pdb.metadata.name = "tail-budget"
    pdb.metadata.namespace = "default"
    client.create_pdb(pdb)
    informers.start()
    informers.wait_for_cache_sync()
    dc.start()
    sched.queue.run()
    try:
        low_names = [f"low-{i}" for i in range(1000)]
        for nm in low_names:
            client.create_pod(
                make_pod(nm).container(cpu="1", memory="128Mi")
                .labels(app="low").priority(0).obj()
            )
        sched.start()
        assert _wait_named_bound(client, low_names, 120), (
            "saturating burst never fully bound"
        )
        sched.wait_for_inflight_binds(timeout=60)

        uploads0 = sched.state_uploads
        denials0 = sched.preemptor.budget_denials
        blocked0 = metrics.evictions_blocked_by_pdb.value()

        high_names = [f"high-{i}" for i in range(40)]
        for nm in high_names:
            client.create_pod(
                make_pod(nm).container(cpu="1", memory="128Mi")
                .priority(100).obj()
            )
        assert _wait_named_bound(client, high_names, 120), (
            "high-priority tail did not fully bind"
        )
        sched.wait_for_inflight_binds(timeout=60)

        # the wave ran on device and booked its victims by tier
        assert sched.preemptor.waves >= 1
        assert sum(sched.preemptor.victims_by_tier.values()) >= 40
        # budget consistency: ample budget => zero denials, zero blocks,
        # and the full watch history never shows a negative budget
        assert sched.preemptor.budget_denials == denials0
        assert metrics.evictions_blocked_by_pdb.value() == blocked0
        assert _pdb_never_negative(server)
        # warm carry: victims ride the delta scatter, never a repack
        assert sched.state_uploads - uploads0 <= 1, (
            f"preemption wave forced {sched.state_uploads - uploads0} "
            "state uploads"
        )
        # exactly-once binds per incarnation over the whole run
        transitions = _bind_transitions_by_uid(server)
        doubles = {u: c for u, c in transitions.items() if c > 1}
        assert not doubles, f"double-bound incarnations: {doubles}"
    finally:
        sched.stop()
        dc.stop()
        informers.stop()


# -- preemption-chaos e2e --------------------------------------------------


def test_preemption_chaos_storm_e2e():
    """The acceptance e2e: a priority-inversion storm under
    preemption-chaos (wave-solve faults + a bind-conflict burst +
    slow-dying victims) binds 100% of the high band, with zero PDB
    overspend and exactly-once binds per pod incarnation."""
    # seed 10: the PREEMPT_SOLVE stream fires on its very first draw
    # (the first wave pays an in-place retry / twin fallback) and the
    # VICTIM_SLOW_DEATH stream fires within the storm's victim count
    injector = FaultInjector(load_profile("preemption-chaos", seed=10))
    install_injector(injector)
    server, client, informers, sched = _e2e(16, "4", pods_cap=12)
    dc = DisruptionController(client, informers)
    sched.preemptor.disruption = dc
    pdb = PodDisruptionBudget(
        selector=LabelSelector(match_labels={"app": "low"}),
        max_unavailable=60,
    )
    pdb.metadata.name = "storm-budget"
    pdb.metadata.namespace = "default"
    client.create_pdb(pdb)
    informers.start()
    informers.wait_for_cache_sync()
    dc.start()
    sched.queue.run()
    try:
        low_names = [f"low-{i}" for i in range(64)]
        for nm in low_names:
            client.create_pod(
                make_pod(nm).container(cpu="1", memory="128Mi")
                .labels(app="low").priority(0).obj()
            )
        sched.start()
        assert _wait_named_bound(client, low_names, 60)
        sched.wait_for_inflight_binds(timeout=60)

        # the inversion storm: a low-priority flood arrives WITH the
        # high band (the flood can never place -- the cluster is full
        # and it cannot preempt equals), so the high band must cut
        # through it via the wave
        high_names = [f"high-{i}" for i in range(24)]
        for i in range(24):
            client.create_pod(
                make_pod(f"noise-{i}").container(cpu="1", memory="128Mi")
                .labels(app="low").priority(0).obj()
            )
            client.create_pod(
                make_pod(high_names[i]).container(cpu="1", memory="128Mi")
                .priority(100).obj()
            )
        assert _wait_named_bound(client, high_names, 120), (
            "high band did not fully bind under preemption-chaos"
        )
        sched.wait_for_inflight_binds(timeout=60)

        # the chaos actually happened
        assert injector.fired_count(FaultPoint.PREEMPT_SOLVE) >= 1
        assert injector.fired_count(FaultPoint.VICTIM_SLOW_DEATH) >= 1
        assert sched.preemptor.waves >= 1
        assert sched.preemptor.victims_slow_death >= 1
        # zero PDB overspend across the full history
        assert _pdb_never_negative(server)
        # exactly-once binds per pod incarnation
        transitions = _bind_transitions_by_uid(server)
        doubles = {u: c for u, c in transitions.items() if c > 1}
        assert not doubles, f"double-bound incarnations: {doubles}"
    finally:
        sched.stop()
        dc.stop()
        informers.stop()


# -- drain-via-preemption --------------------------------------------------


def test_drain_via_preemption_evicts_strictly_fewer():
    """Drain a node whose residents only PARTIALLY fit elsewhere: the
    kernel-planned drain evicts exactly the placeable pods (strictly
    fewer than the whole-node baseline), leaves the rest RUNNING on the
    cordoned node, and paces every eviction through the shared PDB
    budget as replacements land."""
    server, client, informers, sched = _e2e(1, "8", pods_cap=20)
    # receivers: 3 cpu of spare capacity in total (plus the 100m the
    # snapshot-freshening warm pod pins onto r1)
    client.create_node(
        make_node("r1").capacity(cpu="2100m", memory="16Gi", pods=10)
        .label("kubernetes.io/hostname", "r1").obj()
    )
    client.create_node(
        make_node("r2").capacity(cpu="1", memory="16Gi", pods=10).obj()
    )
    dc = DisruptionController(client, informers)
    sched.preemptor.disruption = dc
    pdb = PodDisruptionBudget(
        selector=LabelSelector(match_labels={"app": "drainable"}),
        max_unavailable=1,  # one eviction in flight at a time
    )
    pdb.metadata.name = "drain-budget"
    pdb.metadata.namespace = "default"
    client.create_pdb(pdb)
    # 6 residents bound on the drained node
    for i in range(6):
        p = (
            make_pod(f"res-{i}").node("n0")
            .container(cpu="1", memory="128Mi")
            .labels(app="drainable").priority(0).obj()
        )
        p.status.start_time = time.time() - 100
        client.create_pod(p)
    informers.start()
    informers.wait_for_cache_sync()
    dc.start()
    sched.queue.run()
    respawner = PodRespawner(
        client, should_respawn=lambda p: p.metadata.name.startswith("res-")
    )
    respawner.start()
    try:
        sched.start()
        # freshen the snapshot (an idle scheduler never dispatches);
        # pinned to r1 so the drain ledger below stays deterministic
        client.create_pod(
            make_pod("warm").container(cpu="100m", memory="64Mi")
            .node_selector(**{"kubernetes.io/hostname": "r1"}).obj()
        )
        assert _wait_named_bound(client, ["warm"], 30)
        sched.wait_for_inflight_binds(timeout=30)

        drainer = NodeDrainer(
            client, disruption=dc, preemptor=sched.preemptor
        )
        emptied = drainer.drain_via_preemption("n0", timeout=60)
        baseline = 6  # the whole-node drain would evict every resident
        assert not emptied  # stragglers have no destination
        assert 0 < drainer.evictions < baseline, (
            f"evicted {drainer.evictions} of baseline {baseline}"
        )
        assert drainer.preempt_left_running >= 1
        assert drainer.preempt_planned == drainer.evictions
        # the stragglers still RUN on the cordoned node
        on_node = [
            p for p in client.list_pods()[0]
            if p.spec.node_name == "n0"
            and p.metadata.deletion_timestamp is None
        ]
        assert len(on_node) == baseline - drainer.evictions
        # budget pacing engaged at least once and never overspent
        assert _pdb_never_negative(server)
        # the replacements actually re-placed (the capacity argument)
        deadline = time.time() + 30
        while time.time() < deadline:
            replaced = [
                p for p in client.list_pods()[0]
                if p.metadata.name.startswith("res-")
                and p.spec.node_name in ("r1", "r2")
            ]
            if len(replaced) == drainer.evictions:
                break
            time.sleep(0.05)
        assert len(replaced) == drainer.evictions
    finally:
        respawner.stop()
        sched.stop()
        dc.stop()
        informers.stop()
