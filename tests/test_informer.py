import time

from kubernetes_tpu.apiserver import APIServer
from kubernetes_tpu.client import Client, InformerFactory, ResourceEventHandler
from kubernetes_tpu.testing import make_node, make_pod


def test_informer_pump_list_then_events():
    api = APIServer()
    client = Client(api)
    client.create_pod(make_pod("pre-existing").obj())

    factory = InformerFactory(api)
    added, updated, deleted = [], [], []
    factory.pods().add_event_handler(
        ResourceEventHandler(
            on_add=lambda o: added.append(o.metadata.name),
            on_update=lambda o, n: updated.append(n.metadata.name),
            on_delete=lambda o: deleted.append(o.metadata.name),
        )
    )
    factory.pump()
    assert added == ["pre-existing"]

    client.create_pod(make_pod("live").obj())
    api.guaranteed_update("Pod", "default", "live", lambda p: None)
    client.delete_pod("default", "live")
    factory.pump()
    assert added == ["pre-existing", "live"]
    assert updated == ["live"]
    assert deleted == ["live"]
    # local store reflects state
    assert [p.metadata.name for p in factory.pods().list()] == ["pre-existing"]


def test_filtering_handler_transitions():
    """Assigned/unassigned filter transitions: a pod that becomes assigned
    must be delivered as delete to the unassigned handler and add to the
    assigned handler (reference eventhandlers.go:356-404)."""
    api = APIServer()
    client = Client(api)
    factory = InformerFactory(api)

    unassigned_adds, unassigned_dels, assigned_adds = [], [], []
    factory.pods().add_event_handler(
        ResourceEventHandler(
            filter_func=lambda p: not p.spec.node_name,
            on_add=lambda o: unassigned_adds.append(o.metadata.name),
            on_delete=lambda o: unassigned_dels.append(o.metadata.name),
        )
    )
    factory.pods().add_event_handler(
        ResourceEventHandler(
            filter_func=lambda p: bool(p.spec.node_name),
            on_add=lambda o: assigned_adds.append(o.metadata.name),
        )
    )
    factory.pump()
    client.create_pod(make_pod("p1").obj())
    factory.pump()
    assert unassigned_adds == ["p1"] and assigned_adds == []

    from kubernetes_tpu.api.types import Binding

    client.bind(Binding(pod_namespace="default", pod_name="p1", target_node="n1"))
    factory.pump()
    assert unassigned_dels == ["p1"]
    assert assigned_adds == ["p1"]


def test_informer_threaded_mode():
    api = APIServer()
    client = Client(api)
    factory = InformerFactory(api)
    seen = []
    factory.nodes().add_event_handler(
        ResourceEventHandler(on_add=lambda o: seen.append(o.metadata.name))
    )
    factory.start()
    client.create_node(make_node("n1").obj())
    deadline = time.time() + 2
    while not seen and time.time() < deadline:
        time.sleep(0.01)
    factory.stop()
    assert seen == ["n1"]
