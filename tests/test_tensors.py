"""NodeTensor packing + incremental update tests (the tensor analogue of
the reference's cache_test.go UpdateSnapshot cases)."""

import numpy as np

from kubernetes_tpu.cache.cache import SchedulerCache
from kubernetes_tpu.cache.snapshot import Snapshot, new_snapshot
from kubernetes_tpu.tensors import (
    NodeTensorCache,
    ResourceDims,
    pack_pod_batch,
)
from kubernetes_tpu.tensors.node_tensor import CPU, MEM, PODS
from kubernetes_tpu.testing import make_node, make_pod


def test_pack_basic_resources():
    snap = new_snapshot(
        [make_pod("p").node("n1").container(cpu="500m", memory="1Gi").obj()],
        [make_node("n1").capacity(cpu="4", memory="8Gi", pods=10).obj()],
    )
    nt = NodeTensorCache().update(snap)
    assert nt.num_nodes == 1
    assert nt.capacity == 128  # padded to bucket
    i = nt.row("n1")
    assert nt.allocatable[i, CPU] == 4000
    assert nt.allocatable[i, MEM] == 8 * 1024 * 1024  # KiB
    assert nt.allocatable[i, PODS] == 10
    assert nt.requested[i, CPU] == 500
    assert nt.requested[i, MEM] == 1024 * 1024
    assert nt.requested[i, PODS] == 1
    assert nt.valid[i]
    assert not nt.valid[1]


def test_scalar_resources_get_columns():
    dims = ResourceDims()
    snap = new_snapshot(
        [],
        [
            make_node("g")
            .capacity(cpu="8", memory="16Gi", pods=10, **{"nvidia.com/gpu": 4})
            .obj()
        ],
    )
    nt = NodeTensorCache(dims).update(snap)
    col = dims.column("nvidia.com/gpu")
    # column registered after first pack -> full repack next update
    nt = NodeTensorCache(dims).update(snap)
    assert nt.allocatable[nt.row("g"), col] == 4


def test_incremental_update_only_changed_rows():
    cache = SchedulerCache()
    for i in range(5):
        cache.add_node(make_node(f"n{i}").capacity(cpu="4", memory="8Gi").obj())
    snap = Snapshot()
    cache.update_snapshot(snap)
    tc = NodeTensorCache()
    tc.update(snap)
    assert tc.full_repacks == 1
    repacked_before = tc.rows_repacked

    pod = make_pod("p").node("n2").container(cpu="1").obj()
    cache.add_pod(pod)
    cache.update_snapshot(snap)
    nt = tc.update(snap)
    assert tc.full_repacks == 1  # no membership change
    assert tc.rows_repacked == repacked_before + 1  # only n2 repacked
    assert nt.requested[nt.row("n2"), CPU] == 1000

    # node add => claims a headroom slot in place, NO full repack (the
    # slot layout absorbs membership churn; see test_device_state.py)
    cache.add_node(make_node("n9").capacity(cpu="2", memory="2Gi").obj())
    cache.update_snapshot(snap)
    nt = tc.update(snap)
    assert tc.full_repacks == 1
    assert tc.rows_added == 1
    assert "n9" in nt.names
    assert nt.allocatable[nt.row("n9"), CPU] == 2000
    assert nt.valid[nt.row("n9")]
    assert nt.delta.membership_rows.tolist() == [nt.row("n9")]


def test_topology_encoding():
    tc = NodeTensorCache()
    tc.topology.register_key("zone")
    snap = new_snapshot(
        [],
        [
            make_node("a").labels(zone="z1").obj(),
            make_node("b").labels(zone="z2").obj(),
            make_node("c").obj(),  # no zone
        ],
    )
    nt = tc.update(snap)
    za = nt.topology[nt.row("a"), 0]
    zb = nt.topology[nt.row("b"), 0]
    zc = nt.topology[nt.row("c"), 0]
    assert za != zb and za != 0 and zb != 0
    assert zc == 0  # ABSENT


def test_pod_batch_order_priority_then_fifo():
    pods = [
        make_pod("low").creation_timestamp(1.0).obj(),
        make_pod("high").creation_timestamp(2.0).obj(),
        make_pod("mid-late").creation_timestamp(3.0).obj(),
        make_pod("mid-early").creation_timestamp(2.5).obj(),
    ]
    pods[0].spec.priority = 0
    pods[1].spec.priority = 10
    pods[2].spec.priority = 5
    pods[3].spec.priority = 5
    batch = pack_pod_batch(pods, ResourceDims())
    names = [batch.pods[i].name for i in batch.order]
    assert names == ["high", "mid-early", "mid-late", "low"]


def test_non_zero_defaults_in_batch():
    batch = pack_pod_batch([make_pod("empty").container().obj()], ResourceDims())
    # util/non_zero.go defaults: 100m / 200Mi
    assert batch.non_zero_requests[0, 0] == 100
    assert batch.non_zero_requests[0, 1] == 200 * 1024
    assert batch.requests[0, PODS] == 1


def test_pack_pod_batch_empty():
    from kubernetes_tpu.tensors.node_tensor import ResourceDims, pack_pod_batch

    batch = pack_pod_batch([], ResourceDims())
    assert batch.size == 0
    assert batch.requests.shape == (0, ResourceDims().num_dims)
    assert batch.order.shape == (0,)
