"""Config validation, tpuSolver YAML knobs, legacy Policy translation,
in-suite mesh coverage, and sinkhorn-mode e2e (VERDICT r2 missing #8 +
weak #3/#4).

Reference: apis/config/validation/validation.go, factory.go:239
(createFromConfig), framework/plugins/legacy_registry.go.
"""

import time

import numpy as np
import pytest

import jax
from jax.sharding import Mesh

from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client.client import Client
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.config.loader import load_config_from_dict
from kubernetes_tpu.config.policy import (
    load_policy,
    plugins_from_policy,
    profile_from_policy,
)
from kubernetes_tpu.config.validation import validate_config
from kubernetes_tpu.scheduler.scheduler import (
    new_scheduler,
    new_scheduler_from_config,
)
from kubernetes_tpu.testing import make_node, make_pod


def _wait_bound(client, count, timeout=30.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        pods, _ = client.list_pods()
        if sum(1 for p in pods if p.spec.node_name) >= count:
            return pods
        time.sleep(0.05)
    raise AssertionError("pods not bound in time")


class TestValidation:
    def test_valid_default(self):
        cfg = load_config_from_dict({})
        assert validate_config(cfg) == []

    def test_rejects_bad_percentage(self):
        cfg = load_config_from_dict({"percentageOfNodesToScore": 150})
        assert any("percentageOfNodesToScore" in e for e in validate_config(cfg))

    def test_rejects_bad_solver_mode(self):
        cfg = load_config_from_dict({"tpuSolver": {"solverMode": "hungarian"}})
        assert any("solverMode" in e for e in validate_config(cfg))

    def test_rejects_backoff_inversion(self):
        cfg = load_config_from_dict(
            {"podInitialBackoffSeconds": 20, "podMaxBackoffSeconds": 5}
        )
        assert any("podMaxBackoffSeconds" in e for e in validate_config(cfg))

    def test_rejects_duplicate_profiles(self):
        cfg = load_config_from_dict(
            {"profiles": [{"schedulerName": "a"}, {"schedulerName": "a"}]}
        )
        assert any("unique" in e for e in validate_config(cfg))

    def test_rejects_bad_score_weight(self):
        cfg = load_config_from_dict(
            {
                "profiles": [
                    {
                        "schedulerName": "a",
                        "plugins": {
                            "score": {
                                "enabled": [
                                    {"name": "NodeAffinity", "weight": 0}
                                ]
                            }
                        },
                    }
                ]
            }
        )
        assert any("weight" in e for e in validate_config(cfg))


class TestPolicyTranslation:
    def test_predicates_and_priorities_map(self):
        plugins = plugins_from_policy(
            {
                "predicates": [
                    {"name": "PodFitsResources"},
                    {"name": "PodFitsHostPorts"},
                    {"name": "MatchInterPodAffinity"},
                ],
                "priorities": [
                    {"name": "LeastRequestedPriority", "weight": 2},
                    {"name": "BalancedResourceAllocation", "weight": 1},
                ],
            }
        )
        assert [p.name for p in plugins.filter.enabled] == [
            "NodeResourcesFit", "NodePorts", "InterPodAffinity",
        ]
        assert "NodeResourcesFit" in [
            p.name for p in plugins.pre_filter.enabled
        ]
        scores = {p.name: p.weight for p in plugins.score.enabled}
        assert scores == {
            "NodeResourcesLeastAllocated": 2,
            "NodeResourcesBalancedAllocation": 1,
        }

    def test_unknown_predicate_rejected(self):
        with pytest.raises(ValueError, match="unknown Policy predicate"):
            plugins_from_policy({"predicates": [{"name": "NoSuchPred"}]})

    def test_policy_profile_schedules_end_to_end(self, tmp_path):
        policy = tmp_path / "policy.yaml"
        policy.write_text(
            """
kind: Policy
predicates:
  - name: PodFitsResources
  - name: CheckNodeUnschedulable
priorities:
  - name: LeastRequestedPriority
    weight: 1
"""
        )
        profile = load_policy(str(policy))
        server = APIServer()
        client = Client(server)
        informers = InformerFactory(server)
        sched = new_scheduler(
            client, informers, profiles=[profile], batch=True, max_batch=16
        )
        client.create_node(make_node("n").capacity(cpu="4", memory="8Gi").obj())
        informers.start()
        informers.wait_for_cache_sync()
        sched.queue.run()
        client.create_pod(make_pod("p").container(cpu="1").obj())
        sched.start()
        _wait_bound(client, 1)
        sched.stop()
        informers.stop()

    def test_policy_profile_from_policy_replaces_defaults(self):
        prof = profile_from_policy(
            {"predicates": [{"name": "PodFitsResources"}]}
        )
        assert prof.plugins.filter.disabled[0].name == "*"


class TestConfigDrivenScheduler:
    def _run_burst(self, cfg_dict, nodes=8, pods=40):
        cfg = load_config_from_dict(cfg_dict)
        server = APIServer()
        client = Client(server)
        informers = InformerFactory(server)
        sched = new_scheduler_from_config(client, informers, cfg)
        for i in range(nodes):
            client.create_node(
                make_node(f"n{i}").capacity(cpu="8", memory="16Gi", pods=30).obj()
            )
        informers.start()
        informers.wait_for_cache_sync()
        sched.queue.run()
        for i in range(pods):
            client.create_pod(
                make_pod(f"p{i}").container(cpu="250m", memory="256Mi").obj()
            )
        sched.start()
        _wait_bound(client, pods)
        sched.wait_for_inflight_binds()
        sched.stop()
        informers.stop()
        return sched

    def test_yaml_solver_knobs(self):
        sched = self._run_burst(
            {"tpuSolver": {"maxBatch": 32, "solverMode": "greedy",
                           "batchWindow": "20ms"}}
        )
        assert sched.max_batch == 32
        assert abs(sched.batch_window - 0.02) < 1e-9
        assert sched.pods_solved_on_device >= 40

    def test_yaml_sinkhorn_mode_end_to_end(self):
        """solver_mode=sinkhorn through the FULL BatchScheduler pipeline,
        selected from config (VERDICT r2 weak #3)."""
        sched = self._run_burst(
            {"tpuSolver": {"maxBatch": 32, "solverMode": "sinkhorn"}}
        )
        assert sched.solver_mode == "sinkhorn"
        assert sched.pods_solved_on_device >= 40
        assert sched.pods_fallback == 0

    def test_yaml_mesh_end_to_end(self):
        """meshDevices=8 builds the node-axis Mesh from config and the
        full pipeline schedules across it (in-suite mesh coverage,
        VERDICT r2 weak #4 -- no longer only the driver's dryrun)."""
        if len(jax.devices()) < 8:
            pytest.skip("needs 8 devices (conftest forces 8 CPU devices)")
        sched = self._run_burst({"tpuSolver": {"meshDevices": 8}})
        assert sched.mesh is not None
        assert sched.pods_solved_on_device >= 40

    def test_yaml_sinkhorn_under_mesh(self):
        """solver_mode=sinkhorn WITH the 8-device mesh: GSPMD shards the
        entropic-OT row/col normalizations over the node axis (VERDICT
        r3 missing #7 -- sinkhorn had never run on a mesh in-suite)."""
        if len(jax.devices()) < 8:
            pytest.skip("needs 8 devices (conftest forces 8 CPU devices)")
        sched = self._run_burst(
            {"tpuSolver": {"meshDevices": 8, "solverMode": "sinkhorn"}}
        )
        assert sched.mesh is not None
        assert sched.solver_mode == "sinkhorn"
        assert sched.pods_solved_on_device >= 40
        assert sched.pods_fallback == 0

    def test_preemption_under_mesh(self):
        """Batched device preemption (preempt_batch) running inside a
        mesh-configured scheduler (VERDICT r3 missing #7): saturate,
        burst high-priority, assert device victim search + rebinds."""
        if len(jax.devices()) < 8:
            pytest.skip("needs 8 devices (conftest forces 8 CPU devices)")
        cfg = load_config_from_dict({"tpuSolver": {"meshDevices": 8}})
        server = APIServer()
        client = Client(server)
        informers = InformerFactory(server)
        sched = new_scheduler_from_config(client, informers, cfg)
        for i in range(16):
            client.create_node(
                make_node(f"n{i}")
                .capacity(cpu="8", memory="16Gi", pods=10)
                .obj()
            )
        informers.start()
        informers.wait_for_cache_sync()
        sched.queue.run()
        for i in range(32):
            client.create_pod(
                make_pod(f"fill{i}")
                .container(cpu="3500m", memory="2Gi")
                .priority(0)
                .obj()
            )
        sched.start()
        _wait_bound(client, 32)
        hi = [
            make_pod(f"hi{i}").container(cpu="4", memory="1Gi")
            .priority(100).obj()
            for i in range(8)
        ]
        for hp in hi:
            client.create_pod(hp)
        deadline = time.time() + 60
        while time.time() < deadline:
            pods, _ = client.list_pods()
            bound_hi = sum(
                1 for p in pods
                if p.spec.node_name and p.metadata.name.startswith("hi")
            )
            if bound_hi == 8:
                break
            time.sleep(0.1)
        assert bound_hi == 8, f"bound {bound_hi}/8 high-priority pods"
        assert sched.preemptor.device_preemptions > 0
        sched.stop()
        informers.stop()

    def test_invalid_config_rejected_at_build(self):
        cfg = load_config_from_dict({"tpuSolver": {"maxBatch": -1}})
        with pytest.raises(ValueError, match="maxBatch"):
            new_scheduler_from_config(
                Client(APIServer()), InformerFactory(APIServer()), cfg
            )


class TestMeshKernelInSuite:
    def test_constrained_kernel_under_mesh_matches_single_device(self):
        """The sharded constrained kernel places identically to the
        unsharded one (a sharding regression now fails pytest, not just
        the driver's dryrun)."""
        if len(jax.devices()) < 8:
            pytest.skip("needs 8 devices")
        from kubernetes_tpu.cache.snapshot import new_snapshot
        from kubernetes_tpu.ops.assignment import (
            GreedyConfig,
            greedy_assign_compact,
        )
        from kubernetes_tpu.tensors import NodeTensorCache, pack_pod_batch

        nodes = [
            make_node(f"n{i}").capacity(cpu=str(4 + i % 3), memory="8Gi").obj()
            for i in range(128)
        ]
        snap = new_snapshot([], nodes)
        nt = NodeTensorCache().update(snap)
        pods = [
            make_pod(f"p{i}").container(cpu="500m", memory="256Mi").obj()
            for i in range(32)
        ]
        batch = pack_pod_batch(pods, nt.dims)
        rows = np.ones((8, nt.capacity), dtype=bool)
        midx = np.zeros(32, dtype=np.int32)
        active = np.ones(32, dtype=bool)
        args = (
            nt.allocatable, nt.requested, nt.non_zero_requested, nt.valid,
            batch.requests, batch.non_zero_requests, rows, midx, active,
        )
        plain, _, _ = greedy_assign_compact(*args, config=GreedyConfig())

        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = Mesh(np.array(jax.devices()[:8]), axis_names=("nodes",))
        sh_n1 = NamedSharding(mesh, P("nodes"))
        sh_n2 = NamedSharding(mesh, P("nodes", None))
        sh_rows = NamedSharding(mesh, P(None, "nodes"))
        sh_rep = NamedSharding(mesh, P())
        sharded_args = jax.device_put(
            args,
            (sh_n2, sh_n2, sh_n2, sh_n1, sh_rep, sh_rep, sh_rows, sh_rep,
             sh_rep),
        )
        sharded, _, _ = greedy_assign_compact(
            *sharded_args, config=GreedyConfig()
        )
        assert np.array_equal(np.asarray(plain), np.asarray(sharded))
