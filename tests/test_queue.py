from kubernetes_tpu.framework.interface import PodInfo
from kubernetes_tpu.queue import events
from kubernetes_tpu.queue.heap import Heap
from kubernetes_tpu.queue.scheduling_queue import PriorityQueue
from kubernetes_tpu.testing import make_pod


def priority_less(a: PodInfo, b: PodInfo) -> bool:
    """PrioritySort semantics: higher priority first, then earlier queue time."""
    pa, pb = a.pod.spec.priority, b.pod.spec.priority
    if pa != pb:
        return pa > pb
    return a.timestamp < b.timestamp


def _pq(now):
    return PriorityQueue(priority_less, now=lambda: now[0])


def test_heap_basic():
    h = Heap(lambda x: x[0], lambda a, b: a[1] < b[1])
    h.add(("a", 3))
    h.add(("b", 1))
    h.add(("c", 2))
    assert h.pop() == ("b", 1)
    h.add(("c", 0))  # update key c
    assert h.pop() == ("c", 0)
    assert h.pop() == ("a", 3)
    assert len(h) == 0


def test_pop_orders_by_priority():
    now = [0.0]
    q = _pq(now)
    q.add(make_pod("low").priority(1).obj())
    q.add(make_pod("high").priority(10).obj())
    q.add(make_pod("mid").priority(5).obj())
    assert q.pop().pod.name == "high"
    assert q.pop().pod.name == "mid"
    assert q.pop().pod.name == "low"


def test_unschedulable_then_move_on_event():
    now = [0.0]
    q = _pq(now)
    q.add(make_pod("p1").obj())
    pi = q.pop()
    cycle = q.scheduling_cycle
    q.add_unschedulable_if_not_present(pi, cycle)
    assert q.num_pending()["unschedulable"] == 1

    # node-add event moves it; backoff (1s) still pending at t=0 -> backoffQ
    q.move_all_to_active_or_backoff_queue(events.NodeAdd)
    assert q.num_pending()["backoff"] == 1
    # after backoff expires, flush moves it to activeQ
    now[0] = 3.0
    q.flush_backoff_q_completed()
    assert q.num_pending()["active"] == 1
    assert q.pop().pod.name == "p1"


def test_move_request_cycle_prevents_lost_wakeup():
    """A move request during a pod's scheduling attempt must send the
    failed pod to backoffQ, not unschedulableQ (scheduling_queue.go:141)."""
    now = [0.0]
    q = _pq(now)
    q.add(make_pod("p1").obj())
    pi = q.pop()
    cycle = q.scheduling_cycle
    # concurrent event while p1 was being scheduled:
    q.move_all_to_active_or_backoff_queue(events.NodeAdd)
    q.add_unschedulable_if_not_present(pi, cycle)
    assert q.num_pending()["unschedulable"] == 0
    assert q.num_pending()["backoff"] == 1


def test_backoff_grows_exponentially():
    now = [0.0]
    q = _pq(now)
    q.add(make_pod("p1").obj())
    pi = q.pop()
    assert pi.attempts == 1
    assert q._backoff_duration(pi) == 1.0  # first failure: initial backoff
    pi.attempts = 3
    assert q._backoff_duration(pi) == 4.0  # 1s * 2^(attempts-1)
    pi.attempts = 10
    assert q._backoff_duration(pi) == 10.0  # capped at max


def test_flush_unschedulable_leftover():
    now = [0.0]
    q = _pq(now)
    q.add(make_pod("p1").obj())
    pi = q.pop()
    q.add_unschedulable_if_not_present(pi, q.scheduling_cycle)
    now[0] = 61.0
    q.flush_unschedulable_q_leftover()
    assert q.num_pending()["unschedulable"] == 0
    assert q.num_pending()["active"] == 1  # backoff long expired


def test_pop_batch_drains():
    now = [0.0]
    q = _pq(now)
    for i in range(5):
        q.add(make_pod(f"p{i}").priority(i).obj())
    batch = q.pop_batch(3)
    assert [pi.pod.name for pi in batch] == ["p4", "p3", "p2"]
    assert q.num_pending()["active"] == 2


def test_nominated_pods():
    now = [0.0]
    q = _pq(now)
    p = make_pod("p1").obj()
    q.update_nominated_pod_for_node(p, "n1")
    assert [x.name for x in q.nominated_pods_for_node("n1")] == ["p1"]
    q.delete_nominated_pod_if_exists(p)
    assert q.nominated_pods_for_node("n1") == []


def test_status_only_update_keeps_pod_parked():
    """The scheduler's own PodScheduled-condition write must not wake a
    parked unschedulable pod (isPodUpdated guard, scheduling_queue.go)."""
    from kubernetes_tpu.api.types import PodCondition

    now = [0.0]
    q = _pq(now)
    q.add(make_pod("p1").obj())
    pi = q.pop()
    q.add_unschedulable_if_not_present(pi, q.scheduling_cycle)
    old = pi.pod
    new = make_pod("p1").obj()
    new.status.conditions.append(PodCondition(type="PodScheduled", status="False"))
    new.metadata.resource_version = 99
    q.update(old, new)
    assert q.num_pending() == {"active": 0, "backoff": 0, "unschedulable": 1}
    # but a real spec change does wake it
    labeled = make_pod("p1").labels(x="1").obj()
    q.update(new, labeled)
    assert q.num_pending()["unschedulable"] == 0


def test_update_in_unschedulable_moves_to_active():
    now = [0.0]
    q = _pq(now)
    q.add(make_pod("p1").obj())
    pi = q.pop()
    q.add_unschedulable_if_not_present(pi, q.scheduling_cycle)
    now[0] = 5.0  # backoff expired
    updated = make_pod("p1").labels(v="2").obj()
    q.update(pi.pod, updated)
    assert q.num_pending()["active"] == 1
