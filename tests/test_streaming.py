"""Open-loop streaming subsystem (kubernetes_tpu/streaming/): trace
determinism, arrival-engine pacing + backpressure, the SLO-adaptive
controller's deterministic trajectory and convergence, the config
wiring, and the tier-1 oscillation guard (steady Poisson trace => the
controller converges and STOPS moving)."""

import time

import numpy as np
import pytest

from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client.client import Client
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.config.loader import load_config_from_dict
from kubernetes_tpu.config.validation import validate_config
from kubernetes_tpu.scheduler.scheduler import (
    new_scheduler,
    new_scheduler_from_config,
)
from kubernetes_tpu.streaming.arrivals import (
    ArrivalEngine,
    bursty_trace,
    diurnal_trace,
    load_trace,
    poisson_trace,
    replay_trace,
    save_trace,
)
from kubernetes_tpu.streaming.autobatch import AutoBatchController
from kubernetes_tpu.testing import make_node, make_pod


# -- trace generators --------------------------------------------------------


class TestTraces:
    def test_poisson_deterministic(self):
        a = poisson_trace(1000.0, 5.0, seed=42)
        b = poisson_trace(1000.0, 5.0, seed=42)
        c = poisson_trace(1000.0, 5.0, seed=43)
        assert np.array_equal(a, b)
        assert not np.array_equal(a, c)

    def test_poisson_rate_and_bounds(self):
        offs = poisson_trace(2000.0, 10.0, seed=7)
        # n ~ Poisson(20000): 6 sigma is ~850
        assert abs(offs.size - 20000) < 1000
        assert offs[0] >= 0.0 and offs[-1] < 10.0
        assert np.all(np.diff(offs) >= 0)

    def test_poisson_empty_edge(self):
        assert poisson_trace(0.0, 5.0).size == 0
        assert poisson_trace(100.0, 0.0).size == 0

    def test_bursty_deterministic_and_heavier_than_base(self):
        a = bursty_trace(200.0, 2000.0, 20.0, seed=5)
        b = bursty_trace(200.0, 2000.0, 20.0, seed=5)
        assert np.array_equal(a, b)
        # dwell split ~8s base / ~2s burst: mean rate must land between
        # the base rate and the burst rate
        assert 200.0 * 20.0 < a.size < 2000.0 * 20.0
        assert np.all(np.diff(a) >= 0)

    def test_diurnal_deterministic_and_thinned(self):
        a = diurnal_trace(1000.0, 30.0, seed=3, period=10.0)
        b = diurnal_trace(1000.0, 30.0, seed=3, period=10.0)
        assert np.array_equal(a, b)
        # thinning: mean rate well below peak, above trough
        assert 0.2 * 1000.0 * 30.0 * 0.5 < a.size < 1000.0 * 30.0

    def test_replay_roundtrip(self, tmp_path):
        offs = poisson_trace(500.0, 2.0, seed=1)
        p = str(tmp_path / "trace.json")
        save_trace(p, offs, kind="poisson", seed=1)
        back = replay_trace(p)
        np.testing.assert_allclose(back, offs)

    def test_load_trace_dispatch(self, tmp_path):
        assert load_trace("poisson", 100.0, 1.0, 0).size > 0
        assert load_trace("bursty", 100.0, 5.0, 0).size > 0
        assert load_trace("diurnal", 100.0, 5.0, 0).size > 0
        p = str(tmp_path / "t.json")
        save_trace(p, poisson_trace(100.0, 1.0, 0))
        assert load_trace("replay", 0.0, 0.0, replay_path=p).size > 0
        with pytest.raises(ValueError):
            load_trace("lognormal", 100.0, 1.0)
        with pytest.raises(ValueError):
            load_trace("replay", 100.0, 1.0)  # no path


# -- arrival engine ----------------------------------------------------------


class _StubClient:
    """create_pods_bulk sink; no apiserver."""

    def __init__(self):
        self.created = []

    def create_pods_bulk(self, pods):
        self.created.extend(pods)
        return pods


class TestArrivalEngine:
    def test_replays_full_trace_and_stamps_created_ts(self):
        stub = _StubClient()
        offsets = np.linspace(0.0, 0.2, 50)
        eng = ArrivalEngine(
            stub, offsets, lambda i: make_pod(f"a-{i}").obj()
        )
        eng.start()
        assert eng.join(timeout=10.0)
        assert eng.created == 50
        assert len(stub.created) == 50
        # every pod has an end-to-end creation stamp
        assert set(eng.created_ts) == {f"a-{i}" for i in range(50)}

    def test_backpressure_stalls_instead_of_unbounded_growth(self):
        """THE backpressure unit: with the queue-depth gate closed, the
        engine STALLS (bounded creations, stall counted) instead of
        pushing the heap without bound; opening the gate releases it."""
        stub = _StubClient()
        drained = [0]

        def depth():
            return len(stub.created) - drained[0]

        # 400 arrivals due essentially at once, gate at 64
        offsets = np.linspace(0.0, 0.05, 400)
        eng = ArrivalEngine(
            stub, offsets, lambda i: make_pod(f"b-{i}").obj(),
            depth_fn=depth, max_queue_depth=64,
        )
        eng.start()
        time.sleep(0.5)
        created_while_gated = len(stub.created)
        # the gate held: bounded by the depth bound plus one in-flight
        # chunk, nowhere near the full trace
        assert not eng.done.is_set()
        assert created_while_gated < 400
        assert depth() <= 64 + 256
        assert eng.backpressure_stalls >= 1
        # drain the "queue": the engine must resume and finish
        drained[0] = 10000
        assert eng.join(timeout=10.0)
        assert eng.created == 400
        assert eng.stall_seconds > 0.0

    def test_stop_interrupts_a_stall(self):
        stub = _StubClient()
        offsets = np.zeros(300)
        eng = ArrivalEngine(
            stub, offsets, lambda i: make_pod(f"c-{i}").obj(),
            depth_fn=lambda: 10_000, max_queue_depth=8,
        )
        eng.start()
        time.sleep(0.2)
        eng.stop()
        assert eng.created < 300


# -- the SLO-adaptive controller ---------------------------------------------


def _drive(controller, series):
    """Feed (depth, cycle, t, pop_wait) tuples; return the (window,
    cap) trajectory."""
    out = []
    for depth, cycle, t, pw in series:
        controller.step(depth, cycle, t, pop_wait_seconds=pw)
        out.append((controller.window, controller.batch_cap))
    return out


def _steady_series(
    depth_level, rate, n=120, interval=0.25, seed=0, jitter=0.2
):
    """A steady arrival process as the controller sees it: depth
    fluctuates around a level (seeded Poisson noise), the pop counter
    advances at the service rate."""
    rng = np.random.default_rng(seed)
    series = []
    cycle = 0
    for i in range(n):
        depth = int(rng.poisson(depth_level))
        cycle += int(rate * interval)
        series.append((depth, cycle, interval * (i + 1), 0.0))
    return series


class TestAutoBatchController:
    def test_trajectory_deterministic(self):
        """Fixed seed => fixed input series => the SAME window/cap
        trajectory, grow and shrink phases included."""
        rng = np.random.default_rng(9)
        series = []
        cycle = 0
        for i in range(200):
            # walk the load up into overload and back down
            level = 50 + 4000 * (1 if 60 <= i < 120 else 0)
            series.append((
                int(rng.poisson(level)), cycle, 0.25 * (i + 1), 0.0
            ))
            cycle += 500
        a = AutoBatchController(slo_p99_seconds=1.0, max_batch=4096)
        b = AutoBatchController(slo_p99_seconds=1.0, max_batch=4096)
        ta = _drive(a, series)
        tb = _drive(b, series)
        assert ta == tb
        assert a.grows > 0 and a.shrinks > 0

    def test_grows_to_throughput_pole_under_backlog(self):
        c = AutoBatchController(
            slo_p99_seconds=1.0, latency_batch=256, max_batch=4096
        )
        # deep backlog, slow drain: est sojourn >> slo
        series = [
            (8000, 200 * (i + 1), 0.25 * (i + 1), 0.0) for i in range(40)
        ]
        _drive(c, series)
        assert c.batch_cap == 4096
        assert c.window == c.max_window
        assert c.grows >= 1 and c.shrinks == 0

    def test_saturated_no_drain_counts_as_overload(self):
        c = AutoBatchController(slo_p99_seconds=1.0, max_batch=2048)
        # backlog present, pop counter frozen (rate == 0)
        _drive(c, [(500, 0, 0.25 * (i + 1), 0.0) for i in range(10)])
        assert c.batch_cap == 2048

    def test_shrinks_back_when_idle(self):
        c = AutoBatchController(
            slo_p99_seconds=1.0, latency_batch=256, max_batch=4096
        )
        _drive(c, [
            (8000, 200 * (i + 1), 0.25 * (i + 1), 0.0) for i in range(40)
        ])
        assert c.batch_cap == 4096
        cycle = 200 * 40
        series = []
        for i in range(60):
            cycle += 50
            series.append((0, cycle, 10.0 + 0.25 * (i + 1), 0.0))
        _drive(c, series)
        assert c.batch_cap == 256
        assert c.window == c.min_window

    def test_window_never_exceeds_half_slo(self):
        c = AutoBatchController(
            slo_p99_seconds=0.2, max_window=5.0, max_batch=4096
        )
        assert c.max_window <= 0.1
        _drive(c, [
            (9000, 100 * (i + 1), 0.25 * (i + 1), 0.0) for i in range(50)
        ])
        assert c.window <= 0.1

    def test_idle_dispatcher_blocks_grow(self):
        """A transiently deep queue on an idle dispatcher (pop_wait
        dominating the interval) must not trigger throughput mode --
        the PR-4 stage-timer signal."""
        c = AutoBatchController(slo_p99_seconds=1.0, max_batch=4096)
        # depth high but the dispatcher spent the whole interval
        # blocked on arrivals
        series = []
        pw = 0.0
        for i in range(20):
            pw += 0.25
            series.append((5000, 100 * (i + 1), 0.25 * (i + 1), pw))
        _drive(c, series)
        assert c.batch_cap == c.latency_batch
        assert c.grows == 0

    def test_no_oscillation_on_steady_trace_unit(self):
        """Tier-1 guard (unit half): a steady Poisson trace whose
        pressure sits inside the hysteresis band converges to ZERO
        window/cap changes per 100 steps."""
        c = AutoBatchController(slo_p99_seconds=1.0, max_batch=4096)
        # depth ~300 at 1000 pods/s drain => est sojourn ~0.3s: inside
        # the [0.15, 0.5) hold band
        _drive(c, _steady_series(300, 1000.0, n=100, seed=4))
        assert c.window_changes == 0
        assert c.cap_changes == 0

    def test_rounding_and_clamps(self):
        c = AutoBatchController(
            slo_p99_seconds=1.0, latency_batch=100, max_batch=4096
        )
        assert c.latency_batch == 64  # bucket-rounded down
        c2 = AutoBatchController(
            slo_p99_seconds=1.0, latency_batch=9999, max_batch=512
        )
        assert c2.latency_batch == 512
        with pytest.raises(ValueError):
            AutoBatchController(slo_p99_seconds=0.0)


def _overload_series(n=30, interval=0.25):
    """The sustained-overload shape that used to make the controller
    hunt between poles: a deep backlog whose max-batch drains
    momentarily empty the visible queue every other interval, so the
    RAW pressure signal whipsaws between saturation and idle."""
    series = []
    t, cycle = 0.0, 0
    for step in range(n):
        t += interval
        if step % 2 == 0:
            depth, cycle = 40000, cycle + 500
        else:
            depth, cycle = 50, cycle + 4000
        series.append((depth, cycle, t, 0.0))
    return series


class TestOverloadLatch:
    def test_overload_trajectory_at_most_two_moves(self):
        """ROADMAP item-2 residual b: the EWMA + latch pins the
        controller at the throughput pole on a sustained-overload
        series in <= 2 moves (one grow + the latch's pole jump) where
        the unsmoothed controller made ~10+ grow/shrink moves."""
        c = AutoBatchController(
            slo_p99_seconds=1.0, latency_batch=512, max_batch=4096
        )
        _drive(c, _overload_series())
        assert c.latched
        assert c.grows + c.shrinks <= 2, (c.grows, c.shrinks)
        assert c.window == c.max_window
        assert c.batch_cap == c.max_batch

    def test_unsmoothed_unlatch_controller_hunts(self):
        """The regression witness: alpha=1 (no smoothing) with the
        latch disabled reproduces the pole-hunting this satellite
        fixes -- if this stops hunting, the overload series no longer
        exercises the seam and the latch test above proves nothing."""
        c = AutoBatchController(
            slo_p99_seconds=1.0, latency_batch=512, max_batch=4096,
            pressure_ewma_alpha=1.0, latch_after_steps=10 ** 9,
        )
        _drive(c, _overload_series())
        assert not c.latched
        assert c.grows + c.shrinks >= 10, (c.grows, c.shrinks)

    def test_latch_releases_after_sustained_calm(self):
        c = AutoBatchController(
            slo_p99_seconds=1.0, latency_batch=512, max_batch=4096,
        )
        _drive(c, _overload_series(n=10))
        assert c.latched
        # sustained calm: shallow queue, healthy drain rate
        t0 = 10 * 0.25
        calm = [
            (10, 4000 * 10 + 1000 * (i + 1), t0 + 0.25 * (i + 1), 0.0)
            for i in range(20)
        ]
        _drive(c, calm)
        assert not c.latched
        assert c.batch_cap == c.latency_batch  # shrinks resumed

    def test_latch_respects_idle_dispatcher_guard(self):
        """Depth piling up while the dispatcher is BLOCKED on arrivals
        is not overload: neither grow nor latch may fire."""
        c = AutoBatchController(slo_p99_seconds=1.0, max_batch=4096)
        pw = 0.0
        series = []
        for i in range(20):
            pw += 0.25
            series.append((5000, 100 * (i + 1), 0.25 * (i + 1), pw))
        _drive(c, series)
        assert not c.latched
        assert c.grows == 0

    def test_deterministic(self):
        a = AutoBatchController(slo_p99_seconds=1.0, max_batch=4096)
        b = AutoBatchController(slo_p99_seconds=1.0, max_batch=4096)
        s = _overload_series(n=40)
        assert _drive(a, s) == _drive(b, s)
        assert (a.latched, a.latches, a.pressure_ewma) == (
            b.latched, b.latches, b.pressure_ewma
        )


# -- config wiring -----------------------------------------------------------


class TestRungLadder:
    """The solve-pad rung LADDER (ROADMAP item-2a residual): candidate
    rungs between the latency and throughput poles, pruned from the
    MEASURED per-pad solve cost at warmup, stepped through one rung per
    controller decision."""

    def test_default_stays_two_rungs(self):
        c = AutoBatchController(latency_batch=512, max_batch=4096)
        assert c.rungs == [512, 4096]
        assert not c.auto_rungs

    def test_auto_rungs_geometric_candidates(self):
        c = AutoBatchController(
            latency_batch=512, max_batch=4096, auto_rungs=True
        )
        assert c.rungs == [512, 1024, 2048, 4096]

    def test_explicit_rungs_normalized(self):
        c = AutoBatchController(
            latency_batch=256, max_batch=2048,
            rungs=[300, 1000, 9999],  # quantized, clamped, poles added
        )
        assert c.rungs == [256, 960, 2048]

    def test_calibrate_prunes_rungs_that_dont_pay(self):
        """A rung survives only when its measured solve is meaningfully
        cheaper than the next kept rung above: here 2048 costs ~the
        same as 4096 (fixed overhead dominates) and must drop, while
        1024 and 512 pay."""
        c = AutoBatchController(
            latency_batch=512, max_batch=4096, auto_rungs=True
        )
        rungs = c.calibrate(
            {512: 0.020, 1024: 0.040, 2048: 0.095, 4096: 0.100}
        )
        assert rungs == [512, 1024, 4096]

    def test_calibrate_keeps_poles_and_drops_unmeasured(self):
        c = AutoBatchController(
            latency_batch=512, max_batch=4096, auto_rungs=True
        )
        # middle rungs never measured (warmup skipped them): they drop
        # -- switching to an uncompiled pad would pay JIT mid-run
        assert c.calibrate({512: 0.02, 4096: 0.1}) == [512, 4096]

    def test_calibrate_noop_without_auto_rungs(self):
        c = AutoBatchController(latency_batch=512, max_batch=4096)
        assert c.calibrate({512: 0.0001, 4096: 1.0}) == [512, 4096]

    def test_grow_steps_one_rung_latch_jumps_to_top(self):
        c = AutoBatchController(
            slo_p99_seconds=1.0, latency_batch=512, max_batch=4096,
            rungs=[512, 1024, 2048, 4096],
            # keep the latch out of the way for the stepping half
            latch_after_steps=100,
        )
        series = [
            (8000, 200 * (i + 1), 0.25 * (i + 1), 0.0) for i in range(2)
        ]
        _drive(c, series)  # step 1 primes, step 2 grows
        assert c.batch_cap == 1024  # one rung, not a pole jump
        _drive(
            c,
            [(8000, 200 * (i + 3), 0.25 * (i + 3), 0.0) for i in range(2)],
        )
        assert c.batch_cap == 4096  # kept walking, one rung per step
        # latched controller pole-jumps straight to the TOP rung
        c2 = AutoBatchController(
            slo_p99_seconds=1.0, latency_batch=512, max_batch=4096,
            rungs=[512, 1024, 2048, 4096], latch_after_steps=2,
        )
        _drive(
            c2,
            [(9000, 100 * (i + 1), 0.25 * (i + 1), 0.0) for i in range(6)],
        )
        assert c2.latched
        assert c2.batch_cap == 4096

    def test_shrink_steps_down_the_ladder(self):
        c = AutoBatchController(
            slo_p99_seconds=1.0, latency_batch=512, max_batch=4096,
            rungs=[512, 1024, 2048, 4096],
        )
        c.batch_cap = 4096
        c.window = c.max_window
        # idle: shallow queue, healthy drain (step 1 primes, step 2
        # shrinks ONE rung)
        series = [
            (10, 5000 * (i + 1), 0.25 * (i + 1), 0.0) for i in range(2)
        ]
        _drive(c, series)
        assert c.batch_cap == 2048  # one rung down per decision

    def test_attach_registers_every_rung_for_warmup(self):
        from kubernetes_tpu.scheduler.batch import BatchScheduler

        server = APIServer()
        client = Client(server)
        informers = InformerFactory(server)
        sched = new_scheduler(
            client, informers, batch=True, max_batch=4096
        )
        try:
            assert isinstance(sched, BatchScheduler)
            c = AutoBatchController(
                latency_batch=512, max_batch=4096, auto_rungs=True
            )
            sched.attach_autobatch(c)
            assert {512, 1024, 2048, 4096} <= sched._warmup_pads
        finally:
            sched.stop()
            informers.stop()

    def test_config_auto_rungs_flag(self):
        cfg = load_config_from_dict({
            "tpuSolver": {"maxBatch": 1024},
            "streaming": {
                "enabled": True, "latencyBatch": 128, "autoRungs": True,
            },
        })
        assert cfg.streaming.auto_rungs
        assert validate_config(cfg) == []
        server = APIServer()
        client = Client(server)
        informers = InformerFactory(server)
        sched = new_scheduler_from_config(client, informers, cfg)
        try:
            assert sched.autobatch.auto_rungs
            assert sched.autobatch.rungs == [128, 256, 512, 1024]
        finally:
            sched.stop()
            informers.stop()


class TestStreamingConfig:
    def test_loader_parses_streaming_block(self):
        cfg = load_config_from_dict({
            "streaming": {
                "enabled": True,
                "sloP99": "500ms",
                "maxWindow": "100ms",
                "latencyBatch": 128,
                "bandPriorityThreshold": 50,
                "maxQueueDepth": 5000,
                "trace": "bursty",
                "rate": 750,
                "seed": 9,
            }
        })
        st = cfg.streaming
        assert st.enabled
        assert st.slo_p99_seconds == 0.5
        assert st.max_window_seconds == 0.1
        assert st.latency_batch == 128
        assert st.band_priority_threshold == 50
        assert st.max_queue_depth == 5000
        assert st.trace == "bursty"
        assert st.rate_pods_per_sec == 750.0
        assert st.seed == 9
        assert validate_config(cfg) == []

    def test_validation_rejects_bad_streaming(self):
        cfg = load_config_from_dict({"streaming": {"trace": "lognormal"}})
        assert any("streaming.trace" in e for e in validate_config(cfg))
        cfg = load_config_from_dict({"streaming": {"trace": "replay"}})
        assert any("replayPath" in e for e in validate_config(cfg))
        cfg = load_config_from_dict({"streaming": {"sloP99": 0}})
        assert any("sloP99" in e for e in validate_config(cfg))
        cfg = load_config_from_dict(
            {"streaming": {"minWindow": 1.0, "maxWindow": 0.5}}
        )
        assert any("maxWindow" in e for e in validate_config(cfg))

    def test_from_config_attaches_controller_and_bands(self):
        cfg = load_config_from_dict({
            "tpuSolver": {"maxBatch": 128},
            "streaming": {
                "enabled": True,
                "sloP99": 2.0,
                "latencyBatch": 64,
                "bandPriorityThreshold": 75,
            },
        })
        server = APIServer()
        client = Client(server)
        informers = InformerFactory(server)
        sched = new_scheduler_from_config(client, informers, cfg)
        try:
            assert sched.autobatch is not None
            assert sched.autobatch.slo == 2.0
            assert sched.autobatch.latency_batch == 64
            assert sched.autobatch.max_batch == 128
            assert sched.queue.band_threshold == 75
            # the controller's outputs are live on the scheduler
            assert sched.dispatch_batch_cap == sched.autobatch.batch_cap
            assert sched.solve_pad == sched.autobatch.batch_cap
            assert 64 in sched._warmup_pads
        finally:
            sched.stop()

    def test_band_threshold_arms_without_batch_solver(self):
        """The band lives in the QUEUE: streaming.bandPriorityThreshold
        must arm queue jumping even with tpuSolver disabled (the
        controller, which needs the batch path, stays off)."""
        cfg = load_config_from_dict({
            "tpuSolver": {"enabled": False},
            "streaming": {"enabled": True, "bandPriorityThreshold": 40},
        })
        server = APIServer()
        client = Client(server)
        informers = InformerFactory(server)
        sched = new_scheduler_from_config(client, informers, cfg)
        try:
            assert sched.queue.band_threshold == 40
            assert getattr(sched, "autobatch", None) is None
        finally:
            sched.stop()

    def test_streaming_off_keeps_static_knobs(self):
        cfg = load_config_from_dict({
            "tpuSolver": {"maxBatch": 128, "batchWindow": 0.02},
        })
        server = APIServer()
        client = Client(server)
        informers = InformerFactory(server)
        sched = new_scheduler_from_config(client, informers, cfg)
        try:
            assert sched.autobatch is None
            assert sched.dispatch_batch_cap is None
            assert sched.solve_pad is None
            assert sched.batch_window == 0.02
            assert sched.queue.band_threshold is None
        finally:
            sched.stop()


# -- tier-1 oscillation guard (e2e half) -------------------------------------


def _wait_bound(client, count, timeout=120.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        pods, _ = client.list_pods()
        if sum(1 for p in pods if p.spec.node_name) >= count:
            return
        time.sleep(0.05)
    bound = sum(1 for p in client.list_pods()[0] if p.spec.node_name)
    raise AssertionError(f"only {bound}/{count} pods bound")


def test_controller_oscillation_guard_steady_poisson_e2e():
    """Tier-1 guard (e2e half): a steady seeded Poisson trace through
    the REAL stack with the adaptive controller attached completes with
    a bounded number of controller moves -- the window must converge,
    not thrash, and the arrival engine must never hit backpressure at a
    rate the stack comfortably sustains."""
    server = APIServer()
    client = Client(server)
    informers = InformerFactory(server)
    sched = new_scheduler(client, informers, batch=True, max_batch=256)
    controller = AutoBatchController(
        slo_p99_seconds=2.0,
        latency_batch=64,
        max_batch=256,
        interval_seconds=0.1,
    )
    sched.attach_autobatch(controller)
    for i in range(16):
        client.create_node(
            make_node(f"n{i}").capacity(cpu="64", memory="256Gi", pods=120)
            .obj()
        )
    informers.start()
    informers.wait_for_cache_sync()
    sched.queue.run()
    sched.warmup()  # compiles BOTH solve pads (64 and 256) off the clock
    sched.start()

    n = 800
    offsets = poisson_trace(400.0, n / 400.0, seed=21)[:n]
    if offsets.size < n:
        n = int(offsets.size)
    eng = ArrivalEngine(
        client, offsets,
        lambda i: make_pod(f"sp-{i}")
        .container(cpu="100m", memory="128Mi").obj(),
        depth_fn=sched.queue.active_count,
        max_queue_depth=10 * 256,
    )
    eng.start()
    assert eng.join(timeout=60.0)
    _wait_bound(client, n)
    sched.wait_for_inflight_binds()

    # THE guard: a steady trace must not move the knobs more than a
    # handful of times end to end (controller steps ~10/s here; a
    # thrashing controller would rack up dozens)
    assert controller.steps >= 5
    assert controller.window_changes + controller.cap_changes <= 6, (
        f"controller thrashed: {controller.window_changes} window + "
        f"{controller.cap_changes} cap changes over {controller.steps} "
        f"steps"
    )
    assert eng.backpressure_stalls == 0
    sched.stop()
    informers.stop()
