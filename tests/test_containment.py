"""Blast-radius containment (ISSUE 14): poison-pod bisection +
quarantine, the ladder_exhausted crash-loop fix, the carry integrity
audit, and device-loss rebuild.

The contracts under test:

- randomized differential: seeded bursts with 1-3 poison pods at random
  offsets -- bisection isolates EXACTLY the stamped pods, every healthy
  pod's placement equals the no-poison oracle run, and quarantined pods
  carry the typed PodQuarantined condition;
- a batch that exhausts the ladder twice in a row books
  ``exhausted_crashloops`` and takes containment instead of a third
  identical retry (the old unbounded retry storm);
- carry corruption: the audit detects a silently corrupted
  device-resident row (invisible to the generation handshake), heals it
  through the counted-upload path, and placements stay capacity-safe;
- device loss: resident state rebuilds from the host cache through the
  cold-upload path, metered, with everything still binding;
- the poison-chaos tier-1 guard: a 1k-pod burst with the builtin
  profile -- 100% of healthy pods bind, device-dominant, bounded
  retries, and the flight-recorder dump alone reconstructs every
  bisection and quarantine event.
"""

import json
import random
import threading
import time

import pytest

from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client.client import Client
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.robustness.circuit import RetryPolicy
from kubernetes_tpu.robustness.containment import (
    QUARANTINE_CONDITION,
    ContainmentConfig,
)
from kubernetes_tpu.robustness.faults import (
    FaultInjector,
    FaultPoint,
    FaultProfile,
    POISON_ANNOTATION,
    PointConfig,
    install_injector,
    load_profile,
)
from kubernetes_tpu.robustness.ladder import RobustnessConfig
from kubernetes_tpu.scheduler.scheduler import new_scheduler
from kubernetes_tpu.testing import make_node, make_pod
from kubernetes_tpu.utils import flightrecorder, metrics


@pytest.fixture(autouse=True)
def _clean_injector():
    yield
    install_injector(None)


@pytest.fixture
def thread_crashes(monkeypatch):
    crashes = []
    monkeypatch.setattr(
        threading, "excepthook", lambda args: crashes.append(args)
    )
    return crashes


def _mk_cluster(
    num_nodes=16, max_batch=128, containment=None, capacity_cpu="32",
    capacity_pods=110,
):
    server = APIServer()
    client = Client(server)
    informers = InformerFactory(server)
    sched = new_scheduler(
        client, informers, batch=True, max_batch=max_batch,
        robustness_config=RobustnessConfig(
            solve_timeout_seconds=10.0,
            failure_threshold=3,
            cooloff_seconds=0.2,
            probe_batches=1,
            retry=RetryPolicy(
                max_attempts=1, backoff_seconds=0.01,
                max_backoff_seconds=0.02,
            ),
        ),
        containment_config=containment or ContainmentConfig(
            max_strikes=3, base_hold_seconds=0.1, max_hold_seconds=0.5,
        ),
    )
    # fast requeue clocks so quarantine convergence isn't dominated by
    # the reference's 1s initial backoff
    sched.queue._initial_backoff = 0.1
    sched.queue._max_backoff = 0.5
    for i in range(num_nodes):
        client.create_node(
            make_node(f"node-{i}")
            .capacity(cpu=capacity_cpu, memory="64Gi", pods=capacity_pods)
            .obj()
        )
    informers.start()
    informers.wait_for_cache_sync()
    sched.queue.run()
    return server, client, informers, sched


def _wait(predicate, timeout, interval=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return predicate()


def _bound_map(client):
    pods, _ = client.list_pods()
    return {
        p.metadata.name: p.spec.node_name
        for p in pods if p.spec.node_name
    }


def _overcommitted_nodes(client):
    """Nodes whose bound pods' cpu requests exceed capacity (the
    zero-wrong-placements invariant)."""
    nodes, _ = client.list_nodes()
    cap = {
        n.metadata.name: n.status.allocatable.get("cpu", 0)
        for n in nodes
    }
    used = {}
    pods, _ = client.list_pods()
    for p in pods:
        if not p.spec.node_name:
            continue
        req = sum(
            c.resources.requests.get("cpu", 0) for c in p.spec.containers
        )
        used[p.spec.node_name] = used.get(p.spec.node_name, 0) + req
    return [
        n for n, u in used.items() if cap.get(n) is not None and u > cap[n]
    ]


class TestPoisonBisectionDifferential:
    def test_random_offsets_match_no_poison_oracle(self, thread_crashes):
        """Seeded trials: 1-3 poison pods at random offsets in a
        60-pod burst. Bisection isolates exactly the stamped pods (all
        parked with the typed condition), and every healthy placement
        equals the oracle run without the poison pods."""
        rng = random.Random(20260804)
        for trial in range(2):
            n_poison = rng.randint(1, 3)
            offsets = sorted(rng.sample(range(60), n_poison))
            poison_names = {f"t{trial}-p{i}" for i in offsets}

            def run(with_poison):
                server, client, informers, sched = _mk_cluster(
                    num_nodes=12, capacity_cpu="16"
                )
                if with_poison:
                    install_injector(FaultInjector(FaultProfile(
                        "poison-differential", seed=trial, points={}
                    )))
                try:
                    for i in range(60):
                        name = f"t{trial}-p{i}"
                        if name in poison_names and not with_poison:
                            continue  # oracle: poison pods absent
                        pw = make_pod(name).container(
                            cpu="750m", memory="512Mi"
                        )
                        if with_poison and name in poison_names:
                            pw.annotation(POISON_ANNOTATION, "true")
                        client.create_pod(pw.obj())
                    sched.start()
                    healthy = {
                        f"t{trial}-p{i}" for i in range(60)
                    } - poison_names
                    assert _wait(
                        lambda: healthy <= set(_bound_map(client)), 60
                    ), "healthy pods did not all bind"
                    if with_poison:
                        assert _wait(
                            lambda: sched.queue.quarantine_parked_count()
                            == len(poison_names),
                            60,
                        ), "poison pods did not all park"
                    sched.wait_for_inflight_binds()
                    placements = _bound_map(client)
                    parked = {
                        pi.pod.metadata.name
                        for pi in sched.queue.quarantined_pods()
                    }
                    conditions = {}
                    for name in poison_names:
                        if not with_poison:
                            break
                        live = client.get_pod("default", name)
                        conditions[name] = [
                            c.type for c in live.status.conditions
                            if c.status == "True"
                        ]
                    return placements, parked, conditions, sched
                finally:
                    sched.stop()
                    informers.stop()
                    install_injector(None)

            placements, parked, conditions, sched = run(True)
            oracle, _, _, _ = run(False)

            # exactly the stamped pods were isolated
            assert parked == poison_names
            # none of the poison pods bound
            assert not poison_names & set(placements)
            # typed condition on every quarantined pod
            for name in poison_names:
                assert QUARANTINE_CONDITION in conditions[name], (
                    name, conditions[name]
                )
            # healthy placements equal the no-poison oracle
            for name, node in oracle.items():
                assert placements.get(name) == node, (
                    f"trial {trial}: {name} placed on "
                    f"{placements.get(name)} vs oracle {node}"
                )
            assert not thread_crashes, [
                str(c.exc_value) for c in thread_crashes
            ]


class TestExhaustedCrashloop:
    def test_singleton_poison_trips_crashloop_then_parks(
        self, thread_crashes
    ):
        """A lone poison pod used to be an unbounded retry storm
        (exhaust -> sequential fail -> backoff -> exhaust -> ...).
        Now the second identical exhaustion books exhausted_crashloops
        and strikes it into quarantine; the budget parks it."""
        server, client, informers, sched = _mk_cluster(num_nodes=4)
        install_injector(FaultInjector(FaultProfile(
            "lone-poison", seed=0, points={}
        )))
        crashloops_before = metrics.exhausted_crashloops.value()
        sched.start()
        client.create_pod(
            make_pod("poison-solo").container(cpu="100m")
            .annotation(POISON_ANNOTATION, "true").obj()
        )
        assert _wait(
            lambda: sched.queue.quarantine_parked_count() == 1, 60
        ), "lone poison pod never parked"
        assert (
            metrics.exhausted_crashloops.value() > crashloops_before
        ), "crash loop was never booked"
        # bounded: strikes stopped at the budget, no retry storm
        assert sched.quarantine.parks == 1
        assert (
            sched.quarantine.isolations
            <= sched.containment_config.max_strikes
        )
        live = client.get_pod("default", "poison-solo")
        assert any(
            c.type == QUARANTINE_CONDITION and c.status == "True"
            for c in live.status.conditions
        )
        # healthy traffic still flows after the park
        client.create_pod(make_pod("after").container(cpu="100m").obj())
        assert _wait(lambda: "after" in _bound_map(client), 30)
        sched.wait_for_inflight_binds()
        assert not thread_crashes, [
            str(c.exc_value) for c in thread_crashes
        ]
        sched.stop()
        informers.stop()

    def test_spec_update_releases_parked_pod(self):
        """Operator intervention: a REAL spec/label update releases a
        parked pod for a fresh attempt (status-only writes -- including
        our own condition -- never do)."""
        server, client, informers, sched = _mk_cluster(num_nodes=4)
        install_injector(FaultInjector(FaultProfile(
            "release", seed=0, points={}
        )))
        sched.start()
        client.create_pod(
            make_pod("cured").container(cpu="100m")
            .annotation(POISON_ANNOTATION, "true").obj()
        )
        assert _wait(
            lambda: sched.queue.quarantine_parked_count() == 1, 60
        )
        # "fix" the pod: drop the poison annotation (a real update)
        def fix(p):
            # copy-on-write apiserver: REPLACE nested collections (an
            # in-place pop would mutate the shared old object and make
            # the update look like a no-op to the informer diff)
            p.metadata.annotations = {
                k: v for k, v in p.metadata.annotations.items()
                if k != POISON_ANNOTATION
            }
            p.metadata.labels = {**p.metadata.labels, "fixed": "true"}

        server.guaranteed_update("Pod", "default", "cured", fix)
        assert _wait(lambda: "cured" in _bound_map(client), 30), (
            "released pod did not bind"
        )
        assert sched.queue.quarantine_parked_count() == 0
        # the typed condition must not outlive the park: the release
        # hook clears it from the apiserver
        assert _wait(
            lambda: not any(
                c.type == QUARANTINE_CONDITION
                for c in client.get_pod(
                    "default", "cured"
                ).status.conditions
            ),
            10,
        ), "PodQuarantined condition outlived the release"
        # and the parked gauge refreshed down with the release
        assert metrics.quarantine_parked.value() == 0
        sched.stop()
        informers.stop()


class TestCarryIntegrityAudit:
    def test_corrupt_detect_heal_zero_wrong_placements(
        self, thread_crashes
    ):
        """CARRY_CORRUPT flips a resident row the generation handshake
        cannot see (it compares host vs shadow, never the device). The
        audit's device checksums catch it, heal through the
        counted-upload path, and placements stay capacity-safe with
        batches in flight before and after."""
        server, client, informers, sched = _mk_cluster(
            num_nodes=8, max_batch=32
        )
        sched.start()
        names1 = [f"w1-{i}" for i in range(40)]
        for n in names1:
            client.create_pod(
                make_pod(n).container(cpu="250m", memory="256Mi").obj()
            )
        assert _wait(
            lambda: set(names1) <= set(_bound_map(client)), 60
        )
        sched.wait_for_inflight_binds()
        # audit on the warm, uncorrupted carry: clean (retry through
        # transient busy/raced dispositions)
        assert _wait(
            lambda: sched.audit_carry() in ("clean", "idle"), 10
        )
        uploads_before = sched.state_uploads

        inj = FaultInjector(FaultProfile(
            "corrupt", seed=0,
            points={FaultPoint.CARRY_CORRUPT: PointConfig(
                rate=1.0, max_fires=1
            )},
        ))
        install_injector(inj)
        # one more commit fires the corruption onto the resident carry
        client.create_pod(
            make_pod("trigger").container(cpu="100m").obj()
        )
        assert _wait(lambda: "trigger" in _bound_map(client), 30)
        sched.wait_for_inflight_binds()
        assert _wait(
            lambda: inj.fired_count(FaultPoint.CARRY_CORRUPT) == 1, 10
        )

        # detect + heal
        mm_before = metrics.carry_audit_mismatches.value(array="req")
        assert _wait(lambda: sched.audit_carry() == "mismatch", 10), (
            "audit never detected the corrupted row"
        )
        assert metrics.carry_audit_mismatches.value(
            array="req"
        ) > mm_before
        assert sched.carry_audit_heals >= 1

        # post-heal traffic: binds, re-upload counted, audit clean
        names2 = [f"w2-{i}" for i in range(40)]
        for n in names2:
            client.create_pod(
                make_pod(n).container(cpu="250m", memory="256Mi").obj()
            )
        assert _wait(
            lambda: set(names2) <= set(_bound_map(client)), 60
        )
        sched.wait_for_inflight_binds()
        assert sched.state_uploads > uploads_before, (
            "heal never took the counted-upload path"
        )
        assert _wait(lambda: sched.audit_carry() == "clean", 10)
        # zero wrong placements: no node over capacity
        assert not _overcommitted_nodes(client)
        assert not thread_crashes, [
            str(c.exc_value) for c in thread_crashes
        ]
        sched.stop()
        informers.stop()


class TestAuditUnderLoad:
    def test_audit_concludes_without_quiescence(self, thread_crashes):
        """Bounded staleness (ISSUE 17 satellite): a SATURATED pipeline
        must not defer the carry audit to quiescence. With the
        committer artificially slowed so the pending queue never
        drains, the audit still concludes ("clean"/"mismatch", never a
        wall of "busy") by checksumming the first unmirrored pending
        record's ``carry_in`` -- and a CARRY_CORRUPT stamped into the
        stream is detected while batches remain in flight, within
        pipeline depth rather than "whenever arrivals pause"."""
        server, client, informers, sched = _mk_cluster(
            num_nodes=8, max_batch=16, capacity_pods=4000,
        )
        sched.start()
        # warm the carry so dispatches reuse it (carry_in present)
        for i in range(20):
            client.create_pod(
                make_pod(f"warm-{i}")
                .container(cpu="100m", memory="64Mi").obj()
            )
        assert _wait(
            lambda: all(
                f"warm-{i}" in _bound_map(client) for i in range(20)
            ),
            60,
        )
        sched.wait_for_inflight_binds()

        # slow the committer: every commit now parks 0.2s BEFORE the
        # mirror, exactly the committing-but-unmirrored window the old
        # coarse gate refused as "busy"
        orig_complete = sched._complete_solve

        def slow_complete(p):
            time.sleep(0.2)
            return orig_complete(p)

        sched._complete_solve = slow_complete

        stop_feeding = threading.Event()

        def feeder():
            i = 0
            while not stop_feeding.is_set():
                try:
                    client.create_pod(
                        make_pod(f"load-{i}").container(cpu="10m").obj()
                    )
                except Exception:  # noqa: BLE001 - feeder is best-effort
                    pass
                i += 1
                time.sleep(0.02)

        t = threading.Thread(target=feeder, daemon=True)
        t.start()
        try:
            # audits sampled while the queue is verifiably occupied on
            # BOTH sides of the call must conclude, not answer busy
            in_flight_conclusions = 0
            busy_in_flight = 0
            deadline = time.time() + 30
            while time.time() < deadline and in_flight_conclusions < 3:
                if not sched._pending_exists():
                    time.sleep(0.01)
                    continue
                out = sched.audit_carry()
                if not sched._pending_exists():
                    continue  # drained mid-call: not an in-flight sample
                if out in ("clean", "mismatch"):
                    in_flight_conclusions += 1
                elif out == "busy":
                    busy_in_flight += 1
                time.sleep(0.03)
            assert in_flight_conclusions >= 3, (
                f"audit never concluded under load "
                f"(busy={busy_in_flight})"
            )

            # corruption under CONTINUOUS load: detected without the
            # feeder ever pausing
            inj = FaultInjector(FaultProfile(
                "corrupt-under-load", seed=0,
                points={FaultPoint.CARRY_CORRUPT: PointConfig(
                    rate=1.0, max_fires=1
                )},
            ))
            install_injector(inj)
            assert _wait(
                lambda: inj.fired_count(FaultPoint.CARRY_CORRUPT) == 1,
                20,
            ), "corruption never fired"
            assert _wait(
                lambda: sched.audit_carry() == "mismatch", 20, 0.02
            ), "audit never detected corruption while loaded"
            assert sched.carry_audit_heals >= 1
        finally:
            stop_feeding.set()
            t.join(timeout=5)
            sched._complete_solve = orig_complete
        # drain and verify the heal held: no over-capacity placements
        sched.wait_for_inflight_binds()
        assert _wait(
            lambda: sched.audit_carry() in ("clean", "idle"), 10
        )
        assert not _overcommitted_nodes(client)
        assert not thread_crashes, [
            str(c.exc_value) for c in thread_crashes
        ]
        sched.stop()
        informers.stop()


class TestDeviceLossRebuild:
    def test_device_lost_rebuilds_and_everything_binds(
        self, thread_crashes
    ):
        server, client, informers, sched = _mk_cluster(
            num_nodes=8, max_batch=64
        )
        sched.start()
        names1 = [f"a-{i}" for i in range(30)]
        for n in names1:
            client.create_pod(
                make_pod(n).container(cpu="100m", memory="128Mi").obj()
            )
        assert _wait(
            lambda: set(names1) <= set(_bound_map(client)), 60
        )
        sched.wait_for_inflight_binds()
        lost_before = metrics.device_lost_events.value()
        rebuilds_before = metrics.device_rebuild_ms.count()
        install_injector(FaultInjector(FaultProfile(
            "device-loss", seed=0,
            points={FaultPoint.DEVICE_LOST: PointConfig(
                rate=1.0, max_fires=1
            )},
        )))
        names2 = [f"b-{i}" for i in range(30)]
        for n in names2:
            client.create_pod(
                make_pod(n).container(cpu="100m", memory="128Mi").obj()
            )
        assert _wait(
            lambda: set(names2) <= set(_bound_map(client)), 60
        ), "post-loss wave did not bind"
        sched.wait_for_inflight_binds()
        assert metrics.device_lost_events.value() == lost_before + 1
        assert metrics.device_rebuild_ms.count() == rebuilds_before + 1, (
            "detection -> rebuilt was never metered"
        )
        assert not _overcommitted_nodes(client)
        assert not thread_crashes, [
            str(c.exc_value) for c in thread_crashes
        ]
        sched.stop()
        informers.stop()


class TestPoisonChaosGuard:
    def test_poison_chaos_1k_burst_tier1_guard(self, thread_crashes):
        """The tier-1 acceptance guard: a 1k-pod burst under the
        builtin poison-chaos profile (3 stamped poison pods + one
        carry corruption + one device loss). 100% of healthy pods
        bind, placements device-dominant (>90%), zero unbounded
        retries, and the flight-recorder dump ALONE reconstructs every
        bisection and quarantine event."""
        flightrecorder.RECORDER.reset()
        server, client, informers, sched = _mk_cluster(
            num_nodes=48, max_batch=256
        )
        profile = load_profile("poison-chaos", seed=7)
        inj = FaultInjector(profile)
        install_injector(inj)
        sched.start()
        names = [f"pc-{i}" for i in range(1000)]
        for n in names:
            client.create_pod(
                make_pod(n).container(cpu="500m", memory="256Mi").obj()
            )
        # settled state: every stamped pod parked, every healthy pod
        # bound, nothing left circulating (a 0 == 0 early read must
        # not pass, so the predicate requires at least one stamp)
        def settled():
            counts = sched.queue.num_pending()
            fired = inj.fired_count(FaultPoint.POISON_POD)
            return (
                fired >= 1
                and counts.get("active", 0) == 0
                and counts.get("backoff", 0) == 0
                and counts.get("unschedulable", 0) == 0
                and counts.get("quarantined", 0) == 0
                and sched.queue.quarantine_parked_count() == fired
                and len(_bound_map(client)) == len(names) - fired
            )

        assert _wait(settled, 300, interval=0.2), (
            f"never settled: pending={sched.queue.num_pending()} "
            f"bound={len(_bound_map(client))} "
            f"fired={inj.fired_count(FaultPoint.POISON_POD)}"
        )
        stamped = {
            pi.pod.metadata.name
            for pi in sched.queue.quarantined_pods()
        }
        healthy = set(names) - stamped
        sched.wait_for_inflight_binds()
        assert inj.fired_count(FaultPoint.POISON_POD) >= 1

        bound = _bound_map(client)
        assert healthy <= set(bound)
        assert not stamped & set(bound), "a poison pod bound"
        # device-dominant: >90% of bound pods placed by a device solve
        assert sched.pods_solved_on_device >= 0.9 * len(bound), (
            f"device placed {sched.pods_solved_on_device} of "
            f"{len(bound)}"
        )
        # zero unbounded retries: the whole run's isolations are
        # bounded by stamped * strike budget, and nothing crash-spun
        assert (
            sched.quarantine.isolations
            <= len(stamped) * sched.containment_config.max_strikes
        )
        assert sched.quarantine.parks == len(stamped)
        assert not _overcommitted_nodes(client)
        assert not thread_crashes, [
            str(c.exc_value) for c in thread_crashes
        ]

        # -- reconstruction from the dump alone (JSON round trip) -----
        d = json.loads(flightrecorder.RECORDER.dump_json())
        marks = d["marks"]
        bisect_starts = [m for m in marks if m["kind"] == "bisect_start"]
        bisect_ends = [
            m for m in marks
            if m["kind"] in ("bisect_done", "bisect_abort")
        ]
        isolated_marks = [
            m for m in marks if m["kind"] == "bisect_isolated"
        ]
        quarantine_marks = [
            m for m in marks if m["kind"] == "quarantine"
        ]
        assert len(bisect_starts) == sched.bisections
        assert len(bisect_ends) == sched.bisections
        assert len(quarantine_marks) == sched.quarantine.isolations
        parked_marks = {
            m["pod"] for m in quarantine_marks
            if m["disposition"] == "parked"
        }
        parked_uids = {
            pi.pod.metadata.uid
            for pi in sched.queue.quarantined_pods()
        }
        assert parked_marks == parked_uids
        # every isolation the ledger booked is attributable to a
        # bisect_isolated or crashloop-driven quarantine mark
        assert len(isolated_marks) <= len(quarantine_marks)
        # the poison fault marks round-trip against the injector ledger
        fault_marks = [
            m for m in marks
            if m["kind"] == "fault"
            and m["point"] == FaultPoint.POISON_POD
        ]
        assert len(fault_marks) == inj.fired_count(
            FaultPoint.POISON_POD
        )
        sched.stop()
        informers.stop()
        assert not sched.commit_degraded
