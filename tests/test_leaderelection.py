"""LeaderElector edge cases (PR-2 satellite): renew attempted past the
lease deadline, release() semantics, two electors contending on one
lease, clock-skew tolerance, jittered renew, and the commit-time fencing
probe (holds_lease)."""

import threading
import time

from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client.client import Client
from kubernetes_tpu.config.types import LeaderElectionConfiguration
from kubernetes_tpu.robustness.faults import (
    FaultInjector,
    FaultPoint,
    FaultProfile,
    PointConfig,
)
from kubernetes_tpu.scheduler.leaderelection import LeaderElector
from kubernetes_tpu.utils import metrics


def _elector(client, name, cfg, events=None, clock=time.monotonic):
    events = events if events is not None else []
    return LeaderElector(
        client,
        cfg,
        identity=name,
        on_started_leading=lambda: events.append(("lead", name)),
        on_stopped_leading=lambda: events.append(("stop", name)),
        clock=clock,
    )


def _renew_killer(seed=0):
    """Targeted injector: every renew/acquire round fails."""
    return FaultInjector(FaultProfile(
        "kill-renew", seed=seed,
        points={FaultPoint.LEASE_RENEW_FAIL: PointConfig(rate=1.0)},
    ))


class TestRenewDeadline:
    def test_renew_failures_past_deadline_abdicate(self):
        """The holder's renews fail (injected lease_renew_fail); once the
        renew deadline passes it must abdicate: on_stopped_leading fires,
        is_leader drops, and the failures are metered."""
        server = APIServer()
        client = Client(server)
        cfg = LeaderElectionConfiguration(
            leader_elect=True,
            lease_duration_seconds=0.4,
            renew_deadline_seconds=0.3,
            retry_period_seconds=0.03,
        )
        events = []
        a = _elector(client, "a", cfg, events)
        before = metrics.lease_renew_failures.value()
        t = threading.Thread(target=a.run, daemon=True)
        t.start()
        deadline = time.time() + 3
        while not a.is_leader and time.time() < deadline:
            time.sleep(0.01)
        assert a.is_leader
        # kill every subsequent renew, targeted at this elector only
        a.fault_injector = _renew_killer()
        t.join(timeout=5)
        assert not t.is_alive(), "elector never abdicated"
        assert not a.is_leader
        assert ("stop", "a") in events
        assert metrics.lease_renew_failures.value() > before

    def test_renew_failure_before_deadline_keeps_leading(self):
        """A transient renew failure inside the deadline must NOT
        abdicate: the next successful round re-extends the deadline."""
        server = APIServer()
        client = Client(server)
        cfg = LeaderElectionConfiguration(
            leader_elect=True,
            lease_duration_seconds=2.0,
            renew_deadline_seconds=1.5,
            retry_period_seconds=0.03,
        )
        a = _elector(client, "a", cfg)
        a.fault_injector = FaultInjector(FaultProfile(
            "flaky-renew", seed=7,
            points={
                FaultPoint.LEASE_RENEW_FAIL: PointConfig(
                    rate=0.5, max_fires=5
                )
            },
        ))
        t = threading.Thread(target=a.run, daemon=True)
        t.start()
        deadline = time.time() + 3
        while not a.is_leader and time.time() < deadline:
            time.sleep(0.01)
        assert a.is_leader
        time.sleep(0.5)  # several renew rounds, some failing
        assert a.is_leader, "transient renew failures must not depose"
        a.stop()
        t.join(timeout=2)


class TestRelease:
    def test_release_clears_holder_identity(self):
        server = APIServer()
        client = Client(server)
        cfg = LeaderElectionConfiguration(
            lease_duration_seconds=30.0,
            renew_deadline_seconds=10.0,
            retry_period_seconds=0.05,
        )
        a = _elector(client, "a", cfg)
        assert a._try_acquire_or_renew()
        a.is_leader = True
        a.release()
        lease = server.get("Lease", "kube-system", "kube-scheduler")
        assert lease.holder_identity == ""
        assert not a.is_leader

    def test_release_when_not_leader_is_noop(self):
        """release() by a non-holder must not clobber someone else's
        live lease."""
        server = APIServer()
        client = Client(server)
        cfg = LeaderElectionConfiguration(
            lease_duration_seconds=30.0,
            renew_deadline_seconds=10.0,
            retry_period_seconds=0.05,
        )
        a = _elector(client, "a", cfg)
        b = _elector(client, "b", cfg)
        assert a._try_acquire_or_renew()
        a.is_leader = True
        b.release()  # never led
        assert server.get(
            "Lease", "kube-system", "kube-scheduler"
        ).holder_identity == "a"
        # stale is_leader flag but the lease moved on: still a no-op
        b.is_leader = True
        a.release()
        assert a._try_acquire_or_renew()  # lease is free again
        a.is_leader = True
        b.release()
        assert server.get(
            "Lease", "kube-system", "kube-scheduler"
        ).holder_identity == "a", "non-holder release clobbered the lease"


class TestContention:
    def test_two_electors_one_lease_single_winner(self):
        """Both candidates CAS against one lease record: exactly one
        wins every round, and the loser never flips is_leader."""
        server = APIServer()
        client = Client(server)
        cfg = LeaderElectionConfiguration(
            leader_elect=True,
            lease_duration_seconds=0.6,
            renew_deadline_seconds=0.5,
            retry_period_seconds=0.02,
        )
        a = _elector(client, "a", cfg)
        b = _elector(client, "b", cfg)
        ta = threading.Thread(target=a.run, daemon=True)
        tb = threading.Thread(target=b.run, daemon=True)
        ta.start()
        tb.start()
        deadline = time.time() + 3
        while not (a.is_leader or b.is_leader) and time.time() < deadline:
            time.sleep(0.01)
        # sample repeatedly: never both
        for _ in range(20):
            assert not (a.is_leader and b.is_leader)
            time.sleep(0.02)
        lease = server.get("Lease", "kube-system", "kube-scheduler")
        assert lease.holder_identity in ("a", "b")
        assert lease.lease_transitions == 1  # exactly one acquisition
        a.stop()
        b.stop()
        ta.join(timeout=2)
        tb.join(timeout=2)

    def test_direct_cas_only_one_seizes(self):
        """The holder/expiry check runs inside the atomic update: a
        second candidate's CAS against a live lease loses."""
        server = APIServer()
        client = Client(server)
        cfg = LeaderElectionConfiguration(
            lease_duration_seconds=10.0,
            renew_deadline_seconds=5.0,
            retry_period_seconds=0.05,
        )
        a = _elector(client, "a", cfg)
        b = _elector(client, "b", cfg)
        assert a._try_acquire_or_renew()
        assert not b._try_acquire_or_renew()
        # the holder renews fine against its own record
        assert a._try_acquire_or_renew()


class TestClockSkewTolerance:
    def _pair(self, skew_tolerance):
        server = APIServer()
        client = Client(server)
        t_a = [0.0]
        t_b = [0.0]
        cfg_a = LeaderElectionConfiguration(
            lease_duration_seconds=10.0,
            renew_deadline_seconds=5.0,
            retry_period_seconds=0.05,
        )
        cfg_b = LeaderElectionConfiguration(
            lease_duration_seconds=10.0,
            renew_deadline_seconds=5.0,
            retry_period_seconds=0.05,
            clock_skew_tolerance_seconds=skew_tolerance,
        )
        a = _elector(client, "a", cfg_a, clock=lambda: t_a[0])
        b = _elector(client, "b", cfg_b, clock=lambda: t_b[0])
        return a, b, t_a, t_b

    def test_challenger_grants_skew_grace(self):
        """A challenger whose clock runs slightly ahead must not seize a
        lease the holder still believes is live: seizure waits out
        lease_duration + clockSkewTolerance."""
        a, b, t_a, t_b = self._pair(skew_tolerance=1.0)
        assert a._try_acquire_or_renew()  # renew_time = 0, duration 10
        t_b[0] = 10.2  # past expiry by b's (skewed) clock, inside grace
        assert not b._try_acquire_or_renew()
        t_b[0] = 11.2  # past expiry + tolerance: now seize
        assert b._try_acquire_or_renew()

    def test_zero_tolerance_seizes_at_expiry(self):
        a, b, t_a, t_b = self._pair(skew_tolerance=0.0)
        assert a._try_acquire_or_renew()
        t_b[0] = 10.2
        assert b._try_acquire_or_renew()


class TestJitter:
    def test_jitter_stretches_within_fraction(self):
        server = APIServer()
        client = Client(server)
        cfg = LeaderElectionConfiguration(
            retry_period_seconds=1.0, renew_jitter_fraction=0.25
        )
        a = _elector(client, "a", cfg)
        samples = [a._jittered(1.0) for _ in range(200)]
        assert all(1.0 <= s <= 1.25 for s in samples)
        assert len(set(samples)) > 1, "jitter stream is constant"

    def test_zero_jitter_is_exact(self):
        server = APIServer()
        client = Client(server)
        cfg = LeaderElectionConfiguration(renew_jitter_fraction=0.0)
        a = _elector(client, "a", cfg)
        assert a._jittered(1.0) == 1.0


class TestFencingProbe:
    def test_holds_lease_tracks_ownership(self):
        server = APIServer()
        client = Client(server)
        cfg = LeaderElectionConfiguration(
            lease_duration_seconds=10.0,
            renew_deadline_seconds=5.0,
            retry_period_seconds=0.05,
        )
        t = [0.0]
        a = _elector(client, "a", cfg, clock=lambda: t[0])
        b = _elector(client, "b", cfg, clock=lambda: t[0])
        assert a._try_acquire_or_renew()
        a.is_leader = True
        assert a.holds_lease()
        # lease expires and the standby seizes it: the old holder's
        # fresh read must answer False even though is_leader is stale
        t[0] = 10.5
        assert b._try_acquire_or_renew()
        b.is_leader = True
        assert not a.holds_lease()
        assert b.holds_lease()

    def test_holds_lease_false_on_expired_record(self):
        server = APIServer()
        client = Client(server)
        cfg = LeaderElectionConfiguration(
            lease_duration_seconds=10.0,
            renew_deadline_seconds=5.0,
            retry_period_seconds=0.05,
        )
        t = [0.0]
        a = _elector(client, "a", cfg, clock=lambda: t[0])
        assert a._try_acquire_or_renew()
        a.is_leader = True
        t[0] = 10.5  # past expiry with no renew: can't prove ownership
        assert not a.holds_lease()

    def test_holds_lease_false_when_record_missing(self):
        server = APIServer()
        client = Client(server)
        cfg = LeaderElectionConfiguration(
            lease_duration_seconds=10.0,
            renew_deadline_seconds=5.0,
            retry_period_seconds=0.05,
        )
        a = _elector(client, "a", cfg)
        a.is_leader = True  # believes it leads but no record exists
        assert not a.holds_lease()
