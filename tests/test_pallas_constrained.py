"""Constrained Pallas kernel (ops/pallas_constrained.py) vs the XLA
constrained scan (ops/assignment.greedy_assign_constrained): randomized
differential parity in interpreter mode, over batches packed by the real
family packers exactly the way the BatchScheduler packs them."""

import math
import random

import numpy as np
import pytest

from kubernetes_tpu.cache.snapshot import new_snapshot
from kubernetes_tpu.ops.affinity import (
    noop_affinity_tensors,
    pack_affinity_batch,
    pad_affinity_tensors,
)
from kubernetes_tpu.ops.assignment import (
    GreedyConfig,
    greedy_assign_constrained,
)
from kubernetes_tpu.ops.host_masks import static_mask_compact
from kubernetes_tpu.ops.pallas_constrained import pallas_constrained_solve
from kubernetes_tpu.ops.scoring import (
    noop_score_tensors,
    pack_score_batch,
    pad_score_tensors,
)
from kubernetes_tpu.ops.topology import (
    noop_spread_tensors,
    pack_spread_batch,
    pad_spread_tensors,
)
from kubernetes_tpu.tensors import NodeTensorCache, pack_pod_batch
from kubernetes_tpu.testing import make_node, make_pod

MASK_ROW_BUCKET = 8
POD_BUCKET = 64

DEFAULT_WEIGHTS = {
    "NodeAffinity": 1,
    "TaintToleration": 1,
    "DefaultPodTopologySpread": 1,
    "PodTopologySpread": 2,
    "InterPodAffinity": 1,
}


def _cluster(rng, n_nodes=24):
    nodes = []
    for i in range(n_nodes):
        nd = (
            make_node(f"node-{i}")
            .capacity(cpu="16", memory="32Gi", pods=32)
            .label("topology.kubernetes.io/zone", f"zone-{i % 3}")
            .label("rack", f"rack-{i % 5}")
            .label("kubernetes.io/hostname", f"node-{i}")
        )
        nodes.append(nd.obj())
    apps = ["a", "b", "c"]
    existing = []
    for i in range(rng.randrange(10, 30)):
        p = (
            make_pod(f"ex-{i}")
            .node(f"node-{rng.randrange(n_nodes)}")
            .container(cpu="200m", memory="256Mi")
            .labels(app=rng.choice(apps))
        )
        roll = rng.random()
        if roll < 0.25:
            p = p.pod_affinity(
                "topology.kubernetes.io/zone",
                {"app": rng.choice(apps)},
                anti=True,
            )
        elif roll < 0.4:
            p = p.preferred_pod_affinity(
                "rack",
                {"app": rng.choice(apps)},
                weight=rng.randrange(1, 20),
                anti=rng.random() < 0.5,
            )
        existing.append(p.obj())
    return existing, nodes


def _batch(rng, b=24):
    apps = ["a", "b", "c"]
    out = []
    for i in range(b):
        p = (
            make_pod(f"pod-{i}")
            .container(cpu="300m", memory="384Mi")
            .labels(app=rng.choice(apps))
        )
        roll = rng.random()
        if roll < 0.2:
            p = p.pod_affinity(
                "kubernetes.io/hostname",
                {"app": rng.choice(apps)},
                anti=True,
            )
        elif roll < 0.35:
            p = p.pod_affinity(
                "topology.kubernetes.io/zone", {"app": rng.choice(apps)}
            )
        elif roll < 0.5:
            p = p.spread_constraint(
                max_skew=rng.randrange(1, 4),
                topology_key="topology.kubernetes.io/zone",
                when_unsatisfiable="DoNotSchedule",
                match_labels={"app": p.obj().metadata.labels["app"]},
            )
        elif roll < 0.65:
            p = p.preferred_pod_affinity(
                "topology.kubernetes.io/zone",
                {"app": rng.choice(apps)},
                weight=rng.randrange(1, 30),
                anti=rng.random() < 0.4,
            )
        out.append(p.obj())
    return out


def _packed_problem(seed):
    """Mirror batch.py _dispatch_solve's packing for a constrained batch
    (no nominees, no gangs)."""
    rng = random.Random(seed)
    existing, nodes = _cluster(rng)
    snap = new_snapshot(existing, nodes)
    nt = NodeTensorCache().update(snap)
    pods = _batch(rng)

    batch = pack_pod_batch(pods, nt.dims)
    mask_rows, mask_index = static_mask_compact(pods, snap, nt)
    if batch.unsatisfiable.any():
        mask_rows = np.concatenate(
            [mask_rows, np.zeros((1, nt.capacity), dtype=bool)]
        )
        mask_index = mask_index.copy()
        mask_index[batch.unsatisfiable] = mask_rows.shape[0] - 1

    b = batch.size
    padded = POD_BUCKET * math.ceil(b / POD_BUCKET)
    order = batch.order
    req = np.zeros((padded, nt.dims.num_dims), dtype=np.int32)
    nzr = np.zeros((padded, 2), dtype=np.int32)
    midx = np.zeros(padded, dtype=np.int32)
    active = np.zeros(padded, dtype=bool)
    req[:b] = batch.requests[order]
    nzr[:b] = batch.non_zero_requests[order]
    midx[:b] = mask_index[order]
    active[:b] = True
    u = mask_rows.shape[0]
    u_padded = MASK_ROW_BUCKET * math.ceil(u / MASK_ROW_BUCKET)
    rows = np.zeros((u_padded, nt.capacity), dtype=bool)
    rows[:u] = mask_rows

    ordered = [pods[int(i)] for i in order]
    sp = pack_spread_batch(ordered, snap, nt)
    af = pack_affinity_batch(ordered, snap, nt)
    sc = pack_score_batch(
        ordered, snap, nt, None, DEFAULT_WEIGHTS,
        hard_pod_affinity_weight=1, cluster_affinity_scoring=None,
    )
    sp_t = (
        pad_spread_tensors(sp, padded)
        if sp is not None
        else noop_spread_tensors(padded, nt.capacity)
    )
    af_t = (
        pad_affinity_tensors(af, padded)
        if af is not None
        else noop_affinity_tensors(padded, nt.capacity)
    )
    sc_t = (
        pad_score_tensors(sc, padded)
        if sc is not None
        else noop_score_tensors(padded, nt.capacity)
    )
    common = (
        nt.allocatable, nt.requested, nt.non_zero_requested, nt.valid,
        req, nzr, rows, midx, active,
    )
    return common, tuple(sp_t), tuple(af_t), tuple(sc_t)


@pytest.mark.parametrize("seed", [0, 3, 11, 42])
def test_constrained_kernel_matches_xla(seed):
    common, sp_t, af_t, sc_t = _packed_problem(seed)
    a1, r1, z1 = greedy_assign_constrained(
        *common, sp_t, af_t, sc_t, config=GreedyConfig()
    )
    a2, r2, z2 = pallas_constrained_solve(
        *common, sp_t, af_t, sc_t, config=GreedyConfig(), interpret=True
    )
    np.testing.assert_array_equal(np.asarray(a1), np.asarray(a2))
    np.testing.assert_array_equal(np.asarray(r1), np.asarray(r2))
    np.testing.assert_array_equal(np.asarray(z1), np.asarray(z2))


def test_noop_families_match_basic_path():
    """All-noop family tensors: the constrained kernel must agree with
    the XLA scan on a plain resource batch too."""
    common, _, _, _ = _packed_problem(7)
    padded = common[4].shape[0]
    n_cap = common[0].shape[0]
    sp_t = tuple(noop_spread_tensors(padded, n_cap))
    af_t = tuple(noop_affinity_tensors(padded, n_cap))
    sc_t = tuple(noop_score_tensors(padded, n_cap))
    a1, r1, z1 = greedy_assign_constrained(
        *common, sp_t, af_t, sc_t, config=GreedyConfig()
    )
    a2, r2, z2 = pallas_constrained_solve(
        *common, sp_t, af_t, sc_t, config=GreedyConfig(), interpret=True
    )
    np.testing.assert_array_equal(np.asarray(a1), np.asarray(a2))
    np.testing.assert_array_equal(np.asarray(r1), np.asarray(r2))
    np.testing.assert_array_equal(np.asarray(z1), np.asarray(z2))


def _derive_caps(sp_t, af_t, sc_t):
    """The caps the solver would pick for this batch (all families
    treated as present -- _packed_problem always packs real batches)."""
    from kubernetes_tpu.ops.assignment import caps_for_families

    return caps_for_families(sp_t, af_t, sc_t, True, True, True)


@pytest.mark.parametrize("seed", [0, 11, 42])
def test_constrained_kernel_reduced_caps_matches_xla(seed):
    """The family-specialized kernel (reduced Caps, the VMEM-cap
    breaker) must agree with the XLA scan exactly like the full-caps
    kernel does."""
    common, sp_t, af_t, sc_t = _packed_problem(seed)
    caps = _derive_caps(sp_t, af_t, sc_t)
    a1, r1, z1 = greedy_assign_constrained(
        *common, sp_t, af_t, sc_t, config=GreedyConfig()
    )
    a2, r2, z2 = pallas_constrained_solve(
        *common, sp_t, af_t, sc_t, config=GreedyConfig(),
        interpret=True, caps=caps,
    )
    np.testing.assert_array_equal(np.asarray(a1), np.asarray(a2))
    np.testing.assert_array_equal(np.asarray(r1), np.asarray(r2))
    np.testing.assert_array_equal(np.asarray(z1), np.asarray(z2))


def test_constrained_kernel_zero_caps_matches_basic():
    """All families absent -> Caps all zero: the specialized kernel
    degenerates to the plain greedy scan."""
    from kubernetes_tpu.ops.pallas_constrained import Caps

    common, _, _, _ = _packed_problem(7)
    padded = common[4].shape[0]
    n_cap = common[0].shape[0]
    sp_t = tuple(noop_spread_tensors(padded, n_cap))
    af_t = tuple(noop_affinity_tensors(padded, n_cap))
    sc_t = tuple(noop_score_tensors(padded, n_cap))
    a1, r1, z1 = greedy_assign_constrained(
        *common, sp_t, af_t, sc_t, config=GreedyConfig()
    )
    a2, r2, z2 = pallas_constrained_solve(
        *common, sp_t, af_t, sc_t, config=GreedyConfig(),
        interpret=True, caps=Caps(0, 0, 0, 0, 0, 0, 0),
    )
    np.testing.assert_array_equal(np.asarray(a1), np.asarray(a2))
    np.testing.assert_array_equal(np.asarray(r1), np.asarray(r2))
    np.testing.assert_array_equal(np.asarray(z1), np.asarray(z2))
