"""HTTP extender tests against a real in-process HTTP server
(reference pattern: test/integration/scheduler/extender_test.go)."""

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client.client import Client
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.scheduler.extender import ExtenderConfig, HTTPExtender
from kubernetes_tpu.scheduler.scheduler import new_scheduler
from kubernetes_tpu.testing import make_node, make_pod


class _ExtenderHandler(BaseHTTPRequestHandler):
    bindings = []

    def log_message(self, *a):  # silence
        pass

    def do_POST(self):
        length = int(self.headers["Content-Length"])
        args = json.loads(self.rfile.read(length))
        cache_capable = "nodenames" in args
        if cache_capable:
            names_in = args.get("nodenames", [])
        else:
            names_in = [
                n["metadata"]["name"]
                for n in args.get("nodes", {}).get("items", [])
            ]
        if self.path.endswith("/filter"):
            # reject any node literally named "forbidden"
            names = [n for n in names_in if n != "forbidden"]
            failed = {n: "extender says no" for n in names_in
                      if n == "forbidden"}
            if cache_capable:
                out = {"nodeNames": names, "failedNodes": failed}
            else:
                out = {
                    "nodes": {"items": [{"metadata": {"name": n}}
                                        for n in names]},
                    "failedNodes": failed,
                }
        elif self.path.endswith("/prioritize"):
            # strongly prefer node "preferred"
            out = [
                {"host": n, "score": 10 if n == "preferred" else 0}
                for n in names_in
            ]
        elif self.path.endswith("/bind"):
            _ExtenderHandler.bindings.append(args)
            out = {}
        else:
            self.send_response(404)
            self.end_headers()
            return
        body = json.dumps(out).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


@pytest.fixture
def extender_server():
    _ExtenderHandler.bindings = []
    httpd = HTTPServer(("127.0.0.1", 0), _ExtenderHandler)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{httpd.server_port}"
    httpd.shutdown()


class TestHTTPExtender:
    def test_filter_and_prioritize(self, extender_server):
        from kubernetes_tpu.cache.node_info import NodeInfo

        for cache_capable in (True, False):
            ext = HTTPExtender(ExtenderConfig(
                url_prefix=extender_server,
                filter_verb="filter",
                prioritize_verb="prioritize",
                weight=2,
                node_cache_capable=cache_capable,
            ))
            nodes = [
                NodeInfo(make_node("forbidden").obj()),
                NodeInfo(make_node("preferred").obj()),
            ]
            pod = make_pod("p").obj()
            feasible, failed = ext.filter(pod, nodes)
            assert [ni.node_name for ni in feasible] == ["preferred"], (
                cache_capable
            )
            assert "forbidden" in failed
            scores = ext.prioritize(pod, nodes)
            assert scores["preferred"] == 20  # weighted x2

    def test_managed_resources_interest(self, extender_server):
        ext = HTTPExtender(ExtenderConfig(
            url_prefix=extender_server,
            filter_verb="filter",
            managed_resources=["example.com/fpga"],
        ))
        plain = make_pod("plain").container(cpu="1").obj()
        special = make_pod("special").container(
            cpu="1", **{"example_com__fpga": 1}
        ).obj()
        assert not ext.is_interested(plain)
        assert ext.is_interested(special)

    def test_ignorable_extender_error_passthrough(self):
        ext = HTTPExtender(ExtenderConfig(
            url_prefix="http://127.0.0.1:1",  # nothing listening
            filter_verb="filter",
            ignorable=True,
        ))
        from kubernetes_tpu.cache.node_info import NodeInfo

        nodes = [NodeInfo(make_node("n").obj())]
        feasible, failed = ext.filter(make_pod("p").obj(), nodes)
        assert len(feasible) == 1 and not failed

    def test_non_ignorable_extender_error_raises(self):
        ext = HTTPExtender(ExtenderConfig(
            url_prefix="http://127.0.0.1:1",
            filter_verb="filter",
        ))
        from kubernetes_tpu.cache.node_info import NodeInfo

        with pytest.raises(Exception):
            ext.filter(make_pod("p").obj(), [NodeInfo(make_node("n").obj())])


class TestEndToEndWithExtender:
    def test_extender_steers_scheduling_and_binds(self, extender_server):
        server = APIServer()
        client = Client(server)
        informers = InformerFactory(server)
        cfg = ExtenderConfig(
            url_prefix=extender_server,
            filter_verb="filter",
            prioritize_verb="prioritize",
            bind_verb="bind",
            weight=100,
        )
        sched = new_scheduler(client, informers, extenders=[cfg])
        for name in ("forbidden", "preferred", "other"):
            client.create_node(
                make_node(name).capacity(cpu="8", memory="16Gi").obj()
            )
        informers.start()
        informers.wait_for_cache_sync()
        client.create_pod(make_pod("p").container(cpu="1").obj())
        sched.start()
        deadline = time.time() + 10
        bound = False
        while time.time() < deadline:
            if _ExtenderHandler.bindings:
                bound = True
                break
            time.sleep(0.05)
        sched.stop()
        informers.stop()
        assert bound, "extender bind verb never called"
        assert _ExtenderHandler.bindings[0]["node"] == "preferred"


class TestWireFormat:
    def test_pod_wire_carries_full_spec(self):
        from kubernetes_tpu.scheduler.extender import _pod_to_wire

        pod = (
            make_pod("wire", "prod")
            .labels(app="db")
            .container(cpu="250m", memory="512Mi")
            .obj()
        )
        pod.spec.node_selector = {"disktype": "ssd"}
        wire = _pod_to_wire(pod)
        assert wire["metadata"]["name"] == "wire"
        spec = wire["spec"]
        assert spec["nodeSelector"] == {"disktype": "ssd"}
        c = spec["containers"][0]
        assert c["resources"]["requests"]["cpu"] == "250m"
        assert c["resources"]["requests"]["memory"] == str(512 * 1024 * 1024)

    def test_pod_wire_serializes_affinity(self):
        from kubernetes_tpu.scheduler.extender import _pod_to_wire

        pod = (
            make_pod("aff")
            .pod_affinity("zone", {"app": "db"}, anti=True)
            .obj()
        )
        wire = _pod_to_wire(pod)
        terms = wire["spec"]["affinity"]["podAntiAffinity"][
            "requiredDuringSchedulingIgnoredDuringExecution"
        ]
        assert terms[0]["topologyKey"] == "zone"
        assert terms[0]["labelSelector"]["matchLabels"] == {"app": "db"}
