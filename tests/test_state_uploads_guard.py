"""Tier-1 guard for the device-resident node state (PR 5): a
steady-state 1k-pod burst must perform AT MOST one full [N, R] node
tensor upload (``state_uploads`` must not scale with batch count -- the
carry + generation handshake keep everything else on device), with zero
handshake divergences, and place every pod IDENTICALLY to the
sequential oracle."""

import random
import time

from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client.client import Client
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.scheduler.scheduler import new_scheduler
from kubernetes_tpu.testing import make_node, make_pod

NUM_NODES = 16
NUM_PODS = 1000


class _KeepFirstRng:
    """Deterministic tie-break for the sequential oracle (selectHost
    reservoir sampling): always keep the first candidate, which equals
    the device argmax's lowest-index rule."""

    def randrange(self, n):
        return 1 if n > 1 else 0

    def randint(self, a, b):
        return b


def _build(client, rng):
    for i in range(NUM_NODES):
        client.create_node(
            make_node(f"g{i}")
            .capacity(cpu="64", memory="256Gi", pods=120)
            .obj()
        )
    pods = []
    for i in range(NUM_PODS):
        pods.append(
            make_pod(f"b{i}")
            .creation_timestamp(float(i))
            .container(
                cpu=f"{rng.choice([100, 200, 250])}m",
                memory=f"{rng.choice([128, 256])}Mi",
            )
            .obj()
        )
    return pods


def _wait_all_bound(client, count, timeout=120.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        pods, _ = client.list_pods()
        bound = [p for p in pods if p.spec.node_name]
        if len(bound) >= count:
            return pods
        time.sleep(0.05)
    bound = [p for p in client.list_pods()[0] if p.spec.node_name]
    raise AssertionError(f"only {len(bound)}/{count} pods bound")


def _run(seed, *, batch):
    rng = random.Random(seed)
    server = APIServer()
    client = Client(server)
    informers = InformerFactory(server)
    sched = new_scheduler(
        client, informers, batch=batch, max_batch=256,
        rng=_KeepFirstRng(),
    )
    pods = _build(client, rng)
    informers.start()
    informers.wait_for_cache_sync()
    sched.queue.run()
    for p in pods:
        client.create_pod(p)
    sched.start()
    _wait_all_bound(client, NUM_PODS)
    sched.wait_for_inflight_binds()
    placements = {
        p.metadata.name: p.spec.node_name
        for p in client.list_pods()[0]
    }
    sched.stop()
    informers.stop()
    return placements, sched


def test_steady_state_uploads_bounded_and_oracle_parity():
    want, _oracle = _run(42, batch=False)
    got, sched = _run(42, batch=True)

    # zero placement divergence vs the sequential oracle
    assert all(want.values()), "oracle failed to place a fitting pod"
    assert got == want

    # the whole burst rode the device with NO host fallbacks
    assert sched.pods_fallback == 0
    assert sched.pods_solved_on_device == NUM_PODS
    assert sched.batches_solved >= 2, (
        "burst completed in one batch; the guard needs a multi-batch "
        "steady state to prove anything"
    )

    # THE guard: full [N, R] uploads do not scale with batch count.
    # Zero node-churn events here, so exactly the one cold upload is
    # allowed; every other dispatch must reuse the device carry.
    assert sched.state_uploads <= 1, (
        f"{sched.state_uploads} full node-state uploads for "
        f"{sched.batches_solved} batches -- the carry is not resident"
    )
    assert sched.state_reuses >= sched.batches_solved - 1
    assert sched.carry_divergences == 0


def _bind_transitions_by_uid(server):
    """unbound->bound transitions per pod INCARNATION (uid), replayed
    from the apiserver's full watch history (the test_ha_failover
    harness generalized to churn: uid-keyed, so kill+respawn can't
    mask a double-bind)."""
    w = server.watch("Pod", since_rv=0)
    node = {}
    transitions = {}
    for ev in w.pending():
        pod = ev.object
        uid = pod.metadata.uid
        if ev.type == "DELETED":
            node.pop(uid, None)
            continue
        prev = node.get(uid, "")
        cur = pod.spec.node_name or ""
        if not prev and cur:
            transitions[uid] = transitions.get(uid, 0) + 1
        node[uid] = cur
    w.stop()
    return transitions


def test_churn_burst_uploads_bounded_no_double_binds():
    """PR-6 guard: a 1k-pod burst with 5% node churn (2 cold nodes
    join schedulable, 2 more flap in and out cordoned -- 2 of 40 nodes
    flapped) keeps ``state_uploads <= 1``: membership changes ride the
    in-buffer slot scatters, never a full [N, R] re-upload, with ZERO
    handshake divergences and ZERO double-binds against the full watch
    history."""
    rng = random.Random(7)
    server = APIServer()
    client = Client(server)
    informers = InformerFactory(server)
    sched = new_scheduler(
        client, informers, batch=True, max_batch=256, rng=_KeepFirstRng(),
    )
    num_initial = 38
    for i in range(num_initial):
        client.create_node(
            make_node(f"g{i}")
            .capacity(cpu="64", memory="256Gi", pods=120)
            .obj()
        )
    informers.start()
    informers.wait_for_cache_sync()
    sched.queue.run()

    def _mk_pods(lo, hi):
        out = []
        for i in range(lo, hi):
            out.append(
                make_pod(f"b{i}")
                .creation_timestamp(float(i))
                .container(
                    cpu=f"{rng.choice([100, 200, 250])}m",
                    memory=f"{rng.choice([128, 256])}Mi",
                )
                .obj()
            )
        return out

    sched.start()
    # wave 1: half the burst lands and the carry goes resident
    for p in _mk_pods(0, 500):
        client.create_pod(p)
    _wait_all_bound(client, 500)

    # -- the churn: cold scale-up + a cordoned flap ---------------------
    for name in ("cold-0", "cold-1"):
        client.create_node(
            make_node(name)
            .capacity(cpu="64", memory="256Gi", pods=120)
            .obj()
        )
    for name in ("flap-0", "flap-1"):
        client.create_node(
            make_node(name)
            .capacity(cpu="64", memory="256Gi", pods=120)
            .unschedulable()
            .obj()
        )

    # wave 2 schedules INTO the churn
    for p in _mk_pods(500, 750):
        client.create_pod(p)
    _wait_all_bound(client, 750)
    # the flapped nodes retire (spot reclaim of empty capacity)
    client.delete_node("flap-0")
    client.delete_node("flap-1")
    for p in _mk_pods(750, 1000):
        client.create_pod(p)
    _wait_all_bound(client, NUM_PODS)
    sched.wait_for_inflight_binds()

    pods, _ = client.list_pods()
    assert all(p.spec.node_name for p in pods)
    # cold capacity actually took load: the scale-up is real
    assert any(
        p.spec.node_name in ("cold-0", "cold-1") for p in pods
    ), "no pod landed on the cold scale-up nodes"

    # THE guard: membership churn rode the slot scatters
    assert sched.state_uploads <= 1, (
        f"{sched.state_uploads} full uploads under churn -- membership "
        f"changes are re-uploading [N, R]"
    )
    assert sched.carry_divergences == 0
    assert sched.membership_row_patches >= 4  # 4 adds + 2 retires seen
    tc = sched.tensor_cache
    assert tc.full_repacks == 1  # only the cold pack
    assert tc.rows_added == 4
    assert tc.rows_retired == 2
    assert sched.pods_fallback == 0
    assert sched.batches_solved >= 3

    # zero double-binds, against the full watch history
    transitions = _bind_transitions_by_uid(server)
    doubles = {u: c for u, c in transitions.items() if c > 1}
    assert not doubles, f"double-bound incarnations: {doubles}"

    sched.stop()
    informers.stop()
