"""Tier-1 guard for the device-resident node state (PR 5): a
steady-state 1k-pod burst must perform AT MOST one full [N, R] node
tensor upload (``state_uploads`` must not scale with batch count -- the
carry + generation handshake keep everything else on device), with zero
handshake divergences, and place every pod IDENTICALLY to the
sequential oracle."""

import random
import time

from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client.client import Client
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.scheduler.scheduler import new_scheduler
from kubernetes_tpu.testing import make_node, make_pod

NUM_NODES = 16
NUM_PODS = 1000


class _KeepFirstRng:
    """Deterministic tie-break for the sequential oracle (selectHost
    reservoir sampling): always keep the first candidate, which equals
    the device argmax's lowest-index rule."""

    def randrange(self, n):
        return 1 if n > 1 else 0

    def randint(self, a, b):
        return b


def _build(client, rng):
    for i in range(NUM_NODES):
        client.create_node(
            make_node(f"g{i}")
            .capacity(cpu="64", memory="256Gi", pods=120)
            .obj()
        )
    pods = []
    for i in range(NUM_PODS):
        pods.append(
            make_pod(f"b{i}")
            .creation_timestamp(float(i))
            .container(
                cpu=f"{rng.choice([100, 200, 250])}m",
                memory=f"{rng.choice([128, 256])}Mi",
            )
            .obj()
        )
    return pods


def _wait_all_bound(client, count, timeout=120.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        pods, _ = client.list_pods()
        bound = [p for p in pods if p.spec.node_name]
        if len(bound) >= count:
            return pods
        time.sleep(0.05)
    bound = [p for p in client.list_pods()[0] if p.spec.node_name]
    raise AssertionError(f"only {len(bound)}/{count} pods bound")


def _run(seed, *, batch):
    rng = random.Random(seed)
    server = APIServer()
    client = Client(server)
    informers = InformerFactory(server)
    sched = new_scheduler(
        client, informers, batch=batch, max_batch=256,
        rng=_KeepFirstRng(),
    )
    pods = _build(client, rng)
    informers.start()
    informers.wait_for_cache_sync()
    sched.queue.run()
    for p in pods:
        client.create_pod(p)
    sched.start()
    _wait_all_bound(client, NUM_PODS)
    sched.wait_for_inflight_binds()
    placements = {
        p.metadata.name: p.spec.node_name
        for p in client.list_pods()[0]
    }
    sched.stop()
    informers.stop()
    return placements, sched


def test_steady_state_uploads_bounded_and_oracle_parity():
    want, _oracle = _run(42, batch=False)
    got, sched = _run(42, batch=True)

    # zero placement divergence vs the sequential oracle
    assert all(want.values()), "oracle failed to place a fitting pod"
    assert got == want

    # the whole burst rode the device with NO host fallbacks
    assert sched.pods_fallback == 0
    assert sched.pods_solved_on_device == NUM_PODS
    assert sched.batches_solved >= 2, (
        "burst completed in one batch; the guard needs a multi-batch "
        "steady state to prove anything"
    )

    # THE guard: full [N, R] uploads do not scale with batch count.
    # Zero node-churn events here, so exactly the one cold upload is
    # allowed; every other dispatch must reuse the device carry.
    assert sched.state_uploads <= 1, (
        f"{sched.state_uploads} full node-state uploads for "
        f"{sched.batches_solved} batches -- the carry is not resident"
    )
    assert sched.state_reuses >= sched.batches_solved - 1
    assert sched.carry_divergences == 0
