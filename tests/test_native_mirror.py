"""Differential tests for native mirror_scatter (ISSUE 18).

The bind-echo -> shadow-mirror hot loop (native/_hotpath.c
mirror_scatter) compacts a batch's placed rows and scatter-adds their
demand into the committer's shadow expectation in one C pass. Its
pure-Python twin is scheduler/batch._mirror_scatter_py; the randomized
suite here drives both over seeded assignment batches (NO_NODE
sprinkle, duplicate targets, empty batches) and asserts bit-equal
shadows AND compacted outputs. The validate-before-mutate contract is
pinned separately: an out-of-range assignment must raise before ANY
shadow byte changes, so the dispatcher's fallback-to-twin never
double-applies a delta.
"""

import numpy as np
import pytest

from kubernetes_tpu import native
from kubernetes_tpu.ops.assignment import NO_NODE
from kubernetes_tpu.scheduler.batch import _mirror_scatter, _mirror_scatter_py

needs_native = pytest.mark.skipif(
    native.hotpath is None or native.hotpath.mirror_scatter is None,
    reason="native extension unavailable",
)


def _rand_case(rng):
    b = int(rng.integers(0, 48))
    r = int(rng.integers(1, 7))
    n = int(rng.integers(1, 40))
    a = rng.integers(-1, n, size=max(b, 1)).astype(np.int32)[:b]
    a[rng.random(b) < 0.3] = NO_NODE
    req = rng.integers(0, 5000, size=(b, r)).astype(np.int32)
    nzr = rng.integers(0, 5000, size=(b, 2)).astype(np.int32)
    req_shadow = rng.integers(0, 10000, size=(n, r)).astype(np.int32)
    nzr_shadow = rng.integers(0, 10000, size=(n, 2)).astype(np.int32)
    return a, b, req, nzr, req_shadow, nzr_shadow


@needs_native
class TestMirrorScatterDifferential:
    def test_randomized_bit_equal(self):
        fn = native.hotpath.mirror_scatter
        rng = np.random.default_rng(18)
        nonempty = 0
        for _ in range(300):
            a, b, req, nzr, rs, ns = _rand_case(rng)
            rs_c, ns_c = rs.copy(), ns.copy()
            py = _mirror_scatter_py(a, b, req, nzr, rs, ns)
            rows_out = np.empty(b, dtype=np.int64)
            req_out = np.empty((b, req.shape[1]), dtype=np.int32)
            nzr_out = np.empty((b, 2), dtype=np.int32)
            k = fn(
                np.ascontiguousarray(a[:b], dtype=np.int32),
                np.ascontiguousarray(req[:b]),
                np.ascontiguousarray(nzr[:b]),
                rs_c, ns_c, rows_out, req_out, nzr_out,
            )
            assert np.array_equal(rs, rs_c)
            assert np.array_equal(ns, ns_c)
            if py is None:
                assert k == 0
            else:
                nonempty += 1
                assert k == py[0].size
                assert np.array_equal(rows_out[:k], py[0])
                assert np.array_equal(req_out[:k], py[1])
                assert np.array_equal(nzr_out[:k], py[2])
        assert nonempty > 100  # the fuzz actually exercised placements

    def test_duplicate_targets_accumulate(self):
        # two pods landing on the SAME node must both add (np.add.at
        # semantics) -- the classic fancy-index += bug the twin avoids
        fn = native.hotpath.mirror_scatter
        a = np.array([2, 2, NO_NODE, 2], dtype=np.int32)
        req = np.full((4, 3), 10, dtype=np.int32)
        nzr = np.full((4, 2), 7, dtype=np.int32)
        rs = np.zeros((5, 3), dtype=np.int32)
        ns = np.zeros((5, 2), dtype=np.int32)
        k = fn(a, req, nzr, rs, ns, np.empty(4, np.int64),
               np.empty((4, 3), np.int32), np.empty((4, 2), np.int32))
        assert k == 3
        assert rs[2].tolist() == [30, 30, 30]
        assert ns[2].tolist() == [21, 21]
        assert rs[[0, 1, 3, 4]].sum() == 0

    def test_out_of_range_raises_before_mutating(self):
        fn = native.hotpath.mirror_scatter
        a = np.array([1, 99], dtype=np.int32)
        req = np.ones((2, 3), dtype=np.int32)
        nzr = np.ones((2, 2), dtype=np.int32)
        rs = np.zeros((4, 3), dtype=np.int32)
        ns = np.zeros((4, 2), dtype=np.int32)
        with pytest.raises(ValueError):
            fn(a, req, nzr, rs, ns, np.empty(2, np.int64),
               np.empty((2, 3), np.int32), np.empty((2, 2), np.int32))
        assert rs.sum() == 0 and ns.sum() == 0

    def test_empty_batch(self):
        fn = native.hotpath.mirror_scatter
        rs = np.zeros((3, 2), dtype=np.int32)
        ns = np.zeros((3, 2), dtype=np.int32)
        k = fn(np.empty(0, np.int32), np.empty((0, 2), np.int32),
               np.empty((0, 2), np.int32), rs, ns,
               np.empty(0, np.int64), np.empty((0, 2), np.int32),
               np.empty((0, 2), np.int32))
        assert k == 0


class TestMirrorScatterDispatch:
    def test_env_off_routes_to_twin(self, monkeypatch):
        # KTPU_NATIVE_INGEST=0 is the configured path: no fallback booked
        monkeypatch.setenv("KTPU_NATIVE_INGEST", "0")
        rng = np.random.default_rng(7)
        a, b, req, nzr, rs, ns = _rand_case(rng)
        rs_c, ns_c = rs.copy(), ns.copy()
        out = _mirror_scatter(a, b, req, nzr, rs_c, ns_c)
        py = _mirror_scatter_py(a, b, req, nzr, rs, ns)
        assert np.array_equal(rs, rs_c) and np.array_equal(ns, ns_c)
        if py is None:
            assert out is None
        else:
            for got, want in zip(out, py):
                assert np.array_equal(got, want)

    @needs_native
    def test_env_on_matches_twin(self, monkeypatch):
        monkeypatch.setenv("KTPU_NATIVE_INGEST", "1")
        rng = np.random.default_rng(11)
        for _ in range(20):
            a, b, req, nzr, rs, ns = _rand_case(rng)
            rs_c, ns_c = rs.copy(), ns.copy()
            out = _mirror_scatter(a, b, req, nzr, rs_c, ns_c)
            py = _mirror_scatter_py(a, b, req, nzr, rs, ns)
            assert np.array_equal(rs, rs_c) and np.array_equal(ns, ns_c)
            if py is None:
                assert out is None
            else:
                for got, want in zip(out, py):
                    assert np.array_equal(got, want)
