"""Priority-band queue jumping + adaptive-window accounting in
PriorityQueue.pop_batch (streaming subsystem): high-band pods cut the
batch window instead of waiting it out, a mid-window controller shrink
applies immediately but a grow never extends an armed deadline, the
pop_wait/pop_batch timer split stays honest under band drains, and the
priority-inversion e2e pins the starvation bound (high-prio p99 stays
bounded while a bulk backlog drains)."""

import threading
import time

import pytest

from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client.client import Client
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.plugins.queuesort import PrioritySort
from kubernetes_tpu.queue.scheduling_queue import PriorityQueue
from kubernetes_tpu.scheduler.scheduler import new_scheduler
from kubernetes_tpu.testing import make_node, make_pod
from kubernetes_tpu.utils import metrics

HIGH = 100


def _queue(band_threshold=None):
    sorter = PrioritySort()
    q = PriorityQueue(
        sorter.queue_sort_less, sort_key_func=sorter.queue_sort_key
    )
    q.band_threshold = band_threshold
    return q


def _pod(name, priority=0):
    return make_pod(name).priority(priority).obj()


class TestBandAwareDrain:
    def test_high_band_pod_skips_window(self):
        q = _queue(band_threshold=50)
        q.add(_pod("hi-0", HIGH))
        t0 = time.perf_counter()
        batch = q.pop_batch(10, timeout=0.0, window=5.0)
        elapsed = time.perf_counter() - t0
        assert [pi.pod.metadata.name for pi in batch] == ["hi-0"]
        assert elapsed < 1.0, "high-band pod waited out the window"

    def test_bulk_pods_still_wait_window(self):
        q = _queue(band_threshold=50)
        q.add(_pod("bulk-0", 0))
        t0 = time.perf_counter()
        batch = q.pop_batch(10, timeout=0.0, window=0.3)
        elapsed = time.perf_counter() - t0
        assert len(batch) == 1
        assert elapsed >= 0.25, "bulk-only batch should use the window"

    def test_high_band_arrival_cuts_window_short(self):
        """A high-band pod arriving DURING the window wait dispatches
        the batch immediately -- it must not sit behind the bulk
        batch's amortization wait."""
        q = _queue(band_threshold=50)
        q.add(_pod("bulk-0", 0))
        out = {}

        def drain():
            t0 = time.perf_counter()
            out["batch"] = q.pop_batch(10, timeout=0.0, window=5.0)
            out["elapsed"] = time.perf_counter() - t0

        t = threading.Thread(target=drain)
        t.start()
        time.sleep(0.15)  # let the drain arm its window
        q.add(_pod("hi-0", HIGH))
        t.join(timeout=5.0)
        assert not t.is_alive(), "drain still waiting after band arrival"
        names = {pi.pod.metadata.name for pi in out["batch"]}
        assert names == {"bulk-0", "hi-0"}
        assert out["elapsed"] < 2.0

    def test_bands_off_is_flat_drain(self):
        q = _queue(band_threshold=None)
        q.add(_pod("hi-0", HIGH))
        t0 = time.perf_counter()
        batch = q.pop_batch(10, timeout=0.0, window=0.3)
        elapsed = time.perf_counter() - t0
        assert len(batch) == 1
        # without bands a high-priority pod waits the window like
        # anything else (the pre-PR-7 contract, unchanged)
        assert elapsed >= 0.25

    def test_band_wait_histogram_recorded(self):
        before_high = metrics.queue_band_wait.count(band="high")
        before_bulk = metrics.queue_band_wait.count(band="bulk")
        q = _queue(band_threshold=50)
        q.add_many([_pod("b-0", 0), _pod("b-1", 0), _pod("h-0", HIGH)])
        batch = q.pop_batch(10, timeout=0.0, window=0.0)
        assert len(batch) == 3
        assert metrics.queue_band_wait.count(band="high") == before_high + 1
        assert metrics.queue_band_wait.count(band="bulk") == before_bulk + 2


class TestAdaptiveWindow:
    def test_mid_window_shrink_applies_immediately(self):
        q = _queue()
        q.add(_pod("bulk-0", 0))
        window = {"value": 5.0}
        out = {}

        def drain():
            t0 = time.perf_counter()
            out["batch"] = q.pop_batch(
                10, timeout=0.0, window=lambda: window["value"]
            )
            out["elapsed"] = time.perf_counter() - t0

        t = threading.Thread(target=drain)
        t.start()
        time.sleep(0.15)
        window["value"] = 0.01  # the controller shrinks mid-window
        # wake the waiter so it re-reads the window (the scheduler's
        # own add/notify traffic does this in production; the queue
        # also re-checks at every wakeup)
        q.add(_pod("bulk-1", 0))
        t.join(timeout=5.0)
        assert not t.is_alive(), "shrink did not apply mid-window"
        assert len(out["batch"]) == 2
        assert out["elapsed"] < 2.0

    def test_grow_never_extends_armed_deadline(self):
        """The deadline arms from the window in force at drain start; a
        controller GROW mid-window must not stretch it -- the pods
        already drained were promised the original window."""
        q = _queue()
        q.add(_pod("bulk-0", 0))
        calls = {"n": 0}

        def window():
            calls["n"] += 1
            # armed at 0.2s, then the controller "grows" to 10s
            return 0.2 if calls["n"] == 1 else 10.0

        t0 = time.perf_counter()
        batch = q.pop_batch(10, timeout=0.0, window=window)
        elapsed = time.perf_counter() - t0
        assert len(batch) == 1
        assert elapsed < 2.0, (
            f"armed 0.2s deadline stretched to {elapsed:.2f}s by a "
            f"mid-window grow"
        )

    def test_shrink_is_monotone_once_applied(self):
        """Shrink then re-grow inside one window: the strictest window
        observed wins (deadline only ever moves earlier)."""
        q = _queue()
        q.add(_pod("bulk-0", 0))
        seq = iter([2.0, 0.1, 10.0, 10.0, 10.0])
        last = [0.1]

        def window():
            try:
                last[0] = next(seq)
            except StopIteration:
                pass
            return last[0]

        out = {}

        def drain():
            t0 = time.perf_counter()
            out["batch"] = q.pop_batch(10, timeout=0.0, window=window)
            out["elapsed"] = time.perf_counter() - t0

        t = threading.Thread(target=drain)
        t.start()
        time.sleep(0.05)
        q.add(_pod("bulk-1", 0))  # wakeup: window() reads 0.1 then 10.0
        t.join(timeout=5.0)
        assert not t.is_alive()
        assert out["elapsed"] < 1.5

    def test_pop_wait_split_stays_honest(self):
        q = _queue(band_threshold=50)
        # pre-filled queue: no wait at all
        q.add_many([_pod(f"p-{i}", 0) for i in range(5)])
        q.pop_batch(10, timeout=0.0, window=0.0)
        assert q.last_pop_wait_seconds < 0.05
        # empty queue: the whole timeout is WAIT, not drain work
        t0 = time.perf_counter()
        batch = q.pop_batch(10, timeout=0.25, window=0.0)
        elapsed = time.perf_counter() - t0
        assert batch == []
        assert q.last_pop_wait_seconds == pytest.approx(elapsed, abs=0.1)
        assert q.last_pop_wait_seconds >= 0.15
        # window wait counts as wait; a band cut keeps only the time
        # actually waited
        q.add(_pod("bulk-0", 0))
        out = {}

        def drain():
            out["batch"] = q.pop_batch(10, timeout=0.0, window=5.0)
            out["waited"] = q.last_pop_wait_seconds

        t = threading.Thread(target=drain)
        t.start()
        time.sleep(0.2)
        q.add(_pod("hi-0", HIGH))
        t.join(timeout=5.0)
        assert not t.is_alive()
        assert 0.05 < out["waited"] < 2.0, (
            "band-cut window wait must record the waited time, not the "
            "full window"
        )


# -- priority-inversion e2e ---------------------------------------------------


class _BindTimes:
    """Watch-driven name -> bind wall clock (perf_counter)."""

    def __init__(self, server):
        self._watch = server.watch("Pod", since_rv=server.current_rv())
        self.times = {}
        self._stop = False
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        while not self._stop:
            for ev in self._watch.next_batch(timeout=0.2) or []:
                pod = ev.object
                if ev.type == "MODIFIED" and pod.spec.node_name:
                    self.times.setdefault(
                        pod.metadata.name, time.perf_counter()
                    )

    def stop(self):
        self._stop = True
        self._watch.stop()
        self._thread.join(timeout=2)


def test_priority_inversion_e2e_high_band_bounded_behind_bulk():
    """THE starvation-bound e2e: a bulk backlog (2,500 prio-0 pods,
    forced through many batches) is mid-drain when high-priority pods
    arrive. With bands on, every high-prio pod must bind while a large
    chunk of the bulk backlog is STILL pending, and the high band's
    worst-case latency must be a small fraction of the bulk drain --
    high-priority pods never queue behind the backlog."""
    server = APIServer()
    client = Client(server)
    informers = InformerFactory(server)
    sched = new_scheduler(client, informers, batch=True, max_batch=192)
    sched.batch_window = 0.1  # throughput-ish window the band must cut
    sched.queue.band_threshold = 50
    for i in range(30):
        client.create_node(
            make_node(f"n{i}").capacity(cpu="64", memory="256Gi", pods=120)
            .obj()
        )
    informers.start()
    informers.wait_for_cache_sync()
    sched.queue.run()
    sched.warmup()

    n_bulk, n_high = 2500, 12
    bulk = [
        make_pod(f"bulk-{i}").container(cpu="100m", memory="128Mi").obj()
        for i in range(n_bulk)
    ]
    binds = _BindTimes(server)
    for i in range(0, n_bulk, 256):
        client.create_pods_bulk(bulk[i:i + 256])
    sched.start()

    # wait for the drain to be genuinely mid-flight
    deadline = time.time() + 120
    while len(binds.times) < n_bulk // 10 and time.time() < deadline:
        time.sleep(0.01)
    assert len(binds.times) >= n_bulk // 10, "bulk drain never started"

    high = [
        make_pod(f"hi-{i}").priority(100)
        .container(cpu="100m", memory="128Mi").obj()
        for i in range(n_high)
    ]
    t_high_created = time.perf_counter()
    client.create_pods_bulk(high)

    deadline = time.time() + 120
    while (
        sum(1 for i in range(n_high) if f"hi-{i}" in binds.times) < n_high
        and time.time() < deadline
    ):
        time.sleep(0.01)
    high_times = [binds.times.get(f"hi-{i}") for i in range(n_high)]
    assert all(t is not None for t in high_times), (
        f"only {sum(t is not None for t in high_times)}/{n_high} "
        f"high-prio pods bound"
    )
    t_high_done = max(high_times)
    bulk_done_at_high = sum(
        1 for i in range(n_bulk)
        if binds.times.get(f"bulk-{i}", float("inf")) <= t_high_done
    )

    # let the backlog finish so the drain span is measurable
    deadline = time.time() + 180
    while len(binds.times) < n_bulk + n_high and time.time() < deadline:
        time.sleep(0.05)
    assert len(binds.times) >= n_bulk + n_high, "bulk backlog never drained"
    sched.wait_for_inflight_binds()
    binds.stop()

    bulk_span = max(
        binds.times[f"bulk-{i}"] for i in range(n_bulk)
    ) - min(binds.times[f"bulk-{i}"] for i in range(n_bulk))
    high_worst = t_high_done - t_high_created

    # THE starvation bound: every high-prio pod bound while a large
    # chunk of the bulk backlog was still pending...
    assert bulk_done_at_high < int(n_bulk * 0.9), (
        f"high band finished only after {bulk_done_at_high}/{n_bulk} "
        f"bulk pods -- it waited behind the backlog"
    )
    # ...and the band's worst-case latency is a fraction of the drain
    assert high_worst < max(2.0, 0.5 * bulk_span), (
        f"high-band worst latency {high_worst:.2f}s vs bulk drain span "
        f"{bulk_span:.2f}s"
    )
    sched.stop()
    informers.stop()


class TestPriorityClassBand:
    """ROADMAP item-2 residual d: PriorityClass OBJECTS -- not raw
    integers -- select the band. The named class's value arms the queue
    threshold (and tracks updates live), and the admission classifier
    stamps each pod's class-resolved priority once at ingest so the
    drain-time band check stays a memo read."""

    def _wired(self, band_class="critical"):
        from kubernetes_tpu.api.types import ObjectMeta, PriorityClass
        from kubernetes_tpu.config.loader import load_config_from_dict
        from kubernetes_tpu.scheduler.scheduler import (
            new_scheduler_from_config,
        )

        server = APIServer()
        server.create(PriorityClass(
            metadata=ObjectMeta(name="critical"), value=90
        ))
        cfg = load_config_from_dict({
            "tpuSolver": {"maxBatch": 128},
            "streaming": {"enabled": True, "bandPriorityClass": band_class},
        })
        client = Client(server)
        informers = InformerFactory(server)
        sched = new_scheduler_from_config(client, informers, cfg)
        informers.start()
        informers.wait_for_cache_sync()
        return server, informers, sched

    def test_class_value_arms_threshold_at_sync(self):
        server, informers, sched = self._wired()
        try:
            assert sched.queue.band_threshold == 90
        finally:
            sched.stop()
            informers.stop()

    def test_class_update_rearms_live_and_delete_disarms(self):
        server, informers, sched = self._wired()
        try:
            def bump(obj):
                obj.value = 120

            server.guaranteed_update(
                "PriorityClass", "default", "critical", bump
            )
            deadline = time.time() + 5
            while time.time() < deadline and (
                sched.queue.band_threshold != 120
            ):
                time.sleep(0.02)
            assert sched.queue.band_threshold == 120
            server.delete("PriorityClass", "default", "critical")
            deadline = time.time() + 5
            while time.time() < deadline and (
                sched.queue.band_threshold is not None
            ):
                time.sleep(0.02)
            assert sched.queue.band_threshold is None
        finally:
            sched.stop()
            informers.stop()

    def test_classifier_stamps_class_resolved_priority(self):
        server, informers, sched = self._wired()
        try:
            pod = make_pod("pc-1").container(
                cpu="100m", memory="128Mi"
            ).obj()
            pod.spec.priority_class_name = "critical"
            assert pod.spec.priority == 0  # only the class names it
            sched.classify_pod(pod)
            assert pod.__dict__["_band_priority"] == 90
            # an explicit numeric priority wins over the class
            pod2 = make_pod("pc-2").priority(7).obj()
            pod2.spec.priority_class_name = "critical"
            sched.classify_pod(pod2)
            assert pod2.__dict__["_band_priority"] == 7
        finally:
            sched.stop()
            informers.stop()

    def test_class_resolved_pod_cuts_window(self):
        """A pod whose ONLY priority signal is its PriorityClass must
        still cut the band window (the memo, not spec.priority, drives
        the drain check)."""
        server, informers, sched = self._wired()
        try:
            q = _queue(band_threshold=90)
            low = _pod("low-1", priority=0)
            classy = make_pod("classy").obj()
            classy.spec.priority_class_name = "critical"
            sched.classify_pod(classy)
            q.add(low)
            q.add(classy)
            t0 = time.perf_counter()
            batch = q.pop_batch(10, timeout=0.5, window=5.0)
            took = time.perf_counter() - t0
            assert {pi.pod.metadata.name for pi in batch} == {
                "low-1", "classy"
            }
            assert took < 2.0, "class-resolved pod failed to cut window"
        finally:
            sched.stop()
            informers.stop()
