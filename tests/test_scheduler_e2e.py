"""End-to-end: pods created via the API get bound by the scheduler loop.

Mirrors the reference integration tests (test/integration/scheduler/) with
the in-process API server standing in for apiserver+etcd.
"""

import random

from kubernetes_tpu.apiserver import APIServer
from kubernetes_tpu.client import Client, InformerFactory
from kubernetes_tpu.scheduler import new_scheduler
from kubernetes_tpu.testing import make_node, make_pod


def _setup(async_binding=False):
    api = APIServer()
    client = Client(api)
    factory = InformerFactory(api)
    sched = new_scheduler(
        client,
        factory,
        async_binding=async_binding,
        rng=random.Random(7),
    )
    factory.pump()
    return api, client, factory, sched


def _drive(sched, factory, max_iters=200):
    """Pump informers and run scheduling iterations until idle."""
    for _ in range(max_iters):
        factory.pump()
        if not sched.schedule_one(timeout=0.01):
            if factory.pump() == 0:
                break
    factory.pump()


def test_pods_get_bound():
    api, client, factory, sched = _setup()
    for i in range(3):
        client.create_node(make_node(f"n{i}").capacity(cpu="4", memory="8Gi").obj())
    for i in range(6):
        client.create_pod(make_pod(f"p{i}").container(cpu="1", memory="1Gi").obj())
    _drive(sched, factory)
    pods, _ = client.list_pods()
    assert all(p.spec.node_name for p in pods), [
        (p.name, p.spec.node_name) for p in pods
    ]
    # spread over nodes by LeastAllocated: no node got everything
    nodes_used = {p.spec.node_name for p in pods}
    assert len(nodes_used) == 3


def test_unschedulable_pod_retries_after_node_add():
    api, client, factory, sched = _setup()
    client.create_pod(make_pod("big").container(cpu="8", memory="1Gi").obj())
    _drive(sched, factory)
    pod = client.get_pod("default", "big")
    assert not pod.spec.node_name
    conditions = {c.type: c for c in pod.status.conditions}
    assert conditions["PodScheduled"].status == "False"
    assert conditions["PodScheduled"].reason == "Unschedulable"

    # capacity arrives -> pod is woken and scheduled (after backoff)
    client.create_node(make_node("huge").capacity(cpu="16", memory="32Gi").obj())
    factory.pump()
    sched.queue.flush_backoff_q_completed()
    import time

    deadline = time.time() + 5
    while time.time() < deadline:
        factory.pump()
        sched.queue.flush_backoff_q_completed()
        if sched.schedule_one(timeout=0.05):
            factory.pump()
            pod = client.get_pod("default", "big")
            if pod.spec.node_name:
                break
    assert client.get_pod("default", "big").spec.node_name == "huge"


def test_higher_priority_scheduled_first_under_scarcity():
    api, client, factory, sched = _setup()
    client.create_node(make_node("n").capacity(cpu="2", memory="4Gi").obj())
    client.create_pod(
        make_pod("low").priority(1).container(cpu="2", memory="1Gi").obj()
    )
    client.create_pod(
        make_pod("high").priority(10).container(cpu="2", memory="1Gi").obj()
    )
    _drive(sched, factory)
    assert client.get_pod("default", "high").spec.node_name == "n"
    assert not client.get_pod("default", "low").spec.node_name


def test_node_selector_respected_e2e():
    api, client, factory, sched = _setup()
    client.create_node(
        make_node("gpu-node").label("accel", "tpu").capacity(cpu="4", memory="8Gi").obj()
    )
    client.create_node(make_node("plain").capacity(cpu="4", memory="8Gi").obj())
    client.create_pod(
        make_pod("picky").node_selector(accel="tpu").container(cpu="1", memory="1Gi").obj()
    )
    _drive(sched, factory)
    assert client.get_pod("default", "picky").spec.node_name == "gpu-node"


def test_async_binding_mode():
    api, client, factory, sched = _setup(async_binding=True)
    client.create_node(make_node("n").capacity(cpu="8", memory="16Gi").obj())
    for i in range(4):
        client.create_pod(make_pod(f"p{i}").container(cpu="1", memory="1Gi").obj())
    for _ in range(10):
        factory.pump()
        sched.schedule_one(timeout=0.05)
    assert sched.wait_for_inflight_binds(timeout=5)
    factory.pump()
    pods, _ = client.list_pods()
    assert all(p.spec.node_name for p in pods)
    sched.stop()


def test_tainted_node_avoided():
    api, client, factory, sched = _setup()
    client.create_node(
        make_node("tainted").taint("dedicated", "infra").capacity(cpu="4", memory="8Gi").obj()
    )
    client.create_node(make_node("open").capacity(cpu="4", memory="8Gi").obj())
    client.create_pod(make_pod("p").container(cpu="1", memory="1Gi").obj())
    _drive(sched, factory)
    assert client.get_pod("default", "p").spec.node_name == "open"
