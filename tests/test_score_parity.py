"""System-level differential test (SURVEY.md section 4 tier 5): the SAME
cluster + pending set scheduled through the TPU batch path and the
sequential host path must produce IDENTICAL placements, with the full
default score plugin set in play (ImageLocality, preferred NodeAffinity,
TaintToleration PreferNoSchedule, NodePreferAvoidPods, SelectorSpread,
soft + hard PodTopologySpread, required pod (anti-)affinity, resource
scorers).

Tie-break note: the sequential select_host reservoir-samples among ties
(generic_scheduler.go:242) while the device argmax picks the lowest node
index; the sequential scheduler here gets an rng that never replaces the
incumbent, and scenarios are seeded so score ties don't decide
placements.
"""

import json
import time

import pytest

from kubernetes_tpu.api.types import OwnerReference, Service
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client.client import Client
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.scheduler.scheduler import new_scheduler
from kubernetes_tpu.testing import make_node, make_pod


class _KeepFirstRng:
    """Reservoir sampling never replaces: sequential select_host keeps
    the first max, matching the device argmax (lowest index)."""

    def randrange(self, n):
        return 1 if n > 1 else 0

    def randint(self, a, b):
        return b

    def random(self):
        return 1.0

    def sample(self, population, k):
        return list(population)[:k]


def _build_cluster(client):
    """A cluster exercising every score family. Node order matters: the
    device solves against snapshot order."""
    avoid_annotation = json.dumps(
        {
            "preferAvoidPods": [
                {
                    "podSignature": {
                        "podController": {
                            "kind": "ReplicaSet",
                            "uid": "rs-avoided",
                        }
                    }
                }
            ]
        }
    )
    for i in range(6):
        n = (
            make_node(f"n{i}")
            .labels(
                zone=f"z{i % 3}",
                **{"failure-domain.beta.kubernetes.io/zone": f"z{i % 3}"},
            )
            .capacity(cpu="16", memory="32Gi", pods=40)
        )
        if i in (0, 3):
            n = n.image("registry/app:v1", 500 * 1024 * 1024)
        if i == 1:
            n = n.taint("flaky", "true", effect="PreferNoSchedule")
        n = n.obj()
        if i == 2:
            n.metadata.annotations[
                "scheduler.alpha.kubernetes.io/preferAvoidPods"
            ] = avoid_annotation
        client.create_node(n)
    svc = Service()
    svc.metadata.name = "websvc"
    svc.metadata.namespace = "default"
    svc.selector = {"app": "web"}
    client.create(svc)
    # existing load so resource scores differ across nodes
    for i, (node, cpu) in enumerate(
        [("n0", "2"), ("n1", "4"), ("n2", "1"), ("n4", "6")]
    ):
        client.create_pod(
            make_pod(f"existing-{i}")
            .node(node)
            .labels(app="web" if i % 2 == 0 else "db")
            .container(cpu=cpu, memory=f"{1 + i}Gi")
            .obj()
        )


def _pending_pods():
    pods = []
    ts = 0.0

    def add(p):
        nonlocal ts
        pods.append(p.creation_timestamp(ts).obj())
        ts += 1.0

    # plain resource pods
    for i in range(4):
        add(make_pod(f"plain-{i}").container(cpu="500m", memory="1Gi"))
    # image-locality pods
    for i in range(2):
        add(
            make_pod(f"img-{i}").container(
                cpu="250m", memory="512Mi", image="registry/app:v1"
            )
        )
    # preferred node affinity to z1
    for i in range(2):
        add(
            make_pod(f"naff-{i}")
            .container(cpu="250m", memory="512Mi")
            .preferred_node_affinity_in("zone", ["z1"], weight=10)
        )
    # service-owned pods (SelectorSpread)
    for i in range(4):
        add(
            make_pod(f"web-{i}")
            .labels(app="web")
            .container(cpu="250m", memory="512Mi")
        )
    # soft spread
    for i in range(3):
        add(
            make_pod(f"soft-{i}")
            .labels(app="soft")
            .container(cpu="250m", memory="512Mi")
            .spread_constraint(
                1, "zone", when_unsatisfiable="ScheduleAnyway",
                match_labels={"app": "soft"},
            )
        )
    # hard spread
    for i in range(3):
        add(
            make_pod(f"hard-{i}")
            .labels(app="hard")
            .container(cpu="250m", memory="512Mi")
            .spread_constraint(1, "zone", match_labels={"app": "hard"})
        )
    # required anti-affinity
    for i in range(3):
        add(
            make_pod(f"anti-{i}")
            .labels(app="db")
            .container(cpu="250m", memory="512Mi")
            .pod_affinity("zone", {"app": "db"}, anti=True)
        )
    # avoided ReplicaSet pod (NodePreferAvoidPods keeps it off n2)
    p = make_pod("avoided").container(cpu="250m", memory="512Mi")
    pod = p.creation_timestamp(ts).obj()
    pod.metadata.owner_references.append(
        OwnerReference(kind="ReplicaSet", name="rs", uid="rs-avoided",
                       controller=True)
    )
    pods.append(pod)
    return pods


def _run_sequential(pods):
    server = APIServer()
    client = Client(server)
    informers = InformerFactory(server)
    sched = new_scheduler(
        client, informers, batch=False,
        percentage_of_nodes_to_score=100, rng=_KeepFirstRng(),
        async_binding=False,
    )
    _build_cluster(client)
    informers.start()
    informers.wait_for_cache_sync()
    sched.queue.run()
    for p in pods:
        client.create_pod(p)
    time.sleep(0.2)
    for _ in range(len(pods) + 5):
        if not sched.schedule_one(timeout=0.5):
            break
    placements = {
        p.metadata.name: p.spec.node_name
        for p in client.list_pods()[0]
        if not p.metadata.name.startswith("existing-")
    }
    sched.stop()
    informers.stop()
    return placements


def _run_batch(pods):
    server = APIServer()
    client = Client(server)
    informers = InformerFactory(server)
    sched = new_scheduler(
        client, informers, batch=True, max_batch=64, async_binding=False
    )
    _build_cluster(client)
    informers.start()
    informers.wait_for_cache_sync()
    sched.queue.run()
    for p in pods:
        client.create_pod(p)
    time.sleep(0.2)
    for _ in range(5):
        if sched.schedule_batch(timeout=0.5) == 0:
            break
    placements = {
        p.metadata.name: p.spec.node_name
        for p in client.list_pods()[0]
        if not p.metadata.name.startswith("existing-")
    }
    fallback = sched.pods_fallback
    sched.stop()
    informers.stop()
    return placements, fallback


class TestBatchSequentialParity:
    def test_identical_placements_full_score_set(self):
        pods = _pending_pods()
        seq = _run_sequential([p.deepcopy() for p in pods])
        batch, fallback = _run_batch([p.deepcopy() for p in pods])
        assert fallback == 0, "batch path fell back to sequential"
        assert set(seq) == set(batch)
        diffs = {
            name: (seq[name], batch[name])
            for name in seq
            if seq[name] != batch[name]
        }
        assert not diffs, f"placement divergence: {diffs}"
        # sanity: everything binds except anti-affinity pods squeezed out
        # of zones already hosting db pods (unbound identically on both
        # paths, which the placement compare above already proved)
        unbound = {n for n in seq if not seq[n]}
        assert all(n.startswith("anti-") for n in unbound), unbound
        assert len(unbound) <= 1

    def test_avoided_pod_skips_annotated_node(self):
        pods = _pending_pods()
        batch, _ = _run_batch(pods)
        assert batch["avoided"] != "n2"
