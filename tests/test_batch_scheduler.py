"""BatchScheduler end-to-end tests: device-solved placement through the
full apiserver/informer/bind pipeline, plus fallback routing."""

import time

import pytest

from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client.client import Client
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.scheduler.batch import solver_supported
from kubernetes_tpu.scheduler.scheduler import new_scheduler
from kubernetes_tpu.testing import make_node, make_pod


def _wait_all_bound(client, count, timeout=30.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        pods, _ = client.list_pods()
        bound = [p for p in pods if p.spec.node_name]
        if len(bound) >= count:
            return pods
        time.sleep(0.05)
    raise AssertionError(
        f"only {len([p for p in client.list_pods()[0] if p.spec.node_name])}"
        f"/{count} pods bound"
    )


@pytest.fixture
def cluster():
    server = APIServer()
    client = Client(server)
    informers = InformerFactory(server)
    sched = new_scheduler(client, informers, batch=True, max_batch=64)
    yield server, client, informers, sched
    sched.stop()
    informers.stop()


class TestBatchScheduling:
    def test_burst_scheduled_on_device(self, cluster):
        server, client, informers, sched = cluster
        for i in range(8):
            client.create_node(
                make_node(f"n{i}").capacity(cpu="8", memory="16Gi", pods=30).obj()
            )
        informers.start()
        informers.wait_for_cache_sync()
        sched.queue.run()
        for i in range(40):
            client.create_pod(
                make_pod(f"p{i}").container(cpu="250m", memory="256Mi").obj()
            )
        t = sched.start()
        pods = _wait_all_bound(client, 40)
        sched.wait_for_inflight_binds()
        assert sched.pods_solved_on_device >= 40
        assert sched.pods_fallback == 0
        # capacity respected on every node
        per_node = {}
        for p in pods:
            per_node[p.spec.node_name] = per_node.get(p.spec.node_name, 0) + 1
        assert all(v <= 30 for v in per_node.values())

    def test_infeasible_pod_recorded_unschedulable(self, cluster):
        server, client, informers, sched = cluster
        client.create_node(make_node("n").capacity(cpu="1", memory="1Gi").obj())
        informers.start()
        informers.wait_for_cache_sync()
        sched.queue.run()
        client.create_pod(make_pod("big").container(cpu="64", memory="1Ti").obj())
        client.create_pod(make_pod("ok").container(cpu="500m").obj())
        sched.start()
        _wait_all_bound(client, 1)
        sched.wait_for_inflight_binds()
        deadline = time.time() + 5
        big = None
        while time.time() < deadline:
            big = client.get_pod("default", "big")
            if any(c.type == "PodScheduled" and c.status == "False"
                   for c in big.status.conditions):
                break
            time.sleep(0.05)
        assert big is not None
        assert not big.spec.node_name
        assert any(
            c.type == "PodScheduled" and c.status == "False" and
            c.reason == "Unschedulable"
            for c in big.status.conditions
        )

    def test_fallback_pods_routed_to_sequential_path(self, cluster):
        server, client, informers, sched = cluster
        for name, zone in [("a", "z1"), ("b", "z2")]:
            client.create_node(
                make_node(name).labels(zone=zone)
                .capacity(cpu="8", memory="16Gi", pods=20).obj()
            )
        informers.start()
        informers.wait_for_cache_sync()
        sched.queue.run()
        # volume-bound pods can't solve on device -> sequential fallback
        # (host-port pods now solve on device via the NodePorts static
        # mask; volumes remain the host-side family)
        for i in range(4):
            client.create_pod(
                make_pod(f"s{i}").labels(app="s")
                .container(cpu="100m")
                .gce_pd(f"disk-{i}")
                .obj()
            )
        for i in range(4):
            client.create_pod(make_pod(f"r{i}").container(cpu="100m").obj())
        sched.start()
        pods = _wait_all_bound(client, 8)
        sched.wait_for_inflight_binds()
        assert sched.pods_fallback >= 4
        assert sched.pods_solved_on_device >= 4

    def test_node_selector_respected_via_static_mask(self, cluster):
        server, client, informers, sched = cluster
        client.create_node(
            make_node("gpu").labels(pool="gpu")
            .capacity(cpu="8", memory="16Gi").obj()
        )
        client.create_node(
            make_node("cpu").labels(pool="cpu")
            .capacity(cpu="64", memory="128Gi").obj()
        )
        informers.start()
        informers.wait_for_cache_sync()
        sched.queue.run()
        for i in range(3):
            client.create_pod(
                make_pod(f"g{i}").container(cpu="1")
                .node_selector(pool="gpu").obj()
            )
        sched.start()
        pods = _wait_all_bound(client, 3)
        for p in pods:
            assert p.spec.node_name == "gpu"

    def test_tainted_node_avoided(self, cluster):
        server, client, informers, sched = cluster
        client.create_node(
            make_node("t").taint("dedicated", "infra")
            .capacity(cpu="64", memory="64Gi").obj()
        )
        client.create_node(make_node("ok").capacity(cpu="2", memory="4Gi").obj())
        informers.start()
        informers.wait_for_cache_sync()
        sched.queue.run()
        for i in range(3):
            client.create_pod(make_pod(f"p{i}").container(cpu="100m").obj())
        client.create_pod(
            make_pod("tolerant").container(cpu="100m")
            .toleration("dedicated", value="infra").obj()
        )
        sched.start()
        pods = _wait_all_bound(client, 4)
        for p in pods:
            if p.name == "tolerant":
                continue
            assert p.spec.node_name == "ok"


class TestRegressions:
    def test_unknown_extended_resource_is_unschedulable_not_crash(self, cluster):
        server, client, informers, sched = cluster
        client.create_node(make_node("n").capacity(cpu="8", memory="16Gi").obj())
        informers.start()
        informers.wait_for_cache_sync()
        client.create_pod(
            make_pod("gpu").container(cpu="1", **{"example_com__gpu": 2}).obj()
        )
        client.create_pod(make_pod("ok").container(cpu="1").obj())
        sched.start()
        _wait_all_bound(client, 1)
        sched.wait_for_inflight_binds()
        gpu = client.get_pod("default", "gpu")
        assert not gpu.spec.node_name
        ok = client.get_pod("default", "ok")
        assert ok.spec.node_name == "n"

    def test_tolerate_everything_admits_cordoned_node(self, cluster):
        server, client, informers, sched = cluster
        node = make_node("c").capacity(cpu="8", memory="16Gi").unschedulable().obj()
        client.create_node(node)
        informers.start()
        informers.wait_for_cache_sync()
        p = make_pod("t").container(cpu="1").obj()
        from kubernetes_tpu.api.types import Toleration
        p.spec.tolerations.append(Toleration(key="", operator="Exists"))
        client.create_pod(p)
        sched.start()
        pods = _wait_all_bound(client, 1)
        assert pods[0].spec.node_name == "c"

    def test_fallback_does_not_jump_high_priority_solver_pod(self, cluster):
        server, client, informers, sched = cluster
        client.create_node(make_node("n").capacity(cpu="1", memory="4Gi").obj())
        informers.start()
        informers.wait_for_cache_sync()
        # high-priority plain pod and low-priority spread pod compete for
        # the single cpu; queue order must win
        high = make_pod("high").container(cpu="1").obj()
        high.spec.priority = 100
        low = (
            make_pod("low").labels(app="low").container(cpu="1")
            .spread_constraint(1, "zone", match_labels={"app": "low"})
            .obj()
        )
        client.create_pod(high)
        client.create_pod(low)
        sched.start()
        _wait_all_bound(client, 1)
        sched.wait_for_inflight_binds()
        assert client.get_pod("default", "high").spec.node_name == "n"
        assert not client.get_pod("default", "low").spec.node_name


class TestExistingAntiAffinityGate:
    def test_existing_required_anti_affinity_respected(self, cluster):
        """A pod with no affinity of its own must still honor required
        anti-affinity declared by pods already on nodes (symmetric check);
        the batch path falls back to the sequential oracle for this."""
        server, client, informers, sched = cluster
        for name in ("a", "b"):
            client.create_node(
                make_node(name).labels(host=name)
                .capacity(cpu="8", memory="16Gi").obj()
            )
        informers.start()
        informers.wait_for_cache_sync()
        # guard on node a: anti-affinity against app=web on its host
        guard = (
            make_pod("guard").labels(app="guard")
            .container(cpu="100m")
            .pod_affinity("host", {"app": "web"}, anti=True)
            .obj()
        )
        client.create_pod(guard)
        sched.start()
        _wait_all_bound(client, 1)
        sched.wait_for_inflight_binds()
        guard_node = client.get_pod("default", "guard").spec.node_name
        for i in range(4):
            client.create_pod(
                make_pod(f"web-{i}").labels(app="web").container(cpu="100m").obj()
            )
        pods = _wait_all_bound(client, 5)
        for p in pods:
            if p.name.startswith("web"):
                assert p.spec.node_name != guard_node, p.name


class TestNominatedOverlay:
    def test_batch_does_not_steal_nominated_capacity(self, cluster):
        """Capacity freed by preemption stays reserved for the nominee."""
        server, client, informers, sched = cluster
        client.create_node(make_node("n").capacity(cpu="2", memory="8Gi").obj())
        informers.start()
        informers.wait_for_cache_sync()
        for i in range(2):
            client.create_pod(make_pod(f"low{i}").container(cpu="1").obj())
        sched.start()
        _wait_all_bound(client, 2)
        sched.wait_for_inflight_binds()
        # high-priority pod preempts a victim and gets nominated
        high = make_pod("high").container(cpu="2").obj()
        high.spec.priority = 100
        client.create_pod(high)
        deadline = time.time() + 15
        while time.time() < deadline:
            hp = client.get_pod("default", "high")
            if hp.spec.node_name:
                break
            # meanwhile, opportunistic low-priority pods keep arriving
            time.sleep(0.2)
            client.create_pod(
                make_pod(f"opportunist-{time.monotonic_ns()}")
                .container(cpu="1").obj()
            )
        sched.stop()
        hp = client.get_pod("default", "high")
        assert hp.spec.node_name == "n", "nominee starved by batch pods"


class TestSolverSupported:
    def test_plain_pod(self):
        assert solver_supported(make_pod("p").container(cpu="1").obj())

    def test_required_affinity_supported_on_device(self):
        assert solver_supported(
            make_pod("p").pod_affinity("zone", {"a": "b"}).obj()
        )
        assert solver_supported(
            make_pod("p").pod_affinity("zone", {"a": "b"}, anti=True).obj()
        )

    def test_preferred_affinity_supported_on_device(self):
        # preferred terms ride the ipa_* score family (ops/scoring.py)
        assert solver_supported(
            make_pod("p").preferred_pod_affinity("zone", {"a": "b"}).obj()
        )

    def test_hard_spread_supported_on_device(self):
        assert solver_supported(
            make_pod("p").spread_constraint(1, "zone").obj()
        )

    def test_soft_spread_supported_on_device(self):
        assert solver_supported(
            make_pod("p").spread_constraint(
                1, "zone", when_unsatisfiable="ScheduleAnyway"
            ).obj()
        )

    def test_hard_spread_plus_node_selector_supported(self):
        # per-group eligibility scoping (topology._eligibility_sig)
        # keeps this on device now
        assert solver_supported(
            make_pod("p").spread_constraint(1, "zone")
            .node_selector(pool="x").obj()
        )

    def test_soft_spread_plus_node_selector_not_supported(self):
        assert not solver_supported(
            make_pod("p").spread_constraint(
                1, "zone", when_unsatisfiable="ScheduleAnyway"
            )
            .node_selector(pool="x").obj()
        )

    def test_node_selector_supported(self):
        assert solver_supported(make_pod("p").node_selector(pool="x").obj())


class TestNomineeConstrainedFallback:
    def test_constrained_batch_with_nominee_takes_host_path(self):
        """ADVICE r2 (medium): nominee pods are overlaid as resources
        only, so a constrained batch (affinity) with active nominations
        must route to the host path where _add_nominated_pods runs the
        full filter semantics."""
        from kubernetes_tpu.apiserver.server import APIServer
        from kubernetes_tpu.client.client import Client
        from kubernetes_tpu.client.informer import InformerFactory

        server = APIServer()
        client = Client(server)
        informers = InformerFactory(server)
        sched = new_scheduler(client, informers, batch=True, max_batch=16)
        for i in range(3):
            client.create_node(
                make_node(f"n{i}").labels(zone=f"z{i}")
                .capacity(cpu="8", memory="16Gi").obj()
            )
        informers.start()
        informers.wait_for_cache_sync()
        sched.queue.run()
        # a standing nomination makes nominated_by_node non-empty
        nominee = make_pod("nominee").container(cpu="1").priority(50).obj()
        sched.queue.update_nominated_pod_for_node(nominee, "n0")
        client.create_pod(
            make_pod("anti").labels(app="a")
            .container(cpu="100m", memory="128Mi")
            .pod_affinity("zone", {"app": "a"}, anti=True)
            .obj()
        )
        deadline = time.time() + 15
        while time.time() < deadline:
            sched.schedule_batch(timeout=0.2)
            pods, _ = client.list_pods()
            if any(p.spec.node_name for p in pods):
                break
        sched.wait_for_inflight_binds()
        sched.stop()
        informers.stop()
        pods, _ = client.list_pods()
        assert any(p.spec.node_name for p in pods)
        assert sched.nominee_constrained_fallbacks >= 1
        assert sched.pods_fallback >= 1


class TestDeviceStateDifferential:
    """Randomized event-stream differential for the device-resident
    node state (PR 5): after K batches with interleaved node churn,
    bind failures, and schema growth, the device-resident ``req_state``
    carry must equal a fresh full pack of the host snapshot -- and the
    CPU (XLA) tier must have exercised the delta-scatter path."""

    def test_event_stream_device_state_matches_full_pack(self, monkeypatch):
        import random

        import numpy as np

        from kubernetes_tpu.cache.snapshot import Snapshot
        from kubernetes_tpu.tensors import NodeTensorCache

        rng = random.Random(20260803)
        server = APIServer()
        client = Client(server)
        informers = InformerFactory(server)
        sched = new_scheduler(client, informers, batch=True, max_batch=32)
        for i in range(8):
            client.create_node(
                make_node(f"ds-n{i}")
                .capacity(cpu="64", memory="128Gi", pods=200)
                .obj()
            )
        informers.start()
        informers.wait_for_cache_sync()
        sched.queue.run()

        # bind failures: every 4th bulk transaction rejects its first
        # slot (the pod is forgotten + requeued, so the host diverges
        # from the mirrored expectation -- the scatter-fix case)
        orig_bulk = client.bind_assumed_bulk
        calls = {"n": 0}

        def flaky_bulk(assumed):
            calls["n"] += 1
            if calls["n"] % 4 == 0 and assumed:
                errs = orig_bulk(assumed[1:])
                return [(0, RuntimeError("synthetic bind failure"))] + [
                    (i + 1, e) for i, e in errs
                ]
            return orig_bulk(assumed)

        monkeypatch.setattr(client, "bind_assumed_bulk", flaky_bulk)

        seq = 0
        for k in range(12):
            for _ in range(rng.randint(3, 8)):
                seq += 1
                client.create_pod(
                    make_pod(f"ds-p{seq}")
                    .container(
                        cpu=f"{rng.choice([100, 250, 500])}m",
                        memory="128Mi",
                    )
                    .obj()
                )
            if k % 3 == 2:
                # external churn: a controller deletes a bound pod
                # behind the scheduler's back
                bound = [
                    p for p in client.list_pods()[0] if p.spec.node_name
                ]
                if bound:
                    victim = rng.choice(bound)
                    client.delete_pod(
                        victim.metadata.namespace, victim.metadata.name
                    )
            if k == 5:
                # schema growth: a node advertising a new scalar
                # resource forces a full repack + re-upload
                client.create_node(
                    make_node("ds-gpu")
                    .capacity(
                        cpu="8", memory="16Gi",
                        **{"example_com__gpu": 4},
                    )
                    .obj()
                )
            deadline = time.time() + 5
            while time.time() < deadline:
                if sched.schedule_batch(timeout=0.2):
                    break
        # settle: stop injecting bind failures (a failure during the
        # deterministic tail below would leave the device ahead with no
        # reconciling dispatch left), absorb requeues/deletions, then
        # stop mutating
        monkeypatch.setattr(client, "bind_assumed_bulk", orig_bulk)
        for _ in range(10):
            sched.schedule_batch(timeout=0.1)
        sched.wait_for_inflight_binds(timeout=30)
        for _ in range(5):
            sched.schedule_batch(timeout=0.1)
        sched.wait_for_inflight_binds(timeout=30)

        # one quiet batch reconciles the carry with the settled host
        # state (any leftover external change resolves here) and drains
        # the pending-delta ring
        client.create_pod(
            make_pod("ds-final").container(cpu="100m", memory="64Mi").obj()
        )
        deadline = time.time() + 10
        while time.time() < deadline:
            if sched.schedule_batch(timeout=0.2):
                break
        sched.wait_for_inflight_binds(timeout=30)

        # -- deterministic path coverage (the in-loop churn above races
        # the committer, so which resolution each divergence took is
        # timing-dependent; these two phases are not) ------------------

        # phase A: allocatable growth with nothing in flight. The next
        # dispatch must validate the carry (row CONTENTS unchanged) and
        # ship the one changed alloc row as an (indices, rows) scatter
        # -- NOT a full upload.
        node = client.get_node("ds-n0")
        node.status.capacity["cpu"] += 1000
        node.status.allocatable["cpu"] += 1000
        client.update_node(node)
        deadline = time.time() + 10
        while time.time() < deadline:
            ni = sched.cache._nodes.get("ds-n0")
            if ni is not None and ni.allocatable.milli_cpu == 65000:
                break
            time.sleep(0.02)
        uploads_before = sched.state_uploads
        delta_before = sched.delta_rows_uploaded
        client.create_pod(
            make_pod("ds-final2").container(cpu="100m", memory="64Mi").obj()
        )
        deadline = time.time() + 10
        while time.time() < deadline:
            if sched.schedule_batch(timeout=0.2):
                break
        sched.wait_for_inflight_binds(timeout=30)
        assert sched.delta_rows_uploaded > delta_before, (
            "alloc growth should ride the row scatter"
        )
        assert sched.state_uploads == uploads_before, (
            "alloc growth must not trigger a full [N, R] upload"
        )

        # phase B: external pod delete with nothing in flight -- a
        # changed row our own mirrored placements cannot explain. The
        # next dispatch must COUNT the divergence (scatter-fixed or
        # resolved by a full upload, but never silent).
        bound = [p for p in client.list_pods()[0] if p.spec.node_name]
        victim = bound[0]
        vnode = victim.spec.node_name
        client.delete_pod(victim.metadata.namespace, victim.metadata.name)
        deadline = time.time() + 10
        while time.time() < deadline:
            ni = sched.cache._nodes.get(vnode)
            if ni is not None and all(
                p.metadata.uid != victim.metadata.uid for p in ni.pods
            ):
                break
            time.sleep(0.02)
        div_before = sched.carry_divergences
        client.create_pod(
            make_pod("ds-final3").container(cpu="100m", memory="64Mi").obj()
        )
        deadline = time.time() + 10
        while time.time() < deadline:
            if sched.schedule_batch(timeout=0.2):
                break
        sched.wait_for_inflight_binds(timeout=30)
        assert sched.carry_divergences > div_before, (
            "the external delete must surface as a counted divergence"
        )

        ds = sched._dev
        assert ds.req_dev is not None, "device carry was dropped"
        dev_req = np.asarray(ds.req_dev)
        dev_nzr = np.asarray(ds.nzr_dev)
        names = sched.tensor_cache._names

        # fresh full pack of the settled host state (shared dims +
        # topology registries => identical columns), via a fresh
        # snapshot so the scheduler's change tracking is untouched
        snap2 = Snapshot()
        sched.cache.update_snapshot(snap2)
        fresh = NodeTensorCache(
            sched.tensor_cache.dims, sched.tensor_cache.topology
        ).update(snap2)
        assert sorted(fresh.names) == sorted(names)
        for name in names:
            i = names.index(name)
            j = fresh.row(name)
            assert np.array_equal(dev_req[i], fresh.requested[j]), (
                f"device req_state row for {name} diverged from the "
                f"full pack: {dev_req[i]} != {fresh.requested[j]}"
            )
            assert np.array_equal(
                dev_nzr[i], fresh.non_zero_requested[j]
            ), f"device nzr_state row for {name} diverged"

        # the event stream actually drove the interesting paths
        assert sched.delta_rows_uploaded > 0
        assert sched.carry_divergences > 0
        assert calls["n"] >= 4
        sched.stop()
        informers.stop()


class TestEagerDownload:
    """The dispatch-time result download (PR 4): on this box the core
    gate may disable it, so these tests force the path on."""

    def test_eager_download_result_roundtrip(self):
        import jax.numpy as jnp
        import numpy as np

        from kubernetes_tpu.scheduler.batch import _EagerDownload

        dev = jnp.arange(16, dtype=jnp.int32)
        dl = _EagerDownload(dev)
        out = dl.result()
        assert isinstance(out, np.ndarray)
        assert out.tolist() == list(range(16))
        # result() is idempotent
        assert dl.result() is out

    def test_eager_download_propagates_errors(self):
        from kubernetes_tpu.scheduler.batch import _EagerDownload

        class Boom:
            def __array__(self, *a, **k):
                raise RuntimeError("serving link down")

        dl = _EagerDownload(Boom())
        with pytest.raises(RuntimeError, match="serving link down"):
            dl.result()

    def test_pipeline_binds_with_eager_downloads_forced(self, cluster, monkeypatch):
        """Full dispatch->commit flow with the eager path forced on
        (regardless of the host-core gate)."""
        from kubernetes_tpu.scheduler import batch as batch_mod

        monkeypatch.setattr(batch_mod, "_EAGER_DOWNLOAD_OK", True)
        server, client, informers, sched = cluster
        for i in range(6):
            client.create_node(
                make_node(f"ed-n{i}")
                .capacity(cpu="8", memory="16Gi", pods=32)
                .obj()
            )
        informers.start()
        informers.wait_for_cache_sync()
        for i in range(40):
            client.create_pod(
                make_pod(f"ed-p{i}")
                .container(cpu="100m", memory="128Mi")
                .obj()
            )
        sched.queue.run()
        deadline = time.time() + 30
        done = 0
        while done < 40 and time.time() < deadline:
            done += sched.schedule_batch(timeout=0.5, pipeline=True)
        sched._drain_pending()
        sched.wait_for_inflight_binds(timeout=30)
        _wait_all_bound(client, 40)
        # the device path actually ran with eager downloads in flight
        assert sched.pods_solved_on_device == 40
        assert sched.pods_fallback == 0
