"""Ops-shell tests: metrics exposition, healthz, leader election,
cache debugger, config loading."""

import threading
import time
import urllib.request

import pytest

from kubernetes_tpu.api.types import ObjectMeta
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client.client import Client
from kubernetes_tpu.config.loader import (
    DEFAULT_FEATURE_GATES,
    FeatureGate,
    load_config_from_dict,
)
from kubernetes_tpu.config.types import LeaderElectionConfiguration
from kubernetes_tpu.scheduler.app import SchedulerApp
from kubernetes_tpu.scheduler.leaderelection import LeaderElector
from kubernetes_tpu.testing import make_node, make_pod
from kubernetes_tpu.utils import metrics
from kubernetes_tpu.utils.tracing import Trace


class TestMetrics:
    def test_counter_and_histogram(self):
        c = metrics.Counter("test_total", "help", ("result",))
        c.inc(result="ok")
        c.inc(result="ok")
        assert c.value(result="ok") == 2
        h = metrics.Histogram("test_seconds", "help", buckets=(0.1, 1.0))
        h.observe(0.05)
        h.observe(0.5)
        assert h.count() == 2
        text = "\n".join(h.collect())
        assert 'le="0.1"' in text and "test_seconds_sum" in text

    def test_registry_expose(self):
        text = metrics.registry.expose()
        assert "scheduler_schedule_attempts_total" in text
        assert "scheduler_e2e_scheduling_duration_seconds" in text


class TestSchedulerApp:
    def test_healthz_metrics_and_scheduling(self):
        app = SchedulerApp()
        host, port = app.start_serving()
        client = app.client
        client.create_node(make_node("n").capacity(cpu="8", memory="16Gi").obj())
        app.start()
        client.create_pod(make_pod("p").container(cpu="1").obj())
        deadline = time.time() + 10
        while time.time() < deadline:
            if client.get_pod("default", "p").spec.node_name:
                break
            time.sleep(0.05)
        app.sched.wait_for_inflight_binds()

        base = f"http://{host}:{port}"
        assert urllib.request.urlopen(base + "/healthz").read() == b"ok"
        body = urllib.request.urlopen(base + "/metrics").read().decode()
        assert 'scheduler_schedule_attempts_total{result="scheduled"}' in body
        assert "scheduler_scheduler_cache_size" in body
        dump = urllib.request.urlopen(base + "/debug/cache").read().decode()
        assert "node n" in dump
        app.stop()

    def test_cache_comparer_consistent(self):
        app = SchedulerApp()
        client = app.client
        client.create_node(make_node("n").capacity(cpu="4", memory="8Gi").obj())
        app.start()
        client.create_pod(make_pod("p").container(cpu="1").obj())
        deadline = time.time() + 10
        while time.time() < deadline:
            if client.get_pod("default", "p").spec.node_name:
                break
            time.sleep(0.05)
        app.sched.wait_for_inflight_binds()
        time.sleep(0.3)  # let informer events settle into the cache
        result = app.debugger.comparer.compare()
        assert all(not v for v in result.values()), result
        problems = app.debugger.tensor_comparer.compare()
        assert not problems
        app.stop()


class TestLeaderElection:
    def _elector(self, client, name, events, cfg):
        return LeaderElector(
            client,
            cfg,
            identity=name,
            on_started_leading=lambda: events.append(("lead", name)),
            on_stopped_leading=lambda: events.append(("stop", name)),
        )

    def test_single_leader_and_failover(self):
        server = APIServer()
        client = Client(server)
        cfg = LeaderElectionConfiguration(
            leader_elect=True,
            lease_duration_seconds=0.5,
            renew_deadline_seconds=0.4,
            retry_period_seconds=0.05,
        )
        events = []
        a = self._elector(client, "a", events, cfg)
        b = self._elector(client, "b", events, cfg)
        ta = threading.Thread(target=a.run, daemon=True)
        tb = threading.Thread(target=b.run, daemon=True)
        ta.start()
        time.sleep(0.2)
        tb.start()
        time.sleep(0.3)
        assert a.is_leader and not b.is_leader
        # leader dies: stop renewing
        a.stop()
        ta.join(timeout=2)
        deadline = time.time() + 5
        while time.time() < deadline and not b.is_leader:
            time.sleep(0.05)
        assert b.is_leader, "standby never took over"
        b.stop()

    def test_release_hands_off_immediately(self):
        server = APIServer()
        client = Client(server)
        cfg = LeaderElectionConfiguration(
            lease_duration_seconds=30.0,  # long: only release can hand off
            renew_deadline_seconds=10.0,
            retry_period_seconds=0.05,
        )
        events = []
        a = self._elector(client, "a", events, cfg)
        ta = threading.Thread(target=a.run, daemon=True)
        ta.start()
        deadline = time.time() + 2
        while time.time() < deadline and not a.is_leader:
            time.sleep(0.02)
        assert a.is_leader
        a.stop()
        a.release()
        lease = server.get("Lease", "kube-system", "kube-scheduler")
        assert lease.holder_identity == ""


class TestConfigLoader:
    def test_load_full_config(self):
        raw = {
            "percentageOfNodesToScore": 50,
            "leaderElection": {"leaderElect": True, "leaseDuration": 5},
            "profiles": [
                {
                    "schedulerName": "tpu-scheduler",
                    "plugins": {
                        "score": {
                            "enabled": [{"name": "NodeResourcesMostAllocated",
                                         "weight": 5}],
                            "disabled": [{"name": "NodeResourcesLeastAllocated"}],
                        }
                    },
                    "pluginConfig": [
                        {"name": "InterPodAffinity",
                         "args": {"hard_pod_affinity_weight": 10}},
                    ],
                }
            ],
            "extenders": [
                {"urlPrefix": "http://127.0.0.1:9999", "filterVerb": "filter",
                 "managedResources": [{"name": "example.com/fpga"}]}
            ],
            "featureGates": {"TPUBatchSolver": False},
        }
        cfg = load_config_from_dict(raw)
        assert cfg.percentage_of_nodes_to_score == 50
        assert cfg.leader_election.leader_elect
        assert cfg.leader_election.lease_duration_seconds == 5
        prof = cfg.profiles[0]
        assert prof.scheduler_name == "tpu-scheduler"
        assert prof.plugins.score.enabled[0].weight == 5
        assert prof.plugin_config["InterPodAffinity"][
            "hard_pod_affinity_weight"] == 10
        assert cfg.extenders[0].managed_resources == ["example.com/fpga"]

    def test_feature_gates(self):
        fg = FeatureGate(DEFAULT_FEATURE_GATES)
        assert fg.enabled("TPUBatchSolver")
        fg.set_from_map({"TPUBatchSolver": False})
        assert not fg.enabled("TPUBatchSolver")
        with pytest.raises(ValueError):
            fg.set_from_map({"NoSuchGate": True})


class TestTrace:
    def test_steps_logged_when_long(self, caplog):
        import logging
        with caplog.at_level(logging.INFO, logger="trace"):
            t = Trace("schedule", pod="default/p")
            t.step("filtering")
            t.step("scoring")
            t.log_if_long(0.0)
        assert "filtering" in caplog.text and "schedule" in caplog.text


class TestDurationParsing:
    def test_go_style_durations(self):
        from kubernetes_tpu.config.loader import _duration_seconds

        assert _duration_seconds("30s") == 30.0
        assert _duration_seconds("1m30s") == 90.0
        assert _duration_seconds("500ms") == 0.5
        assert _duration_seconds(5) == 5.0
        assert _duration_seconds("2.5") == 2.5
        with pytest.raises(ValueError):
            _duration_seconds("bogus")

    def test_extender_http_timeout_duration_string(self):
        cfg = load_config_from_dict(
            {"extenders": [{"urlPrefix": "http://x", "httpTimeout": "30s"}]}
        )
        assert cfg.extenders[0].http_timeout_seconds == 30.0
