"""Preferred inter-pod affinity scoring on device: randomized
batch-vs-sequential differentials plus targeted behavior tests.

Reference: interpodaffinity/scoring.go:110-268 (processExistingPod /
processTerm) and :294 (NormalizeScore). The sequential path's
InterPodAffinity plugin is the oracle; the batch path must produce the
same placements on identical clusters.
"""

import random
import time

import pytest

from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client.client import Client
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.scheduler.scheduler import new_scheduler
from kubernetes_tpu.testing import make_node, make_pod


def _wait_decided(client, sched, count, timeout=60.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        pods, _ = client.list_pods()
        pending = [
            p for p in pods
            if not p.spec.node_name and not p.status.conditions
        ]
        if len(pods) >= count and not pending:
            sched.wait_for_inflight_binds()
            return client.list_pods()[0]
        time.sleep(0.05)
    raise AssertionError("pods not decided in time")


def _build_cluster(rng, client):
    zones = ["z1", "z2", "z3", "z4"]
    for i in range(12):
        # distinct capacities keep resource scores tie-free: the
        # sequential path breaks ties via reservoir RNG + a rotating
        # start index, which no deterministic device argmax can mirror
        client.create_node(
            make_node(f"n{i}")
            .labels(zone=zones[i % len(zones)], rack=f"r{i % 6}")
            .capacity(cpu=str(8 + 2 * i), memory=f"{24 + 5 * i}Gi")
            .obj()
        )
    apps = ["web", "db", "cache"]
    existing = []
    for j in range(10):
        w = (
            make_pod(f"ex{j}")
            .node(f"n{rng.randrange(12)}")
            .labels(app=rng.choice(apps))
            .container(cpu="100m", memory="128Mi")
        )
        roll = rng.random()
        if roll < 0.3:
            w.preferred_pod_affinity(
                "zone", {"app": rng.choice(apps)},
                weight=rng.choice([1, 5, 10]),
            )
        elif roll < 0.5:
            w.preferred_pod_affinity(
                "zone", {"app": rng.choice(apps)},
                weight=rng.choice([1, 5]), anti=True,
            )
        elif roll < 0.65:
            w.pod_affinity("rack", {"app": rng.choice(apps)})
        existing.append(w.obj())
        client.create_pod(existing[-1])
    return existing


def _build_batch(rng, prefix):
    apps = ["web", "db", "cache"]
    out = []
    for i in range(12):
        w = (
            make_pod(f"{prefix}{i}")
            .labels(app=rng.choice(apps))
            .creation_timestamp(float(i))
            .container(cpu="200m", memory="256Mi")
        )
        roll = rng.random()
        if roll < 0.4:
            w.preferred_pod_affinity(
                "zone", {"app": rng.choice(apps)},
                weight=rng.choice([1, 5, 10]),
            )
        elif roll < 0.7:
            w.preferred_pod_affinity(
                "rack", {"app": rng.choice(apps)},
                weight=rng.choice([1, 5]), anti=True,
            )
        out.append(w.obj())
    return out


class _KeepFirstRng:
    """Reservoir sampling never replaces: sequential select_host keeps
    the first max, matching the device argmax (lowest index)."""

    def randrange(self, n):
        return 1 if n > 1 else 0

    def randint(self, a, b):
        return b


def _run(rng_seed, batch):
    """Schedule the same random scenario through the batch or the
    sequential path; returns {pod name: node}."""
    rng = random.Random(rng_seed)
    server = APIServer()
    client = Client(server)
    informers = InformerFactory(server)
    sched = new_scheduler(
        client, informers, batch=batch, max_batch=64,
        percentage_of_nodes_to_score=100, rng=_KeepFirstRng(),
    )
    _build_cluster(rng, client)
    informers.start()
    informers.wait_for_cache_sync()
    sched.queue.run()
    for p in _build_batch(rng, "m"):
        client.create_pod(p)
    sched.start()
    pods = _wait_decided(client, sched, 22)
    if batch:
        assert sched.pods_fallback == 0, "expected pure device solve"
    sched.stop()
    informers.stop()
    return {
        p.metadata.name: p.spec.node_name
        for p in pods
        if p.metadata.name.startswith("m")
    }


@pytest.mark.parametrize("seed", [3, 11, 29])
def test_batch_matches_sequential_with_preferred_affinity(seed):
    assert _run(seed, batch=True) == _run(seed, batch=False)


def test_preferred_affinity_attracts_within_batch():
    """A follower with preferred affinity placed AFTER its leader in the
    same batch lands in the leader's zone (within-batch count replay)."""
    server = APIServer()
    client = Client(server)
    informers = InformerFactory(server)
    sched = new_scheduler(client, informers, batch=True, max_batch=32)
    for name, zone in (("a", "z1"), ("b", "z2")):
        client.create_node(
            make_node(name).labels(zone=zone)
            .capacity(cpu="8", memory="16Gi").obj()
        )
    informers.start()
    informers.wait_for_cache_sync()
    sched.queue.run()
    client.create_pod(
        make_pod("leader").labels(app="db").priority(10)
        .creation_timestamp(0.0)
        .container(cpu="100m", memory="128Mi").obj()
    )
    client.create_pod(
        make_pod("follower").labels(app="web").creation_timestamp(1.0)
        .container(cpu="100m", memory="128Mi")
        .preferred_pod_affinity("zone", {"app": "db"}, weight=100)
        .obj()
    )
    sched.start()
    pods = _wait_decided(client, sched, 2)
    sched.stop()
    informers.stop()
    by_name = {p.metadata.name: p for p in pods}
    assert by_name["leader"].spec.node_name
    assert (
        by_name["follower"].spec.node_name
        == by_name["leader"].spec.node_name
    )
    assert sched.pods_fallback == 0


def test_preferred_anti_affinity_repels_within_batch():
    server = APIServer()
    client = Client(server)
    informers = InformerFactory(server)
    sched = new_scheduler(client, informers, batch=True, max_batch=32)
    for name, zone in (("a", "z1"), ("b", "z2")):
        client.create_node(
            make_node(name).labels(zone=zone)
            .capacity(cpu="8", memory="16Gi").obj()
        )
    informers.start()
    informers.wait_for_cache_sync()
    sched.queue.run()
    for i in range(2):
        client.create_pod(
            make_pod(f"p{i}").labels(app="db")
            .creation_timestamp(float(i))
            .container(cpu="100m", memory="128Mi")
            .preferred_pod_affinity(
                "zone", {"app": "db"}, weight=100, anti=True
            )
            .obj()
        )
    sched.start()
    pods = _wait_decided(client, sched, 2)
    sched.stop()
    informers.stop()
    nodes = {p.spec.node_name for p in pods}
    assert len(nodes) == 2, f"expected spread, got {nodes}"
    assert sched.pods_fallback == 0


def test_existing_pod_symmetric_terms_score_plain_batch():
    """An existing pod's preferred affinity toward the incoming pods
    pulls a PLAIN batch (no terms of its own) to its zone
    (processExistingPod :111)."""
    server = APIServer()
    client = Client(server)
    informers = InformerFactory(server)
    sched = new_scheduler(client, informers, batch=True, max_batch=32)
    for name, zone in (("a", "z1"), ("b", "z2")):
        client.create_node(
            make_node(name).labels(zone=zone)
            .capacity(cpu="8", memory="16Gi").obj()
        )
    # existing pod on node a prefers app=web near it, strongly
    client.create_pod(
        make_pod("magnet").node("a").labels(app="db")
        .container(cpu="100m", memory="128Mi")
        .preferred_pod_affinity("zone", {"app": "web"}, weight=100)
        .obj()
    )
    informers.start()
    informers.wait_for_cache_sync()
    sched.queue.run()
    client.create_pod(
        make_pod("plain").labels(app="web")
        .container(cpu="100m", memory="128Mi").obj()
    )
    sched.start()
    pods = _wait_decided(client, sched, 2)
    sched.stop()
    informers.stop()
    by_name = {p.metadata.name: p for p in pods}
    assert by_name["plain"].spec.node_name == "a"
    assert sched.pods_fallback == 0
