from kubernetes_tpu.cache.cache import SchedulerCache
from kubernetes_tpu.cache.node_info import NodeInfo
from kubernetes_tpu.cache.snapshot import Snapshot, new_snapshot
from kubernetes_tpu.testing import make_node, make_pod


def _node(name="n1", cpu="4", mem="32Gi"):
    return make_node(name).capacity(cpu=cpu, memory=mem).obj()


def _pod(name="p1", cpu="1", mem="1Gi", node=""):
    w = make_pod(name).container(cpu=cpu, memory=mem)
    if node:
        w.node(node)
    return w.obj()


def test_node_info_accumulation():
    ni = NodeInfo(_node())
    assert ni.allocatable.milli_cpu == 4000
    p = _pod(node="n1")
    ni.add_pod(p)
    assert ni.requested.milli_cpu == 1000
    assert ni.requested.memory == 1024**3
    assert len(ni.pods) == 1
    assert ni.remove_pod(p)
    assert ni.requested.milli_cpu == 0
    assert len(ni.pods) == 0


def test_node_info_nonzero_defaults():
    ni = NodeInfo(_node())
    p = make_pod("empty").container(cpu="0", memory="0").node("n1").obj()
    ni.add_pod(p)
    assert ni.requested.milli_cpu == 0
    assert ni.non_zero_requested.milli_cpu == 100
    assert ni.non_zero_requested.memory == 200 * 1024 * 1024


def test_host_ports():
    ni = NodeInfo(_node())
    p = make_pod("hp").container(cpu="1", memory="1Gi", host_port=8080).node("n1").obj()
    ni.add_pod(p)
    assert ni.used_ports.conflicts("0.0.0.0", "TCP", 8080)
    assert not ni.used_ports.conflicts("0.0.0.0", "TCP", 8081)
    assert not ni.used_ports.conflicts("0.0.0.0", "UDP", 8080)


def test_cache_assume_add_expire():
    now = [0.0]
    cache = SchedulerCache(ttl_seconds=30.0, now=lambda: now[0])
    cache.add_node(_node("n1"))
    p = _pod("p1", node="n1")

    cache.assume_pod(p)
    assert cache.is_assumed_pod(p)
    assert cache.pod_count() == 1
    cache.finish_binding(p)

    # before TTL: still there
    now[0] = 10.0
    assert cache.cleanup_expired_assumed_pods() == []
    # after TTL: expired
    now[0] = 31.0
    expired = cache.cleanup_expired_assumed_pods()
    assert [e.key() for e in expired] == ["default/p1"]
    assert cache.pod_count() == 0


def test_cache_assume_then_confirm():
    cache = SchedulerCache()
    cache.add_node(_node("n1"))
    p = _pod("p1", node="n1")
    cache.assume_pod(p)
    cache.finish_binding(p)
    cache.add_pod(p)  # informer confirms
    assert not cache.is_assumed_pod(p)
    assert cache.cleanup_expired_assumed_pods() == []
    assert cache.pod_count() == 1


def test_incremental_snapshot_copies_only_changed():
    cache = SchedulerCache()
    cache.add_node(_node("n1"))
    cache.add_node(_node("n2"))
    snap = Snapshot()
    cache.update_snapshot(snap)
    assert snap.num_nodes() == 2
    ni1_before = snap.get_node_info("n1")
    ni2_before = snap.get_node_info("n2")

    cache.add_pod(_pod("p1", node="n2"))
    cache.update_snapshot(snap)
    # n1 untouched => same object; n2 changed => recloned
    assert snap.get_node_info("n1") is ni1_before
    assert snap.get_node_info("n2") is not ni2_before
    assert snap.get_node_info("n2").requested.milli_cpu == 1000


def test_snapshot_node_removal():
    cache = SchedulerCache()
    n1, n2 = _node("n1"), _node("n2")
    cache.add_node(n1)
    cache.add_node(n2)
    snap = Snapshot()
    cache.update_snapshot(snap)
    cache.remove_node(n2)
    cache.update_snapshot(snap)
    assert snap.num_nodes() == 1
    assert snap.get_node_info("n2") is None


def test_new_snapshot_helper():
    nodes = [_node("n1"), _node("n2")]
    pods = [_pod("p1", node="n1"), _pod("p2", node="missing")]
    snap = new_snapshot(pods, nodes)
    assert snap.num_nodes() == 2
    assert len(snap.get_node_info("n1").pods) == 1
    assert snap.list_pods()[0].name == "p1"
