"""NodePorts on device: host-port pods solve through the batch solver
(existing-pod conflicts in the static mask; within-batch conflicts as
synthetic anti rows, ops/affinity.add_host_port_rows) with differential
checks against the NodePorts plugin semantics (reference
nodeports/node_ports.go)."""

import time

import numpy as np
import pytest

from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.cache.snapshot import new_snapshot
from kubernetes_tpu.client.client import Client
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.framework.interface import CycleState
from kubernetes_tpu.ops.host_masks import static_mask
from kubernetes_tpu.plugins.nodeports import NodePorts
from kubernetes_tpu.scheduler.scheduler import new_scheduler
from kubernetes_tpu.tensors import NodeTensorCache
from kubernetes_tpu.testing import make_node, make_pod


def _port_pod(name, port, ip="", proto="TCP"):
    w = make_pod(name).container(
        cpu="100m", memory="128Mi", host_port=port, protocol=proto
    )
    if ip:
        w.pod.spec.containers[0].ports[0].host_ip = ip
    return w.obj()


class TestStaticMaskPortParity:
    def test_mask_matches_nodeports_plugin(self):
        nodes = [
            make_node(f"n{i}").capacity(cpu="8", memory="16Gi", pods=20).obj()
            for i in range(6)
        ]
        existing = []
        # n0: TCP 8080 wildcard; n1: TCP 8080 on a specific ip;
        # n2: UDP 8080
        e0 = _port_pod("e0", 8080)
        e0.spec.node_name = "n0"
        e1 = _port_pod("e1", 8080, ip="10.0.0.1")
        e1.spec.node_name = "n1"
        e2 = _port_pod("e2", 8080, proto="UDP")
        e2.spec.node_name = "n2"
        existing = [e0, e1, e2]
        snap = new_snapshot(existing, nodes)
        nt = NodeTensorCache().update(snap)
        plugin = NodePorts()
        cases = [
            _port_pod("w0", 8080),                 # wildcard TCP
            _port_pod("w1", 8080, ip="10.0.0.1"),  # same specific ip
            _port_pod("w2", 8080, ip="10.0.0.2"),  # different ip
            _port_pod("w3", 8080, proto="UDP"),
            _port_pod("w4", 9090),
        ]
        mask = static_mask(cases, snap, nt)
        for b, pod in enumerate(cases):
            for ni in snap.list_node_infos():
                want = plugin.filter(CycleState(), pod, ni) is None
                got = bool(mask[b][nt.row(ni.node_name)])
                assert got == want, (
                    f"{pod.metadata.name} vs {ni.node_name}: "
                    f"mask={got} plugin={want}"
                )


class TestNodePortsDeviceE2E:
    def test_host_port_pods_solve_on_device_without_conflicts(self):
        server = APIServer()
        client = Client(server)
        informers = InformerFactory(server)
        sched = new_scheduler(client, informers, batch=True, max_batch=64)
        for i in range(8):
            client.create_node(
                make_node(f"n{i}").capacity(cpu="8", memory="16Gi", pods=20)
                .obj()
            )
        informers.start()
        informers.wait_for_cache_sync()
        sched.queue.run()
        # 8 pods all wanting hostPort 8080: exactly one per node
        pods = [_port_pod(f"hp{i}", 8080) for i in range(8)]
        for p in pods:
            client.create_pod(p)
        sched.start()
        deadline = time.time() + 60
        while time.time() < deadline:
            cur, _ = client.list_pods()
            if sum(1 for p in cur if p.spec.node_name) == 8:
                break
            time.sleep(0.05)
        cur, _ = client.list_pods()
        hosts = [p.spec.node_name for p in cur if p.spec.node_name]
        assert len(hosts) == 8, f"bound {len(hosts)}/8"
        assert len(set(hosts)) == 8, f"port conflict: {hosts}"
        # the device path handled them (no sequential fallback)
        assert sched.pods_fallback == 0
        assert sched.pods_solved_on_device >= 8
        sched.stop()
        informers.stop()

    def test_ninth_pod_unschedulable_when_ports_exhausted(self):
        server = APIServer()
        client = Client(server)
        informers = InformerFactory(server)
        sched = new_scheduler(client, informers, batch=True, max_batch=64)
        for i in range(3):
            client.create_node(
                make_node(f"n{i}").capacity(cpu="8", memory="16Gi", pods=20)
                .obj()
            )
        informers.start()
        informers.wait_for_cache_sync()
        sched.queue.run()
        pods = [_port_pod(f"hp{i}", 9000) for i in range(4)]
        for p in pods:
            client.create_pod(p)
        sched.start()
        deadline = time.time() + 60
        while time.time() < deadline:
            cur, _ = client.list_pods()
            if sum(1 for p in cur if p.spec.node_name) >= 3:
                break
            time.sleep(0.05)
        time.sleep(1.0)
        cur, _ = client.list_pods()
        bound = [p for p in cur if p.spec.node_name]
        assert len(bound) == 3, f"bound {len(bound)}, want exactly 3"
        assert len({p.spec.node_name for p in bound}) == 3
        sched.stop()
        informers.stop()


class TestWithinBatchPortRows:
    """Within-batch conflicts now solve via synthetic anti rows
    (ops/affinity.add_host_port_rows) in ONE batch instead of
    one-pod-per-batch serialization."""

    @pytest.mark.parametrize("seed", [0, 7, 21])
    def test_random_port_mix_never_double_books(self, seed):
        import random

        from kubernetes_tpu.cache.node_info import (
            HostPortInfo,
            pod_host_ports,
        )

        rng = random.Random(seed)
        server = APIServer()
        client = Client(server)
        informers = InformerFactory(server)
        sched = new_scheduler(client, informers, batch=True, max_batch=64)
        for i in range(10):
            client.create_node(
                make_node(f"n{i}").capacity(
                    cpu="16", memory="32Gi", pods=30
                ).obj()
            )
        informers.start()
        informers.wait_for_cache_sync()
        sched.queue.run()
        pods = []
        for i in range(24):
            port = rng.choice([8080, 8080, 9090])
            proto = rng.choice(["TCP", "TCP", "UDP"])
            ip = rng.choice(["", "", "10.0.0.1", "10.0.0.2"])
            pods.append(_port_pod(f"hp{i}", port, ip=ip, proto=proto))
        for p in pods:
            client.create_pod(p)
        sched.start()
        deadline = time.time() + 60
        while time.time() < deadline:
            cur, _ = client.list_pods()
            pend = [p for p in cur if not p.spec.node_name]
            if not pend or all(
                any(
                    c.type == "PodScheduled" and c.status == "False"
                    for c in p.status.conditions
                )
                for p in pend
            ):
                break
            time.sleep(0.05)
        sched.wait_for_inflight_binds()
        cur, _ = client.list_pods()
        by_node = {}
        for p in cur:
            if p.spec.node_name:
                by_node.setdefault(p.spec.node_name, []).append(p)
        # invariant: no node's bound pods conflict
        for node, plist in by_node.items():
            hp = HostPortInfo()
            for p in plist:
                for ip, proto, port in pod_host_ports(p):
                    assert not hp.conflicts(ip, proto, port), (
                        f"double-booked {proto}:{port}@{ip} on {node}"
                    )
                    hp.add(ip, proto, port)
        # with 10 nodes, every 8080-wildcard-free combination should
        # bind; at minimum most pods do, all on the device path
        assert sched.pods_fallback == 0
        sched.stop()
        informers.stop()
