"""Node lifecycle controller: stale heartbeats taint the node NoExecute
and evict intolerant pods; recovery removes the taint.

Reference: pkg/controller/nodelifecycle/node_lifecycle_controller.go
(:303 monitorNodeHealth, NoExecute taint manager eviction).
"""

from kubernetes_tpu.api.types import TAINT_EFFECT_NO_EXECUTE
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client.client import Client
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.controllers import NodeLifecycleController
from kubernetes_tpu.controllers.nodelifecycle import TAINT_UNREACHABLE
from kubernetes_tpu.kubelet import HollowKubelet
from kubernetes_tpu.testing import make_node, make_pod


def _env():
    server = APIServer()
    client = Client(server)
    informers = InformerFactory(server)
    clock = {"now": 1000.0}
    ctrl = NodeLifecycleController(
        client, informers, grace_period=40.0, now=lambda: clock["now"]
    )
    return server, client, informers, ctrl, clock


def test_stale_lease_taints_and_evicts():
    server, client, informers, ctrl, clock = _env()
    client.create_node(make_node("n").capacity(cpu="4", memory="8Gi").obj())
    client.create_pod(make_pod("victim").node("n").container(cpu="1").obj())
    tolerant = (
        make_pod("survivor").node("n").container(cpu="1")
        .toleration(TAINT_UNREACHABLE, operator="Exists",
                    effect=TAINT_EFFECT_NO_EXECUTE)
        .obj()
    )
    client.create_pod(tolerant)
    kubelet = HollowKubelet(client, "n", now=lambda: clock["now"])

    # heartbeat at t=1000
    kubelet.heartbeat_once()
    informers.pods().pump()
    informers.nodes().pump()

    # fresh: nothing happens
    ctrl.monitor_once()
    node = client.get_node("n")
    assert not any(t.key == TAINT_UNREACHABLE for t in node.spec.taints)

    # lease goes stale
    clock["now"] += 120.0
    ctrl.monitor_once()
    node = client.get_node("n")
    assert any(
        t.key == TAINT_UNREACHABLE and t.effect == TAINT_EFFECT_NO_EXECUTE
        for t in node.spec.taints
    )
    assert any(
        c.type == "Ready" and c.status == "Unknown"
        for c in node.status.conditions
    )
    names = {p.metadata.name for p in client.list_pods()[0]}
    assert "victim" not in names  # evicted
    assert "survivor" in names  # tolerates NoExecute
    assert ctrl.evictions == 1


def test_recovered_heartbeat_untaints():
    server, client, informers, ctrl, clock = _env()
    client.create_node(make_node("n").capacity(cpu="4", memory="8Gi").obj())
    kubelet = HollowKubelet(client, "n", now=lambda: clock["now"])
    kubelet.heartbeat_once()
    informers.nodes().pump()
    clock["now"] += 120.0
    ctrl.monitor_once()
    informers.nodes().pump()
    node = client.get_node("n")
    assert any(t.key == TAINT_UNREACHABLE for t in node.spec.taints)
    # heartbeat resumes
    kubelet.heartbeat_once()
    informers.nodes().pump()
    ctrl.monitor_once()
    node = client.get_node("n")
    assert not any(t.key == TAINT_UNREACHABLE for t in node.spec.taints)


class TestTaintEvictionPdbGate:
    """PR-6 satellite: taint evictions route through the SAME
    DisruptionController.can_disrupt budget as node drains."""

    def _env(self):
        from kubernetes_tpu.controllers import DisruptionController

        server = APIServer()
        client = Client(server)
        informers = InformerFactory(server)
        disruption = DisruptionController(client, informers)
        clock = {"now": 1000.0}
        ctrl = NodeLifecycleController(
            client, informers, grace_period=40.0,
            now=lambda: clock["now"], disruption=disruption,
        )
        return server, client, informers, ctrl, disruption, clock

    def _pdb(self, client, match, min_available):
        from kubernetes_tpu.api.types import (
            LabelSelector, PodDisruptionBudget,
        )

        pdb = PodDisruptionBudget(
            selector=LabelSelector(match_labels=match),
            min_available=min_available,
        )
        pdb.metadata.name = "guard"
        pdb.metadata.namespace = "default"
        client.create_pdb(pdb)

    def test_eviction_blocked_until_budget_reopens(self):
        server, client, informers, ctrl, disruption, clock = self._env()
        client.create_node(
            make_node("n").capacity(cpu="8", memory="16Gi").obj()
        )
        self._pdb(client, {"app": "web"}, min_available=2)
        for i in range(2):
            client.create_pod(
                make_pod(f"w{i}").labels(app="web").node("n")
                .container(cpu="1").obj()
            )
        kubelet = HollowKubelet(client, "n", now=lambda: clock["now"])
        kubelet.heartbeat_once()
        informers.pods().pump()
        informers.nodes().pump()
        informers.pdbs().pump()
        disruption.sync_all()  # 2 healthy - 2 minAvailable = 0 allowed
        clock["now"] += 120.0
        ctrl.monitor_once()
        # tainted, but NOTHING evicted: the budget said no
        node = client.get_node("n")
        assert any(t.key == TAINT_UNREACHABLE for t in node.spec.taints)
        names = {p.metadata.name for p in client.list_pods()[0]}
        assert names == {"w0", "w1"}
        assert ctrl.evictions == 0
        assert ctrl.evictions_blocked == 2
        # replacements bind on a healthy node; the reconcile loop
        # re-opens the budget; the NEXT monitor pass evicts
        for i in range(2):
            client.create_pod(
                make_pod(f"r{i}").labels(app="web").node("m")
                .container(cpu="1").obj()
            )
        informers.pods().pump()
        disruption.sync_all()  # 4 healthy - 2 = 2 allowed
        ctrl.monitor_once()
        names = {p.metadata.name for p in client.list_pods()[0]}
        assert "w0" not in names and "w1" not in names
        assert ctrl.evictions == 2


class TestNodeDrainer:
    """Cordon + PDB-gated eviction (kubectl drain semantics): a drain
    and a taint eviction spend one budget."""

    def _env(self):
        from kubernetes_tpu.controllers import (
            DisruptionController, NodeDrainer,
        )

        server = APIServer()
        client = Client(server)
        informers = InformerFactory(server)
        disruption = DisruptionController(client, informers)
        drainer = NodeDrainer(client, disruption=disruption, poll=0.01)
        return server, client, informers, disruption, drainer

    def test_cordon_flips_unschedulable(self):
        server, client, informers, disruption, drainer = self._env()
        client.create_node(make_node("n").capacity(cpu="4").obj())
        assert drainer.cordon("n")
        assert client.get_node("n").spec.unschedulable
        assert drainer.uncordon("n")
        assert not client.get_node("n").spec.unschedulable
        assert not drainer.cordon("missing")

    def test_drain_empties_node_within_budget(self):
        server, client, informers, disruption, drainer = self._env()
        client.create_node(make_node("n").capacity(cpu="8").obj())
        for i in range(3):
            client.create_pod(
                make_pod(f"p{i}").node("n").container(cpu="1").obj()
            )
        informers.pods().pump()
        # no PDB: everything is disruptable
        assert drainer.drain("n", timeout=5.0)
        assert drainer.evictions == 3
        assert drainer.drains == 1
        assert client.get_node("n").spec.unschedulable
        assert not [
            p for p in client.list_pods()[0]
            if p.spec.node_name == "n"
        ]

    def test_drain_blocked_by_pdb_reports_failure(self):
        from kubernetes_tpu.api.types import (
            LabelSelector, PodDisruptionBudget,
        )

        server, client, informers, disruption, drainer = self._env()
        client.create_node(make_node("n").capacity(cpu="8").obj())
        pdb = PodDisruptionBudget(
            selector=LabelSelector(match_labels={"app": "web"}),
            min_available=2,
        )
        pdb.metadata.name = "guard"
        pdb.metadata.namespace = "default"
        client.create_pdb(pdb)
        for i in range(3):
            client.create_pod(
                make_pod(f"p{i}").labels(app="web").node("n")
                .container(cpu="1").obj()
            )
        informers.pods().pump()
        informers.pdbs().pump()
        disruption.sync_all()  # 3 healthy - 2 = 1 allowed
        assert not drainer.drain("n", timeout=0.5)
        # exactly one eviction fit the budget; the stragglers stay, the
        # node stays cordoned (what a real drain reports back)
        assert drainer.evictions == 1
        assert drainer.evictions_blocked >= 1
        assert drainer.drains == 0
        assert client.get_node("n").spec.unschedulable
        remaining = [
            p for p in client.list_pods()[0]
            if p.spec.node_name == "n"
        ]
        assert len(remaining) == 2
