"""Node lifecycle controller: stale heartbeats taint the node NoExecute
and evict intolerant pods; recovery removes the taint.

Reference: pkg/controller/nodelifecycle/node_lifecycle_controller.go
(:303 monitorNodeHealth, NoExecute taint manager eviction).
"""

from kubernetes_tpu.api.types import TAINT_EFFECT_NO_EXECUTE
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client.client import Client
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.controllers import NodeLifecycleController
from kubernetes_tpu.controllers.nodelifecycle import TAINT_UNREACHABLE
from kubernetes_tpu.kubelet import HollowKubelet
from kubernetes_tpu.testing import make_node, make_pod


def _env():
    server = APIServer()
    client = Client(server)
    informers = InformerFactory(server)
    clock = {"now": 1000.0}
    ctrl = NodeLifecycleController(
        client, informers, grace_period=40.0, now=lambda: clock["now"]
    )
    return server, client, informers, ctrl, clock


def test_stale_lease_taints_and_evicts():
    server, client, informers, ctrl, clock = _env()
    client.create_node(make_node("n").capacity(cpu="4", memory="8Gi").obj())
    client.create_pod(make_pod("victim").node("n").container(cpu="1").obj())
    tolerant = (
        make_pod("survivor").node("n").container(cpu="1")
        .toleration(TAINT_UNREACHABLE, operator="Exists",
                    effect=TAINT_EFFECT_NO_EXECUTE)
        .obj()
    )
    client.create_pod(tolerant)
    kubelet = HollowKubelet(client, "n", now=lambda: clock["now"])

    # heartbeat at t=1000
    kubelet.heartbeat_once()
    informers.pods().pump()
    informers.nodes().pump()

    # fresh: nothing happens
    ctrl.monitor_once()
    node = client.get_node("n")
    assert not any(t.key == TAINT_UNREACHABLE for t in node.spec.taints)

    # lease goes stale
    clock["now"] += 120.0
    ctrl.monitor_once()
    node = client.get_node("n")
    assert any(
        t.key == TAINT_UNREACHABLE and t.effect == TAINT_EFFECT_NO_EXECUTE
        for t in node.spec.taints
    )
    assert any(
        c.type == "Ready" and c.status == "Unknown"
        for c in node.status.conditions
    )
    names = {p.metadata.name for p in client.list_pods()[0]}
    assert "victim" not in names  # evicted
    assert "survivor" in names  # tolerates NoExecute
    assert ctrl.evictions == 1


def test_recovered_heartbeat_untaints():
    server, client, informers, ctrl, clock = _env()
    client.create_node(make_node("n").capacity(cpu="4", memory="8Gi").obj())
    kubelet = HollowKubelet(client, "n", now=lambda: clock["now"])
    kubelet.heartbeat_once()
    informers.nodes().pump()
    clock["now"] += 120.0
    ctrl.monitor_once()
    informers.nodes().pump()
    node = client.get_node("n")
    assert any(t.key == TAINT_UNREACHABLE for t in node.spec.taints)
    # heartbeat resumes
    kubelet.heartbeat_once()
    informers.nodes().pump()
    ctrl.monitor_once()
    node = client.get_node("n")
    assert not any(t.key == TAINT_UNREACHABLE for t in node.spec.taints)
