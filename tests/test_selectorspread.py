"""SelectorSpread / ServiceAffinity / NodeLabel tests."""

import pytest

from kubernetes_tpu.api.types import ObjectMeta, Service
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.cache.snapshot import new_snapshot
from kubernetes_tpu.client.client import Client
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.framework.interface import CycleState, NodeScore, StatusCode
from kubernetes_tpu.plugins.selectorspread import (
    DefaultPodTopologySpread,
    NodeLabel,
    ServiceAffinity,
    get_zone_key,
)
from kubernetes_tpu.scheduler.generic import SNAPSHOT_STATE_KEY
from kubernetes_tpu.testing import make_node, make_pod


class _Handle:
    def __init__(self, informers):
        self.informers = informers


@pytest.fixture
def env():
    server = APIServer()
    client = Client(server)
    informers = InformerFactory(server)
    # materialize informers used by the plugins
    for acc in ("services", "replication_controllers", "replica_sets",
                "stateful_sets"):
        getattr(informers, acc)()
    return server, client, informers, _Handle(informers)


def _state(pods, nodes):
    snap = new_snapshot(pods, nodes)
    state = CycleState()
    state.write(SNAPSHOT_STATE_KEY, snap)
    return state, snap


class TestSelectorSpread:
    def _score(self, env, pod, pods, nodes):
        server, client, informers, handle = env
        informers.pump()
        state, snap = _state(pods, nodes)
        pl = DefaultPodTopologySpread(handle)
        assert pl.pre_score(state, pod, snap.list_node_infos()) is None
        scores = []
        for ni in snap.list_node_infos():
            raw, status = pl.score(state, pod, ni.node_name)
            assert status is None
            scores.append(NodeScore(ni.node_name, raw))
        assert pl.normalize_score(state, pod, scores) is None
        return {ns.name: ns.score for ns in scores}

    def test_spreads_service_pods_across_nodes(self, env):
        server, client, informers, handle = env
        client.create(Service(
            metadata=ObjectMeta(name="svc", namespace="default"),
            selector={"app": "web"},
        ))
        nodes = [make_node("a").obj(), make_node("b").obj()]
        pods = [make_pod("p1").node("a").labels(app="web").obj()]
        pod = make_pod("new").labels(app="web").obj()
        by_node = self._score(env, pod, pods, nodes)
        assert by_node["b"] > by_node["a"]

    def test_no_controller_all_equal(self, env):
        nodes = [make_node("a").obj(), make_node("b").obj()]
        pods = [make_pod("p1").node("a").labels(app="web").obj()]
        pod = make_pod("new").labels(app="web").obj()
        by_node = self._score(env, pod, pods, nodes)
        assert by_node["a"] == by_node["b"] == 100

    def test_zone_weighting(self, env):
        server, client, informers, handle = env
        client.create(Service(
            metadata=ObjectMeta(name="svc", namespace="default"),
            selector={"app": "web"},
        ))
        zkey = "topology.kubernetes.io/zone"
        nodes = [
            make_node("a1").label(zkey, "z1").obj(),
            make_node("a2").label(zkey, "z1").obj(),
            make_node("b1").label(zkey, "z2").obj(),
        ]
        # z1 heavily loaded: a1 has 2 pods, a2 has 0; z2 empty
        pods = [
            make_pod("p1").node("a1").labels(app="web").obj(),
            make_pod("p2").node("a1").labels(app="web").obj(),
        ]
        pod = make_pod("new").labels(app="web").obj()
        by_node = self._score(env, pod, pods, nodes)
        # empty node in empty zone beats empty node in loaded zone
        assert by_node["b1"] > by_node["a2"] > by_node["a1"]

    def test_get_zone_key(self):
        n = make_node("x").label("topology.kubernetes.io/zone", "z1") \
            .label("topology.kubernetes.io/region", "r1").obj()
        assert get_zone_key(n) == "r1:\x00:z1"
        assert get_zone_key(make_node("y").obj()) == ""


class TestServiceAffinity:
    def test_label_homogeneity(self, env):
        server, client, informers, handle = env
        client.create(Service(
            metadata=ObjectMeta(name="svc", namespace="default"),
            selector={"app": "db"},
        ))
        informers.pump()
        nodes = [
            make_node("r1").labels(region="r1").obj(),
            make_node("r2").labels(region="r2").obj(),
        ]
        mate = make_pod("mate").node("r1").labels(app="db").obj()
        state, snap = _state([mate], nodes)
        pl = ServiceAffinity({"affinity_labels": ["region"]}, handle)
        pod = make_pod("new").labels(app="db").obj()
        assert pl.pre_filter(state, pod) is None
        assert pl.filter(state, pod, snap.get_node_info("r1")) is None
        status = pl.filter(state, pod, snap.get_node_info("r2"))
        assert status is not None and status.code == StatusCode.UNSCHEDULABLE

    def test_first_pod_lands_anywhere(self, env):
        server, client, informers, handle = env
        informers.pump()
        nodes = [make_node("r1").labels(region="r1").obj()]
        state, snap = _state([], nodes)
        pl = ServiceAffinity({"affinity_labels": ["region"]}, handle)
        pod = make_pod("new").labels(app="db").obj()
        assert pl.pre_filter(state, pod) is None
        assert pl.filter(state, pod, snap.get_node_info("r1")) is None


class TestNodeLabel:
    def test_presence_absence(self):
        pl = NodeLabel({"present_labels": ["ssd"], "absent_labels": ["spot"]})
        state = CycleState()
        from kubernetes_tpu.cache.node_info import NodeInfo
        good = NodeInfo(make_node("g").labels(ssd="true").obj())
        missing = NodeInfo(make_node("m").obj())
        spotty = NodeInfo(make_node("s").labels(ssd="1", spot="1").obj())
        assert pl.filter(state, make_pod("p").obj(), good) is None
        assert pl.filter(state, make_pod("p").obj(), missing) is not None
        assert pl.filter(state, make_pod("p").obj(), spotty) is not None

    def test_conflicting_args_rejected(self):
        with pytest.raises(ValueError):
            NodeLabel({"present_labels": ["x"], "absent_labels": ["x"]})

    def test_preference_score(self):
        pl = NodeLabel({"present_labels_preference": ["ssd"]})
        nodes = [make_node("a").labels(ssd="1").obj(), make_node("b").obj()]
        state, snap = _state([], nodes)
        assert pl.score(state, make_pod("p").obj(), "a")[0] == 100
        assert pl.score(state, make_pod("p").obj(), "b")[0] == 0
