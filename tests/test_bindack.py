"""Closed-loop bind acks (ISSUE 17): the BindAckTracker ledger,
zombie-kubelet rebind-after-timeout, the heartbeat-lapse eviction-storm
guard, and the kubelet-chaos tier-1 guard.

The contracts under test:

- the tracker books Running transitions as acks, unbinds overdue pods
  EXACTLY once per incarnation (uid-fenced -- a second timeout on the
  same uid is surfaced, never looped), books the ack-wins-race as
  ``acked-late``, and taints/untaints suspect nodes;
- zombie e2e: pods bound to a never-acking node rebind elsewhere and
  reach Running, pinned by a uid-keyed replay of the apiserver watch
  history (one unbind per uid, zero double-binds);
- heartbeat-lapse storm: every taint eviction routes through the shared
  DisruptionController.can_disrupt budget -- the ledger stays balanced
  and no PDB budget ever goes negative;
- kubelet-chaos guard: a 1k-pod burst under the builtin profile (5%
  slow acks, a zombie node, bounded heartbeat lapses) converges to 100%
  Running with exactly-once rebinds, zero double-binds, and a
  flight-recorder dump that alone reconstructs every rebind and every
  heartbeat-lapse eviction.
"""

import threading
import time

import pytest

from kubernetes_tpu.api.types import (
    POD_RUNNING,
    TAINT_EFFECT_NO_EXECUTE,
)
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client.client import Client
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.config.types import BindAckConfiguration
from kubernetes_tpu.controllers import (
    DisruptionController,
    NodeLifecycleController,
)
from kubernetes_tpu.controllers.nodelifecycle import TAINT_UNREACHABLE
from kubernetes_tpu.kubelet import FleetConfig, HollowNodeFleet
from kubernetes_tpu.robustness.faults import (
    FaultInjector,
    install_injector,
    load_profile,
)
from kubernetes_tpu.scheduler.bindack import (
    BindAckTracker,
    TAINT_BIND_ACK_TIMEOUT,
)
from kubernetes_tpu.scheduler.scheduler import new_scheduler
from kubernetes_tpu.testing import make_node, make_pod
from kubernetes_tpu.utils import flightrecorder


@pytest.fixture(autouse=True)
def _clean_injector():
    yield
    install_injector(None)


def _wait(pred, timeout, interval=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


def _pod_timelines(server):
    """uid -> [(event_type, node_name, phase)] in watch-history order:
    the replay that pins exactly-once rebinds and zero double-binds."""
    out = {}
    for ev in server._history["Pod"]:
        out.setdefault(ev.object.metadata.uid, []).append(
            (ev.type, ev.object.spec.node_name, ev.object.status.phase)
        )
    return out


def _unbinds_and_doublebinds(timelines):
    """Per uid: bound->unbound transitions, and direct node->other-node
    rewrites (a double-bind -- must never happen)."""
    unbinds, double_binds = {}, []
    for uid, frames in timelines.items():
        prev_node = None
        for _type, node, _phase in frames:
            if prev_node and not node:
                unbinds[uid] = unbinds.get(uid, 0) + 1
            if prev_node and node and node != prev_node:
                double_binds.append((uid, prev_node, node))
            prev_node = node
    return unbinds, double_binds


class TestBindAckTracker:
    def _env(self, **kw):
        server = APIServer()
        client = Client(server)
        for n in ("n0", "n1"):
            client.create_node(
                make_node(n).capacity(cpu="8", memory="16Gi").obj()
            )
        tracker = BindAckTracker(client, **kw)
        return server, client, tracker

    def _bound(self, client, name="p", node="n0"):
        client.create_pod(
            make_pod(name).node(node).container(cpu="1").obj()
        )
        return client.get_pod("default", name)

    def test_running_transition_is_the_ack(self):
        server, client, tracker = self._env(ack_timeout_seconds=60.0)
        pod = self._bound(client)
        tracker.track_bound([("default", "p", pod.metadata.uid, "n0")])
        assert tracker.pending_count() == 1
        client.update_pod_status(
            "default", "p",
            lambda p: setattr(p.status, "phase", POD_RUNNING),
        )
        tracker.observe_pod(pod, client.get_pod("default", "p"))
        assert tracker.pending_count() == 0
        assert tracker.acks == 1
        assert tracker.sweep() == 0  # nothing overdue, nothing unbound

    def test_timeout_unbinds_exactly_once_per_incarnation(self):
        server, client, tracker = self._env(
            ack_timeout_seconds=0.05, node_suspect_threshold=1,
        )
        pod = self._bound(client)
        uid = pod.metadata.uid
        tracker.track_bound([("default", "p", uid, "n0")])
        time.sleep(0.1)
        assert tracker.sweep() == 1
        after = client.get_pod("default", "p")
        assert after.spec.node_name == ""
        assert tracker.rebinds == 1 and tracker.timeouts == 1
        # the suspect node is tainted NoSchedule: the rebind cannot
        # re-pick the zombie
        node = client.get_node("n0")
        assert any(
            t.key == TAINT_BIND_ACK_TIMEOUT for t in node.spec.taints
        )
        # the rebind lands on n1... and n1 ALSO never acks: the uid
        # fence surfaces the second timeout and leaves the pod bound
        server.guaranteed_update(
            "Pod", "default", "p",
            lambda p: setattr(p.spec, "node_name", "n1"),
        )
        tracker.track_bound([("default", "p", uid, "n1")])
        time.sleep(0.1)
        assert tracker.sweep() == 0
        assert tracker.timeouts == 2
        assert client.get_pod("default", "p").spec.node_name == "n1"
        assert tracker.pending_count() == 0  # surfaced, not re-armed

    def test_ack_wins_the_unbind_race_booked_late(self):
        server, client, tracker = self._env(ack_timeout_seconds=0.05)
        pod = self._bound(client)
        tracker.track_bound([("default", "p", pod.metadata.uid, "n0")])
        # the kubelet ack lands before the sweep: the store refuses the
        # unbind with the typed ``acked`` conflict
        client.update_pod_status(
            "default", "p",
            lambda p: setattr(p.status, "phase", POD_RUNNING),
        )
        time.sleep(0.1)
        assert tracker.sweep() == 0
        assert tracker.acks_late == 1
        assert tracker.rebinds == 0
        assert client.get_pod("default", "p").spec.node_name == "n0"

    def test_ack_from_suspect_node_untaints(self):
        server, client, tracker = self._env(
            ack_timeout_seconds=0.05, node_suspect_threshold=1,
        )
        pod = self._bound(client, name="slow")
        tracker.track_bound([("default", "slow", pod.metadata.uid, "n0")])
        time.sleep(0.1)
        tracker.sweep()
        assert any(
            t.key == TAINT_BIND_ACK_TIMEOUT
            for t in client.get_node("n0").spec.taints
        )
        # a later pod on the same node DOES ack: the sync loop is alive
        other = self._bound(client, name="ok")
        tracker.track_bound([("default", "ok", other.metadata.uid, "n0")])
        client.update_pod_status(
            "default", "ok",
            lambda p: setattr(p.status, "phase", POD_RUNNING),
        )
        tracker.observe_pod(other, client.get_pod("default", "ok"))
        assert not any(
            t.key == TAINT_BIND_ACK_TIMEOUT
            for t in client.get_node("n0").spec.taints
        )

    def test_deleted_pod_leaves_the_ledger(self):
        server, client, tracker = self._env(ack_timeout_seconds=0.05)
        pod = self._bound(client)
        tracker.track_bound([("default", "p", pod.metadata.uid, "n0")])
        client.delete_pod("default", "p")
        tracker.observe_gone(pod.metadata.uid)
        time.sleep(0.1)
        assert tracker.sweep() == 0
        assert tracker.pending_count() == 0


class TestZombieKubeletE2E:
    def test_rebind_lands_elsewhere_exactly_once(self):
        """Bound-but-never-acked pods on the zombie node are unbound
        after the ack timeout and rebind on a live node; the watch
        history pins one unbind per uid and zero double-binds."""
        server = APIServer()
        client = Client(server)
        informers = InformerFactory(server)
        sched = new_scheduler(
            client, informers, batch=True, max_batch=16,
            bind_ack_config=BindAckConfiguration(
                enabled=True, ack_timeout_seconds=0.6,
                sweep_interval_seconds=0.1,
            ),
        )
        names = ["n0", "n1", "n2"]
        for n in names:
            client.create_node(
                make_node(n).capacity(cpu="16", memory="32Gi", pods=110)
                .obj()
            )
        fleet = HollowNodeFleet(
            client, names,
            FleetConfig(heartbeat_interval_seconds=0.2),
        )
        fleet.mark_zombie(["n0"])
        informers.start()
        informers.wait_for_cache_sync()
        sched.queue.run()
        fleet.start()
        for i in range(9):
            client.create_pod(
                make_pod(f"p{i}").container(cpu="500m", memory="256Mi")
                .obj()
            )
        sched.start()
        try:
            assert _wait(
                lambda: sum(
                    1 for p in client.list_pods()[0]
                    if p.status.phase == POD_RUNNING
                ) == 9,
                60,
            ), "zombie-held pods never converged to Running"
        finally:
            sched.stop()
            fleet.stop()
            informers.stop()
        pods, _ = client.list_pods()
        assert all(p.spec.node_name != "n0" for p in pods), (
            "a Running pod sits on the zombie node"
        )
        tracker = sched.bind_ack_tracker
        assert tracker.rebinds >= 1, "no bind ever targeted the zombie?"
        # the zombie stays tainted: it never acked anything
        assert any(
            t.key == TAINT_BIND_ACK_TIMEOUT
            for t in client.get_node("n0").spec.taints
        )
        # uid-keyed watch-history replay: exactly-once per incarnation
        timelines = _pod_timelines(server)
        unbinds, double_binds = _unbinds_and_doublebinds(timelines)
        assert not double_binds, double_binds
        assert all(n == 1 for n in unbinds.values()), unbinds
        # every uid that ever sat on the zombie and survived was
        # rebound exactly once
        zombie_uids = {
            uid for uid, frames in timelines.items()
            if any(node == "n0" for _t, node, _p in frames)
        }
        assert zombie_uids, "no bind ever landed on the zombie"
        assert zombie_uids == set(unbinds)
        assert tracker.rebinds == len(zombie_uids)


class TestHeartbeatLapseStormGuard:
    def test_evictions_route_through_shared_budget(self):
        """Three nodes lapse at once over a PDB-guarded workload: only
        the budget's worth of pods is evicted, the rest are BLOCKED (not
        dropped), and no PDB ledger ever goes negative."""
        server = APIServer()
        client = Client(server)
        informers = InformerFactory(server)
        clock = {"now": 1000.0}
        disruption = DisruptionController(client, informers)
        ctrl = NodeLifecycleController(
            client, informers, grace_period=40.0,
            now=lambda: clock["now"], disruption=disruption,
        )
        names = ["n0", "n1", "n2"]
        for n in names:
            client.create_node(
                make_node(n).capacity(cpu="16", memory="32Gi").obj()
            )
        from kubernetes_tpu.api.types import (
            LabelSelector,
            PodDisruptionBudget,
        )

        pdb = PodDisruptionBudget(
            selector=LabelSelector(match_labels={"app": "web"}),
            min_available=4,
        )
        pdb.metadata.name = "guard"
        pdb.metadata.namespace = "default"
        client.create_pdb(pdb)
        for i in range(6):
            client.create_pod(
                make_pod(f"w{i}").labels(app="web").node(names[i % 3])
                .container(cpu="1").obj()
            )
        fleet = HollowNodeFleet(
            client, names, FleetConfig(), now=lambda: clock["now"]
        )
        fleet.heartbeat_once()
        informers.pods().pump()
        informers.nodes().pump()
        informers.pdbs().pump()
        disruption.sync_all()  # 6 healthy - 4 minAvailable = 2 allowed
        # every heartbeat stops at once: the storm
        clock["now"] += 120.0
        ctrl.monitor_once()
        # all three nodes unreachable, but the eviction wave is bounded
        # by the SHARED budget: 2 evicted, 4 blocked, zero negative
        for n in names:
            assert any(
                t.key == TAINT_UNREACHABLE
                and t.effect == TAINT_EFFECT_NO_EXECUTE
                for t in client.get_node(n).spec.taints
            )
        assert ctrl.evictions == 2
        assert ctrl.evictions_blocked == 4
        assert len(client.list_pods()[0]) == 4
        status = client.get(
            "PodDisruptionBudget", "default", "guard"
        ).status
        assert status.disruptions_allowed == 0  # spent, never negative
        # the ledger balances: every intolerant pod was either evicted
        # or blocked -- none silently dropped
        assert ctrl.evictions + ctrl.evictions_blocked == 6
        # repeated passes while stale never push the budget negative
        ctrl.monitor_once()
        status = client.get(
            "PodDisruptionBudget", "default", "guard"
        ).status
        assert status.disruptions_allowed == 0
        assert ctrl.evictions == 2


class TestKubeletChaosGuard:
    def test_1k_burst_converges_with_reconstructable_dump(self):
        """The tier-1 acceptance guard: 1000 pods over 100 hollow nodes
        under the builtin kubelet-chaos profile (5% slow acks, one
        zombie node, bounded heartbeat lapses with a live lifecycle
        monitor evicting through the PDB gate). Everything converges to
        Running; the watch history pins exactly-once rebinds and zero
        double-binds; the flight-recorder dump alone reconstructs every
        rebind and every heartbeat-lapse eviction."""
        flightrecorder.RECORDER.reset()
        server = APIServer()
        client = Client(server)
        informers = InformerFactory(server)
        sched = new_scheduler(
            client, informers, batch=True, max_batch=256,
            bind_ack_config=BindAckConfiguration(
                enabled=True, ack_timeout_seconds=2.5,
                sweep_interval_seconds=0.25,
            ),
        )
        names = [f"node-{i}" for i in range(100)]
        for n in names:
            client.create_node(
                make_node(n).capacity(cpu="32", memory="64Gi", pods=110)
                .obj()
            )
        # build the fleet BEFORE installing the profile so the zombie
        # set is pinned to exactly one node (1%) regardless of the
        # profile's per-node draw; slow acks + lapses still draw live
        fleet = HollowNodeFleet(
            client, names,
            FleetConfig(shard_size=25, heartbeat_interval_seconds=0.25),
        )
        install_injector(FaultInjector(load_profile("kubelet-chaos")))
        fleet.mark_zombie(["node-0"])
        disruption = DisruptionController(client, informers)
        monitor = NodeLifecycleController(
            client, informers, grace_period=0.9, monitor_interval=0.1,
            disruption=disruption,
        )
        informers.start()
        informers.wait_for_cache_sync()
        sched.queue.run()
        fleet.start()
        expected = [f"p{i}" for i in range(1000)]
        for name in expected:
            client.create_pod(
                make_pod(name).container(cpu="250m", memory="128Mi").obj()
            )
        sched.start()
        monitor.start()
        # the replacement controller: evicted pods respawn (same name,
        # fresh uid) so "100% Running" is well-defined under evictions
        stop_respawn = threading.Event()

        def respawn():
            while not stop_respawn.is_set():
                live = {p.metadata.name for p in client.list_pods()[0]}
                for name in expected:
                    if name not in live:
                        try:
                            client.create_pod(
                                make_pod(name)
                                .container(cpu="250m", memory="128Mi")
                                .obj()
                            )
                        except ValueError:
                            pass  # lost the respawn race: fine
                stop_respawn.wait(0.2)

        respawner = threading.Thread(target=respawn, daemon=True)
        respawner.start()

        def all_running():
            pods, _ = client.list_pods()
            return (
                len(pods) == 1000
                and all(p.status.phase == POD_RUNNING for p in pods)
            )

        try:
            assert _wait(all_running, 120, interval=0.25), (
                "kubelet-chaos burst never converged to 100% Running"
            )
        finally:
            stop_respawn.set()
            respawner.join(timeout=2)
            monitor.stop()
            sched.stop()
            fleet.stop()
            informers.stop()
        pods, _ = client.list_pods()
        assert all(p.spec.node_name != "node-0" for p in pods), (
            "a Running pod sits on the zombie"
        )
        # -- uid-keyed watch-history replay -------------------------------
        timelines = _pod_timelines(server)
        unbinds, double_binds = _unbinds_and_doublebinds(timelines)
        assert not double_binds, double_binds
        assert all(n == 1 for n in unbinds.values()), (
            "a uid was unbound more than once per incarnation"
        )
        # every surviving incarnation that sat on the zombie rebound
        # exactly once (evicted incarnations legitimately end DELETED)
        deleted = {
            uid for uid, frames in timelines.items()
            if frames[-1][0] == "DELETED"
        }
        zombie_uids = {
            uid for uid, frames in timelines.items()
            if any(node == "node-0" for _t, node, _p in frames)
        }
        assert zombie_uids, "no bind ever landed on the zombie node"
        for uid in zombie_uids - deleted:
            assert unbinds.get(uid) == 1, (
                f"zombie-held uid {uid} was not rebound exactly once"
            )
        # -- the dump alone reconstructs the story ------------------------
        dump = flightrecorder.RECORDER.dump()
        rebind_marks = {
            m["pod"] for m in dump["marks"] if m["kind"] == "rebind"
        }
        assert rebind_marks == set(unbinds), (
            "flight-recorder rebind marks diverge from the history replay"
        )
        eviction_marks = {
            m["pod"] for m in dump["marks"]
            if m["kind"] == "taint_eviction"
        }
        assert eviction_marks == deleted, (
            "flight-recorder eviction marks diverge from the deletions"
        )
        if monitor.evictions:
            # lapses fired: each eviction arc is anchored by its node's
            # heartbeat_lapse mark
            lapsed_nodes = {
                m["node"] for m in dump["marks"]
                if m["kind"] == "heartbeat_lapse"
            }
            evicted_from = {
                m["node"] for m in dump["marks"]
                if m["kind"] == "taint_eviction"
            }
            assert evicted_from <= lapsed_nodes
