"""Hollow-node plane (ISSUE 17): routed watch fan-out, the unbind
primitive, and the sharded HollowNodeFleet.

The contracts under test:

- RoutedWatch delivers an event ONLY to the watchers registered for its
  route key (Pod -> spec.nodeName): uninterested cursors see nothing,
  events that never had a route (unbound pods) are invisible, retained
  history replays filtered through ``since_rv``, and a stalled consumer
  overflows to ``Gone`` (the 410 relist contract);
- ``unbind`` atomically releases a binding under the store lock, fenced
  by uid, node, and the Running phase (a kubelet ack that lands first
  WINS as a typed ``acked`` conflict);
- the fleet acks bindings into Running, renews Leases + Ready, drifts
  allocatable within bounds, suppresses acks on zombies, goes fully
  silent when dark, and refuses a stale ack for a rebound incarnation
  inside the status mutate.
"""

import time

import pytest

from kubernetes_tpu.api.types import POD_RUNNING, RESOURCE_PODS
from kubernetes_tpu.apiserver.server import (
    APIServer,
    BindConflict,
    Gone,
)
from kubernetes_tpu.client.client import Client
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.kubelet import FleetConfig, HollowNodeFleet
from kubernetes_tpu.kubelet.hollow import LEASE_NAMESPACE
from kubernetes_tpu.robustness.faults import install_injector
from kubernetes_tpu.scheduler.scheduler import new_scheduler
from kubernetes_tpu.testing import make_node, make_pod


@pytest.fixture(autouse=True)
def _clean_injector():
    yield
    install_injector(None)


def _wait(pred, timeout, interval=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


class TestRoutedWatch:
    def test_delivers_only_to_interested_routes(self):
        server = APIServer()
        client = Client(server)
        _, rv = server.list("Pod")
        w0 = server.watch_routes("Pod", {"n0"}, since_rv=rv)
        w1 = server.watch_routes("Pod", {"n1"}, since_rv=rv)
        client.create_pod(make_pod("a").node("n0").container(cpu="1").obj())
        client.create_pod(make_pod("b").node("n1").container(cpu="1").obj())
        evs0 = w0.pending()
        evs1 = w1.pending()
        assert [e.object.metadata.name for e in evs0] == ["a"]
        assert [e.object.metadata.name for e in evs1] == ["b"]
        # nothing queued behind: one dict probe routed each event once
        assert w0.pending() == [] and w1.pending() == []

    def test_unrouted_events_are_invisible(self):
        """An unbound pod has no route key: a kubelet's filtered watch
        never sees it until spec.nodeName points at it."""
        server = APIServer()
        client = Client(server)
        _, rv = server.list("Pod")
        w = server.watch_routes("Pod", {"n0"}, since_rv=rv)
        client.create_pod(make_pod("floating").container(cpu="1").obj())
        assert w.pending() == []
        # the bind MODIFIED carries the route: now it arrives
        server.guaranteed_update(
            "Pod", "default", "floating",
            lambda p: setattr(p.spec, "node_name", "n0"),
        )
        evs = w.pending()
        assert [e.object.metadata.name for e in evs] == ["floating"]

    def test_replay_since_rv(self):
        """The list+watch handshake: retained history after since_rv is
        replayed (filtered) at registration."""
        server = APIServer()
        client = Client(server)
        client.create_pod(make_pod("old").node("n0").container(cpu="1").obj())
        _, rv = server.list("Pod")
        client.create_pod(make_pod("new").node("n0").container(cpu="1").obj())
        client.create_pod(make_pod("other").node("n9").container(cpu="1").obj())
        w = server.watch_routes("Pod", {"n0"}, since_rv=rv)
        evs = w.pending()
        # only the post-rv event for OUR route; "old" is pre-rv, "other"
        # routes elsewhere
        assert [e.object.metadata.name for e in evs] == ["new"]

    def test_stalled_consumer_overflows_to_gone(self):
        server = APIServer(watch_history_limit=8)
        client = Client(server)
        _, rv = server.list("Pod")
        w = server.watch_routes("Pod", {"n0"}, since_rv=rv)
        for i in range(10):
            client.create_pod(
                make_pod(f"p{i}").node("n0").container(cpu="1").obj()
            )
        with pytest.raises(Gone):
            w.pending()
        # the Gone drained the overflow: the consumer relists and the
        # cursor is usable again
        client.create_pod(make_pod("fresh").node("n0").container(cpu="1").obj())
        assert [e.object.metadata.name for e in w.pending()] == ["fresh"]


class TestUnbind:
    def _bound(self, client, name="p", node="n0"):
        client.create_pod(
            make_pod(name).node(node).container(cpu="1").obj()
        )
        return client.get_pod("default", name)

    def test_unbind_releases_binding(self):
        server = APIServer()
        client = Client(server)
        pod = self._bound(client)
        out = server.unbind(
            "default", "p",
            expect_uid=pod.metadata.uid, expect_node="n0",
        )
        assert out.spec.node_name == ""
        assert out.status.phase != POD_RUNNING
        assert out.status.start_time is None
        # idempotent: already unbound is success, not an error
        again = server.unbind("default", "p")
        assert again.spec.node_name == ""

    def test_acked_pod_refuses_unbind(self):
        """The store lock settles the ack-vs-unbind race: Running wins
        and comes back as the typed ``acked`` conflict."""
        server = APIServer()
        client = Client(server)
        pod = self._bound(client)
        client.update_pod_status(
            "default", "p",
            lambda p: setattr(p.status, "phase", POD_RUNNING),
        )
        with pytest.raises(BindConflict) as err:
            server.unbind(
                "default", "p",
                expect_uid=pod.metadata.uid, expect_node="n0",
            )
        assert err.value.kind == "acked"
        assert client.get_pod("default", "p").spec.node_name == "n0"

    def test_uid_and_node_fences(self):
        server = APIServer()
        client = Client(server)
        pod = self._bound(client)
        with pytest.raises(BindConflict) as err:
            server.unbind("default", "p", expect_uid="other-incarnation")
        assert err.value.kind == "uid-mismatch"
        with pytest.raises(BindConflict) as err:
            server.unbind(
                "default", "p",
                expect_uid=pod.metadata.uid, expect_node="n7",
            )
        assert err.value.kind == "already-bound"
        assert client.get_pod("default", "p").spec.node_name == "n0"


class TestHollowNodeFleet:
    def _env(self, num_nodes=4, **cfg):
        server = APIServer()
        client = Client(server)
        names = [f"n{i}" for i in range(num_nodes)]
        for n in names:
            client.create_node(
                make_node(n).capacity(cpu="8", memory="16Gi", pods=110).obj()
            )
        fleet = HollowNodeFleet(client, names, FleetConfig(**cfg))
        return server, client, fleet, names

    def test_pump_acks_bound_pods(self):
        server, client, fleet, names = self._env()
        for i in range(6):
            client.create_pod(
                make_pod(f"p{i}").node(names[i % 4])
                .container(cpu="500m").obj()
            )
        fleet.pump()
        pods, _ = client.list_pods()
        assert all(p.status.phase == POD_RUNNING for p in pods)
        assert fleet.pods_acked == 6
        # acks are idempotent over the same incarnation
        fleet.pump()
        assert fleet.pods_acked == 6

    def test_stale_ack_fenced_after_rebind(self):
        """A late ack from the old node must not mark a requeued (or
        rebound) incarnation Running: the uid/node fence inside the
        status mutate refuses it under the store lock."""
        server, client, fleet, names = self._env()
        client.create_pod(
            make_pod("p").node("n0").container(cpu="1").obj()
        )
        pod = client.get_pod("default", "p")
        old_uid = pod.metadata.uid
        # rebind-after-timeout won: the pod moved to n1
        server.unbind("default", "p", expect_uid=old_uid, expect_node="n0")
        server.guaranteed_update(
            "Pod", "default", "p",
            lambda p: setattr(p.spec, "node_name", "n1"),
        )
        # the old node's ack fires late
        fleet.shards[0]._fire_ack(("default", "p", old_uid, "n0"))
        assert fleet.stale_acks == 1
        assert client.get_pod("default", "p").status.phase != POD_RUNNING

    def test_zombie_heartbeats_but_never_acks(self):
        server, client, fleet, names = self._env()
        fleet.mark_zombie(["n0"])
        client.create_pod(
            make_pod("stuck").node("n0").container(cpu="1").obj()
        )
        fleet.pump()
        fleet.heartbeat_once()
        assert client.get_pod("default", "stuck").status.phase != POD_RUNNING
        assert fleet.pods_acked == 0
        assert fleet.acks_suppressed >= 1
        # the lease still renews: only bind-ack tracking can see a zombie
        lease = server.get("Lease", LEASE_NAMESPACE, "n0")
        assert lease.renew_time > 0

    def test_dark_node_goes_fully_silent(self):
        server, client, fleet, names = self._env()
        fleet.heartbeat_once()
        first = server.get("Lease", LEASE_NAMESPACE, "n0").renew_time
        fleet.go_dark(["n0"])
        client.create_pod(
            make_pod("p").node("n0").container(cpu="1").obj()
        )
        time.sleep(0.01)
        fleet.pump()
        fleet.heartbeat_once()
        assert client.get_pod("default", "p").status.phase != POD_RUNNING
        assert server.get("Lease", LEASE_NAMESPACE, "n0").renew_time == first
        # the siblings kept renewing
        assert server.get("Lease", LEASE_NAMESPACE, "n1").renew_time > 0

    def test_allocatable_drift_stays_bounded(self):
        server, client, fleet, names = self._env(
            num_nodes=2, allocatable_drift=1.0, seed=7,
        )
        base = client.get_node("n0").status.allocatable[RESOURCE_PODS]
        for _ in range(40):
            fleet.heartbeat_once()
        assert fleet.allocatable_drifts > 0
        for n in names:
            cur = client.get_node(n).status.allocatable[RESOURCE_PODS]
            assert base - 2 <= cur <= base + 2

    def test_sharding_splits_nodes(self):
        server, client, fleet, names = self._env(num_nodes=7, shard_size=3)
        assert [len(s.nodes) for s in fleet.shards] == [3, 3, 1]
        assert fleet.node_names == set(names)

    def test_threaded_fleet_closes_the_loop_with_scheduler(self):
        """The closed control loop: create -> schedule -> bind -> shard
        watch wakes -> ack -> Running, with heartbeats flowing, driven
        by the fleet's own threads."""
        server = APIServer()
        client = Client(server)
        informers = InformerFactory(server)
        sched = new_scheduler(client, informers, batch=True, max_batch=32)
        names = [f"n{i}" for i in range(6)]
        for n in names:
            client.create_node(
                make_node(n).capacity(cpu="8", memory="16Gi", pods=110).obj()
            )
        fleet = HollowNodeFleet(
            client, names,
            FleetConfig(shard_size=2, heartbeat_interval_seconds=0.2),
        )
        informers.start()
        informers.wait_for_cache_sync()
        sched.queue.run()
        fleet.start()
        for i in range(24):
            client.create_pod(
                make_pod(f"p{i}").container(cpu="500m", memory="256Mi").obj()
            )
        sched.start()
        try:
            assert _wait(
                lambda: sum(
                    1 for p in client.list_pods()[0]
                    if p.status.phase == POD_RUNNING
                ) == 24,
                30,
            ), "closed loop never drove all pods to Running"
        finally:
            sched.stop()
            fleet.stop()
            informers.stop()
        assert fleet.pods_acked >= 24
        leases, _ = server.list("Lease")
        assert {le.metadata.name for le in leases} >= set(names)
