"""Gang all-or-nothing group masks in the device solver (SURVEY stage 6).

A half-fitting gang must place ZERO pods (no capacity reserved, no
Permit-timeout churn); a fitting gang places fully and releases through
Permit. Reference hook: framework/v1alpha1/interface.go:384 (Permit) +
the out-of-tree coscheduling pattern.
"""

import time

from kubernetes_tpu.api.types import ObjectMeta, POD_GROUP_LABEL, PodGroup
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client.client import Client
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.scheduler.scheduler import new_scheduler
from kubernetes_tpu.testing import make_node, make_pod


def _cluster(max_batch=32):
    server = APIServer()
    client = Client(server)
    informers = InformerFactory(server)
    sched = new_scheduler(client, informers, batch=True, max_batch=max_batch)
    return server, client, informers, sched


def _gang_pod(name, group, cpu="1"):
    p = make_pod(name).container(cpu=cpu, memory="128Mi").obj()
    p.metadata.labels[POD_GROUP_LABEL] = group
    return p


def _pg(client, name, min_member):
    client.create_pod_group(
        PodGroup(
            metadata=ObjectMeta(name=name, namespace="default"),
            min_member=min_member,
        )
    )


def test_half_fitting_gang_places_nothing():
    server, client, informers, sched = _cluster()
    # capacity for 4 gang pods; the gang needs 6
    for i in range(2):
        client.create_node(
            make_node(f"n{i}").capacity(cpu="2", memory="8Gi").obj()
        )
    _pg(client, "g6", 6)
    informers.start()
    informers.wait_for_cache_sync()
    sched.queue.run()
    for i in range(6):
        client.create_pod(_gang_pod(f"g{i}", "g6"))
    deadline = time.time() + 10
    while time.time() < deadline:
        sched.schedule_batch(timeout=0.2)
        if sched.queue.num_pending()["unschedulable"] == 6:
            break
    sched.wait_for_inflight_binds()
    pods, _ = client.list_pods()
    bound = [p for p in pods if p.spec.node_name]
    # all-or-nothing: NOTHING placed, nothing parked at Permit
    assert bound == []
    assert sched.queue.num_pending()["unschedulable"] == 6
    for fw in sched.profiles.values():
        assert not fw.waiting_pods.list() if hasattr(
            fw.waiting_pods, "list"
        ) else True
    sched.stop()
    informers.stop()


def test_fitting_gang_places_fully_on_device():
    server, client, informers, sched = _cluster()
    for i in range(3):
        client.create_node(
            make_node(f"n{i}").capacity(cpu="4", memory="8Gi").obj()
        )
    _pg(client, "g6", 6)
    informers.start()
    informers.wait_for_cache_sync()
    sched.queue.run()
    for i in range(6):
        client.create_pod(_gang_pod(f"g{i}", "g6"))
    sched.start()
    deadline = time.time() + 30
    while time.time() < deadline:
        pods, _ = client.list_pods()
        if sum(1 for p in pods if p.spec.node_name) == 6:
            break
        time.sleep(0.05)
    sched.wait_for_inflight_binds()
    sched.stop()
    informers.stop()
    pods, _ = client.list_pods()
    assert sum(1 for p in pods if p.spec.node_name) == 6


def test_gang_failure_releases_capacity_to_other_pods():
    """The re-solve gives the failed gang's capacity to later plain pods
    in the same batch instead of leaving it reserved."""
    server, client, informers, sched = _cluster()
    client.create_node(
        make_node("n0").capacity(cpu="4", memory="8Gi").obj()
    )
    _pg(client, "g8", 8)
    informers.start()
    informers.wait_for_cache_sync()
    sched.queue.run()
    # gang of 8 x 1cpu (needs 8, only 4 fit) + 4 plain 1cpu pods,
    # created gang-first so they sort ahead in the batch
    for i in range(8):
        client.create_pod(_gang_pod(f"g{i}", "g8"))
    for i in range(4):
        client.create_pod(
            make_pod(f"plain{i}").container(cpu="1", memory="128Mi").obj()
        )
    sched.start()
    deadline = time.time() + 30
    while time.time() < deadline:
        pods, _ = client.list_pods()
        plain_bound = sum(
            1
            for p in pods
            if p.spec.node_name and p.metadata.name.startswith("plain")
        )
        if plain_bound == 4:
            break
        time.sleep(0.05)
    sched.wait_for_inflight_binds()
    sched.stop()
    informers.stop()
    pods, _ = client.list_pods()
    gang_bound = [
        p for p in pods
        if p.spec.node_name and p.metadata.name.startswith("g")
    ]
    plain_bound = [
        p for p in pods
        if p.spec.node_name and p.metadata.name.startswith("plain")
    ]
    assert gang_bound == []
    assert len(plain_bound) == 4


def test_split_arrival_gang_assembles_via_permit():
    """A gang split across two batches still assembles: the first half
    waits at Permit (members known), the second half completes it."""
    server, client, informers, sched = _cluster()
    for i in range(4):
        client.create_node(
            make_node(f"n{i}").capacity(cpu="2", memory="8Gi").obj()
        )
    _pg(client, "g6", 6)
    informers.start()
    informers.wait_for_cache_sync()
    sched.queue.run()
    # all 6 members exist up front (known to the informer), but the
    # queue is drained in two waves
    pods = [_gang_pod(f"g{i}", "g6") for i in range(6)]
    for p in pods[:4]:
        client.create_pod(p)
    sched.start()
    time.sleep(1.0)
    for p in pods[4:]:
        client.create_pod(p)
    deadline = time.time() + 30
    while time.time() < deadline:
        got, _ = client.list_pods()
        if sum(1 for p in got if p.spec.node_name) == 6:
            break
        time.sleep(0.05)
    sched.wait_for_inflight_binds()
    sched.stop()
    informers.stop()
    got, _ = client.list_pods()
    assert sum(1 for p in got if p.spec.node_name) == 6
