"""Checkpoint/resume philosophy (SURVEY section 5): all scheduler state
is SOFT -- a replacement instance rebuilds cache/queue/device tensors
from the API via list+watch and carries on, mid-workload.

Reference: scheduler HA semantics (server.go:241: a new leader re-lists
and resumes; nothing is persisted by the scheduler itself).
"""

import time

from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client.client import Client
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.scheduler.scheduler import new_scheduler
from kubernetes_tpu.testing import make_node, make_pod


def _wait_bound(client, count, timeout=30.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        pods, _ = client.list_pods()
        bound = sum(1 for p in pods if p.spec.node_name)
        if bound >= count:
            return bound
        time.sleep(0.05)
    return sum(1 for p in client.list_pods()[0] if p.spec.node_name)


def test_replacement_scheduler_resumes_mid_burst():
    server = APIServer()
    client = Client(server)
    for i in range(6):
        client.create_node(
            make_node(f"n{i}").capacity(cpu="8", memory="16Gi", pods=30).obj()
        )

    # first instance schedules half the burst, then dies
    informers1 = InformerFactory(server)
    sched1 = new_scheduler(client, informers1, batch=True, max_batch=16)
    informers1.start()
    informers1.wait_for_cache_sync()
    sched1.queue.run()
    for i in range(24):
        client.create_pod(
            make_pod(f"p{i}").container(cpu="250m", memory="256Mi").obj()
        )
    sched1.start()
    assert _wait_bound(client, 8) >= 8
    sched1.stop()
    informers1.stop()

    # more pods land while nobody is scheduling
    for i in range(24, 36):
        client.create_pod(
            make_pod(f"p{i}").container(cpu="250m", memory="256Mi").obj()
        )

    # a FRESH instance (new informers, cache, queue, tensor cache)
    # rebuilds everything from the API and finishes the workload
    informers2 = InformerFactory(server)
    sched2 = new_scheduler(client, informers2, batch=True, max_batch=16)
    informers2.start()
    informers2.wait_for_cache_sync()
    sched2.queue.run()
    sched2.start()
    bound = _wait_bound(client, 36, timeout=60.0)
    sched2.wait_for_inflight_binds()
    sched2.stop()
    informers2.stop()
    assert bound == 36, f"only {bound}/36 bound after restart"

    # no double-booking across the handover: every pod exactly one node,
    # per-node capacity respected
    pods, _ = client.list_pods()
    per_node = {}
    for p in pods:
        assert p.spec.node_name, f"{p.metadata.name} unbound"
        per_node[p.spec.node_name] = per_node.get(p.spec.node_name, 0) + 1
    assert all(v <= 30 for v in per_node.values())
    # the replacement's cache agrees with the API view
    assert sched2.cache.pod_count() == 36
