from benchmarks.runner import main
import sys

sys.exit(main())
