"""Perf-matrix runner: drive every workload in the YAML config through
the full pipeline and emit DataItems JSON.

Mirrors the reference harness end to end:
- workload matrix     ~ test/integration/scheduler_perf/config/
                        performance-config.yaml
- throughput sampling ~ util.go:197 throughputCollector (1s windows)
- DataItems output    ~ util.go:109 (dataItems with labels + unit)
- init-pods warm fill ~ scheduler_perf_test.go:130 perfScheduling

Solver-path counters (pods on device, fallbacks, envelope fallbacks,
pipeline drains, carry reuse) ride in each item's labels so the
batch-path cliffs VERDICT r2 flagged are visible per workload.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# mesh workloads on a CPU box: KTPU_FORCE_HOST_DEVICES=8 splits the host
# platform into N virtual devices so the sharded path runs for real.
# Must land before jax initializes its backends (the kubernetes_tpu
# imports below pull jax in), and is a no-op on multi-chip hardware
# (jax.devices() returns the accelerators regardless).
_force_devs = os.environ.get("KTPU_FORCE_HOST_DEVICES")
if _force_devs and "xla_force_host_platform_device_count" not in os.environ.get(
    "XLA_FLAGS", ""
):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={int(_force_devs)}"
    ).strip()

from kubernetes_tpu.api.types import (
    POD_GROUP_LABEL,
    POD_RUNNING,
    ObjectMeta,
    PodGroup,
    Service,
)
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client.client import Client
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.ops.assignment import GreedyConfig
from kubernetes_tpu.scheduler.scheduler import new_scheduler
from kubernetes_tpu.testing import make_node, make_pod

ZONE_LABEL = "topology.kubernetes.io/zone"
HOSTNAME_LABEL = "kubernetes.io/hostname"


class BindCollector:
    """Event-driven throughput + latency collector over a Pod watch
    stream (the reference polls the informer once per second,
    util.go:228; a watch gives the same samples without polling)."""

    def __init__(self, server: APIServer, targets) -> None:
        self._watch = server.watch("Pod", since_rv=server.current_rv())
        self.bind_times: Dict[str, float] = {}
        self._cond = threading.Condition()
        self._stop = False
        self._targets = set(targets)
        self._outstanding = len(self._targets)
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop:
            evs = self._watch.next_batch(timeout=0.2)
            if not evs:
                continue
            now = time.perf_counter()
            with self._cond:
                for ev in evs:
                    if ev.type != "MODIFIED":
                        continue
                    pod = ev.object
                    if not pod.spec.node_name:
                        continue
                    name = pod.metadata.name
                    if name in self.bind_times:
                        continue
                    self.bind_times[name] = now
                    if name in self._targets:
                        self._outstanding -= 1
                if self._outstanding <= 0:
                    self._cond.notify_all()

    def wait(self, timeout: float) -> bool:
        deadline = time.time() + timeout
        with self._cond:
            while self._outstanding > 0:
                remaining = deadline - time.time()
                if remaining <= 0:
                    return False
                self._cond.wait(min(remaining, 0.5))
            return True

    def wait_fraction(self, fraction: float, timeout: float) -> bool:
        """Wait until ``fraction`` of the targets have bound AND the
        bind rate has gone quiet (no new binds for one settle window) --
        the completion criterion for capacity-starved workloads where
        full placement is impossible by design."""
        need = int(fraction * len(self._targets))
        deadline = time.time() + timeout
        last_count = -1
        quiet_since = time.time()
        while time.time() < deadline:
            with self._cond:
                count = len(self._targets) - self._outstanding
            if count != last_count:
                last_count = count
                quiet_since = time.time()
            elif count >= need and time.time() - quiet_since >= 2.0:
                return True
            time.sleep(0.05)
        return last_count >= need

    def stop(self) -> None:
        self._stop = True
        self._watch.stop()
        self._thread.join(timeout=2)


def _percentile(sorted_vals: List[float], p: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(len(sorted_vals) * p / 100.0))
    return sorted_vals[idx]


def _build_pod(name: str, spec: Dict[str, Any], idx: int):
    w = make_pod(name)
    w.container(
        cpu=str(spec.get("cpu", "100m")),
        memory=str(spec.get("memory", "128Mi")),
        host_port=int(spec.get("host_port", 0)),
        **{
            k.replace("/", "__").replace(".", "_"): v
            for k, v in (spec.get("scalars") or {}).items()
        },
    )
    if spec.get("labels"):
        w.labels(**spec["labels"])
    if spec.get("priority_mix"):
        # weighted priority rotation, e.g.
        #   priority_mix: [{priority: 0, weight: 9}, {priority: 100,
        #   weight: 1}]
        # -- the priority-inversion-storm shape: a low-priority flood
        # with a high-priority tail interleaved through it, so the high
        # band must cut the queue AND preempt to meet its SLO
        pattern: List[int] = []
        for m in spec["priority_mix"]:
            pattern.extend(
                [int(m["priority"])] * int(m.get("weight", 1))
            )
        w.priority(pattern[idx % len(pattern)])
    elif spec.get("priority") is not None:
        w.priority(int(spec["priority"]))
    sp = spec.get("spread")
    if sp:
        w.spread_constraint(
            max_skew=int(sp.get("max_skew", 1)),
            topology_key=sp.get("topology_key", ZONE_LABEL),
            when_unsatisfiable=sp.get("when_unsatisfiable", "DoNotSchedule"),
            match_labels=sp.get("match_labels") or {},
        )
    af = spec.get("affinity")
    if af:
        if af.get("preferred"):
            w.preferred_pod_affinity(
                topology_key=af.get("topology_key", ZONE_LABEL),
                match_labels=af.get("match_labels") or {},
                weight=int(af.get("weight", 1)),
                anti=bool(af.get("anti")),
            )
        else:
            w.pod_affinity(
                topology_key=af.get("topology_key", ZONE_LABEL),
                match_labels=af.get("match_labels") or {},
                anti=bool(af.get("anti")),
            )
    if spec.get("node_selector"):
        w.node_selector(**spec["node_selector"])
    naff = spec.get("node_affinity_in")
    if naff:
        # required node affinity; values may rotate per pod index so a
        # 5k-node matrix entry exercises per-pod static-mask variety
        values = naff.get("values") or []
        if naff.get("rotate") and values:
            values = [values[idx % len(values)]]
        w.node_affinity_in(naff["key"], list(values))
    for s in range(int(spec.get("secret_volumes", 0))):
        w.secret_volume(f"secret-{idx % 16}-{s}")
    numa = spec.get("numa_aligned")
    if numa:
        w.pod.metadata.annotations[
            "numa.kubernetes-tpu.io/aligned"
        ] = str(numa)
    pvs = spec.get("pvs")
    if pvs:
        # one pre-bound PVC per pod (reference SchedulingInTreePVs /
        # SchedulingCSIPVs shape, scheduler_perf performance-config
        # :44/:87); the PVC/PV pair is created by run_workload
        for k in range(int(pvs.get("per_pod", 1))):
            w.pvc(f"pvc-{w.pod.metadata.name}-{k}")
    return w.obj()


def _wait_fraction_bound(coll: BindCollector, frac: float, timeout: float) -> bool:
    """Block until ``frac`` of the collector's targets have bound (the
    lifecycle scenarios trigger mid-burst, not at t=0)."""
    need = int(frac * len(coll._targets))
    deadline = time.time() + timeout
    while time.time() < deadline:
        with coll._cond:
            if len(coll._targets) - coll._outstanding >= need:
                return True
        time.sleep(0.05)
    return False


def _wait_live_bound(client: Client, timeout: float) -> bool:
    """Every pod currently in the apiserver is bound -- the lifecycle
    settle condition (respawned incarnations included, which the
    name-keyed collector cannot see)."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        pods, _ = client.list_pods()
        if pods and all(p.spec.node_name for p in pods):
            return True
        time.sleep(0.1)
    return False


def _pdb_from_spec(spec: Dict[str, Any], name: str):
    """One PodDisruptionBudget from a workload's ``pdb:`` block
    ({match_labels, min_available, max_unavailable}) -- shared by the
    drain-wave, drain-via-preemption, and preemption-wave setups so the
    spec shape has one reader."""
    from kubernetes_tpu.api.types import LabelSelector, PodDisruptionBudget

    pdb = PodDisruptionBudget(
        selector=LabelSelector(
            match_labels=dict(spec.get("match_labels") or {})
        ),
        min_available=spec.get("min_available"),
        max_unavailable=spec.get("max_unavailable"),
    )
    pdb.metadata.name = name
    pdb.metadata.namespace = "default"
    return pdb


def _lifecycle_setup(
    lifecycle: Dict[str, Any],
    wl: Dict[str, Any],
    server: APIServer,
    client: Client,
    informers: InformerFactory,
    num_nodes: int,
    injector,
    sched=None,
):
    """Build the scenario actor for a ``lifecycle:`` workload. Returns
    (components-to-stop, scenario(coll, timeout_s) callable, counters,
    stop event that aborts an in-progress scenario)."""
    from kubernetes_tpu.controllers import DisruptionController, NodeDrainer
    from kubernetes_tpu.robustness.faults import (
        FaultInjector, FaultPoint, FaultProfile, PointConfig,
    )
    from kubernetes_tpu.robustness.lifecycle import (
        ClusterLifecycleDriver, PodRespawner,
    )

    mode = lifecycle.get("mode", "drain_wave")
    at_fraction = float(lifecycle.get("at_fraction", 0.3))
    stoppers = []
    counters: Dict[str, Any] = {"mode": mode}
    # teardown signal: the scenario thread (and any in-progress drain)
    # must be interruptible, or an exception path leaves a daemon
    # draining nodes under the settle checks for minutes
    stop_evt = threading.Event()

    if mode == "drain_via_preemption":
        # ISSUE-11 acceptance shape: cordoned nodes empty by DEVICE-
        # CHOSEN per-pod evictees (the preemptor's victim-search kernel
        # run as a plan) instead of whole-node eviction. The row's
        # counters carry the whole-node BASELINE (every resident at
        # drain start) next to what was actually evicted, so the
        # strictly-fewer claim is a label, not a vibe.
        disruption = DisruptionController(client, informers)
        disruption.start()
        stoppers.append(disruption)
        pdb_spec = lifecycle.get("pdb")
        if pdb_spec:
            client.create_pdb(
                _pdb_from_spec(pdb_spec, "drain-preempt-budget")
            )
        if sched is not None and getattr(sched, "preemptor", None):
            sched.preemptor.disruption = disruption
        respawner = PodRespawner(client)
        respawner.start()
        stoppers.append(respawner)
        drainer = NodeDrainer(
            client, disruption=disruption,
            should_abort=stop_evt.is_set,
            preemptor=getattr(sched, "preemptor", None),
        )
        counters["drainer"] = drainer
        counters["respawner"] = respawner
        counters["baseline_pods"] = 0

        def scenario(coll, timeout_s):
            _wait_fraction_bound(coll, at_fraction, timeout_s)
            waves = int(lifecycle.get("waves", 3))
            per = int(lifecycle.get("nodes_per_wave", 2))
            wave_timeout = float(lifecycle.get("wave_timeout_s", 60))
            idx = 0
            for _w in range(waves):
                if stop_evt.is_set():
                    return
                victims = [
                    f"node-{(idx + j) % num_nodes}" for j in range(per)
                ]
                idx += per
                for v in victims:
                    if stop_evt.is_set():
                        return
                    pods, _rv = client.list_pods()
                    counters["baseline_pods"] += sum(
                        1 for p in pods if p.spec.node_name == v
                    )
                    drainer.drain_via_preemption(v, timeout=wave_timeout)
                if lifecycle.get("uncordon", True):
                    for v in victims:
                        drainer.uncordon(v)

        return stoppers, scenario, counters, stop_evt

    if mode == "drain_wave":
        disruption = DisruptionController(client, informers)
        disruption.start()
        stoppers.append(disruption)
        pdb_spec = lifecycle.get("pdb")
        if pdb_spec:
            client.create_pdb(_pdb_from_spec(pdb_spec, "wave-budget"))
        respawner = PodRespawner(client)
        respawner.start()
        stoppers.append(respawner)
        drainer = NodeDrainer(
            client, disruption=disruption, should_abort=stop_evt.is_set
        )

        counters["drainer"] = drainer
        counters["respawner"] = respawner

        def scenario(coll, timeout_s):
            _wait_fraction_bound(coll, at_fraction, timeout_s)
            waves = int(lifecycle.get("waves", 3))
            per = int(lifecycle.get("nodes_per_wave", 2))
            wave_timeout = float(lifecycle.get("wave_timeout_s", 60))
            idx = 0
            for _w in range(waves):
                if stop_evt.is_set():
                    return
                victims = [
                    f"node-{(idx + j) % num_nodes}" for j in range(per)
                ]
                idx += per
                for v in victims:
                    if stop_evt.is_set():
                        return
                    drainer.drain(v, timeout=wave_timeout)
                # the wave is "upgraded": back into service before the
                # next wave cordons -- rolling, never net capacity loss
                if lifecycle.get("uncordon", True):
                    for v in victims:
                        drainer.uncordon(v)

        return stoppers, scenario, counters, stop_evt

    if mode in ("reclaim_storm", "chaos"):
        if mode == "reclaim_storm":
            # a private injector (never installed): deterministic storm
            # count, no solver faults
            injector = FaultInjector(FaultProfile(
                name="bench-reclaim", seed=int(wl.get("fault_seed", 0)),
                points={FaultPoint.RECLAIM_STORM: PointConfig(
                    rate=1.0,
                    max_fires=int(lifecycle.get("storms", 1)),
                )},
            ))
        assert injector is not None, "chaos mode needs fault_profile"
        driver = ClusterLifecycleDriver(
            client,
            injector=injector,
            tick_interval=float(lifecycle.get("tick_interval", 0.2)),
            flap_down_seconds=float(lifecycle.get("flap_down_seconds", 0.5)),
            storm_fraction=float(lifecycle.get("storm_fraction", 0.1)),
            storm_down_seconds=float(
                lifecycle.get("storm_down_seconds", 1.0)
            ),
        )
        stoppers.append(driver)

        counters["driver"] = driver  # resolved to numbers at teardown

        def scenario(coll, timeout_s):
            _wait_fraction_bound(coll, at_fraction, timeout_s)
            driver.start()
            # hold the scenario open until the chaos actually landed
            # (teardown stops the driver; a fast burst would otherwise
            # outrun the first tick) and the reclaimed capacity is back
            min_events = int(lifecycle.get("min_events", 1))
            deadline = time.time() + float(lifecycle.get("duration_s", 30))
            while time.time() < deadline and not stop_evt.is_set():
                if (
                    driver.flaps + driver.storms >= min_events
                    and driver.down_count() == 0
                ):
                    break
                time.sleep(0.1)

        return stoppers, scenario, counters, stop_evt

    if mode == "scale_up":
        node_spec = wl.get("node") or {}

        def scenario(coll, timeout_s):
            # the trigger: the burst saturates the starved cluster
            _wait_fraction_bound(coll, at_fraction, timeout_s)
            add = int(lifecycle.get("add_nodes", num_nodes // 10))
            for i in range(add):
                nw = make_node(f"cold-{i}").capacity(
                    cpu=str(node_spec.get("cpu", "32")),
                    memory=str(node_spec.get("memory", "64Gi")),
                    pods=int(node_spec.get("pods", 110)),
                )
                nw.label(ZONE_LABEL, f"zone-{i % 10}")
                nw.label(HOSTNAME_LABEL, f"cold-{i}")
                client.create_node(nw.obj())
            counters["nodes_added"] = add

        return stoppers, scenario, counters, stop_evt

    raise ValueError(f"unknown lifecycle mode {mode!r}")


def run_partition_workload(
    wl: Dict[str, Any], defaults: Dict[str, Any]
) -> Dict[str, Any]:
    """A perf-matrix workload through N ACTIVE partitioned stacks
    (scheduler/partition.py) instead of one scheduler -- the matrix
    shape of ``bench.py --partitions``, here so partition modes get
    standing rows (ROADMAP item-4d: zone-aligned partitioning was
    wired but had no perf-matrix number). Workload key::

        partitions: {count: 2, zone_aligned: true}

    With ``zone_aligned`` the node space splits by the zone label
    (crc32 over the zone instead of the node name), so a whole zone
    homes on -- and fails over with -- one partition; the workload's
    ``zones`` count therefore bounds the useful partition count. The
    result rows carry the conflict ledger (absorbed == requeues +
    stale, the PR-8 tier-1 invariant) and the spill count next to the
    throughput so an imbalanced or conflict-heavy run is visible in
    the matrix, not just slow."""
    from kubernetes_tpu.config.types import (
        KubeSchedulerConfiguration,
        PartitionConfiguration,
    )
    from kubernetes_tpu.scheduler.app import SchedulerApp

    name = wl["name"]
    num_nodes = int(wl["nodes"])
    zones = int(wl.get("zones", defaults.get("zones", 10)))
    max_batch = int(wl.get("max_batch", defaults.get("max_batch", 1024)))
    timeout_s = float(wl.get("timeout_s", defaults.get("timeout_s", 420)))
    node_spec = wl.get("node") or {}
    pt = wl["partitions"]
    n_parts = int(pt.get("count", 2))
    zone_aligned = bool(pt.get("zone_aligned", False))

    server = APIServer()

    def cfg():
        c = KubeSchedulerConfiguration(
            partition=PartitionConfiguration(
                enabled=True,
                num_partitions=n_parts,
                zone_aligned=zone_aligned,
                # generous leases: the measured burst saturates the box,
                # and a starved renew thread mid-burst would turn the
                # row into a takeover storm (bench.py --partitions
                # rationale); takeover latency has its own chaos harness
                lease_duration_seconds=10.0,
                retry_period_seconds=1.0,
            )
        )
        c.tpu_solver.max_batch = max_batch
        return c

    apps = []
    coll = None
    try:
        apps = [
            SchedulerApp(config=cfg(), server=server)
            for _ in range(n_parts)
        ]
        client = apps[0].client
        for i in range(num_nodes):
            nw = make_node(f"node-{i}").capacity(
                cpu=str(node_spec.get("cpu", defaults.get("node_cpu", "32"))),
                memory=str(
                    node_spec.get("memory", defaults.get("node_memory", "64Gi"))
                ),
                pods=int(node_spec.get("pods", defaults.get("node_pods", 110))),
            )
            nw.label(ZONE_LABEL, f"zone-{i % zones}")
            nw.label(HOSTNAME_LABEL, f"node-{i}")
            client.create_node(nw.obj())
        for app in apps:
            app.sched.max_batch = max_batch
        for app in apps:
            app.start()
        # settle: every partition claimed by exactly one stack. A claim
        # that never lands would otherwise surface 900s later as an
        # opaque bind timeout (pods homed to the unclaimed partition
        # sit forever), so an unsettled map is an explicit error row.
        deadline = time.time() + 15
        held: List[int] = []
        while time.time() < deadline:
            held = sorted(
                k for app in apps for k in app.coordinator.held_partitions()
            )
            if held == list(range(n_parts)):
                break
            time.sleep(0.05)
        if held != list(range(n_parts)):
            return {
                "name": name,
                "error": (
                    f"partition map never settled: held {held} of "
                    f"{n_parts} partitions after 15s"
                ),
            }
        # warmup AFTER start+settle: app.start() is what syncs the
        # informers, and each stack's cache scopes to its held
        # partitions -- warming earlier sees zero nodes and compiles
        # nothing (the measured burst would then pay the JIT). jit
        # caches are process-global and the stacks' ~N/P node tensors
        # bucket-pad to the same capacity, so one warmup covers every
        # stack
        apps[0].sched.warmup()

        init_n = int(wl.get("init_pods", 0))
        init_spec = wl.get("init_pod") or wl.get("pod") or {}
        if init_n:
            init_names = [f"init-{i}" for i in range(init_n)]
            icoll = BindCollector(server, init_names)
            for i, nm in enumerate(init_names):
                client.create_pod(_build_pod(nm, init_spec, i))
            if not icoll.wait(timeout_s):
                icoll.stop()
                return {"name": name, "error": "init pods did not all schedule"}
            icoll.stop()

        measure_pods = int(wl["measure_pods"])
        pod_spec = wl.get("pod") or {}
        pods = [
            _build_pod(f"measure-{i}", pod_spec, i)
            for i in range(measure_pods)
        ]
        target_names = [p.metadata.name for p in pods]
        coll = BindCollector(server, target_names)
        create_times: Dict[str, float] = {}
        start = time.perf_counter()
        for p in pods:
            create_times[p.metadata.name] = time.perf_counter()
            client.create_pod(p)
        ok = coll.wait(timeout_s)
        elapsed = time.perf_counter() - start
        for app in apps:
            app.sched.wait_for_inflight_binds(timeout=60)

        bound = sum(1 for n in target_names if n in coll.bind_times)
        result: Dict[str, Any] = {
            "name": name,
            "ok": bool(ok and bound >= measure_pods),
            "bound": bound,
            "total": measure_pods,
            "elapsed_s": round(elapsed, 3),
            "throughput_pods_per_s": (
                round(bound / elapsed, 1) if elapsed else 0.0
            ),
        }
        lat = sorted(
            coll.bind_times[n] - create_times[n]
            for n in target_names
            if n in coll.bind_times and n in create_times
        )
        if lat:
            result["latency_ms"] = {
                "Perc50": round(_percentile(lat, 50) * 1000, 1),
                "Perc90": round(_percentile(lat, 90) * 1000, 1),
                "Perc99": round(_percentile(lat, 99) * 1000, 1),
            }
        absorbed = sum(a.sched.bind_conflicts_absorbed for a in apps)
        requeues = sum(a.sched.conflict_requeues for a in apps)
        stale = sum(a.sched.conflict_stale_binds for a in apps)
        result["partition"] = {
            "count": n_parts,
            "zone_aligned": zone_aligned,
            "bind_conflicts_absorbed": absorbed,
            "conflict_requeues": requeues,
            "conflict_stale_binds": stale,
            "ledger_balanced": absorbed == requeues + stale,
            "pods_spilled": sum(a.sched.pods_spilled for a in apps),
            "takeovers": sum(a.coordinator.takeovers for a in apps),
            "pods_fallback": sum(a.sched.pods_fallback for a in apps),
        }
        return result
    finally:
        if coll is not None:
            coll.stop()
        for app in apps:
            try:
                app.stop()
            except Exception:  # noqa: BLE001 - teardown keeps going
                pass


def run_workload(wl: Dict[str, Any], defaults: Dict[str, Any]) -> Dict[str, Any]:
    if wl.get("partitions"):
        return run_partition_workload(wl, defaults)
    name = wl["name"]
    num_nodes = int(wl["nodes"])
    zones = int(wl.get("zones", defaults.get("zones", 10)))
    max_batch = int(wl.get("max_batch", defaults.get("max_batch", 1024)))
    timeout_s = float(wl.get("timeout_s", defaults.get("timeout_s", 420)))
    node_spec = wl.get("node") or {}

    server = APIServer()
    client = Client(server)
    informers = InformerFactory(server)
    solver_cfg = GreedyConfig(**wl["solver"]) if wl.get("solver") else None
    # workload-scoped node-axis mesh (the sharded delta path): the
    # requested device count is CLAMPED to what this process actually
    # has, so the matrix stays runnable on a 1-chip box (mesh of 1) and
    # uses the full mesh on multi-chip hardware. CPU boxes can force
    # virtual devices with KTPU_FORCE_HOST_DEVICES=N (read before jax
    # initializes, see main()).
    mesh = None
    mesh_devices = int(wl.get("mesh_devices", 0))
    if mesh_devices > 0:
        import jax
        from jax.sharding import Mesh

        import numpy as _np

        devs = jax.devices()
        mesh_devices = min(mesh_devices, len(devs))
        mesh = Mesh(_np.array(devs[:mesh_devices]), axis_names=("nodes",))
    # `fleet:` closes the bind loop (ISSUE 17): a sharded
    # HollowNodeFleet acks every bind into Running, the scheduler's
    # BindAckTracker treats a bind as pending until that ack lands (and
    # rebinds on timeout), and the row's success gate becomes
    # pods RUNNING, not pods bound
    fleet_cfg = wl.get("fleet")
    bind_ack_config = None
    if fleet_cfg is not None and fleet_cfg.get("bind_ack") is not False:
        from kubernetes_tpu.config.types import BindAckConfiguration

        ba = dict(fleet_cfg.get("bind_ack") or {})
        bind_ack_config = BindAckConfiguration(enabled=True, **ba)
    sched = new_scheduler(
        client,
        informers,
        batch=True,
        max_batch=max_batch,
        solver_config=solver_cfg,
        solver_mode=wl.get("solver_mode", "greedy"),
        mesh=mesh,
        bind_ack_config=bind_ack_config,
    )

    # workload-scoped open-loop streaming (kubernetes_tpu/streaming/):
    # the measured pods arrive as a seeded trace through the
    # ArrivalEngine instead of one t=0 bulk create, the SLO-adaptive
    # controller replaces the static batch window, and the backpressure
    # bound gates the engine. Attached BEFORE warmup so the controller's
    # latency solve pad is compiled off the clock.
    streaming = None
    controller = None
    if wl.get("streaming"):
        from kubernetes_tpu.config.loader import streaming_from_dict
        from kubernetes_tpu.streaming.autobatch import AutoBatchController

        # same camelCase schema as the top-level config's streaming:
        # block; in a workload block the controller defaults ON
        streaming = streaming_from_dict(
            {"enabled": True, **wl["streaming"]}
        )
        if streaming.enabled:
            controller = AutoBatchController(
                slo_p99_seconds=streaming.slo_p99_seconds,
                min_window=streaming.min_window_seconds,
                max_window=streaming.max_window_seconds,
                latency_batch=streaming.latency_batch,
                max_batch=max_batch,
                interval_seconds=streaming.controller_interval_seconds,
                auto_rungs=getattr(streaming, "auto_rungs", False),
            )
            sched.attach_autobatch(controller)
        if streaming.band_priority_threshold is not None:
            sched.queue.band_threshold = streaming.band_priority_threshold

    # workload-scoped multi-tenant fairness plane (ISSUE 15): pods
    # spread over `namespaces:` tenants, optional per-namespace
    # ResourceQuota hard caps, the QuotaController admission gate, and
    # the DRF dominant-share solve-order bias. Counters land in the
    # row's tenant_* labels (Jain bind-fairness index, dominant-share
    # spread, quota denials/refunds/parked).
    n_namespaces = int(wl.get("namespaces", 1))
    tenancy_cfg = wl.get("tenancy")
    quota_ctrl = None
    tenancy_stoppers: List[Any] = []
    if tenancy_cfg is not None or n_namespaces > 1:
        from kubernetes_tpu.scheduler.tenancy import arm_tenancy

        tenancy_cfg = tenancy_cfg or {}
        quota_ctrl = arm_tenancy(sched, client, informers)
        tenancy_stoppers.append(quota_ctrl)
    quota_spec = wl.get("quota")
    if quota_spec:
        from kubernetes_tpu.api.resource import parse_cpu, parse_memory
        from kubernetes_tpu.api.types import ResourceQuota
        from kubernetes_tpu.api.types import ObjectMeta as _QOM

        hard: Dict[str, int] = {}
        for rname, qty in quota_spec.items():
            if rname == "cpu":
                hard["cpu"] = parse_cpu(qty)
            elif rname == "memory":
                hard["memory"] = parse_memory(qty)
            else:
                hard[rname] = int(qty)
        for t in range(max(1, n_namespaces)):
            server.create(ResourceQuota(
                metadata=_QOM(name="quota", namespace=f"tenant-{t}"),
                hard=dict(hard),
            ))

    # workload-scoped preemption wave wiring (ISSUE 11): the shared
    # DisruptionController PDB gate on the scheduler's Preemptor (every
    # wave eviction spends can_disrupt -- zero overspend by
    # construction), an optional PDB over the fill, and a respawner so
    # evicted victims re-enter as pending arrivals (the cascade shape).
    # Counters land in the row's preemption_* labels.
    preempt_cfg = wl.get("preemption")
    preempt_stoppers: List[Any] = []
    preempt_metrics0: Dict[str, float] = {}
    if preempt_cfg:
        from kubernetes_tpu.controllers import DisruptionController
        from kubernetes_tpu.robustness.lifecycle import PodRespawner
        from kubernetes_tpu.utils import metrics as _metrics

        disruption = DisruptionController(client, informers)
        disruption.start()
        sched.preemptor.disruption = disruption
        preempt_stoppers.append(disruption)
        pdb_spec = preempt_cfg.get("pdb")
        if pdb_spec:
            client.create_pdb(
                _pdb_from_spec(pdb_spec, "preemption-budget")
            )
        rsp_prefix = preempt_cfg.get("respawn_prefix")
        if rsp_prefix:
            respawner = PodRespawner(
                client,
                should_respawn=(
                    lambda p: p.metadata.name.startswith(rsp_prefix)
                ),
            )
            respawner.start()
            preempt_stoppers.append(respawner)
        preempt_metrics0 = {
            "blocked": _metrics.evictions_blocked_by_pdb.value(),
            "nominations_set": _metrics.nominations_set.value(),
            "nominations_cleared": _metrics.nominations_cleared.value(),
        }

    for i in range(num_nodes):
        nw = make_node(f"node-{i}").capacity(
            cpu=str(node_spec.get("cpu", defaults.get("node_cpu", "32"))),
            memory=str(node_spec.get("memory", defaults.get("node_memory", "64Gi"))),
            pods=int(node_spec.get("pods", defaults.get("node_pods", 110))),
            **{
                k.replace("/", "__").replace(".", "_"): v
                for k, v in (node_spec.get("scalars") or {}).items()
            },
        )
        nw.label(ZONE_LABEL, f"zone-{i % zones}")
        nw.label(HOSTNAME_LABEL, f"node-{i}")
        if node_spec.get("numa_groups"):
            nw.label(
                "numa.kubernetes-tpu.io/gpu-groups",
                str(node_spec["numa_groups"]),
            )
        client.create_node(nw.obj())

    # per-node CSINode objects (nodevolumelimits/csi.go attach limits):
    # the volume-count device columns read allocatable from these, so a
    # CSI workload exercises the limit columns end to end. Absent
    # CSINodes mean "no limit known" (the reference allows).
    csn = wl.get("csi_node") or node_spec.get("csi_node")
    if csn:
        from kubernetes_tpu.api.types import CSINode, CSINodeDriver
        from kubernetes_tpu.api.types import ObjectMeta as _OM

        for i in range(num_nodes):
            server.create(
                CSINode(
                    metadata=_OM(name=f"node-{i}", namespace=""),
                    drivers=[
                        CSINodeDriver(
                            name=csn.get("driver", "ebs.csi.aws.com"),
                            node_id=f"node-{i}",
                            allocatable_count=int(
                                csn.get("allocatable", 8)
                            ),
                        )
                    ],
                )
            )

    for svc in wl.get("services") or []:
        server.create(
            Service(
                metadata=ObjectMeta(name=svc["name"], namespace="default"),
                selector=dict(svc.get("selector") or {}),
            )
        )

    # SchedulingSecrets (reference performance-config.yaml): pods mount
    # secret volumes; the pool matches _build_pod's secret-{idx%16}-{s}
    # naming so every reference resolves to a stored Secret
    # pre-bound PVC/PV pairs for PV workloads: every pod with a "pvs"
    # spec references pvc-{podname}-{k}, bound 1:1 to a PV. "csi" PVs
    # carry a csi driver source (attach limits resolve them -> exact
    # host path); "simple" PVs have no source/zone/affinity (provably
    # node-independent -> the solver takes them)
    def _make_pv_pairs(names: List[str], pvs_spec: Dict[str, Any]) -> None:
        from kubernetes_tpu.api.types import (
            PersistentVolume, PersistentVolumeClaim,
        )

        per_pod = int(pvs_spec.get("per_pod", 1))
        kind = pvs_spec.get("type", "simple")
        for nm in names:
            for k in range(per_pod):
                cn = f"pvc-{nm}-{k}"
                vn = f"pv-{nm}-{k}"
                server.create(
                    PersistentVolumeClaim(
                        metadata=ObjectMeta(
                            name=cn, namespace="default"
                        ),
                        volume_name=vn,
                        requested_bytes=1 << 30,
                    )
                )
                pv = PersistentVolume(
                    # cluster-scoped: the PV lister looks up namespace ""
                    metadata=ObjectMeta(name=vn, namespace=""),
                    capacity_bytes=1 << 30,
                    claim_ref_namespace="default",
                    claim_ref_name=cn,
                )
                if kind == "csi":
                    pv.csi_driver = "ebs.csi.aws.com"
                    pv.csi_volume_handle = vn
                server.create(pv)

    n_sec = int((wl.get("pod") or {}).get("secret_volumes", 0) or 0)
    if n_sec:
        from kubernetes_tpu.api.types import Secret

        for i in range(16):
            for s in range(n_sec):
                server.create(
                    Secret(
                        metadata=ObjectMeta(
                            name=f"secret-{i}-{s}", namespace="default"
                        ),
                        data={"token": f"t-{i}-{s}"},
                    )
                )

    gang = wl.get("gang")
    measure_pods = int(wl["measure_pods"])
    if gang:
        group_size = int(gang.get("group_size", 10))
        for g in range(-(-measure_pods // group_size)):
            server.create(
                PodGroup(
                    metadata=ObjectMeta(name=f"group-{g}", namespace="default"),
                    min_member=int(gang.get("min_member", group_size)),
                )
            )

    # workload-scoped fault profile (the chaos-profile variants): the
    # injector is installed for the whole run and ALWAYS uninstalled on
    # exit so the next matrix entry starts clean
    injector = None
    if wl.get("fault_profile"):
        from kubernetes_tpu.robustness.faults import (
            FaultInjector, install_injector, load_profile,
        )

        injector = FaultInjector(load_profile(
            wl["fault_profile"], seed=int(wl.get("fault_seed", 0))
        ))
        install_injector(injector)

    lifecycle = wl.get("lifecycle")
    lifecycle_stoppers: List[Any] = []
    lifecycle_scenario = None
    lifecycle_counters: Dict[str, Any] = {}
    lifecycle_stop = None
    if lifecycle:
        (
            lifecycle_stoppers, lifecycle_scenario,
            lifecycle_counters, lifecycle_stop,
        ) = _lifecycle_setup(
            lifecycle, wl, server, client, informers, num_nodes,
            injector, sched=sched,
        )

    hollow = None
    if wl.get("hollow"):
        # hollow-node pool (kubemark pattern, hollow_kubelet.go:64):
        # bound pods get acked Running and nodes heartbeat, so churn
        # workloads exercise the full control loop
        from kubernetes_tpu.kubelet import HollowNodePool

        hollow = HollowNodePool(
            client, [f"node-{i}" for i in range(num_nodes)]
        )
        hollow.start()

    fleet = None
    fleet_lifecycle = None
    fleet_disruption = None
    fleet_respawner = None
    zombie_nodes: List[str] = []
    if fleet_cfg is not None:
        from kubernetes_tpu.kubelet import FleetConfig, HollowNodeFleet

        _fc_keys = (
            "shard_size", "ack_latency_seconds", "ack_latency_jitter",
            "heartbeat_interval_seconds", "lease_duration_seconds",
            "allocatable_drift", "seed",
        )
        fleet = HollowNodeFleet(
            client,
            [f"node-{i}" for i in range(num_nodes)],
            FleetConfig(**{
                k: fleet_cfg[k] for k in _fc_keys if k in fleet_cfg
            }),
        )
        n_zombie = int(fleet_cfg.get("zombies", 0))
        if n_zombie:
            # zombie kubelets: lease renews forever, acks never come --
            # only the bind-ack timeout can route around them
            zombie_nodes = [f"node-{i}" for i in range(n_zombie)]
            fleet.mark_zombie(zombie_nodes)
        fleet.start()
        lc = fleet_cfg.get("lifecycle")
        if lc:
            from kubernetes_tpu.controllers import DisruptionController
            from kubernetes_tpu.controllers.nodelifecycle import (
                NodeLifecycleController,
            )

            fleet_disruption = DisruptionController(client, informers)
            fleet_disruption.start()
            fleet_lifecycle = NodeLifecycleController(
                client, informers,
                grace_period=float(lc.get("grace_period", 40.0)),
                monitor_interval=float(lc.get("monitor_interval", 5.0)),
                disruption=fleet_disruption,
            )
            fleet_lifecycle.start()
        if fleet_cfg.get("respawn_evicted"):
            # heartbeat-lapse evictions DELETE pods; the respawner
            # feeds each one back as a fresh pending arrival so the
            # closed loop must land it somewhere alive
            from kubernetes_tpu.robustness.lifecycle import PodRespawner

            fleet_respawner = PodRespawner(
                client,
                should_respawn=(
                    lambda p: p.metadata.name.startswith("measure-")
                ),
            )
            fleet_respawner.start()

    coll = None
    engine = None
    try:
        informers.start()
        informers.wait_for_cache_sync()
        sched.queue.run()
        if quota_ctrl is not None:
            quota_ctrl.sync_all()
            quota_ctrl.start()
        sched.warmup()

        # -- init fill (off the clock) ------------------------------------------
        init_spec = wl.get("init_pod") or wl.get("pod") or {}
        init_n = int(wl.get("init_pods", 0))
        if init_n and init_spec.get("pvs"):
            _make_pv_pairs(
                [f"init-{i}" for i in range(init_n)], init_spec["pvs"]
            )
        if (wl.get("pod") or {}).get("pvs"):
            _make_pv_pairs(
                [f"measure-{i}" for i in range(int(wl["measure_pods"]))],
                (wl.get("pod") or {})["pvs"],
            )
        if init_n:
            init_names = [f"init-{i}" for i in range(init_n)]
            coll = BindCollector(server, init_names)
            for i, nm in enumerate(init_names):
                client.create_pod(_build_pod(nm, init_spec, i))
            t = sched.start()
            if not coll.wait(timeout_s):
                return {"name": name, "error": "init pods did not all schedule"}
            coll.stop()
        else:
            t = sched.start()

        # warm the preemption path off the clock (kernel compile +
        # victim-pack build): a few high-priority pods preempt before
        # the measured burst -- steady-state clusters preempt routinely,
        # and the reference harness likewise schedules warm-up pods
        # before ResetTimer (scheduler_perf_test.go:130)
        n_warm_preempt = int(wl.get("init_preempt", 0))
        if n_warm_preempt:
            warm_spec = dict(wl.get("pod") or {})
            warm_names = [f"warmpre-{i}" for i in range(n_warm_preempt)]
            wcoll = BindCollector(server, warm_names)
            for i, nm in enumerate(warm_names):
                client.create_pod(_build_pod(nm, warm_spec, i))
            wcoll.wait(timeout_s)
            wcoll.stop()
            sched.wait_for_inflight_binds(timeout=60)

        # freeze the init-fill object graph out of cyclic-GC scans
        # (utils/gc_tuning.py rationale)
        from kubernetes_tpu.utils.gc_tuning import freeze_steady_state_graph

        freeze_steady_state_graph()

        # -- measured burst -------------------------------------------------------
        pod_spec = wl.get("pod") or {}
        selector_mix = int(wl.get("selector_mix", 0))
        pods = []
        for i in range(measure_pods):
            spec_i = pod_spec
            if wl.get("daemonset"):
                # DaemonSet-style fan-out: pod i pins to node i -- every
                # pod carries a DISTINCT nodeSelector, so the static
                # mask is per-pod, not per-batch
                spec_i = dict(pod_spec)
                spec_i["node_selector"] = {
                    HOSTNAME_LABEL: f"node-{i % num_nodes}"
                }
            elif selector_mix:
                # mask-diversity mix: pods rotate through selector_mix
                # distinct zone nodeSelectors, so every batch carries
                # ~selector_mix deduplicated [U, N] static-mask rows --
                # at the 100k-node mesh tier that is exactly the
                # payload the sharded (column-split, bool) mask upload
                # exists to cut (PR 10)
                spec_i = dict(pod_spec)
                spec_i["node_selector"] = {
                    ZONE_LABEL: f"zone-{i % selector_mix}"
                }
            p = _build_pod(f"measure-{i}", spec_i, i)
            if gang:
                p.metadata.labels[POD_GROUP_LABEL] = (
                    f"group-{i // int(gang.get('group_size', 10))}"
                )
            if quota_ctrl is not None:
                # tenant identity IS the namespace: round-robin so every
                # batch spans tenants (the fairness plane's arbitration
                # surface)
                p.metadata.namespace = (
                    f"tenant-{i % max(1, n_namespaces)}"
                )
            pods.append(p)

        # quota-churn scenario: raise every tenant's hard caps mid-run
        # (`quota_scenario: {mode: raise, at_fraction: F, factor: K}`)
        # -- the parked remainder must wake on the quota events and
        # bind, pinning the event-driven release path end to end
        quota_scenario = wl.get("quota_scenario")
        if quota_scenario and quota_ctrl is not None:

            def _run_quota_scenario(coll_ref=None):
                frac = float(quota_scenario.get("at_fraction", 0.5))
                factor = int(quota_scenario.get("factor", 2))
                _wait_fraction_bound(coll_ref, frac, timeout_s)
                for t in range(max(1, n_namespaces)):
                    def grow(obj, _f=factor):
                        obj.hard = {
                            name: qty * _f
                            for name, qty in obj.hard.items()
                        }
                    try:
                        client.update_resource_quota_status(
                            f"tenant-{t}", "quota", grow
                        )
                    except KeyError:
                        pass

        # -- poison seeding (blast-radius containment, ISSUE 14) -----------
        # `poison: {count: N, seed: S}` stamps N measured pods at seeded
        # random offsets; they must end QUARANTINED (parked, typed
        # condition), never bound, while every healthy pod still binds
        # -- so they are excluded from the bind targets and the workload
        # additionally fails unless all of them parked.
        poison_cfg = wl.get("poison")
        poison_names: set = set()
        if poison_cfg:
            import random as _random

            from kubernetes_tpu.robustness.faults import (
                FaultInjector,
                FaultProfile,
                POISON_ANNOTATION,
                install_injector,
            )

            prng = _random.Random(int(poison_cfg.get("seed", 0)))
            count = min(int(poison_cfg.get("count", 1)), len(pods))
            for i in sorted(prng.sample(range(len(pods)), count)):
                pods[i].metadata.annotations[POISON_ANNOTATION] = "true"
                poison_names.add(pods[i].metadata.name)
            if injector is None:
                # poison manifests only with an injector installed
                injector = FaultInjector(FaultProfile(
                    "poison-workload", seed=0, points={}
                ))
                install_injector(injector)

        churn = wl.get("churn")
        target_names = [
            p.metadata.name for p in pods
            if p.metadata.name not in poison_names
        ]
        coll = BindCollector(server, target_names)
        create_times: Dict[str, float] = {}

        from kubernetes_tpu.utils import timeline as _timeline

        _timeline.reset()
        start = time.perf_counter()
        _timeline.mark("burst_start")
        scenario_thread = None
        if lifecycle_scenario is not None:
            scenario_thread = threading.Thread(
                target=lifecycle_scenario,
                args=(coll, timeout_s),
                name="lifecycle-scenario",
                daemon=True,
            )
            scenario_thread.start()
        quota_thread = None
        if quota_scenario and quota_ctrl is not None:
            quota_thread = threading.Thread(
                target=_run_quota_scenario, args=(coll,),
                name="quota-scenario", daemon=True,
            )
            quota_thread.start()
        fleet_storm = (fleet_cfg or {}).get("dark")
        fleet_dark_state = None
        if fleet_storm and fleet is not None:
            # heartbeat-lapse storm: N hollow agents go fully dark
            # mid-burst (no acks, no lease renewals); the nodelifecycle
            # monitor must notice the lapsed leases, taint NoExecute,
            # and evict through the shared disruption budget
            fleet_dark_state = {"fired": False, "nodes": []}

            def _run_fleet_storm(coll_ref, _fleet=fleet,
                                 _skip=len(zombie_nodes),
                                 _state=fleet_dark_state):
                frac = float(fleet_storm.get("at_fraction", 0.5))
                _wait_fraction_bound(coll_ref, frac, timeout_s)
                count = int(fleet_storm.get("count", 0))
                dark = [f"node-{i}" for i in range(_skip, _skip + count)]
                _state["nodes"] = dark
                _fleet.go_dark(dark)
                _state["fired"] = True

            threading.Thread(
                target=_run_fleet_storm, args=(coll,),
                name="fleet-storm", daemon=True,
            ).start()
        ok = True
        streaming_rec: Dict[str, Any] = {}
        if streaming:
            from kubernetes_tpu.streaming.arrivals import (
                ArrivalEngine, trace_from_config,
            )

            # generate until the trace covers every measured pod, then
            # trim: the workload measures exactly measure_pods arrivals.
            # A replay trace is FIXED -- growing the duration cannot add
            # arrivals, so an undersized recording is a config error,
            # not a retry loop
            dur = measure_pods / streaming.rate_pods_per_sec
            offsets = trace_from_config(streaming, duration=dur)
            if streaming.trace == "replay":
                if offsets.size < measure_pods:
                    return {
                        "name": name,
                        "error": (
                            f"replay trace holds {offsets.size} arrivals "
                            f"< measure_pods {measure_pods}"
                        ),
                    }
            else:
                while offsets.size < measure_pods:
                    dur *= 1.3
                    offsets = trace_from_config(streaming, duration=dur)
            offsets = offsets[:measure_pods]
            engine = ArrivalEngine(
                client, offsets, lambda i: pods[i],
                depth_fn=sched.queue.active_count,
                max_queue_depth=streaming.max_queue_depth,
            )
            engine.start()
            frac = float(wl.get("min_bound_fraction", 1.0))
            if frac < 1.0:
                ok = coll.wait_fraction(frac, timeout_s)
            else:
                ok = coll.wait(timeout_s)
            engine.stop()
            create_times.update(engine.created_ts)
            streaming_rec = {
                "trace": streaming.trace,
                "rate": streaming.rate_pods_per_sec,
                "seed": streaming.seed,
                "arrived": engine.created,
                "backpressure_stalls": engine.backpressure_stalls,
                "stall_seconds": round(engine.stall_seconds, 3),
            }
        elif churn:
            # BASELINE #5: steady-state churn -- delete a slice of running
            # pods and schedule replacements, round after round
            rounds = int(churn.get("rounds", 5))
            per_round = int(churn.get("delete_per_round", len(pods) // rounds))
            chunks = [
                pods[r * len(pods) // rounds: (r + 1) * len(pods) // rounds]
                for r in range(rounds)
            ]
            running, _ = client.list_pods()
            victims = [p for p in running if p.spec.node_name]
            vi = 0
            for r, chunk in enumerate(chunks):
                for _ in range(min(per_round, len(victims) - vi)):
                    v = victims[vi]
                    vi += 1
                    client.delete_pod(v.metadata.namespace, v.metadata.name)
                for p in chunk:
                    create_times[p.metadata.name] = time.perf_counter()
                    client.create_pod(p)
                # wait for this round's chunk before the next delete wave
                round_deadline = time.time() + timeout_s / rounds
                while time.time() < round_deadline:
                    with coll._cond:
                        if all(
                            p.metadata.name in coll.bind_times for p in chunk
                        ):
                            break
                    time.sleep(0.02)
            ok = coll.wait(timeout_s)
        else:
            for p in pods:
                create_times[p.metadata.name] = time.perf_counter()
                client.create_pod(p)
            frac = float(wl.get("min_bound_fraction", 1.0))
            if frac < 1.0:
                ok = coll.wait_fraction(frac, timeout_s)
            else:
                ok = coll.wait(timeout_s)
        elapsed = time.perf_counter() - start
        if float(wl.get("min_bound_fraction", 1.0)) < 1.0 and coll.bind_times:
            # wait_fraction needs a 2s quiet window to decide the system
            # settled; the measured window ends at the LAST BIND, not at
            # the detector's return
            elapsed = max(coll.bind_times.values()) - start
        if _timeline.ENABLED:
            print(_timeline.dump(start), file=sys.stderr, flush=True)
        sched.wait_for_inflight_binds(timeout=60)

        if poison_names:
            # settle: every stamped pod must finish its strike budget
            # and park (the containment acceptance half of the row)
            q_deadline = time.time() + 120
            while (
                time.time() < q_deadline
                and sched.queue.quarantine_parked_count()
                < len(poison_names)
            ):
                time.sleep(0.1)
            ok = ok and (
                sched.queue.quarantine_parked_count()
                == len(poison_names)
            )

        if lifecycle:
            # teardown restores reclaimed capacity (driver.stop());
            # THEN every live incarnation must place -- respawned
            # clones are invisible to the name-keyed collector
            if scenario_thread is not None:
                scenario_thread.join(timeout=timeout_s)
                if scenario_thread.is_alive():
                    lifecycle_stop.set()  # deadline passed: abort it
                    scenario_thread.join(timeout=30)
            for comp in lifecycle_stoppers:
                comp.stop()
            lifecycle_stoppers = []
            settled = _wait_live_bound(client, 120.0)
            sched.wait_for_inflight_binds(timeout=60)
            drv = lifecycle_counters.pop("driver", None)
            if drv is not None:
                lifecycle_counters.update(
                    flaps=drv.flaps, storms=drv.storms,
                    nodes_reclaimed=drv.nodes_reclaimed,
                    pods_killed=drv.pods_killed,
                    pods_respawned=drv.pods_respawned,
                )
            drn = lifecycle_counters.pop("drainer", None)
            if drn is not None:
                lifecycle_counters.update(
                    evictions=drn.evictions,
                    evictions_blocked=drn.evictions_blocked,
                    drains_completed=drn.drains,
                )
                if drn.preempt_planned or drn.preempt_left_running:
                    lifecycle_counters.update(
                        preempt_planned=drn.preempt_planned,
                        preempt_left_running=drn.preempt_left_running,
                    )
            rsp = lifecycle_counters.pop("respawner", None)
            if rsp is not None:
                lifecycle_counters["pods_respawned"] = rsp.respawned
            lifecycle_counters["settled"] = settled
            ok = ok and settled

        pods_running = 0
        if fleet is not None:
            # the closed-loop settle: a bind only COUNTS once the hollow
            # kubelet acked it into Running. Ack-timeout rebinds and
            # respawned evictees keep landing after the last first-bind,
            # so the Running census converges later than the collector.
            need_running = int(
                float(wl.get("min_bound_fraction", 1.0))
                * len(target_names)
            )

            def _count_running():
                return sum(
                    1 for p in client.list_pods()[0]
                    if p.metadata.name.startswith("measure-")
                    and p.status.phase == POD_RUNNING
                    and p.metadata.deletion_timestamp is None
                )

            def _running_on_dark():
                # a dark-storm row only settles once the eviction loop
                # has actually run: the storm fired AND no surviving
                # Running pod still rests on a dark node
                if fleet_dark_state is None:
                    return 0
                dark = set(fleet_dark_state["nodes"])
                return sum(
                    1 for p in client.list_pods()[0]
                    if p.metadata.name.startswith("measure-")
                    and p.status.phase == POD_RUNNING
                    and p.metadata.deletion_timestamp is None
                    and p.spec.node_name in dark
                )

            def _settled():
                if pods_running < need_running:
                    return False
                if fleet_dark_state is not None and (
                    not fleet_dark_state["fired"]
                    or _running_on_dark() > 0
                ):
                    return False
                return True

            settle_deadline = time.time() + min(timeout_s, 300.0)
            pods_running = _count_running()
            while time.time() < settle_deadline and not _settled():
                time.sleep(0.25)
                pods_running = _count_running()

        bound = sum(1 for n in target_names if n in coll.bind_times)
        # capacity-starved workloads (GangContention) EXPECT a fraction
        # of pods to stay pending; they pass on reaching the fraction
        # with clean bookkeeping instead of full placement
        min_frac = float(wl.get("min_bound_fraction", 1.0))
        # same floor as wait_fraction's need so the detector and the ok
        # verdict can't disagree on fractional thresholds
        need = int(min_frac * len(target_names))
        result: Dict[str, Any] = {
            "name": name,
            "ok": bool(ok and bound >= need),
            "bound": bound,
            "total": len(target_names),
            "elapsed_s": round(elapsed, 3),
            "throughput_pods_per_s": round(bound / elapsed, 1) if elapsed else 0.0,
        }

        lat = sorted(
            coll.bind_times[n] - create_times[n]
            for n in target_names
            if n in coll.bind_times and n in create_times
        )
        if lat:
            result["latency_ms"] = {
                "Perc50": round(_percentile(lat, 50) * 1000, 1),
                "Perc90": round(_percentile(lat, 90) * 1000, 1),
                "Perc99": round(_percentile(lat, 99) * 1000, 1),
            }
        # 1s-window throughput samples (reference throughputCollector)
        if coll.bind_times:
            t0 = min(coll.bind_times.values())
            windows: Dict[int, int] = {}
            for v in coll.bind_times.values():
                windows[int((v - t0))] = windows.get(int(v - t0), 0) + 1
            samples = sorted(windows.values())
            result["throughput_samples"] = {
                "Average": round(sum(samples) / len(samples), 1),
                "Perc50": _percentile(samples, 50),
                "Perc90": _percentile(samples, 90),
                "Perc99": _percentile(samples, 99),
            }
        # placement-quality: per-node cpu utilization spread (the churn
        # workloads exist to compare greedy vs the sinkhorn global
        # prior; throughput alone can't show placement quality)
        from kubernetes_tpu.api.types import (
            RESOURCE_CPU,
            pod_resource_requests,
        )

        node_cpu: Dict[str, int] = {}
        for p in client.list_pods()[0]:
            if p.spec.node_name:
                node_cpu[p.spec.node_name] = node_cpu.get(
                    p.spec.node_name, 0
                ) + pod_resource_requests(p).get(RESOURCE_CPU, 0)
        utils = []
        for node_obj in client.list_nodes()[0]:
            cap = node_obj.status.allocatable.get(RESOURCE_CPU, 0)
            if cap:
                utils.append(
                    node_cpu.get(node_obj.metadata.name, 0) / cap
                )
        if utils:
            mean = sum(utils) / len(utils)
            var = sum((u - mean) ** 2 for u in utils) / len(utils)
            result["utilization_cpu"] = {
                "mean": round(mean, 4),
                "std": round(var ** 0.5, 4),
                "max": round(max(utils), 4),
            }
        result["solver"] = {
            "mesh_devices": mesh_devices,
            # which mesh tier the workload ACTUALLY solved on:
            # "pallas" = the shard_map'd per-shard tier (PR 10),
            # "xla" = the GSPMD twin (KTPU_MESH_PALLAS=0, ineligible
            # shape, or breaker-routed fallback), "" = no mesh
            "mesh_tier": getattr(sched, "mesh_solver_tier", ""),
            "batches": sched.batches_solved,
            "pods_on_device": sched.pods_solved_on_device,
            "pods_fallback": sched.pods_fallback,
            "classified": getattr(sched, "admissions_classified", 0),
            "reclassified": getattr(sched, "reclassifications", 0),
            "volume_reject_retries": getattr(
                sched, "volume_reject_retries", 0
            ),
            "envelope_fallbacks": sched.envelope_fallbacks,
            "pipeline_drains": sched.pipeline_drains,
            "state_reuses": sched.state_reuses,
            "state_uploads": sched.state_uploads,
            "delta_rows_uploaded": getattr(
                sched, "delta_rows_uploaded", 0
            ),
            "carry_divergences": getattr(
                sched, "carry_divergences", 0
            ),
            "membership_row_patches": getattr(
                sched, "membership_row_patches", 0
            ),
            "gang_resolves": sched.gang_resolves,
        }
        tc = getattr(sched, "tensor_cache", None)
        if tc is not None:
            # churn observability: slot adds/retires vs counted full
            # repacks (a lifecycle workload should move the first two
            # and leave full_repacks at the one cold pack)
            result["solver"]["tensor_full_repacks"] = tc.full_repacks
            result["solver"]["tensor_rows_added"] = tc.rows_added
            result["solver"]["tensor_rows_retired"] = tc.rows_retired
        qm = getattr(sched, "quarantine", None)
        if poison_names or (qm is not None and qm.isolations):
            # blast-radius containment labels (the poison-chaos row's
            # own numbers): bisection work done, the strike ledger, and
            # the parked outcome the ok verdict above depends on
            result["containment"] = {
                "poison_pods": len(poison_names),
                "bisections": getattr(sched, "bisections", 0),
                "isolations": qm.isolations if qm is not None else 0,
                "holds": qm.holds if qm is not None else 0,
                "parks": qm.parks if qm is not None else 0,
                "quarantine_parked": (
                    sched.queue.quarantine_parked_count()
                ),
                "carry_audit_heals": getattr(
                    sched, "carry_audit_heals", 0
                ),
            }
        if preempt_cfg:
            from kubernetes_tpu.utils import metrics as _metrics

            pre = sched.preemptor
            prec: Dict[str, Any] = {
                "waves": pre.waves,
                # which tier the LAST wave actually solved on (the
                # solver_mesh_tier analogue: pallas / xla / host)
                "wave_tier": pre.wave_solver_tier,
                "budget_denials": pre.budget_denials,
                "victims_slow_death": pre.victims_slow_death,
                "device_preemptions": pre.device_preemptions,
                "host_preemptions": pre.host_preemptions,
                "evictions_blocked_by_pdb": int(
                    _metrics.evictions_blocked_by_pdb.value()
                    - preempt_metrics0["blocked"]
                ),
                "nominations_set": int(
                    _metrics.nominations_set.value()
                    - preempt_metrics0["nominations_set"]
                ),
                "nominations_cleared": int(
                    _metrics.nominations_cleared.value()
                    - preempt_metrics0["nominations_cleared"]
                ),
            }
            for tier, n in sorted(pre.victims_by_tier.items()):
                prec[f"victims_{tier}"] = n
            thr = preempt_cfg.get("high_priority_threshold")
            if thr is not None:
                # the inversion pin: with a threshold declared, EVERY
                # high-band pod must have bound -- an unbound high pod
                # fails the row even when the bulk fraction passed
                unbound = sum(
                    1 for p in client.list_pods()[0]
                    if p.spec.priority >= int(thr)
                    and not p.spec.node_name
                    and p.metadata.deletion_timestamp is None
                )
                prec["high_priority_unbound"] = unbound
                result["ok"] = bool(result["ok"]) and unbound == 0
            result["preemption"] = prec
        if quota_ctrl is not None:
            # fairness + ledger labels: Jain index over per-tenant bind
            # counts, the min-tenant share of fair share, the dominant-
            # share spread, and the quota ledger's counters. Overspend
            # (any quota's used > hard) fails the row outright -- the
            # zero-overspend invariant is the acceptance bar.
            thr0 = (tenancy_cfg or {}).get("high_priority_threshold")
            if thr0 is not None:
                # settle: the high band binds through PREEMPTION waves
                # (evict -> victim termination -> nominee rebind), which
                # keep landing after the bulk fraction went quiet --
                # read the inversion verdict only once the band settled
                # (bounded; a genuinely starved band still fails below)
                settle_deadline = time.time() + 120
                while time.time() < settle_deadline:
                    if not any(
                        p.spec.priority >= int(thr0)
                        and not p.spec.node_name
                        and p.metadata.deletion_timestamp is None
                        for p in client.list_pods()[0]
                    ):
                        break
                    time.sleep(0.25)
                sched.wait_for_inflight_binds(timeout=60)
            per_ns: Dict[str, int] = {}
            overspend = False
            all_pods, _rv = client.list_pods()
            for p in all_pods:
                if p.spec.node_name and p.metadata.namespace.startswith(
                    "tenant-"
                ):
                    per_ns[p.metadata.namespace] = per_ns.get(
                        p.metadata.namespace, 0
                    ) + 1
            for q, _rv2 in [client.list_resource_quotas()]:
                for quota_obj in q:
                    for rname, hard_qty in quota_obj.hard.items():
                        if quota_obj.status.used.get(rname, 0) > hard_qty:
                            overspend = True
            counts = [
                per_ns.get(f"tenant-{t}", 0)
                for t in range(max(1, n_namespaces))
            ]
            total_bound = sum(counts)
            jain = 0.0
            if total_bound:
                jain = (total_bound ** 2) / (
                    len(counts) * sum(c * c for c in counts)
                )
            fair = total_bound / max(1, len(counts))
            min_fair_frac = (
                min(counts) / fair if fair > 0 else 1.0
            )
            tt = getattr(sched, "tenant_shares", None)
            trec: Dict[str, Any] = {
                "namespaces": n_namespaces,
                "jain_bind_index": round(jain, 4),
                "min_fair_fraction": round(min_fair_frac, 4),
                "max_dominant_share": (
                    round(tt.max_share(), 4) if tt is not None else 0.0
                ),
                "dominant_share_spread": (
                    round(tt.share_spread(), 4) if tt is not None else 0.0
                ),
                "quota_denials": quota_ctrl.admissions_denied,
                "quota_grants": quota_ctrl.admissions_granted,
                "quota_refunds": quota_ctrl.refunds,
                "quota_releases": quota_ctrl.releases,
                "quota_parked": sched.queue.quota_parked_count(),
                "overspend": overspend,
            }
            result["tenant"] = trec
            result["ok"] = bool(result["ok"]) and not overspend
            min_jain = (tenancy_cfg or {}).get("min_jain")
            if min_jain is not None:
                result["ok"] = bool(result["ok"]) and (
                    jain >= float(min_jain)
                )
            min_ff = (tenancy_cfg or {}).get("min_fair_fraction")
            if min_ff is not None:
                result["ok"] = bool(result["ok"]) and (
                    min_fair_frac >= float(min_ff)
                )
            thr = (tenancy_cfg or {}).get("high_priority_threshold")
            if thr is not None:
                # the multi-tenant inversion pin: every high-band pod
                # binds even while the bulk flood contends across
                # tenants and quotas
                unbound_high = sum(
                    1 for p in all_pods
                    if p.spec.priority >= int(thr)
                    and not p.spec.node_name
                    and p.metadata.deletion_timestamp is None
                )
                trec["high_priority_unbound"] = unbound_high
                result["ok"] = bool(result["ok"]) and unbound_high == 0
        if fleet is not None:
            # closed-loop labels + the Running gate: the row fails
            # unless the needed fraction of measured pods is RUNNING
            # (not merely bound), none of them sits on a zombie, and
            # the ack/rebind/eviction ledgers ride along for the
            # dashboard
            tracker = sched.bind_ack_tracker
            frec: Dict[str, Any] = {
                "pods_running": pods_running,
                "pods_acked": fleet.pods_acked,
                "heartbeats": fleet.heartbeats_sent,
                "heartbeat_lapses": fleet.heartbeat_lapses,
                "stale_acks": fleet.stale_acks,
                "acks_suppressed": fleet.acks_suppressed,
            }
            if tracker is not None:
                frec.update(
                    acks=tracker.acks,
                    acks_late=tracker.acks_late,
                    ack_timeouts=tracker.timeouts,
                    rebinds=tracker.rebinds,
                    ack_pending=tracker.pending_count(),
                )
            if fleet_lifecycle is not None:
                frec.update(
                    evictions=fleet_lifecycle.evictions,
                    evictions_blocked=fleet_lifecycle.evictions_blocked,
                )
            if fleet_respawner is not None:
                frec["pods_respawned"] = fleet_respawner.respawned
            if zombie_nodes:
                zset = set(zombie_nodes)
                on_zombie = sum(
                    1 for p in client.list_pods()[0]
                    if p.spec.node_name in zset
                    and p.metadata.deletion_timestamp is None
                )
                frec["pods_on_zombies"] = on_zombie
                result["ok"] = bool(result["ok"]) and on_zombie == 0
            if fleet_dark_state is not None:
                on_dark = _running_on_dark()
                frec["storm_fired"] = bool(fleet_dark_state["fired"])
                frec["pods_on_dark"] = on_dark
                result["ok"] = bool(
                    result["ok"]
                    and fleet_dark_state["fired"]
                    and on_dark == 0
                )
            result["fleet"] = frec
            result["ok"] = bool(result["ok"]) and pods_running >= need
        if lifecycle_counters:
            result["lifecycle"] = lifecycle_counters
        if streaming_rec:
            if controller is not None:
                streaming_rec.update(
                    window_ms=round(controller.window * 1000, 2),
                    batch_cap=controller.batch_cap,
                    window_changes=controller.window_changes,
                    cap_changes=controller.cap_changes,
                )
            result["streaming"] = streaming_rec
        return result
    finally:
        # EVERY component stops on EVERY exit path (including exceptions
        # mid-churn): leaked scheduler/informer/collector/heartbeat
        # threads would keep running against the abandoned server and
        # perturb every later workload in the matrix
        if coll is not None:
            coll.stop()
        if engine is not None:
            engine.stop()
        if lifecycle_stop is not None:
            lifecycle_stop.set()
        for comp in lifecycle_stoppers:
            try:
                comp.stop()
            except Exception:  # noqa: BLE001 - teardown keeps going
                pass
        for comp in preempt_stoppers:
            try:
                comp.stop()
            except Exception:  # noqa: BLE001 - teardown keeps going
                pass
        for comp in tenancy_stoppers:
            try:
                comp.stop()
            except Exception:  # noqa: BLE001 - teardown keeps going
                pass
        if injector is not None:
            from kubernetes_tpu.robustness.faults import install_injector

            install_injector(None)
        sched.stop()
        if hollow is not None:
            hollow.stop()
        for comp in (fleet_respawner, fleet_lifecycle,
                     fleet_disruption, fleet):
            if comp is not None:
                try:
                    comp.stop()
                except Exception:  # noqa: BLE001 - teardown keeps going
                    pass
        informers.stop()


def to_data_items(results: List[Dict[str, Any]]) -> Dict[str, Any]:
    """The reference dashboard JSON shape (util.go:109 DataItems)."""
    items = []
    for r in results:
        labels = {"Name": r["name"]}
        labels.update(
            {f"solver_{k}": str(v) for k, v in (r.get("solver") or {}).items()}
        )
        labels.update(
            {
                f"containment_{k}": str(v)
                for k, v in (r.get("containment") or {}).items()
            }
        )
        labels.update(
            {
                f"lifecycle_{k}": str(v)
                for k, v in (r.get("lifecycle") or {}).items()
            }
        )
        labels.update(
            {
                f"streaming_{k}": str(v)
                for k, v in (r.get("streaming") or {}).items()
            }
        )
        labels.update(
            {
                f"partition_{k}": str(v)
                for k, v in (r.get("partition") or {}).items()
            }
        )
        labels.update(
            {
                f"preemption_{k}": str(v)
                for k, v in (r.get("preemption") or {}).items()
            }
        )
        labels.update(
            {
                f"tenant_{k}": str(v)
                for k, v in (r.get("tenant") or {}).items()
            }
        )
        labels.update(
            {
                f"fleet_{k}": str(v)
                for k, v in (r.get("fleet") or {}).items()
            }
        )
        if r.get("error") or not r.get("ok", False):
            labels["error"] = r.get("error", f"{r.get('bound')}/{r.get('total')} bound")
        items.append(
            {
                # "Average" keeps the reference semantics (mean of 1s
                # window samples, util.go:197); the end-to-end
                # bound/elapsed rate rides its own "Overall" key
                "data": {
                    **(r.get("throughput_samples") or {}),
                    "Overall": r.get("throughput_pods_per_s", 0.0),
                },
                "unit": "pods/s",
                "labels": {**labels, "Metric": "SchedulingThroughput"},
            }
        )
        if r.get("latency_ms"):
            items.append(
                {
                    "data": dict(r["latency_ms"]),
                    "unit": "ms",
                    "labels": {**labels, "Metric": "PodToBindLatency"},
                }
            )
        if r.get("utilization_cpu"):
            # placement quality (the Churn vs ChurnSinkhorn A/B hinges
            # on spread, not throughput): per-node cpu utilization
            # mean / stddev / max after the workload settles
            items.append(
                {
                    "data": dict(r["utilization_cpu"]),
                    "unit": "fraction",
                    "labels": {**labels, "Metric": "NodeCpuUtilization"},
                }
            )
    return {"version": "v1", "dataItems": items}


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    import yaml

    ap = argparse.ArgumentParser(prog="benchmarks")
    ap.add_argument(
        "--config",
        default=os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "config",
            "performance-config.yaml",
        ),
    )
    ap.add_argument("--out", default="BENCHMARKS.json")
    ap.add_argument("--only", default="", help="substring filter on workload name")
    args = ap.parse_args(argv)

    with open(args.config) as f:
        cfg = yaml.safe_load(f)
    defaults = cfg.get("defaults") or {}
    results = []
    for wl in cfg.get("workloads") or []:
        if args.only and args.only not in wl["name"]:
            continue
        print(f"=== {wl['name']} ===", file=sys.stderr, flush=True)
        t0 = time.perf_counter()
        try:
            r = run_workload(wl, defaults)
        except Exception as e:  # noqa: BLE001 - keep the matrix running
            import traceback

            traceback.print_exc()
            r = {"name": wl["name"], "ok": False, "error": repr(e)}
        r["wall_s"] = round(time.perf_counter() - t0, 1)
        print(json.dumps(r), file=sys.stderr, flush=True)
        results.append(r)

    # cross-row throughput floors (`throughput_floor: {of: <row>,
    # fraction: F}`): the closed-loop BigClusterBasic row must keep
    # >= F of its bind-and-forget sibling's throughput -- the ack spine
    # may not eat the pipeline. Evaluated after the matrix so the
    # reference row's number exists; a missing/failed reference row
    # skips the floor rather than inventing one.
    by_name = {r["name"]: r for r in results}
    for wl in cfg.get("workloads") or []:
        floor = wl.get("throughput_floor")
        if not floor or wl["name"] not in by_name:
            continue
        row = by_name[wl["name"]]
        ref = by_name.get(floor.get("of", ""))
        if ref is None or not ref.get("ok"):
            continue
        frac = float(floor.get("fraction", 0.8))
        ref_thr = float(ref.get("throughput_pods_per_s", 0.0))
        row_thr = float(row.get("throughput_pods_per_s", 0.0))
        row["throughput_floor"] = {
            "of": floor.get("of"), "fraction": frac,
            "reference_pods_per_s": ref_thr,
        }
        if ref_thr > 0 and row_thr < frac * ref_thr:
            row["ok"] = False
            row["error"] = (
                f"closed-loop throughput {row_thr} < {frac} x "
                f"{ref_thr} ({floor.get('of')})"
            )

    out = to_data_items(results)
    with open(args.out, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))
    return 0 if all(r.get("ok") for r in results) else 1


if __name__ == "__main__":
    sys.exit(main())
