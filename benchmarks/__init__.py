"""Perf-matrix benchmark harness.

The analogue of the reference's BenchmarkPerfScheduling
(/root/reference/test/integration/scheduler_perf/scheduler_perf_test.go:112):
a YAML workload matrix (config/performance-config.yaml) driven end-to-end
through the real pipeline (apiserver -> informers -> queue -> TPU batch
solver -> bulk bind), emitting DataItems-style JSON
(test/integration/scheduler_perf/util.go:109) with throughput samples and
pod-to-bind latency percentiles per workload.

Run: python -m benchmarks [--config PATH] [--out PATH] [--only NAME]
"""
